#!/usr/bin/env python
"""Seeded chaos sweep over the resilience subsystem (ISSUE 1, CI tooling).

Runs every failure-injection scenario the runtime claims to survive -
injected task faults under retry, worker death mid-UTS, runtime deadlines,
poison-task quarantine, and a procworld peer crash - across one or more
seeds, and exits nonzero if any scenario fails OR hangs.

Hang enforcement is the tool's own: ``faulthandler.dump_traceback_later``
arms a process-wide timer that dumps every thread's stack and hard-exits
(status 1) if the sweep overruns ``--timeout-s``, so a regression that
re-introduces an unbounded wait fails CI loudly instead of wedging it.
Each launch additionally runs under its own ``deadline_s`` (the feature
under test bounding the test).

Usage:
    python tools/chaos_soak.py                    # fast smoke (tier-1)
    python tools/chaos_soak.py --scale soak --seeds 8   # standalone soak

One JSON line per scenario; a summary line last.
"""

from __future__ import annotations

import argparse
import faulthandler
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import hclib_tpu as hc  # noqa: E402
from hclib_tpu.models import fib, uts  # noqa: E402
from hclib_tpu.modules.procworld import (  # noqa: E402
    ProcWorld,
    ProcWorldError,
)


class _FakeKV:
    """Minimal coordination-service stand-in (threads as ranks) so the
    procworld crash scenario runs in one process with no cluster - the
    same seam tests/test_procworld_unit.py uses."""

    def __init__(self) -> None:
        self._kv = {}
        self._ctr = {}
        self._cv = threading.Condition()

    def key_value_set_bytes(self, key, val):
        with self._cv:
            self._kv[key] = bytes(val)
            self._cv.notify_all()

    def key_value_try_get_bytes(self, key):
        with self._cv:
            if key in self._kv:
                return self._kv[key]
        raise RuntimeError(f"NOT_FOUND: key {key} not found")

    def blocking_key_value_get_bytes(self, key, timeout_ms):
        deadline = time.monotonic() + timeout_ms / 1000.0
        with self._cv:
            while key not in self._kv:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise RuntimeError(
                        f"DEADLINE_EXCEEDED: GetKeyValue() timed out "
                        f"with key: {key}"
                    )
                self._cv.wait(left)
            return self._kv[key]

    def key_value_delete(self, key):
        with self._cv:
            self._kv.pop(key, None)

    def key_value_increment(self, key, n):
        with self._cv:
            self._ctr[key] = self._ctr.get(key, 0) + n
            return self._ctr[key]

    def wait_at_barrier(self, bid, timeout_ms, *a, **k):
        raise RuntimeError("UNIMPLEMENTED: no barriers in the soak fake")


# ------------------------------------------------------------- scenarios

def scenario_fib_retry(seed: int, scale: str) -> dict:
    """Injected task faults healed by runtime-default retry."""
    n = 12 if scale == "smoke" else 18
    plan = hc.FaultPlan(
        seed=seed, task_failure_rate=0.15, max_task_failures=50
    )
    out = fib.run(
        n, "finish", nworkers=2,
        fault_plan=plan,
        default_retry=hc.RetryPolicy(max_attempts=8, backoff_s=0.0005,
                                     jitter=0, seed=seed),
        deadline_s=60.0,
    )
    faults = len(plan.trace_key())
    assert faults > 0, "plan injected nothing; scenario is vacuous"
    return {"value": out["value"], "faults": faults}


def scenario_uts_kill_worker(seed: int, scale: str) -> dict:
    """Worker thread death mid-UTS; identity re-binds, traversal exact.
    The kill fires on worker 1's first scheduling poll; on a loaded
    1-vCPU host the short tree can drain before that thread is ever
    scheduled, so the kill is raced over a few attempts - every attempt
    must stay exact, and the kill must land within the attempt budget."""
    params = uts.T3
    plan = hc.FaultPlan(
        seed=seed, kill_worker=1, kill_worker_after=1,
        steal_delay_rate=0.05, steal_delay_s=0.001,
    )
    expect = uts.count_seq(params)[0]
    attempts = 0
    for attempts in range(1, 6):
        nodes, leaves, depth = uts.count_parallel(
            params, nworkers=4, grain=1,
            fault_plan=plan, deadline_s=120.0,
        )
        assert nodes == expect, f"UTS corrupted: {nodes} != {expect}"
        if ("kill_worker", 1) in plan.trace_key():
            break
    assert ("kill_worker", 1) in plan.trace_key(), "worker never died"
    return {"nodes": expect, "attempts": attempts,
            "trace": len(plan.trace_key())}


def scenario_deadline(seed: int, scale: str) -> dict:
    """A wedged program surfaces as StallError in bounded time."""
    t0 = time.monotonic()
    try:
        hc.launch(
            lambda: hc.Promise().future.wait(), nworkers=2, deadline_s=0.5
        )
    except hc.StallError:
        dt = time.monotonic() - t0
        assert dt < 10.0, f"deadline enforcement took {dt:.1f}s"
        return {"bounded_s": round(dt, 3)}
    raise AssertionError("wedged launch returned without StallError")


def scenario_quarantine(seed: int, scale: str) -> dict:
    """Poison tasks quarantine; the rest of the batch completes."""
    n = 64 if scale == "smoke" else 512
    done = []
    lock = threading.Lock()
    poison = {i for i in range(n) if i % 13 == seed % 13}

    def body(i):
        if i in poison:
            raise ValueError(f"poison item {i}")
        with lock:
            done.append(i)

    rt = hc.Runtime(
        nworkers=4,
        default_retry=hc.RetryPolicy(max_attempts=2, backoff_s=0,
                                     jitter=0, quarantine=True),
    )
    rt.run(lambda: hc.forasync(body, [n], tile=1), deadline_s=60.0)
    res = rt.stats_dict()["resilience"]
    assert len(done) == n - len(poison), (len(done), n, len(poison))
    assert res["quarantined"] == len(poison), res
    return {"completed": len(done), "quarantined": res["quarantined"]}


def scenario_procworld_crash(seed: int, scale: str) -> dict:
    """Peer progress-engine crash: the blocked waiter gets a structured
    ProcWorldError (tombstone/poison), never its full timeout."""
    kv = _FakeKV()
    plan = hc.FaultPlan(seed=seed, peer_crash_rank=1, peer_crash_after=0)
    a = ProcWorld(_client=kv, _rank=0, _size=2, timeout_s=20.0)
    b = ProcWorld(_client=kv, _rank=1, _size=2, timeout_s=20.0,
                  fault_plan=plan)
    try:
        import numpy as np

        with b._heap_lock:
            b._heap["x"] = np.zeros(2, np.int32)
        t0 = time.monotonic()
        try:
            a.get(1, "x")
        except ProcWorldError:
            dt = time.monotonic() - t0
            assert dt < 15.0, f"peer-death detection took {dt:.1f}s"
            return {"detected_s": round(dt, 3)}
        raise AssertionError("get() against crashed peer succeeded")
    finally:
        a.close()
        b.close()


SCENARIOS = [
    ("fib_retry", scenario_fib_retry),
    ("uts_kill_worker", scenario_uts_kill_worker),
    ("deadline", scenario_deadline),
    ("quarantine", scenario_quarantine),
    ("procworld_crash", scenario_procworld_crash),
]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seeds", type=int, default=1,
                    help="number of seeds (starting at --seed-base)")
    ap.add_argument("--seed-base", type=int, default=0)
    ap.add_argument("--scale", choices=("smoke", "soak"), default="smoke")
    ap.add_argument("--timeout-s", type=float, default=300.0,
                    help="hard whole-sweep ceiling; overrun = exit 1 "
                         "with all-thread stack dumps")
    args = ap.parse_args(argv)

    # The tool's own hang enforcement: dump + hard-exit on overrun.
    faulthandler.dump_traceback_later(args.timeout_s, exit=True)
    failures = 0
    t0 = time.monotonic()
    for seed in range(args.seed_base, args.seed_base + args.seeds):
        for name, fn in SCENARIOS:
            row = {"scenario": name, "seed": seed, "scale": args.scale}
            ts = time.monotonic()
            try:
                row.update(fn(seed, args.scale))
                row["ok"] = True
            except Exception as e:  # scenario failed; keep sweeping
                failures += 1
                row["ok"] = False
                row["error"] = f"{type(e).__name__}: {e}"
            row["seconds"] = round(time.monotonic() - ts, 3)
            print(json.dumps(row), flush=True)
    faulthandler.cancel_dump_traceback_later()
    print(json.dumps({
        "summary": True, "failures": failures,
        "scenarios": len(SCENARIOS) * args.seeds,
        "seconds": round(time.monotonic() - t0, 3),
    }), flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
