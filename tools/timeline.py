"""Render instrumentation dumps and device counters into human-readable
per-worker timelines and reports.

The analogue of the reference's trace station (tools/timeline.py renders
worker timelines from binary logs; tools/hclib_instrument_parser.c decodes
the per-thread dumps) for this runtime's two observability sources:

1. **Host event dumps** (`runtime/instrument.py`, live - the reference's
   recorder is stubbed): ``python tools/timeline.py hclib.<ts>.dump/``
   pairs START/END records per worker, draws a density timeline (one row
   per worker, one column per time bucket, shade = busy fraction), and
   tabulates per-event-type counts/durations.

2. **Device per-round counters** (megakernel/resident ``info`` dicts with
   ``per_device_counts``): ``python tools/timeline.py --device info.json``
   renders a per-device report (executed / rounds / backlog bars) so a
   multi-chip run's load balance is readable at a glance. JSON files are
   produced by ``tools/perf_regression.py --multichip`` and by any caller
   that saves a run's ``info``.

Both modes print plain text (no plotting deps); the module's render
functions return the string so tests can assert on content.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Optional, Sequence

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
if os.path.dirname(_HERE) not in sys.path:
    sys.path.insert(0, os.path.dirname(_HERE))

SHADES = " .:-=*#%@"  # density ramp for timeline cells (ASCII-safe)


def _shade(frac: float) -> str:
    i = int(round(max(0.0, min(1.0, frac)) * (len(SHADES) - 1)))
    return SHADES[i]


def _bar(value: float, vmax: float, width: int = 40) -> str:
    n = 0 if vmax <= 0 else int(round(width * value / vmax))
    return "#" * n + "." * (width - n)


def spans_from_events(events: np.ndarray) -> List[Dict]:
    """Pair START/END records (by event type + correlation id) into spans.

    Unmatched STARTs are kept open-ended (end = last timestamp seen);
    SINGLE records become zero-length marks. Returns a list of dicts
    {type, id, t0, t1} with nanosecond timestamps."""
    from hclib_tpu.runtime.instrument import END, SINGLE, START

    open_: Dict[tuple, int] = {}
    spans: List[Dict] = []
    last_ts = 0
    for rec in events:
        ts = int(rec["ts_ns"])
        last_ts = max(last_ts, ts)
        key = (int(rec["type"]), int(rec["id"]))
        tr = int(rec["transition"])
        if tr == START:
            open_[key] = ts
        elif tr == END:
            t0 = open_.pop(key, ts)
            spans.append({"type": key[0], "id": key[1], "t0": t0, "t1": ts})
        elif tr == SINGLE:
            spans.append({"type": key[0], "id": key[1], "t0": ts, "t1": ts})
    for (etype, eid), t0 in open_.items():
        spans.append({"type": etype, "id": eid, "t0": t0, "t1": last_ts,
                      "open": True})
    return spans


def render_dump(path: str, width: int = 72) -> str:
    """Per-worker density timeline + per-event-type table for one dump dir."""
    from hclib_tpu.runtime.instrument import load_dump

    names, by_worker = load_dump(path)
    all_spans = {w: spans_from_events(ev) for w, ev in by_worker.items()}
    ts = [s["t0"] for sp in all_spans.values() for s in sp] + [
        s["t1"] for sp in all_spans.values() for s in sp
    ]
    out = [f"dump: {path}"]
    if not ts:
        out.append("(no events recorded)")
        return "\n".join(out)
    t_lo, t_hi = min(ts), max(ts)
    total = max(t_hi - t_lo, 1)
    out.append(
        f"{sum(len(v) for v in by_worker.values())} events, "
        f"{len(by_worker)} workers, span {total / 1e6:.3f} ms"
    )
    out.append("")
    out.append("per-worker timeline (shade = busy fraction per bucket):")
    bucket = total / width
    for w in sorted(all_spans):
        busy = np.zeros(width)
        nspans = 0
        for s in all_spans[w]:
            nspans += 1
            b0 = (s["t0"] - t_lo) / bucket
            b1 = max((s["t1"] - t_lo) / bucket, b0 + 1e-9)
            for b in range(int(b0), min(int(np.ceil(b1)), width)):
                # overlap of [b0, b1) with bucket b
                busy[b] += max(
                    0.0, min(b1, b + 1) - max(b0, b)
                )
        row = "".join(_shade(f) for f in busy)
        frac = sum(
            s["t1"] - s["t0"] for s in all_spans[w]
        ) / total
        out.append(f"  w{w:<3d}|{row}| {100 * frac:5.1f}% busy, {nspans} spans")
    out.append(
        f"      +{'-' * width}+  0 = {0.0:.3f} ms .. {total / 1e6:.3f} ms"
    )
    out.append("")
    out.append("per-event-type summary:")
    out.append(
        f"  {'type':<20} {'count':>8} {'total ms':>10} {'mean us':>10} "
        f"{'max us':>10}"
    )
    for tid in sorted({s['type'] for sp in all_spans.values() for s in sp}):
        durs = np.array(
            [
                (s["t1"] - s["t0"]) / 1e3
                for sp in all_spans.values()
                for s in sp
                if s["type"] == tid
            ]
        )
        name = names[tid] if tid < len(names) else f"type{tid}"
        out.append(
            f"  {name:<20} {len(durs):>8} {durs.sum() / 1e3:>10.3f} "
            f"{durs.mean():>10.2f} {durs.max():>10.2f}"
        )
    return "\n".join(out)


def render_device_report(info: Dict, width: int = 40) -> str:
    """Per-device load report from a megakernel/resident ``info`` dict.

    Understands the ``per_device_counts`` layout (8 ints per device:
    head, tail, alloc, pending, value_alloc, executed, overflow, rounds)
    plus optional top-level fields (rounds, executed, seconds, name)."""
    counts = info.get("per_device_counts")
    out = []
    name = info.get("name", "device run")
    hdr = f"{name}: {info.get('executed', '?')} tasks"
    if info.get("rounds") is not None:
        hdr += f", {info['rounds']} rounds"
    if info.get("seconds") is not None:
        hdr += f", {info['seconds']:.3f} s"
        if info.get("executed") and info["seconds"] > 0:
            hdr += f" ({info['executed'] / info['seconds']:,.0f} tasks/s)"
    out.append(hdr)
    if not counts:
        out.append("(no per_device_counts in info)")
        return "\n".join(out)
    counts = np.asarray(counts)
    ex = counts[:, 5]
    vmax = ex.max()
    out.append("per-device executed (load balance):")
    for d in range(counts.shape[0]):
        extras = []
        if counts[d, 3]:
            extras.append(f"pending={counts[d, 3]}")
        if counts[d, 6]:
            extras.append(f"OVERFLOW=0x{counts[d, 6]:x}")
        out.append(
            f"  dev{d:<2d}|{_bar(ex[d], vmax, width)}| {ex[d]:>9,}"
            + (" " + " ".join(extras) if extras else "")
        )
    tot = int(ex.sum())
    imb = float(vmax) * len(ex) / tot if tot else 0.0
    out.append(
        f"  total {tot:,} tasks; imbalance max/mean = {imb:.2f}x; "
        f"rows alloc'd per device: {counts[:, 2].tolist()}"
    )
    extra = info.get("migrated")
    if extra is not None:
        out.append(f"  migrated rows: {extra}")
    return "\n".join(out)


def render_stats(stats: Dict, width: int = 40) -> str:
    """Worker-stats report (executed/spawned/steals + steal matrix) from
    ``Runtime.stats_dict()`` output or its saved JSON."""
    workers = stats.get("workers", [])
    out = [
        f"host runtime: {stats.get('nworkers', len(workers))} workers, "
        f"{sum(w.get('executed', 0) for w in workers)} tasks executed"
    ]
    vmax = max((w.get("executed", 0) for w in workers), default=0)
    for i, w in enumerate(workers):
        out.append(
            f"  w{i:<3d}|{_bar(w.get('executed', 0), vmax, width)}| "
            f"executed={w.get('executed', 0):<8} "
            f"spawned={w.get('spawned', 0):<8} steals={w.get('steals', 0)}"
        )
    mats = [w.get("stolen_from") for w in workers]
    if any(mats) and len(workers) > 1:
        out.append("steal matrix (row = thief, col = victim, shade = count):")
        m = np.asarray([x or [0] * len(workers) for x in mats], dtype=float)
        peak = m.max() or 1.0
        for i, row in enumerate(m):
            out.append(
                f"  w{i:<3d}|" + "".join(_shade(v / peak) for v in row) + "|"
            )
    return "\n".join(out)


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="render hclib_tpu traces/counters as text timelines"
    )
    ap.add_argument("path", nargs="?", help="instrument dump directory")
    ap.add_argument(
        "--device", action="append", default=[],
        help="JSON file holding a run info dict (per_device_counts)",
    )
    ap.add_argument(
        "--stats", action="append", default=[],
        help="JSON file holding Runtime.stats_dict() output",
    )
    ap.add_argument("--width", type=int, default=72)
    args = ap.parse_args(argv)
    shown = False
    if args.path:
        print(render_dump(args.path, width=args.width))
        shown = True
    bar_width = min(args.width, 60)
    for f in args.device:
        with open(f) as fh:
            print(render_device_report(json.load(fh), width=bar_width))
        shown = True
    for f in args.stats:
        with open(f) as fh:
            print(render_stats(json.load(fh), width=bar_width))
        shown = True
    if not shown:
        ap.print_help()
        return 2
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
