"""Render instrumentation dumps and device counters into human-readable
per-worker timelines, reports, and Chrome Trace / Perfetto JSON.

The analogue of the reference's trace station (tools/timeline.py renders
worker timelines from binary logs; tools/hclib_instrument_parser.c decodes
the per-thread dumps) for this runtime's observability sources:

1. **Host event dumps** (`runtime/instrument.py`, live - the reference's
   recorder is stubbed): ``python tools/timeline.py hclib.<ts>.dump/``
   pairs START/END records per worker, draws a density timeline (one row
   per worker, one column per time bucket, shade = busy fraction), and
   tabulates per-event-type counts/durations. ``--top N`` lists the N
   longest spans.

2. **Device per-round counters** (megakernel/resident ``info`` dicts with
   ``per_device_counts``): ``python tools/timeline.py --device info.json``
   renders a per-device report (executed / rounds / backlog bars) so a
   multi-chip run's load balance is readable at a glance.

3. **Perfetto export** (``--perfetto out.json``): merges host EventLog
   dumps and device flight-recorder rings (``--trace trace.json``, the
   JSON form of ``info['trace']`` - see device/tracebuf.py) into ONE
   Chrome Trace Event file: a process per device, a thread per
   worker/lane, with device round-relative time aligned to the host wall
   clock through the per-run epoch bracket (the clockprobe bracketing
   trick: both EventLog and the epoch use ``time.monotonic_ns``). Open at
   https://ui.perfetto.dev.

Text modes print plain text (no plotting deps); render functions return
strings so tests can assert on content.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
if os.path.dirname(_HERE) not in sys.path:
    sys.path.insert(0, os.path.dirname(_HERE))

SHADES = " .:-=*#%@"  # density ramp for timeline cells (ASCII-safe)


def _shade(frac: float) -> str:
    i = int(round(max(0.0, min(1.0, frac)) * (len(SHADES) - 1)))
    return SHADES[i]


def _bar(value: float, vmax: float, width: int = 40) -> str:
    n = 0 if vmax <= 0 else int(round(width * value / vmax))
    return "#" * n + "." * (width - n)


def _type_name(names: Sequence[str], tid: int) -> str:
    """ONE labeling rule for event-type ids everywhere: manifest name when
    the id is in range, ``type<N>`` otherwise (ids past the manifest come
    from types registered after the dump, or foreign dumps)."""
    if 0 <= tid < len(names):
        return names[tid]
    return f"type<{tid}>"


def spans_from_events(events: np.ndarray) -> List[Dict]:
    """Pair START/END records (by event type + correlation id) into spans.

    Unmatched STARTs are kept open-ended (end = last timestamp seen,
    flagged ``open``); SINGLE records become zero-length marks. Returns a
    list of dicts {type, id, t0, t1} with nanosecond timestamps."""
    from hclib_tpu.runtime.instrument import END, SINGLE, START

    open_: Dict[tuple, int] = {}
    spans: List[Dict] = []
    last_ts = 0
    for rec in events:
        ts = int(rec["ts_ns"])
        last_ts = max(last_ts, ts)
        key = (int(rec["type"]), int(rec["id"]))
        tr = int(rec["transition"])
        if tr == START:
            open_[key] = ts
        elif tr == END:
            t0 = open_.pop(key, ts)
            spans.append({"type": key[0], "id": key[1], "t0": t0, "t1": ts})
        elif tr == SINGLE:
            spans.append({"type": key[0], "id": key[1], "t0": ts, "t1": ts})
    for (etype, eid), t0 in open_.items():
        spans.append({"type": etype, "id": eid, "t0": t0, "t1": last_ts,
                      "open": True})
    return spans


def _density(spans: List[Dict], t_lo: int, bucket: float,
             width: int) -> np.ndarray:
    """Busy fraction per time bucket, vectorized: exact fractional overlap
    of every span with every bucket via two edge scatters (np.add.at) plus
    a diff-array cumsum for whole interior buckets - O(spans + width)
    instead of the old O(spans * width) python loop."""
    busy = np.zeros(width)
    if not spans:
        return busy
    x0 = (np.array([s["t0"] for s in spans], dtype=float) - t_lo) / bucket
    x1 = (np.array([s["t1"] for s in spans], dtype=float) - t_lo) / bucket
    x1 = np.maximum(x1, x0 + 1e-9)
    x0 = np.clip(x0, 0.0, width)
    x1 = np.clip(x1, 0.0, width)
    a0 = np.minimum(np.floor(x0).astype(int), width - 1)
    a1 = np.minimum(np.floor(x1).astype(int), width - 1)
    same = a0 == a1
    np.add.at(busy, a0[same], (x1 - x0)[same])
    multi = ~same
    np.add.at(busy, a0[multi], a0[multi] + 1.0 - x0[multi])
    np.add.at(busy, a1[multi], x1[multi] - a1[multi])
    diff = np.zeros(width + 1)
    np.add.at(diff, a0[multi] + 1, 1.0)
    np.add.at(diff, a1[multi], -1.0)
    busy += np.cumsum(diff)[:width]
    return busy


def render_dump(path: str, width: int = 72, top: int = 0) -> str:
    """Per-worker density timeline + per-event-type table for one dump
    dir; ``top`` > 0 appends the N longest spans. The external lane (non-
    worker threads, manifest ``external_lane``) renders as ``ext``."""
    from hclib_tpu.runtime.instrument import load_dump, load_manifest

    names, by_worker = load_dump(path)
    try:
        manifest = load_manifest(path)
    except Exception:
        manifest = {}
    ext_lane = manifest.get("external_lane")
    all_spans = {w: spans_from_events(ev) for w, ev in by_worker.items()}
    ts = [s["t0"] for sp in all_spans.values() for s in sp] + [
        s["t1"] for sp in all_spans.values() for s in sp
    ]
    out = [f"dump: {path}"]
    if not ts:
        out.append("(no events recorded)")
        return "\n".join(out)
    t_lo, t_hi = min(ts), max(ts)
    total = max(t_hi - t_lo, 1)
    nworkers = len(by_worker) - (1 if ext_lane in by_worker else 0)
    out.append(
        f"{sum(len(v) for v in by_worker.values())} events, "
        f"{nworkers} workers, span {total / 1e6:.3f} ms"
    )
    if manifest.get("external_records"):
        out[-1] += f" ({manifest['external_records']} external-lane records)"
    out.append("")
    out.append("per-worker timeline (shade = busy fraction per bucket):")
    bucket = total / width
    for w in sorted(all_spans):
        spans = all_spans[w]
        if w == ext_lane and not spans:
            continue  # an idle external lane adds noise, not signal
        busy = _density(spans, t_lo, bucket, width)
        row = "".join(_shade(f) for f in busy)
        frac = sum(s["t1"] - s["t0"] for s in spans) / total
        label = "ext " if w == ext_lane else f"w{w:<3d}"
        out.append(
            f"  {label}|{row}| {100 * frac:5.1f}% busy, {len(spans)} spans"
        )
    out.append(
        f"      +{'-' * width}+  0 = {0.0:.3f} ms .. {total / 1e6:.3f} ms"
    )
    out.append("")
    out.append("per-event-type summary:")
    out.append(
        f"  {'type':<20} {'count':>8} {'total ms':>10} {'mean us':>10} "
        f"{'max us':>10}"
    )
    for tid in sorted({s['type'] for sp in all_spans.values() for s in sp}):
        durs = np.array(
            [
                (s["t1"] - s["t0"]) / 1e3
                for sp in all_spans.values()
                for s in sp
                if s["type"] == tid
            ]
        )
        out.append(
            f"  {_type_name(names, tid):<20} {len(durs):>8} "
            f"{durs.sum() / 1e3:>10.3f} "
            f"{durs.mean():>10.2f} {durs.max():>10.2f}"
        )
    if top > 0:
        ranked = sorted(
            (
                (s["t1"] - s["t0"], w, s)
                for w, sp in all_spans.items()
                for s in sp
            ),
            key=lambda x: -x[0],
        )[:top]
        out.append("")
        out.append(f"top {len(ranked)} spans by duration:")
        for dur, w, s in ranked:
            who = "ext" if w == ext_lane else f"w{w}"
            flag = " OPEN" if s.get("open") else ""
            out.append(
                f"  {dur / 1e3:>10.1f} us  {who:<4} "
                f"{_type_name(names, s['type']):<20} id={s['id']}{flag}"
            )
    return "\n".join(out)


def render_device_report(info: Dict, width: int = 40) -> str:
    """Per-device load report from a megakernel/resident ``info`` dict.

    Understands the ``per_device_counts`` layout (8 ints per device:
    head, tail, alloc, pending, value_alloc, executed, overflow, rounds)
    plus optional top-level fields (rounds, executed, seconds, name)."""
    counts = info.get("per_device_counts")
    out = []
    name = info.get("name", "device run")
    hdr = f"{name}: {info.get('executed', '?')} tasks"
    if info.get("rounds") is not None:
        hdr += f", {info['rounds']} rounds"
    if info.get("seconds") is not None:
        hdr += f", {info['seconds']:.3f} s"
        if info.get("executed") and info["seconds"] > 0:
            hdr += f" ({info['executed'] / info['seconds']:,.0f} tasks/s)"
    out.append(hdr)
    if not counts:
        out.append("(no per_device_counts in info)")
        return "\n".join(out)
    counts = np.asarray(counts)
    ex = counts[:, 5]
    vmax = ex.max()
    out.append("per-device executed (load balance):")
    for d in range(counts.shape[0]):
        extras = []
        if counts[d, 3]:
            extras.append(f"pending={counts[d, 3]}")
        if counts[d, 6]:
            extras.append(f"OVERFLOW=0x{counts[d, 6]:x}")
        out.append(
            f"  dev{d:<2d}|{_bar(ex[d], vmax, width)}| {ex[d]:>9,}"
            + (" " + " ".join(extras) if extras else "")
        )
    tot = int(ex.sum())
    imb = float(vmax) * len(ex) / tot if tot else 0.0
    out.append(
        f"  total {tot:,} tasks; imbalance max/mean = {imb:.2f}x; "
        f"rows alloc'd per device: {counts[:, 2].tolist()}"
    )
    extra = info.get("migrated")
    if extra is not None:
        out.append(f"  migrated rows: {extra}")
    tiers = info.get("tiers")
    if isinstance(tiers, dict):
        tiers = [tiers]
    if tiers:
        # Batched-dispatch tier per device (ISSUE 7): occupancy is the
        # lane-firing-policy signal - a bar per device so a starving
        # lane reads at a glance next to its load bar.
        out.append("per-device batch-lane occupancy:")
        for d, t in enumerate(tiers):
            occ = float(t.get("batch_occupancy", 0.0))
            detail = (
                f" {t.get('batch_rounds', 0):>5} rounds, "
                f"{t.get('batch_tasks', 0):>7,} batched, "
                f"{t.get('scalar_tasks', 0):>6,} scalar, "
                f"{t.get('prefetch_hits', 0):>5} pf hits, "
                f"{t.get('spilled', 0):>5} spills"
            )
            out.append(f"  dev{d:<2d}|{_bar(occ, 1.0, width)}| "
                       f"{occ:4.2f}{detail}")
    return "\n".join(out)


def render_stats(stats: Dict, width: int = 40) -> str:
    """Worker-stats report (executed/spawned/steals + steal matrix) from
    ``Runtime.stats_dict()`` output or its saved JSON."""
    workers = stats.get("workers", [])
    out = [
        f"host runtime: {stats.get('nworkers', len(workers))} workers, "
        f"{sum(w.get('executed', 0) for w in workers)} tasks executed"
    ]
    vmax = max((w.get("executed", 0) for w in workers), default=0)
    for i, w in enumerate(workers):
        out.append(
            f"  w{i:<3d}|{_bar(w.get('executed', 0), vmax, width)}| "
            f"executed={w.get('executed', 0):<8} "
            f"spawned={w.get('spawned', 0):<8} steals={w.get('steals', 0)}"
        )
    mats = [w.get("stolen_from") for w in workers]
    if any(mats) and len(workers) > 1:
        out.append("steal matrix (row = thief, col = victim, shade = count):")
        m = np.asarray([x or [0] * len(workers) for x in mats], dtype=float)
        peak = m.max() or 1.0
        for i, row in enumerate(m):
            out.append(
                f"  w{i:<3d}|" + "".join(_shade(v / peak) for v in row) + "|"
            )
    return "\n".join(out)


# ------------------------------------------------------------- perfetto

def _meta(pid: int, tid: Optional[int], name_key: str, name: str) -> Dict:
    ev = {"ph": "M", "pid": pid, "name": name_key,
          "args": {"name": name}}
    if tid is not None:
        ev["tid"] = tid
    return ev


def _host_events(dump_path: str) -> List[Dict]:
    from hclib_tpu.runtime.instrument import load_dump, load_manifest

    names, by_worker = load_dump(dump_path)
    try:
        ext_lane = load_manifest(dump_path).get("external_lane")
    except Exception:
        ext_lane = None
    events: List[Dict] = [_meta(0, None, "process_name", "host runtime")]
    for w in sorted(by_worker):
        spans = spans_from_events(by_worker[w])
        if w == ext_lane and not spans:
            continue
        tname = "external" if w == ext_lane else f"worker {w}"
        events.append(_meta(0, w, "thread_name", tname))
        for s in spans:
            events.append({
                "ph": "X",
                "pid": 0,
                "tid": w,
                "ts": s["t0"] / 1e3,  # Chrome trace ts/dur are in us
                "dur": max((s["t1"] - s["t0"]) / 1e3, 0.001),
                "name": _type_name(names, s["type"]),
                "cat": "host",
                "args": {"id": s["id"], "open": bool(s.get("open"))},
            })
    return events


# Lane-thread base tid inside a device process: tids [0, _TID_LANES) are
# the fixed tracks (rounds / scalar / events), lane fid f maps to
# _TID_LANES + f.
_TID_ROUNDS, _TID_SCALAR, _TID_EVENTS, _TID_TENANTS, _TID_LANES = (
    0, 1, 2, 3, 16
)


def _device_events(trace: Dict, pid0: int) -> List[Dict]:
    """Chrome-trace events for one trace_info dict: a process per ring
    (device), a thread per worker/lane track, round-relative record time
    interpolated into the host epoch bracket."""
    from hclib_tpu.device import tracebuf as tb

    ep = trace["epoch"]
    t0, t1 = float(ep["t0_ns"]), float(ep["t1_ns"])
    events: List[Dict] = []
    for d, ring in enumerate(trace["rings"]):
        pid = pid0 + d
        recs = np.asarray(ring["records"])
        events.append(_meta(pid, None, "process_name", f"device {d}"))
        if recs.size == 0:
            continue
        rmax = float(max(int(recs[:, 1].max()) + 1, 1))
        slot_us = max((t1 - t0) / rmax / 1e3, 0.001)

        def ts_us(r):
            return (t0 + (t1 - t0) * (float(r) / rmax)) / 1e3

        used_tids: Dict[int, str] = {}

        def span(tid, tname, r0, dur_slots, name, args):
            used_tids.setdefault(tid, tname)
            events.append({
                "ph": "X", "pid": pid, "tid": tid,
                "ts": ts_us(r0),
                "dur": max(dur_slots, 0.25) * slot_us,
                "name": name, "cat": "device", "args": args,
            })

        open_rounds: List[Tuple[int, Dict]] = []
        quiesce_at: Optional[int] = None
        for tag, t, a, b in recs.tolist():
            if tag == tb.TR_ROUND_BEGIN:
                open_rounds.append((t, {"backlog": a, "pending": b}))
            elif tag == tb.TR_ROUND_END:
                rb, args = open_rounds.pop() if open_rounds else (t, {})
                args = dict(args)
                args.update({"executed": a, "pending": b})
                span(_TID_ROUNDS, "rounds", rb, t + 1 - rb, "round", args)
            elif tag == tb.TR_FIRE_SCALAR:
                span(_TID_SCALAR, "scalar dispatch", t, 0.5,
                     f"fn{a}", {"row": b})
            elif tag == tb.TR_FIRE_BATCH:
                fid, take = a >> 16, a & 0xFFFF
                span(_TID_LANES + fid, f"lane fn{fid}", t, 0.5,
                     f"batch x{take}", {"take": take, "prefetched": b})
            elif tag == tb.TR_FIRE_AGE:
                # Fire-reason record (lane_max_age): this round's batch
                # jumped ring-drain-first; rendered on the lane's own
                # track so starved-then-forced fires read at a glance.
                fid, take = a >> 16, a & 0xFFFF
                span(_TID_LANES + fid, f"lane fn{fid}", t, 0.25,
                     f"age fire x{take}", {"take": take, "age": b})
            elif tag == tb.TR_FIRE_BUCKET:
                # Priority-tier fire record (priority_buckets): which
                # bucket ring this round's batch retired - rendered on
                # the firing lane's track (the b word names it) so the
                # lowest-nonempty-first discipline reads directly off
                # the timeline next to the round's TR_FIRE_BATCH.
                bkt, take = a >> 16, a & 0xFFFF
                span(_TID_LANES + b, f"lane fn{b}", t, 0.25,
                     f"b{bkt} fire x{take}", {"bucket": bkt,
                                              "take": take})
            elif tag == tb.TR_PREFETCH_ISSUE:
                span(_TID_LANES + a, f"lane fn{a}", t, 0.25,
                     "prefetch", {"count": b})
            elif tag == tb.TR_PREFETCH_DRAIN:
                span(_TID_LANES + a, f"lane fn{a}", t, 0.25,
                     "prefetch drain", {"count": b})
            elif tag == tb.TR_SPILL:
                span(_TID_LANES + a, f"lane fn{a}", t, 0.25,
                     "spill", {"count": b})
            elif tag == tb.TR_QUIESCE:
                quiesce_at = t
                span(_TID_EVENTS, "events", t, 0.25, "quiesce",
                     {"at": a})
            elif tag == tb.TR_CKPT:
                if a < 0:
                    # Durable-store event (BundleStore, host-emitted):
                    # a = -(1 + CK_code) keys the CK_NAMES table and b
                    # is the generation acted on - save/load/fallback/
                    # quarantine/poison land on the events track beside
                    # the device export brackets.
                    code = -int(a) - 1
                    name = tb.CK_NAMES.get(code, f"ckpt<{code}>")
                    span(_TID_EVENTS, "events", t, 0.5, name,
                         {"generation": b})
                    continue
                # The checkpoint bracket: quiesce observation -> state
                # export, rendered as one span so the drain cost (lane
                # spills, wire settling on the mesh) is readable at a
                # glance in Perfetto.
                q0 = quiesce_at if quiesce_at is not None else t
                span(_TID_EVENTS, "events", q0, max(t - q0, 0) + 0.5,
                     "checkpoint (quiesce→export)",
                     {"pending": a, "ready_backlog": b})
                quiesce_at = None
            elif tag == tb.TR_CREDIT:
                # Steal-credit traffic: channel ((hop << 8) | peer) and
                # the CR_* delta code - dropped/duplicated/regenerated
                # credits read directly off the events track.
                hop, peer = a >> 8, a & 0xFF
                delta = tb.CR_NAMES.get(b, f"delta<{b}>")
                span(_TID_EVENTS, "events", t, 0.25,
                     f"credit {delta}", {"hop": hop, "peer": peer})
            elif tag == tb.TR_XFER:
                span(_TID_EVENTS, "events", t, 0.5,
                     f"xfer x{b}", {"partner": a, "rows": b})
            elif tag == tb.TR_ABORT:
                span(_TID_EVENTS, "events", t, 0.5, "abort",
                     {"observed_round": a})
            elif tag == tb.TR_FAULT:
                kind = tb.FLT_NAMES.get(a, f"fault<{a}>")
                span(_TID_EVENTS, "events", t, 0.5, kind,
                     {"code": a, "detail": b})
            elif tag == tb.TR_INJECT:
                span(_TID_EVENTS, "events", t, 0.5,
                     f"inject +{a}", {"installed": a})
            elif tag == tb.TR_TENANT:
                # One WRR tenant-poll visit: installs and lazy expired
                # drops per lane, on a dedicated track so per-tenant
                # ingress fairness reads directly off the timeline.
                lane, inst = a >> 16, a & 0xFFFF
                name = f"t{lane} +{inst}"
                if b:
                    name += f" ({b} expired)"
                span(_TID_TENANTS, "tenant ingress", t, 0.5, name,
                     {"lane": lane, "installed": inst, "expired": b})
            elif tag == tb.TR_EGRESS:
                # A retired row parked on a full completion mailbox
                # (explicit backpressure, never loss): the submit token
                # and the park ring occupancy after the park, on the
                # events track so egress pressure reads off the
                # timeline next to the installs that caused it.
                span(_TID_EVENTS, "events", t, 0.5,
                     f"egress park x{b}",
                     {"token": a, "parked": b})
            elif tag == tb.TR_LATENCY:
                # One tracked retirement (telemetry plane, ISSUE 19):
                # tenant lane and log2 bucket packed in a, the raw
                # admit->retire delta (rounds) in b - latency outliers
                # read off the events track right where they retired.
                ten, bkt = a >> 16, a & 0xFFFF
                span(_TID_EVENTS, "events", t, 0.25,
                     f"latency t{ten} 2^{bkt}",
                     {"tenant": ten, "bucket": bkt, "rounds": b})
            elif tag == tb.TR_SPLICE:
                # Dynamic-graph splice progress (ISSUE 20): applied and
                # dropped update deltas observed by one serving-pump
                # visit packed in a, spare-block occupancy after it in
                # b - the update storm's absorption rate reads off the
                # events track beside the rounds that did the work.
                app, drop = a >> 16, a & 0xFFFF
                name = f"splice +{app}"
                if drop:
                    name += f" ({drop} dropped)"
                span(_TID_EVENTS, "events", t, 0.5, name,
                     {"applied": app, "dropped": drop, "spare_used": b})
            elif tag == tb.TR_SCALE:
                # Autoscaler decision (host-emitted ring, slice index as
                # timebase): label resizes with their mesh arrow so the
                # control loop's story reads directly off the track.
                frm, to = a >> 8, a & 0xFF
                kind = tb.SC_NAMES.get(b, f"scale<{b}>")
                name = (
                    f"{kind} {frm}→{to}" if frm != to else kind
                )
                span(_TID_EVENTS, "autoscaler", t, 0.5, name,
                     {"from_ndev": frm, "to_ndev": to, "slice": t})
            else:
                name = tb.TAG_NAMES.get(tag, f"tag{tag}")
                span(_TID_EVENTS, "events", t, 0.25, name,
                     {"a": a, "b": b})
        # Close dangling round_begins (fuel exit mid-record is possible).
        for rb, args in open_rounds:
            span(_TID_ROUNDS, "rounds", rb, 1, "round (open)", args)
        for tid, tname in sorted(used_tids.items()):
            events.append(_meta(pid, tid, "thread_name", tname))
    return events


def request_flow_events(
    spans: Dict[int, Sequence[int]],
    futures: Sequence = (),
    ns_per_round: Optional[float] = None,
    pid: int = 90,
) -> List[Dict]:
    """Per-request Perfetto flow events (ISSUE 19): join the device
    lifecycle stamps with the host submit/resolve wall stamps.

    ``spans`` is ``StreamingMegakernel.telemetry_spans()`` -
    ``{token: (admit, install, fire)}`` in cumulative scheduler rounds
    (retire == fire). ``futures`` are the submit-side ``Future``
    objects (matched by ``.token``); a resolved one contributes the
    host-measured submit->result wall span, mapped onto the round
    timebase through ``ns_per_round`` (the stream's epoch-bracket
    factor) so the RESULT marker lands where the host actually saw the
    value - the host/device gap IS the egress+poll latency. Each
    request renders as two phase slices (queued: admit->install,
    inflight: install->fire) on one "requests" track plus a flow chain
    (``s``/``t``/``f`` sharing the token as id) threading
    submit->admit->install->fire/retire->result, so Perfetto draws the
    arrows across tracks. The round timebase renders as 1 round = 1 us
    (the same convention as the device rings)."""
    events: List[Dict] = []
    fut_by_token = {}
    for f in futures:
        tok = getattr(f, "token", None)
        if tok is not None:
            fut_by_token[int(tok)] = f
    tid = 1
    for tok in sorted(spans):
        admit, install, fire = (int(x) for x in spans[tok][:3])
        flow = {"cat": "request", "id": int(tok), "pid": pid,
                "tid": tid, "name": f"req {tok}"}
        events.append({
            "ph": "X", "pid": pid, "tid": tid, "ts": admit,
            "dur": max(install - admit, 0) + 0.25,
            "name": f"req {tok} queued",
            "args": {"token": tok, "admit": admit, "install": install},
        })
        events.append({
            "ph": "X", "pid": pid, "tid": tid, "ts": install,
            "dur": max(fire - install, 0) + 0.25,
            "name": f"req {tok} inflight",
            "args": {"token": tok, "fire": fire, "retire": fire},
        })
        events.append({**flow, "ph": "s", "ts": admit})
        events.append({**flow, "ph": "t", "ts": install})
        f = fut_by_token.get(int(tok))
        t_done = getattr(f, "t_done", None)
        t_submit = getattr(f, "t_submit", None)
        if (
            f is not None and t_done is not None
            and t_submit is not None and ns_per_round
        ):
            # Host wall span mapped to rounds, anchored at admit (the
            # pump stamps admission at publish, so submit-to-admit ring
            # wait is inside the host span but before the anchor).
            result_r = admit + (
                (float(t_done) - float(t_submit)) * 1e9 / ns_per_round
            )
            events.append({**flow, "ph": "t", "ts": fire})
            events.append({
                **flow, "ph": "f", "bp": "e",
                "ts": max(result_r, fire),
            })
            events.append({
                "ph": "X", "pid": pid, "tid": tid,
                "ts": max(result_r, fire), "dur": 0.25,
                "name": f"req {tok} result",
                "args": {"token": tok,
                         "host_latency_s": float(t_done)
                         - float(t_submit)},
            })
        else:
            events.append({**flow, "ph": "f", "bp": "e", "ts": fire})
    events.append(_meta(pid, tid, "thread_name", "requests"))
    events.append(_meta(pid, 0, "process_name", "requests"))
    return events


def export_perfetto(
    out_path: str,
    dump_path: Optional[str] = None,
    traces: Sequence[Dict] = (),
) -> Dict:
    """Merge a host EventLog dump and device flight-recorder traces into
    one Chrome Trace Event JSON (open at https://ui.perfetto.dev).
    ``traces`` are ``info['trace']`` dicts (or their JSON-loaded form).
    Returns the trace dict; writes it to ``out_path`` when non-empty."""
    from hclib_tpu.device.tracebuf import trace_from_jsonable

    events: List[Dict] = []
    if dump_path:
        events.extend(_host_events(dump_path))
    pid0 = 1
    for tr in traces:
        if tr.get("rings") and isinstance(
            tr["rings"][0].get("records"), list
        ):
            tr = trace_from_jsonable(tr)
        events.extend(_device_events(tr, pid0))
        pid0 += len(tr["rings"])
    doc = {"traceEvents": events, "displayTimeUnit": "ms"}
    if out_path:
        with open(out_path, "w") as f:
            json.dump(doc, f)
    return doc


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="render hclib_tpu traces/counters as text timelines "
        "or Perfetto JSON"
    )
    ap.add_argument("path", nargs="?", help="instrument dump directory")
    ap.add_argument(
        "--device", action="append", default=[],
        help="JSON file holding a run info dict (per_device_counts)",
    )
    ap.add_argument(
        "--stats", action="append", default=[],
        help="JSON file holding Runtime.stats_dict() output",
    )
    ap.add_argument(
        "--trace", action="append", default=[],
        help="JSON file holding a device trace (info['trace'] via "
        "tracebuf.trace_to_jsonable)",
    )
    ap.add_argument(
        "--perfetto", metavar="OUT",
        help="write a merged Chrome-trace/Perfetto JSON from the dump "
        "(positional path) and --trace files",
    )
    ap.add_argument(
        "--top", type=int, default=0,
        help="also list the N longest spans of the dump",
    )
    ap.add_argument("--width", type=int, default=72)
    args = ap.parse_args(argv)
    shown = False
    if args.perfetto:
        traces = []
        for f in args.trace:
            with open(f) as fh:
                traces.append(json.load(fh))
        doc = export_perfetto(
            args.perfetto, dump_path=args.path, traces=traces
        )
        print(
            f"perfetto: {len(doc['traceEvents'])} events -> "
            f"{args.perfetto}"
        )
        shown = True
    elif args.path:
        print(render_dump(args.path, width=args.width, top=args.top))
        shown = True
    bar_width = min(args.width, 60)
    for f in args.device:
        with open(f) as fh:
            print(render_device_report(json.load(fh), width=bar_width))
        shown = True
    for f in args.stats:
        with open(f) as fh:
            print(render_stats(json.load(fh), width=bar_width))
        shown = True
    if not shown:
        ap.print_help()
        return 2
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
