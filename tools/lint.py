#!/usr/bin/env python
"""Static-check gate (the reference's astyle + cppcheck station,
tools/astyle/run.sh + tools/cppcheck/run.sh - README.md:116-129).

No third-party linters exist in this environment, so this is a small
stdlib checker tuned to the rules the tree actually follows:

Python (ast-based, so no false positives from strings/comments):
  - parses (syntax gate)
  - no unused imports (``from __future__ import annotations`` and
    ``__init__.py`` re-exports are exempt; a ``# noqa`` on the import
    line opts out)
  - no bare ``except:``
  - no mutable default arguments
  - no tabs, no trailing whitespace, lines <= 96 chars
  - no raw ``os.environ`` READS of ``HCLIB_TPU_*`` names outside
    ``runtime/env.py`` (the typed registry is the single parse point;
    writes - tests seeding the environment - stay legal)
  - every ``HCLIB_TPU_*`` name mentioned anywhere in the tree must have
    a row in the ``runtime/env.py`` registry (the doc table cannot
    silently lag the code)
  - every ``TR_*``/``SC_*``/``CR_*``/``FLT_*``/``FS_*`` tag or
    payload-code constant defined in ``device/tracebuf.py`` must have
    a name row in its family's decode table (``TAG_NAMES`` /
    ``SC_NAMES`` / ``CR_NAMES`` / ``FLT_NAMES`` / ``FS_NAMES`` - what
    the metrics summarizer and the Perfetto exporter label with) AND a
    decode mention in ``tools/timeline.py`` - the one-table-edit
    invariant the TR_SCALE/SC_* plumbing relies on, enforced instead
    of remembered (both files parsed as ASTs, stdlib-only)

C++ (native/src):
  - no tabs, no trailing whitespace, lines <= 100 chars

Usage: ``python tools/lint.py [paths...]`` (default: the whole repo).
Exit 1 on any violation; the violations print as ``path:line: message``.
CI runs this before the test suite; tests/test_native.py runs it too so
a plain ``pytest`` catches violations locally.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Iterator, List, Optional, Set, Tuple

PY_MAX_LINE = 96
CC_MAX_LINE = 100
# The env-registry module: the ONLY file allowed to read HCLIB_TPU_*
# names from os.environ, and the source of truth for the name table.
ENV_MODULE = os.path.join("hclib_tpu", "runtime", "env.py")
_ENV_NAME = re.compile(r"HCLIB_TPU_[A-Z][A-Z0-9_]*")
SKIP_DIRS = {
    ".git", ".jax_cache", "__pycache__", ".pytest_cache", ".hypothesis",
    "perf-logs", ".claude", "build", "dist", ".eggs",
}


def _files(paths: List[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs if d not in SKIP_DIRS)
            for f in sorted(files):
                if f.endswith((".py", ".cpp", ".cc", ".hpp", ".h")):
                    yield os.path.join(root, f)


def _check_whitespace(
    path: str, src: str, max_line: int
) -> List[Tuple[int, str]]:
    out = []
    for i, line in enumerate(src.splitlines(), 1):
        if "\t" in line:
            out.append((i, "tab character"))
        if line != line.rstrip():
            out.append((i, "trailing whitespace"))
        if len(line) > max_line:
            out.append((i, f"line too long ({len(line)} > {max_line})"))
    return out


def _used_names(tree: ast.AST) -> set:
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            # attribute roots resolve through Name nodes already; nothing
            # extra needed, but keep the branch for clarity
            pass
    return used


def _is_os_environ(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Attribute)
        and node.attr == "environ"
        and isinstance(node.value, ast.Name)
        and node.value.id == "os"
    )


def _hclib_names(node: ast.AST) -> Set[str]:
    """HCLIB_TPU_* tokens inside any string constants under ``node``."""
    out: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            out |= set(_ENV_NAME.findall(n.value))
    return out


def registry_names(repo: str) -> Set[str]:
    """Registered names (canonical + legacy aliases) parsed from the
    env module's AST - no import, so the linter stays stdlib-only and
    works on a tree that doesn't import."""
    with open(os.path.join(repo, ENV_MODULE)) as f:
        tree = ast.parse(f.read())
    names: Set[str] = set()
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "_v"
        ):
            for arg in [node.args[0]] + [
                kw.value for kw in node.keywords if kw.arg == "legacy"
            ] + (list(node.args[4:5])):
                for n in ast.walk(arg):
                    if isinstance(n, ast.Constant) and isinstance(
                        n.value, str
                    ):
                        names.add(n.value)
    return names


def _check_env_usage(
    path: str, tree: ast.AST, repo: str, registered: Set[str],
    noqa,
) -> List[Tuple[int, str]]:
    out: List[Tuple[int, str]] = []
    rel = os.path.relpath(path, repo)
    is_env_module = rel == ENV_MODULE
    for node in ast.walk(tree):
        # Rule 1: raw environ READS of HCLIB_TPU_* outside the registry.
        hit: Optional[ast.AST] = None
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            # pop is the cleanup-write spelling tests use next to their
            # seeding writes - only the read idioms are flagged.
            and node.func.attr in ("get", "setdefault")
            and _is_os_environ(node.func.value)
            and any(_hclib_names(a) for a in node.args)
        ):
            hit = node
        elif (
            isinstance(node, ast.Subscript)
            and _is_os_environ(node.value)
            and isinstance(node.ctx, ast.Load)
            and _hclib_names(node.slice)
        ):
            hit = node
        if hit is not None and not is_env_module and not noqa(hit.lineno):
            out.append((
                hit.lineno,
                "raw os.environ read of an HCLIB_TPU_* name: go "
                "through hclib_tpu.runtime.env (typed registry)",
            ))
    # Rule 2: every mentioned name has a registry row.
    for name in sorted(_hclib_names(tree) - registered):
        out.append((
            1,
            f"env var {name} is not in the runtime/env.py registry: "
            "add a row (name, type, default, doc)",
        ))
    return out


TRACEBUF = os.path.join("hclib_tpu", "device", "tracebuf.py")
TIMELINE = os.path.join("tools", "timeline.py")
# Structural constants sharing the tag prefixes but not record tags.
_TAG_EXEMPT = {"TR_WORDS"}
# Tag/code families and the name table each must key into (TR_* record
# tags; SC_* scale kinds; CR_* credit deltas; FLT_* fault codes; CK_*
# checkpoint-store subcodes; FS_* reserved for fault-stats words if
# they ever move tracebuf-side).
_TAG_TABLES = {
    "TR_": "TAG_NAMES",
    "SC_": "SC_NAMES",
    "CR_": "CR_NAMES",
    "FLT_": "FLT_NAMES",
    "CK_": "CK_NAMES",
    "FS_": "FS_NAMES",
}
_TAG_RE = re.compile(r"^(TR|SC|CR|FLT|CK|FS)_[A-Z][A-Z0-9_]*$")


def check_trace_tables(repo: str) -> List[Tuple[str, int, str]]:
    """The trace-tag coverage rule: every TR_*/SC_*/CR_*/FLT_*/FS_*
    constant assigned at tracebuf.py module level (by literal OR
    expression - ``TR_NEW = TR_OLD + 1`` counts) must (a) be a key of
    its family's name table (``_TAG_TABLES``) - the single table
    metrics and Perfetto label from - and (b) be mentioned by
    tools/timeline.py (its decode rows reference record tags as
    ``tb.<TAG>``; payload-code families decode through their name
    table, so the table reference counts). Violations: (path, line,
    message)."""
    with open(os.path.join(repo, TRACEBUF)) as f:
        tree = ast.parse(f.read())
    tags: List[Tuple[str, int]] = []
    tables: dict = {}
    for node in tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            for t in targets:
                if not isinstance(t, ast.Name):
                    continue
                if (
                    _TAG_RE.match(t.id)
                    and t.id not in _TAG_EXEMPT
                    and not t.id.endswith("_NAMES")
                    # Any value expression counts (TR_NEW = TR_OLD + 1
                    # is the natural way to append a tag); only dict/
                    # sequence containers are structural, not tags.
                    and not isinstance(
                        node.value,
                        (ast.Dict, ast.List, ast.Tuple, ast.Set),
                    )
                ):
                    tags.append((t.id, node.lineno))
                if t.id in set(_TAG_TABLES.values()):
                    keys = set()
                    for n in ast.walk(node.value):
                        if isinstance(n, ast.Name):
                            keys.add(n.id)
                    tables[t.id] = keys
    with open(os.path.join(repo, TIMELINE)) as f:
        tl_tree = ast.parse(f.read())
    tl_names: Set[str] = set()
    for n in ast.walk(tl_tree):
        if isinstance(n, ast.Attribute):
            tl_names.add(n.attr)
        elif isinstance(n, ast.Name):
            tl_names.add(n.id)
    out: List[Tuple[str, int, str]] = []
    for tag, lineno in tags:
        table = next(
            t for p, t in _TAG_TABLES.items() if tag.startswith(p)
        )
        named = tag in tables.get(table, set())
        if not named:
            out.append((
                TRACEBUF, lineno,
                f"trace tag {tag} has no {table} row (the metrics/"
                "Perfetto name tables must cover every tag - one table "
                "edit, not three drifting copies)",
            ))
        # TR_* tags decode individually; SC_*/FS_* decode through their
        # name table, so the table being consulted by timeline.py
        # satisfies the decode-row half for them.
        needed = tag if tag.startswith("TR_") else table
        if needed not in tl_names:
            out.append((
                TRACEBUF, lineno,
                f"trace tag {tag} has no decode row in tools/"
                f"timeline.py ({needed} never referenced): add a "
                "branch (or name-table rendering) so the tag is "
                "legible in Perfetto",
            ))
    return out


def _check_python(
    path: str, src: str, repo: Optional[str] = None,
    registered: Optional[Set[str]] = None,
) -> List[Tuple[int, str]]:
    out = _check_whitespace(path, src, PY_MAX_LINE)
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        out.append((e.lineno or 0, f"syntax error: {e.msg}"))
        return out
    lines = src.splitlines()

    def noqa(lineno: int) -> bool:
        return 0 < lineno <= len(lines) and "# noqa" in lines[lineno - 1]

    if repo is not None and registered is not None:
        out.extend(_check_env_usage(path, tree, repo, registered, noqa))

    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            out.append((node.lineno, "bare except:"))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None
            ]:
                if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                    out.append(
                        (node.lineno,
                         f"mutable default argument in {node.name}()")
                    )
    if os.path.basename(path) != "__init__.py":
        used = _used_names(tree)
        # Names referenced only inside docstring doctests or __all__
        # strings count as used (modules re-export through __all__).
        exported = set()
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Assign)
                and any(
                    isinstance(t, ast.Name) and t.id == "__all__"
                    for t in node.targets
                )
                and isinstance(node.value, (ast.List, ast.Tuple))
            ):
                exported |= {
                    e.value
                    for e in node.value.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                }
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = (a.asname or a.name).split(".")[0]
                    if (
                        name not in used
                        and name not in exported
                        and not noqa(node.lineno)
                    ):
                        out.append((node.lineno, f"unused import '{name}'"))
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    name = a.asname or a.name
                    if (
                        name not in used
                        and name not in exported
                        and not noqa(node.lineno)
                    ):
                        out.append((node.lineno, f"unused import '{name}'"))
    return out


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = argv or [repo]
    try:
        registered = registry_names(repo)
    except OSError:
        registered = None  # env module missing: skip the env rules
    except SyntaxError:
        # env.py's own syntax error surfaces as a normal finding in the
        # per-file loop below; don't die with a traceback here.
        registered = None
    bad = 0
    for path in _files(paths):
        with open(path, errors="replace") as f:
            src = f.read()
        if path.endswith(".py"):
            problems = _check_python(
                path, src, repo if registered is not None else None,
                registered,
            )
        else:
            problems = _check_whitespace(path, src, CC_MAX_LINE)
        for lineno, msg in sorted(problems):
            print(f"{os.path.relpath(path, repo)}:{lineno}: {msg}")
            bad += 1
    try:
        table_problems = check_trace_tables(repo)
    except (OSError, SyntaxError):
        table_problems = []  # missing/broken file surfaces above
    for rel, lineno, msg in table_problems:
        print(f"{rel}:{lineno}: {msg}")
        bad += 1
    if bad:
        print(f"lint: {bad} violation(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
