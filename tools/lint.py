#!/usr/bin/env python
"""Static-check gate (the reference's astyle + cppcheck station,
tools/astyle/run.sh + tools/cppcheck/run.sh - README.md:116-129).

No third-party linters exist in this environment, so this is a small
stdlib checker tuned to the rules the tree actually follows:

Python (ast-based, so no false positives from strings/comments):
  - parses (syntax gate)
  - no unused imports (``from __future__ import annotations`` and
    ``__init__.py`` re-exports are exempt; a ``# noqa`` on the import
    line opts out)
  - no bare ``except:``
  - no mutable default arguments
  - no tabs, no trailing whitespace, lines <= 96 chars

C++ (native/src):
  - no tabs, no trailing whitespace, lines <= 100 chars

Usage: ``python tools/lint.py [paths...]`` (default: the whole repo).
Exit 1 on any violation; the violations print as ``path:line: message``.
CI runs this before the test suite; tests/test_native.py runs it too so
a plain ``pytest`` catches violations locally.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Iterator, List, Tuple

PY_MAX_LINE = 96
CC_MAX_LINE = 100
SKIP_DIRS = {
    ".git", ".jax_cache", "__pycache__", ".pytest_cache", ".hypothesis",
    "perf-logs", ".claude", "build", "dist", ".eggs",
}


def _files(paths: List[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs if d not in SKIP_DIRS)
            for f in sorted(files):
                if f.endswith((".py", ".cpp", ".cc", ".hpp", ".h")):
                    yield os.path.join(root, f)


def _check_whitespace(
    path: str, src: str, max_line: int
) -> List[Tuple[int, str]]:
    out = []
    for i, line in enumerate(src.splitlines(), 1):
        if "\t" in line:
            out.append((i, "tab character"))
        if line != line.rstrip():
            out.append((i, "trailing whitespace"))
        if len(line) > max_line:
            out.append((i, f"line too long ({len(line)} > {max_line})"))
    return out


def _used_names(tree: ast.AST) -> set:
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            # attribute roots resolve through Name nodes already; nothing
            # extra needed, but keep the branch for clarity
            pass
    return used


def _check_python(path: str, src: str) -> List[Tuple[int, str]]:
    out = _check_whitespace(path, src, PY_MAX_LINE)
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        out.append((e.lineno or 0, f"syntax error: {e.msg}"))
        return out
    lines = src.splitlines()

    def noqa(lineno: int) -> bool:
        return 0 < lineno <= len(lines) and "# noqa" in lines[lineno - 1]

    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            out.append((node.lineno, "bare except:"))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None
            ]:
                if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                    out.append(
                        (node.lineno,
                         f"mutable default argument in {node.name}()")
                    )
    if os.path.basename(path) != "__init__.py":
        used = _used_names(tree)
        # Names referenced only inside docstring doctests or __all__
        # strings count as used (modules re-export through __all__).
        exported = set()
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Assign)
                and any(
                    isinstance(t, ast.Name) and t.id == "__all__"
                    for t in node.targets
                )
                and isinstance(node.value, (ast.List, ast.Tuple))
            ):
                exported |= {
                    e.value
                    for e in node.value.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                }
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = (a.asname or a.name).split(".")[0]
                    if (
                        name not in used
                        and name not in exported
                        and not noqa(node.lineno)
                    ):
                        out.append((node.lineno, f"unused import '{name}'"))
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    name = a.asname or a.name
                    if (
                        name not in used
                        and name not in exported
                        and not noqa(node.lineno)
                    ):
                        out.append((node.lineno, f"unused import '{name}'"))
    return out


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = argv or [repo]
    bad = 0
    for path in _files(paths):
        with open(path, errors="replace") as f:
            src = f.read()
        if path.endswith(".py"):
            problems = _check_python(path, src)
        else:
            problems = _check_whitespace(path, src, CC_MAX_LINE)
        for lineno, msg in sorted(problems):
            print(f"{os.path.relpath(path, repo)}:{lineno}: {msg}")
            bad += 1
    if bad:
        print(f"lint: {bad} violation(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
