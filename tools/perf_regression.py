#!/usr/bin/env python
"""Performance-regression harness.

Reference design (test/performance-regression/full-apps/): driver scripts run
each app N pinned trials with HCLIB_PROFILE_LAUNCH_BODY=1, record mean launch-
body wall time per app into dated logs (regression-logs-*/<ts>.dat, one
"<app> <mean ns>" line per app), and compare new runs against past logs.

This harness runs the suite (fib, fib-ddt, nqueens, qsort, cilksort, FFT,
UTS, Cholesky, Smith-Waterman - the BASELINE.md apps plus the BASELINE.json
configs), writes ``perf-logs/<unix_ts>.json`` with per-app mean/min/std
nanoseconds, and flags regressions against the most recent prior log.
Every run also executes the **instrument-overhead guard**: the same
spawn-storm workload with the EventLog recorder off vs on, failing when
the ratio exceeds ``--instrument-tolerance`` (default 3x; the
recorder measures ~1.2-1.8x on no-op spawn storms, but a loaded CI box
swings the denominator) - the
observability layer must never silently tax the hot path. The
**ingress-overhead guard** bounds the multi-tenant front door the same
way: tenancy-off streams compile zero new device words and stay
bit-identical to seed, and the 1-tenant enabled path is bounded vs the
plain streaming-inject baseline in the SAME run
(``--ingress-tolerance``). The **forasync-tile guard** holds the
forasync device tier's floor: the same map loop through host forasync
(scalar-spawn) and the batch-lane tile tier must stay bit-identical,
the tile tier must beat the host arm by ``--forasync-floor`` (default
2x) in the SAME run, and its batch-lane occupancy must not collapse
(``--forasync-occupancy``).

Usage:
  python tools/perf_regression.py               # full sizes, 3 trials
  python tools/perf_regression.py --quick       # tiny sizes (CI/smoke)
  python tools/perf_regression.py --trials 5 --tolerance 0.2
  python tools/perf_regression.py --device      # + TPU device suite
  python tools/perf_regression.py --multichip   # 8-device mesh at scale
Exit code 1 if any app regressed beyond tolerance vs the previous log.

``--device`` adds the TPU engines (megakernel fib scalar + batch tiers,
Cholesky GFLOP/s, Smith-Waterman GCUPS - fused sweep AND the wave-DAG
batched-dispatch engine with its batch-occupancy counter, UTS nodes/s) -
the numbers of record bench.py reports, guarded here so no TPU claim
floats free of a harness. Device entries record a RATE (higher is
better); host entries record wall time.

``--multichip`` runs the benchmark-scale multi-device acceptance
workloads (hclib_tpu/device/stress.py) on a virtual 8-device CPU mesh:
a >=100k-task maximally-skewed fib forest through the sharded steal
runner, and the unified resident kernel (dependency-bearing migration +
remote atomics) under Mosaic-interpreter-scale load. Each run's exact
totals are asserted inside the workload; wall time and tasks/s are
recorded like any other app, and the per-device load reports are written
next to the log as ``<ts>.<name>.json`` (render them with
``python tools/timeline.py --device <file>``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Callable, Dict, List, Tuple

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _suite(quick: bool) -> List[Tuple[str, Callable[[], dict]]]:
    from hclib_tpu.models import cholesky, fft, fib, nqueens, smithwaterman, sort, uts

    if quick:
        return [
            ("fib", lambda: fib.run(18, "finish")),
            ("fib-ddt", lambda: fib.run(18, "ddf")),
            ("nqueens", lambda: nqueens.run(7)),
            ("qsort", lambda: sort.run(1 << 14, "qsort")),
            ("cilksort", lambda: sort.run(1 << 14, "cilksort")),
            ("fft", lambda: fft.run(1 << 12, threshold=1 << 10)),
            ("uts", lambda: uts.run(uts.T3)),
            ("cholesky", lambda: cholesky.run(n=64, tile=32)),
            ("smithwaterman", lambda: smithwaterman.run(m=128, n=128, tile=64)),
        ]
    return [
        ("fib", lambda: fib.run(27, "finish")),
        ("fib-ddt", lambda: fib.run(24, "ddf")),
        ("nqueens", lambda: nqueens.run(11)),
        ("qsort", lambda: sort.run(1 << 21, "qsort")),
        ("cilksort", lambda: sort.run(1 << 21, "cilksort")),
        ("fft", lambda: fft.run(1 << 18)),
        ("uts", lambda: uts.run(uts.T1)),
        ("cholesky", lambda: cholesky.run(n=512, tile=64)),
        ("smithwaterman", lambda: smithwaterman.run(m=2048, n=2048, tile=256)),
    ]


def _device_suite(trials: int) -> List[Tuple[str, Callable[[], float], str]]:
    """TPU device engines: (name, fn -> rate, unit). Each fn measures its
    own steady-state rate (slope harness, bench.py); --trials scales the
    throttle-window spreading (1 = quick smoke, no sleeps)."""
    import bench as b

    spread = 8.0 if trials > 1 else 0.0
    return [
        ("device-fib-scalar", b.bench_device_fib, "tasks/s"),
        ("device-fib-batch", b.bench_device_vfib, "tasks/s"),
        (
            "device-cholesky",
            lambda: b.bench_device_cholesky(
                trials=max(1, trials), spread_seconds=spread
            ) * 1e9,
            "FLOP/s",
        ),
        ("device-sw", lambda: b.bench_device_sw() * 1e9, "CUPS"),
        (
            # The batched same-kind dispatch tier's flagship workload: the
            # wave-DAG SW chunks grouped + prefetched by the scheduler.
            "device-sw-wave",
            lambda: b.bench_device_sw_wave(
                trials=max(1, trials), spread_seconds=spread
            ) * 1e9,
            "CUPS",
        ),
        (
            # Occupancy of the batch rounds behind that number (fraction
            # of offered batch slots filled, higher is better; populated
            # by device-sw-wave, so it reads None - recorded as a SKIP,
            # not a failure - when that entry didn't run or failed). A
            # collapse here means the DAG stopped exposing same-kind
            # parallelism to the tier even if GCUPS weather hides it.
            "device-sw-wave-occupancy",
            lambda: b.LAST_SW_WAVE_TIERS.get("batch_occupancy"),
            "fraction",
        ),
        ("device-uts", lambda: b.bench_device_uts()[0], "nodes/s"),
    ]


def _instrument_overhead(quick: bool, trials: int) -> dict:
    """Observability-tax guard: the same spawn-storm workload with the
    EventLog recorder off vs on (min-of-N each, interleaved start so a
    machine-load drift taxes both arms). The recorder (and by policy the
    whole flight-recorder layer) must never silently tax the hot path -
    the ratio is bounded by --instrument-tolerance."""
    import hclib_tpu as hc

    ntasks = 2000 if quick else 6000

    def run_once(instr: bool) -> int:
        rt = hc.Runtime(nworkers=2, instrument=instr)

        def body():
            with hc.finish():
                for _ in range(ntasks):
                    hc.async_(lambda: None)

        t0 = time.perf_counter_ns()
        rt.run(body)
        return time.perf_counter_ns() - t0

    n = max(2, trials)
    base, instr = [], []
    for _ in range(n):
        base.append(run_once(False))
        instr.append(run_once(True))
    return {
        "base_ns": min(base),
        "instrumented_ns": min(instr),
        "ratio": min(instr) / min(base),
        "tasks": ntasks,
    }


def _ingress_overhead(quick: bool, trials: int) -> dict:
    """Multi-tenant ingress tax guard (ISSUE 8), same-run arms: the same
    injected workload through (a) the plain single-firehose stream -
    tenancy OFF compiles zero new device words (no tctl input/echo, no
    WRR poll; ``tenants=False`` overrides any env spelling) and must
    stay bit-identical to the seed path - and (b) a 1-tenant enabled
    stream, whose results must be bit-identical to (a) and whose wall
    time is bounded by --ingress-tolerance (it pays the tctl copy + one
    lane's WRR bookkeeping per round)."""
    import numpy as np

    from hclib_tpu.device.descriptor import TaskGraphBuilder
    from hclib_tpu.device.inject import StreamingMegakernel
    from hclib_tpu.device.megakernel import Megakernel

    ntasks = 48 if quick else 160

    def mark(ctx):
        # Every task writes its OWN value slot: the cross-arm compare is
        # over the whole ivalues vector, so a dropped or misrouted ring
        # row shows up as a wrong slot even when an aggregate sum would
        # come out equal by coincidence.
        ctx.set_value(ctx.arg(1), ctx.arg(0))

    def mk():
        return Megakernel(
            kernels=[("mark", mark)], capacity=max(256, ntasks + 8),
            num_values=ntasks + 8, succ_capacity=8, interpret=True,
        )

    def run_once(tenants) -> Tuple[int, bytes]:
        sm = StreamingMegakernel(mk(), ring_capacity=max(256, ntasks),
                                 tenants=tenants)
        if tenants is False:
            assert sm.tenants is None  # zero new device words: no tctl ABI
            for i in range(ntasks):
                sm.inject(0, args=[i + 1, i + 1])
        else:
            for i in range(ntasks):
                assert sm.submit("t0", 0, args=[i + 1, i + 1])
        sm.close()
        b = TaskGraphBuilder()
        b.add(0, args=[0, 0])
        t0 = time.perf_counter_ns()
        iv, info = sm.run_stream(b)
        dt = time.perf_counter_ns() - t0
        iv = np.asarray(iv)
        expect = np.zeros(ntasks + 8, iv.dtype)
        expect[1 : ntasks + 1] = np.arange(1, ntasks + 1)
        if not np.array_equal(iv, expect):
            raise AssertionError(
                f"ingress-overhead: arm (tenants={tenants!r}) dropped "
                f"or misrouted rows: {np.flatnonzero(iv != expect)}"
            )
        if tenants is False:
            # Tenancy off = seed ABI: no tenant echo anywhere in the
            # run's surfaces.
            assert "tenants" not in info and "tenants" not in (
                sm.stats_dict()
            )
        else:
            assert info["tenants"]["t0"]["completed"] == ntasks
        return dt, iv.tobytes()

    run_once(False)  # warm both jits outside the timed arms
    run_once(1)
    n = max(2, trials)
    base, ten, values = [], [], set()
    for _ in range(n):
        dt, v = run_once(False)
        base.append(dt)
        values.add(v)
        dt, v = run_once(1)
        ten.append(dt)
        values.add(v)
    if len(values) != 1:
        raise AssertionError(
            "ingress-overhead: tenancy-on ivalues diverged from the "
            f"plain stream ({len(values)} distinct result vectors)"
        )
    return {
        "base_ns": min(base),
        "tenant_ns": min(ten),
        "ratio": min(ten) / min(base),
        "tasks": ntasks,
        "bit_identical": True,
    }


def _checkpoint_overhead(quick: bool, trials: int) -> dict:
    """Checkpoint-tax guard (ISSUE 5): the same seeded UTS megakernel
    traversal with checkpoint support off vs compiled-in-but-never-
    quiesced (min-of-N each, interleaved arms like the instrument guard).
    The quiesce word must never silently tax a run that doesn't
    checkpoint; the enabled-but-idle path is bounded by
    --checkpoint-tolerance (it pays one qctl DMA per scheduling round).
    Also measures the quiesce LAG - how far past the requested round the
    boundary landed, in tasks - which must stay within one batch width
    (the same overshoot contract fuel has).

    The third arm prices ``quiesce_stride`` (ISSUE 6): polling the qctl
    word every Nth round instead of every round must land at or below
    the per-round arm's cost (it does strictly fewer DMAs), and its
    quiesce lag may grow by at most stride-1 rounds' worth of tasks -
    both bounded here so the knob can never silently regress either
    side of its trade."""
    from hclib_tpu.device.descriptor import TaskGraphBuilder
    from hclib_tpu.device.workloads import (
        UTS_NODE, make_uts_megakernel,
    )

    kw = dict(interpret=True, max_depth=6 if quick else 8)
    STRIDE = 4

    def builder():
        b = TaskGraphBuilder()
        b.add(UTS_NODE, args=[1, 0])
        return b

    mk_off = make_uts_megakernel(**kw)
    mk_on = make_uts_megakernel(checkpoint=True, **kw)
    mk_strided = make_uts_megakernel(
        checkpoint=True, quiesce_stride=STRIDE, **kw
    )
    nodes = mk_off.run(builder())[2]["executed"]  # also warms the jit
    mk_on.run(builder())  # warm the enabled build too
    mk_strided.run(builder())
    n = max(2, trials)
    base, on, strided = [], [], []
    for _ in range(n):
        t0 = time.perf_counter_ns()
        mk_off.run(builder())
        base.append(time.perf_counter_ns() - t0)
        t0 = time.perf_counter_ns()
        mk_on.run(builder())
        on.append(time.perf_counter_ns() - t0)
        t0 = time.perf_counter_ns()
        mk_strided.run(builder())
        strided.append(time.perf_counter_ns() - t0)
    # Quiesce latency: request the cut at half the tree; the observed
    # boundary must not drift (lag in tasks) and the quiesced entry must
    # not cost more than an uninterrupted run (it does strictly less).
    at = nodes // 2
    t0 = time.perf_counter_ns()
    _, _, info_q = mk_on.run(builder(), quiesce=at)
    quiesce_ns = time.perf_counter_ns() - t0
    lag = info_q["quiesce"]["executed_at"] - at
    _, _, info_qs = mk_strided.run(builder(), quiesce=at)
    lag_s = info_qs["quiesce"]["executed_at"] - at
    return {
        "base_ns": min(base),
        "checkpoint_ns": min(on),
        "ratio": min(on) / min(base),
        "stride": STRIDE,
        "stride_ns": min(strided),
        "stride_ratio": min(strided) / min(base),
        "nodes": nodes,
        "quiesce_entry_ns": quiesce_ns,
        "quiesce_lag_tasks": int(lag),
        "stride_lag_tasks": int(lag_s),
    }


def _forasync_tile(quick: bool, trials: int) -> dict:
    """forasync-tile guard (ISSUE 9), same-run arms: the SAME map loop
    through (a) host forasync - per-tile scalar-spawn through the host
    scheduler, the reference's execution model - and (b) the device tile
    tier (batch lanes + operand prefetch). Results must be bit-identical
    and the tile tier must hold a tasks/s floor vs the scalar-spawn arm
    (--forasync-floor, default 2x; measured 8-30x on CPU interpret). A
    third arm - scalar DEVICE dispatch - is recorded informationally:
    interpret-mode walls do not show the dispatch win (the interpreter
    serializes the DMAs the lanes overlap on hardware), so the device-
    internal ratio is reported, not bounded. The lane-occupancy bound
    (--forasync-occupancy) fails if the static tile set stops filling
    its batches - the tier silently degrading to near-scalar firing."""
    import numpy as np

    import hclib_tpu as hc
    from hclib_tpu.device.forasync_tier import (
        make_forasync_megakernel, run_forasync_device,
    )
    from hclib_tpu.device.workloads import (
        map_body, map_data, map_loop, map_reference,
    )

    # Quick stays large enough that the host arm's per-index python cost
    # dominates its scheduler noise: the ratio is ~4-8x unloaded and must
    # clear the 2x floor even on a loaded CI box.
    T = 32 if quick else 64
    tk, bounds, tile = map_loop(T)
    vin, vout = map_data(T)
    ref = map_reference(vin)
    mk_tier = make_forasync_megakernel(tk, width=8, interpret=True)
    mk_scalar = make_forasync_megakernel(tk, width=0, interpret=True)

    def run_host() -> np.ndarray:
        vh = vout.copy()

        def main():
            hc.forasync(map_body(vin, vh), bounds, tile=tile)

        hc.launch(main, nworkers=4)
        return vh

    def run_dev(mk, width) -> np.ndarray:
        d, info = run_forasync_device(
            tk, bounds, tile, {"vin": vin, "vout": vout.copy()},
            width=width, mk=mk,
        )
        if width:
            run_dev.tiers = info["tiers"]
        return np.asarray(d["vout"])

    results = {run_host().tobytes(), run_dev(mk_tier, 8).tobytes(),
               run_dev(mk_scalar, 0).tobytes(), ref.tobytes()}  # + warm
    if len(results) != 1:
        raise AssertionError(
            "forasync-tile: arms diverged (host/scalar/tile results not "
            "bit-identical)"
        )
    n = max(2, trials)
    host, tier, scalar = [], [], []
    for _ in range(n):
        t0 = time.perf_counter_ns()
        run_host()
        host.append(time.perf_counter_ns() - t0)
        t0 = time.perf_counter_ns()
        run_dev(mk_tier, 8)
        tier.append(time.perf_counter_ns() - t0)
        t0 = time.perf_counter_ns()
        run_dev(mk_scalar, 0)
        scalar.append(time.perf_counter_ns() - t0)
    occ = run_dev.tiers["batch_occupancy"]
    return {
        "tiles": T,
        "host_ns": min(host),
        "tile_tier_ns": min(tier),
        "device_scalar_ns": min(scalar),
        "tier_vs_host": min(host) / min(tier),
        "tier_vs_device_scalar": min(scalar) / min(tier),
        "occupancy": occ,
        "prefetch_hits": run_dev.tiers["prefetch_hits"],
        "bit_identical": True,
    }


def _frontier_batch(quick: bool, trials: int) -> dict:
    """frontier-batch guard (ISSUE 10), same-run arms: the SAME seeded
    R-MAT BFS through (a) scalar dispatch - one EXPAND per lax.switch
    round, the bit-identity reference - and (b) the batched frontier
    tier (edge-slab prefetch + the age-triggered firing policy, on at
    its frontier default lane_max_age = 4*width). Distances must be
    bit-identical to each other AND the host reference, the batched arm
    must hold a TEPS floor against the scalar arm measured in the same
    run (--frontier-floor; interpret mode serializes the edge-slab DMAs
    the lanes overlap on hardware, so the measured ratio is ~0.5x and
    the floor prices 'never collapses'), and the batched arm's
    lane_partial_age must stay under --frontier-age-ceiling with its
    device-side max_starved_age bounded by the knob - the proof that
    the new firing policy keeps the lanes from starving while the
    frontier spawner keeps the ring hot."""
    import numpy as np

    from hclib_tpu.device.frontier import (
        Graph, _KINDS, host_bfs, make_frontier_megakernel, run_frontier,
    )
    from hclib_tpu.device.workloads import rmat_edges

    scale = 6 if quick else 8
    width = 8
    n, src, dst, w = rmat_edges(scale, efactor=8, seed=7)
    g = Graph(n, src, dst, w)
    cap = 768 if quick else 1024
    # The TIMED batched arm is untraced (tracing taxes only the batched
    # side: in-kernel TR_* emission + host ring decode - an unfair
    # thumb on the ratio); one separate traced run below supplies the
    # lane_partial_age / age-gauge readings.
    mk_b = make_frontier_megakernel(
        _KINDS["bfs"](), g, width=width, capacity=cap, interpret=True,
    )
    lane_max_age = mk_b.lane_max_age
    mk_s = make_frontier_megakernel(
        _KINDS["bfs"](), g, width=0, capacity=cap, interpret=True,
    )
    mk_tr = make_frontier_megakernel(
        _KINDS["bfs"](), g, width=width, capacity=cap, interpret=True,
        trace=4096,
    )
    ref = host_bfs(g, 0)

    def run_arm(mk):
        d, info = run_frontier("bfs", g, 0, mk=mk, interpret=True)
        run_arm.info = info
        return d

    d_b = run_arm(mk_b)
    d_tr = run_arm(mk_tr)
    info_b = run_arm.info  # the traced run's gauges
    d_s = run_arm(mk_s)
    if not np.array_equal(d_tr, ref):
        raise AssertionError(
            "frontier-batch: traced arm diverged from the host reference"
        )
    if not (np.array_equal(d_b, ref) and np.array_equal(d_s, ref)):
        raise AssertionError(
            "frontier-batch: arms diverged (scalar/batched/host BFS "
            "distances not bit-identical)"
        )
    n_tr = max(2, trials)
    b_ns, s_ns = [], []
    for _ in range(n_tr):
        t0 = time.perf_counter_ns()
        run_arm(mk_b)
        b_ns.append(time.perf_counter_ns() - t0)
        edges_b = run_arm.info["edges"]
        t0 = time.perf_counter_ns()
        run_arm(mk_s)
        s_ns.append(time.perf_counter_ns() - t0)
        edges_s = run_arm.info["edges"]
    teps_b = edges_b / (min(b_ns) / 1e9)
    teps_s = edges_s / (min(s_ns) / 1e9)
    t = info_b["tiers"]
    if t["max_starved_age"] > lane_max_age:
        raise AssertionError(
            f"frontier-batch: device starved age {t['max_starved_age']} "
            f"exceeds lane_max_age {lane_max_age} - the age trigger "
            "stopped bounding starvation"
        )
    return {
        "edges": g.m,
        "batched_teps": round(teps_b),
        "scalar_teps": round(teps_s),
        "batched_vs_scalar": teps_b / teps_s,
        "occupancy": t["batch_occupancy"],
        "age_fires": t["age_fires"],
        "max_starved_age": t["max_starved_age"],
        "lane_max_age": lane_max_age,
        "lane_partial_age": t.get("lane_partial_age", 0),
        "bit_identical": True,
    }


def _priority_tier(quick: bool, trials: int) -> dict:
    """priority-tier guard (ISSUE 15), same-run arms on the SAME seeded
    weighted R-MAT: (a) the unordered batched frontier (PR 10's
    label-correcting SSSP - the bit-identity reference), (b) the
    priority-bucketed build (TRUE delta-stepping: bucket = dist//delta,
    lowest-nonempty-first). Distances must be bit-identical to each
    other AND the host Dijkstra, and the bucketed arm must do at most
    --priority-expand-ceiling (0.8x) of the unordered arm's executed
    EXPANDs - ordered retirement is claimed as *asymptotically less
    work*, so the guard prices the work count, which interpret mode
    measures exactly (no DMA-overlap weather). A PageRank pair on the
    same graph additionally bounds the bucketed arm's peak live row
    set (info['allocated'] - the bump allocator's high-water mark) at
    --priority-live-ceiling of the unordered arm's: the bounded-
    frontier fix for the PR 10 breadth blowup."""
    import numpy as np

    from hclib_tpu.device.frontier import (
        Graph, _KINDS, host_pagerank_push, host_sssp,
        make_frontier_megakernel, run_frontier,
    )
    from hclib_tpu.device.workloads import rmat_edges

    scale = 6 if quick else 8
    width = 8
    buckets = 8
    n, src, dst, w = rmat_edges(scale, efactor=8, seed=7)
    g = Graph(n, src, dst, w)
    cap = 768 if quick else 1024
    mk_u = make_frontier_megakernel(
        _KINDS["sssp"](), g, width=width, capacity=cap, interpret=True,
    )
    mk_b = make_frontier_megakernel(
        _KINDS["sssp"](), g, width=width, capacity=cap, interpret=True,
        priority_buckets=buckets,
    )
    ref = host_sssp(g, 0)
    d_u, info_u = run_frontier("sssp", g, 0, mk=mk_u, interpret=True)
    d_b, info_b = run_frontier("sssp", g, 0, mk=mk_b, interpret=True)
    if not (np.array_equal(d_u, ref) and np.array_equal(d_b, ref)):
        raise AssertionError(
            "priority-tier: SSSP arms diverged (unordered/delta-stepping"
            "/host Dijkstra distances not bit-identical)"
        )
    # Work-count arms (deterministic - one run each IS the measurement;
    # wall time also logged for the record).
    n_tr = max(2, trials)
    u_ns, b_ns = [], []
    for _ in range(n_tr):
        t0 = time.perf_counter_ns()
        run_frontier("sssp", g, 0, mk=mk_u, interpret=True)
        u_ns.append(time.perf_counter_ns() - t0)
        t0 = time.perf_counter_ns()
        run_frontier("sssp", g, 0, mk=mk_b, interpret=True)
        b_ns.append(time.perf_counter_ns() - t0)
    teps_u = info_u["edges"] / (min(u_ns) / 1e9)
    teps_b = info_b["edges"] / (min(b_ns) / 1e9)
    # PageRank live-set arms: deep mass cascade (m0 = 1<<14) where the
    # FIFO breadth-first push balloons the live set.
    m0, reps = 1 << 14, 64
    pscale = 5 if quick else 6
    n2, s2, d2, w2 = rmat_edges(pscale, efactor=8, seed=7)
    g2 = Graph(n2, s2, d2, w2)
    twin, _ = host_pagerank_push(g2, m0=m0, reps=reps)
    r_u, pr_u = run_frontier(
        "pagerank", g2, width=width, m0=m0, reps=reps, interpret=True,
        capacity=4096,
    )
    r_b, pr_b = run_frontier(
        "pagerank", g2, width=width, m0=m0, reps=reps, interpret=True,
        capacity=4096, priority_buckets=buckets,
    )
    if not (np.array_equal(np.asarray(r_u), twin)
            and np.array_equal(np.asarray(r_b), twin)):
        raise AssertionError(
            "priority-tier: PageRank arms diverged from the integer twin"
        )
    return {
        "edges": g.m,
        "expanded_unordered": info_u["executed"],
        "expanded_bucketed": info_b["executed"],
        "expand_ratio": info_b["executed"] / info_u["executed"],
        "unordered_teps": round(teps_u),
        "bucketed_teps": round(teps_b),
        "teps_ratio": teps_b / teps_u,
        "bucket_inversions": info_b["tiers"]["bucket_inversions"],
        "pr_live_unordered": pr_u["allocated"],
        "pr_live_bucketed": pr_b["allocated"],
        "pr_live_ratio": pr_b["allocated"] / pr_u["allocated"],
        "bit_identical": True,
    }


def _program_cache(quick: bool, trials: int) -> dict:
    """Program-cache guard (ISSUE 18), same-run arms:

    (a) cold-vs-warm: two content-identical megakernel instances; the
        second instance's FIRST run must ride the process-wide program
        cache (hit asserted) and beat the cold build by
        --progcache-floor (the whole point of the cache is killing the
        trace/lower/compile tax);
    (b) cache-off bit identity: a fresh instance with
        HCLIB_TPU_PROGRAM_CACHE=0 must produce the cold arm's exact
        result bytes with the registry counters untouched;
    (c) eviction correctness: at cap=1 a second distinct program evicts
        the first; rebuilding the first misses (counted) and is
        bit-identical to its original run.
    """
    import os as _os

    import numpy as np

    from hclib_tpu.device.descriptor import TaskGraphBuilder
    from hclib_tpu.device.megakernel import Megakernel
    from hclib_tpu.runtime import progcache

    ntasks = 16 if quick else 48

    def mark(ctx):
        ctx.set_value(ctx.arg(1), ctx.arg(0))

    def mark2(ctx):
        ctx.set_value(ctx.arg(1), ctx.arg(0) + 1)

    def mk(body=mark):
        return Megakernel(
            kernels=[("mark", body)], capacity=max(64, ntasks + 8),
            num_values=ntasks + 8, succ_capacity=8, interpret=True,
        )

    def run_once(m) -> Tuple[int, bytes, dict]:
        b = TaskGraphBuilder()
        for i in range(ntasks):
            b.add(0, args=[i + 1, i + 1])
        t0 = time.perf_counter_ns()
        iv, _, info = m.run(b)
        dt = time.perf_counter_ns() - t0
        return dt, np.asarray(iv).tobytes(), info["program_cache"]

    saved = {
        k: _os.environ.pop(k, None)
        for k in ("HCLIB_TPU_PROGRAM_CACHE", "HCLIB_TPU_PROGRAM_CACHE_CAP")
    }
    try:
        progcache.reset()
        # (a) cold vs warm: first runs of fresh identical instances.
        cold_ns, cold_bytes, pc = run_once(mk())
        if pc["hit"]:
            raise AssertionError("program-cache: cold arm reported a hit")
        warm = []
        for _ in range(max(2, trials)):
            warm_ns, warm_bytes, pc = run_once(mk())
            if not pc["hit"]:
                raise AssertionError(
                    "program-cache: content-identical rebuild missed"
                )
            if warm_bytes != cold_bytes:
                raise AssertionError(
                    "program-cache: warm result bytes diverged"
                )
            warm.append(warm_ns)
        warm_ns = min(warm)
        # (b) cache off: bit-identical, counters untouched.
        before = progcache.cache_stats()
        _os.environ["HCLIB_TPU_PROGRAM_CACHE"] = "0"
        off_ns, off_bytes, pc = run_once(mk())
        del _os.environ["HCLIB_TPU_PROGRAM_CACHE"]
        if pc["hit"] or off_bytes != cold_bytes:
            raise AssertionError(
                "program-cache: cache-off arm hit or diverged"
            )
        if progcache.cache_stats() != before:
            raise AssertionError(
                "program-cache: cache-off arm moved the counters"
            )
        # (c) eviction correctness at cap=1.
        _os.environ["HCLIB_TPU_PROGRAM_CACHE_CAP"] = "1"
        progcache.reset()
        _, first_bytes, _ = run_once(mk())
        run_once(mk(mark2))  # distinct program: evicts the first
        if progcache.cache_stats()["evictions"] < 1:
            raise AssertionError("program-cache: cap=1 never evicted")
        _, again_bytes, pc = run_once(mk())
        if pc["hit"]:
            raise AssertionError(
                "program-cache: evicted program reported a hit"
            )
        if again_bytes != first_bytes:
            raise AssertionError(
                "program-cache: post-eviction rebuild diverged"
            )
    finally:
        for k, v in saved.items():
            if v is None:
                _os.environ.pop(k, None)
            else:
                _os.environ[k] = v
        progcache.reset()
    return {
        "cold_ns": cold_ns,
        "warm_ns": warm_ns,
        "off_ns": off_ns,
        "speedup": cold_ns / warm_ns,
        "tasks": ntasks,
        "bit_identical": True,
        "eviction_correct": True,
    }


def _telemetry_overhead(quick: bool, trials: int) -> dict:
    """Telemetry-tax guard (ISSUE 19), same-run arms: the same
    submitted workload through (a) a telemetry-OFF egress stream and
    (b) the telemetry-ON stream. Off compiles ZERO new device words -
    asserted by lowered-text byte identity: a build forced off while
    the telemetry env knob is SET must lower to the exact text the
    env-free default build lowers to (and the enabled build must
    differ - the tele/tlat words exist only on-path). The on arm's
    result vector must be bit-identical to (a), its on-device
    histogram must account for every submitted retirement exactly,
    and its wall is bounded by --telemetry-tolerance (it pays the
    tele/tlat echo plus the branch-free log2 fold per retire)."""
    import os as _os

    import numpy as np

    from hclib_tpu.device.descriptor import RING_ROW, TaskGraphBuilder
    from hclib_tpu.device.egress import EGR_WORDS, EgressSpec
    from hclib_tpu.device.inject import StreamingMegakernel
    from hclib_tpu.device.megakernel import Megakernel
    from hclib_tpu.device.telemetry import (
        LAT_BUCKETS, LAT_WORDS, TelemetryBlock,
    )
    from hclib_tpu.device.tenants import TenantSpec, TenantTable

    ntasks = 48 if quick else 160
    cap = max(256, ntasks + 8)

    def mark(ctx):
        ctx.set_value(ctx.arg(1), ctx.arg(0))

    def mk():
        return Megakernel(
            kernels=[("mark", mark)], capacity=cap,
            num_values=ntasks + 8, succ_capacity=8, interpret=True,
        )

    def sm_new(tel):
        table = TenantTable(
            [TenantSpec("t0")], cap, clock=lambda: 0.0,
            egress=EgressSpec(depth=cap),
        )
        return StreamingMegakernel(mk(), ring_capacity=cap,
                                   tenants=table, telemetry=tel)

    def lower_text(sm) -> str:
        m = sm.mk
        b = TaskGraphBuilder()
        b.add(0, args=[0, 0])
        tasks, succ, ready, counts = b.finalize(
            capacity=m.capacity, succ_capacity=m.succ_capacity
        )
        args = [
            tasks, succ, ready, counts,
            np.zeros(m.num_values, np.int32),
            np.zeros((sm.ring_capacity, RING_ROW), np.int32),
            np.zeros(8, np.int32),
            np.zeros((len(sm.tenants), 8), np.int32),
            np.zeros((sm._egress.depth, EGR_WORDS), np.int32),
            np.zeros((sm._egress.depth, EGR_WORDS), np.int32),
            np.zeros(8, np.int32),
            np.zeros(m.capacity, np.int32),
        ]
        if sm.telemetry:
            args += [
                np.zeros((1 + len(sm.tenants), LAT_BUCKETS), np.int32),
                np.zeros((m.capacity, LAT_WORDS), np.int32),
            ]
        return sm._build(1 << 10, 64).lower(*args).as_text()

    # Off-path identity first, outside the timed arms: env knob SET
    # but constructor-forced off must be byte-identical to env-free.
    saved_env = _os.environ.pop("HCLIB_TPU_TELEMETRY", None)
    try:
        base_text = lower_text(sm_new(None))    # env-free default: off
        _os.environ["HCLIB_TPU_TELEMETRY"] = "1"
        forced_off = lower_text(sm_new(False))
        env_on = lower_text(sm_new(None))
    finally:
        if saved_env is None:
            _os.environ.pop("HCLIB_TPU_TELEMETRY", None)
        else:
            _os.environ["HCLIB_TPU_TELEMETRY"] = saved_env
    if forced_off != base_text:
        raise AssertionError(
            "telemetry-overhead: telemetry=False with the env knob set "
            "lowered DIFFERENT text than the env-free build - the off "
            "path is compiling telemetry words"
        )
    if env_on == base_text:
        raise AssertionError(
            "telemetry-overhead: the enabled build lowered the SAME "
            "text as the off build - the tele/tlat words never compiled"
        )

    def run_once(tel) -> Tuple[int, bytes]:
        sm = sm_new(tel)
        futs = []
        for i in range(ntasks):
            h = sm.submit("t0", 0, args=[i + 1, i + 1])
            assert h
            futs.append(h.future)
        sm.close()
        b = TaskGraphBuilder()
        b.add(0, args=[0, 0])
        t0 = time.perf_counter_ns()
        iv, info = sm.run_stream(b)
        dt = time.perf_counter_ns() - t0
        iv = np.asarray(iv)
        expect = np.zeros(ntasks + 8, iv.dtype)
        expect[1 : ntasks + 1] = np.arange(1, ntasks + 1)
        if not np.array_equal(iv, expect):
            raise AssertionError(
                f"telemetry-overhead: arm (telemetry={tel!r}) dropped "
                f"or misrouted rows: {np.flatnonzero(iv != expect)}"
            )
        bad = [f.state for f in futs if f.state != "RESULT"]
        if bad:
            raise AssertionError(
                f"telemetry-overhead: {len(bad)} futures unresolved "
                f"(telemetry={tel!r}): {sorted(set(bad))}"
            )
        if tel:
            snap = sm.telemetry_snapshot()
            total = TelemetryBlock(snap["tele"]).total() if snap else -1
            if total != ntasks:
                raise AssertionError(
                    "telemetry-overhead: on-device histogram counted "
                    f"{total} retirements, expected {ntasks}"
                )
        else:
            # Telemetry off = no new surfaces anywhere in the run.
            assert "telemetry" not in info
            assert sm.telemetry_snapshot() is None
        return dt, iv.tobytes()

    run_once(False)  # warm both jits outside the timed arms
    run_once(True)
    n = max(2, trials)
    base, tele, values = [], [], set()
    for _ in range(n):
        dt, v = run_once(False)
        base.append(dt)
        values.add(v)
        dt, v = run_once(True)
        tele.append(dt)
        values.add(v)
    if len(values) != 1:
        raise AssertionError(
            "telemetry-overhead: telemetry-on ivalues diverged from "
            f"the off stream ({len(values)} distinct result vectors)"
        )
    return {
        "base_ns": min(base),
        "telemetry_ns": min(tele),
        "ratio": min(tele) / min(base),
        "tasks": ntasks,
        "bit_identical": True,
        "off_text_identical": True,
    }


def _dyngraph_incremental(quick: bool, trials: int) -> dict:
    """Dynamic-graph incremental-recompute guard (ISSUE 20): phase 1
    runs the seeded SSSP to its fixpoint on the STATIC graph; phase 2
    feeds ONLY the update stream into the same megakernel, reusing
    phase 1's converged labels as the initial values - so the only
    EXPANDs it executes are the re-relaxations the splices actually
    caused. That incremental EXPAND count must stay a small fraction
    of the from-scratch run on the mutated graph, measured in the same
    process; both fixpoints are asserted bit-identical to the
    ``host_dyngraph`` mutated-graph reference. Work counts are exact
    (no timed arms), so ``trials`` is unused."""
    import numpy as np

    from hclib_tpu.device.descriptor import TaskGraphBuilder
    from hclib_tpu.device.dyngraph import (
        DG_UPDATE, INF, DynGraph, _bind_updates, _seed_builders,
        fk_data, host_dyngraph, make_dyngraph_megakernel, run_dyngraph,
    )
    from hclib_tpu.device.workloads import rmat_edges

    scale = 5 if quick else 7
    n, src_e, dst_e, w_e = rmat_edges(scale, efactor=8, seed=7)
    capacity = 512 if quick else 1024
    rng = np.random.default_rng(13)
    n_ups = 6 if quick else 16
    ups = [
        (int(u), int(v), int(w))
        for u, v, w in zip(
            rng.integers(0, n, n_ups),
            rng.integers(0, n, n_ups),
            rng.integers(1, 8, n_ups),
        )
    ]
    src = 0
    g = DynGraph(
        n, src_e, dst_e, w_e, spare_blocks=2, upd_cap=max(16, n_ups),
    )
    mk = make_dyngraph_megakernel(
        "sssp", g, width=8, capacity=capacity, interpret=True,
    )
    _bind_updates(mk, g)  # empty stream: phase 1 is the static run
    st = g.st_base
    iv0 = g.preset_values(mk.num_values, INF)
    iv0[st + src] = 0
    builders, _ = _seed_builders(
        g, "sssp", src, 1 << 14, 64, (), mk.num_values, 1,
        lambda i, tot: 0,
    )
    iv1, _, info1 = mk.run(
        builders[0], data=dict(fk_data(g, mk)), ivalues=iv0,
        fuel=1 << 22,
    )

    # Phase 2: the update stream ALONE, seeded with the converged
    # labels. Fresh data buffers (pristine spare rows) are correct -
    # phase 1 ran no splices, so its adjacency never mutated.
    for u, v, w in ups:
        g.add_update(u, v, w)
    _bind_updates(mk, g)
    b2 = TaskGraphBuilder()
    b2.reserve_values(g.num_value_slots)
    for uid, (u, v, w) in enumerate(g.updates):
        b2.add(DG_UPDATE, args=[u, v, w, uid])
    iv2, _, info2 = mk.run(
        b2, data=dict(fk_data(g, mk)), ivalues=np.asarray(iv1),
        fuel=1 << 22,
    )
    rows = np.asarray(iv2, np.int64)
    res_incr = rows[st : st + n].astype(np.int64)
    flags = rows[g.flag_base : g.flag_base + g.upd_cap]
    applied = int((flags != 0).sum())
    ref = np.asarray(host_dyngraph("sssp", g), np.int64)
    if not np.array_equal(res_incr, ref):
        raise AssertionError(
            "dyngraph-incremental: the update-only rerun's fixpoint "
            "diverged from the mutated-graph reference"
        )

    # From-scratch arm: the same storm raced with the traversal on a
    # fresh graph - everything recomputes. The prebuilt megakernel is
    # reusable (identical (n, kind, st_base) layout stamp).
    g2 = DynGraph(
        n, src_e, dst_e, w_e, spare_blocks=2, upd_cap=max(16, n_ups),
    )
    res_full, info_full = run_dyngraph(
        "sssp", g2, src, updates=ups, capacity=capacity,
        interpret=True, mk=mk,
    )
    if not np.array_equal(np.asarray(res_full, np.int64), ref):
        raise AssertionError(
            "dyngraph-incremental: the from-scratch arm diverged from "
            "the mutated-graph reference"
        )
    incr_expands = int(info2["executed"]) - len(ups)
    full_expands = int(info_full["executed"]) - len(ups)
    return {
        "incr_expands": incr_expands,
        "full_expands": full_expands,
        "expand_ratio": incr_expands / max(full_expands, 1),
        "static_expands": int(info1["executed"]),
        "updates": len(ups),
        "updates_applied": applied,
        "bit_identical": True,
    }


def _latest_log(log_dir: str, quick: bool) -> Dict[str, dict]:
    """Most recent log of the SAME size class (quick vs full): comparing
    tiny smoke inputs against full-size baselines is meaningless in either
    direction."""
    if not os.path.isdir(log_dir):
        return {}
    for name in sorted(
        (f for f in os.listdir(log_dir) if f.endswith(".json")),
        reverse=True,
    ):
        with open(os.path.join(log_dir, name)) as f:
            try:
                log = json.load(f)
            except ValueError:
                continue
        # Skip non-harness JSONs sharing the directory (per-workload
        # info side files, clock logs): only real logs carry "apps".
        if not isinstance(log, dict) or "apps" not in log:
            continue
        if bool(log.get("quick")) == quick:
            return log.get("apps", {})
    return {}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true", help="tiny inputs (smoke)")
    ap.add_argument("--device", action="store_true",
                    help="also run the TPU device suite (rates)")
    ap.add_argument("--multichip", action="store_true",
                    help="also run the 8-device mesh acceptance workloads")
    ap.add_argument("--trials", type=int, default=3)
    ap.add_argument("--tolerance", type=float, default=0.25,
                    help="allowed fractional slowdown vs previous log")
    ap.add_argument("--instrument-tolerance", type=float, default=3.0,
                    help="max instrument=True slowdown ratio (the "
                    "flight-recorder/EventLog overhead guard)")
    ap.add_argument("--ingress-tolerance", type=float, default=3.0,
                    help="max enabled(1-tenant)/plain-stream wall ratio "
                         "for the ingress-overhead guard (interpret-mode "
                         "walls swing; results must be bit-identical "
                         "regardless)")
    ap.add_argument("--checkpoint-tolerance", type=float, default=3.0,
                    help="max checkpoint-enabled-but-idle slowdown ratio "
                    "(the quiesce-word overhead guard; the off path is "
                    "compiled out entirely)")
    ap.add_argument("--mesh-batch-floor", type=float, default=0.5,
                    help="mesh-batch-dispatch guard: minimum batched "
                    "forest-steal tasks/s as a fraction of the scalar-"
                    "mesh arm measured in the same run (interpret-mode "
                    "wall time is weather-prone, so the floor price is "
                    "'never collapses', not 'always faster')")
    ap.add_argument("--mesh-batch-occupancy", type=float, default=0.5,
                    help="mesh-batch-dispatch guard: minimum per-device "
                    "batch-slot occupancy (from tstats) on devices that "
                    "fired batch rounds - a collapse means the mesh "
                    "stopped exposing same-kind width to the tier")
    ap.add_argument("--forasync-floor", type=float, default=2.0,
                    help="forasync-tile guard: minimum tile-tier tasks/s "
                    "as a multiple of the host scalar-spawn arm measured "
                    "in the same run (measured 8-30x; 2x is the collapse "
                    "floor)")
    ap.add_argument("--forasync-occupancy", type=float, default=0.8,
                    help="forasync-tile guard: minimum batch-lane "
                    "occupancy of the static tile set (near 1.0 by "
                    "construction; a drop means the tier stopped "
                    "batching the loop)")
    ap.add_argument("--frontier-floor", type=float, default=0.25,
                    help="frontier-batch guard: minimum batched-frontier "
                    "TEPS as a fraction of the scalar-dispatch arm "
                    "measured in the same run. Interpret mode SERIALIZES "
                    "the edge-slab DMAs the lanes overlap on hardware "
                    "(the PR 9 forasync finding), so the batched arm "
                    "measures ~0.5x here while the dispatch win is a "
                    "hardware number - the floor prices 'never "
                    "collapses', not 'faster under the interpreter'")
    ap.add_argument("--frontier-age-ceiling", type=float, default=8,
                    help="frontier-batch guard: maximum lane_partial_age "
                    "(consecutive-partial-fire streak, rounds) on the "
                    "batched BFS arm - the age-triggered firing policy "
                    "keeps it near zero; a climb means lanes are "
                    "starving again")
    ap.add_argument("--priority-expand-ceiling", type=float, default=0.8,
                    help="priority-tier guard: maximum executed-EXPAND "
                         "ratio of delta-stepping SSSP over the "
                         "unordered label-correcting arm on the same "
                         "seeded weighted R-MAT (the ISSUE 15 "
                         "ordered-work dividend; measured ~0.7x at "
                         "scale 8, delta = w_max/8)")
    ap.add_argument("--priority-live-ceiling", type=float, default=0.8,
                    help="priority-tier guard: maximum peak-live-row "
                         "ratio of bounded-frontier PageRank over the "
                         "FIFO breadth-first arm (measured ~0.4-0.6x "
                         "at m0=1<<14 - the live-set blowup fix)")
    ap.add_argument("--progcache-floor", type=float, default=3.0,
                    help="program-cache guard: minimum cold/warm "
                         "first-build speedup for a content-identical "
                         "second instance (the compile-tax kill)")
    ap.add_argument("--telemetry-tolerance", type=float, default=1.3,
                    help="max telemetry-on/off wall ratio for the "
                         "telemetry-overhead guard (the tele/tlat echo "
                         "plus per-retire histogram fold; results must "
                         "be bit-identical and the off path must lower "
                         "byte-identical text regardless)")
    ap.add_argument("--dyngraph-expand-ceiling", type=float, default=0.5,
                    help="dyngraph-incremental guard: maximum "
                         "incremental-EXPAND count of the update-only "
                         "rerun as a fraction of the from-scratch run "
                         "on the mutated graph (the ISSUE 20 "
                         "incremental-recompute dividend; the rerun "
                         "re-expands only what the splices actually "
                         "invalidated)")
    ap.add_argument("--log-dir", default=os.path.join(
        os.path.dirname(__file__), "..", "perf-logs"))
    ap.add_argument("--apps", default="", help="comma-separated subset")
    args = ap.parse_args(argv)

    if args.multichip:
        # Must land before jax initializes: the mesh workloads need the
        # CPU backend with 8 virtual devices.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        os.environ.setdefault(
            "JAX_COMPILATION_CACHE_DIR",
            os.path.join(os.path.dirname(__file__), "..", ".jax_cache"),
        )
        os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")

    wanted = {a for a in args.apps.split(",") if a}
    prev = _latest_log(args.log_dir, args.quick)
    results: Dict[str, dict] = {}
    failures: List[str] = []

    for name, fn in _suite(args.quick):
        if wanted and name not in wanted:
            continue
        times_ns = []
        for _ in range(args.trials):
            t0 = time.perf_counter_ns()
            fn()  # each run() self-checks its result
            times_ns.append(time.perf_counter_ns() - t0)
        mean = sum(times_ns) / len(times_ns)
        results[name] = {
            "mean_ns": mean,
            "min_ns": min(times_ns),
            "trials": len(times_ns),
        }
        line = f"{name:15s} mean {mean / 1e6:10.2f} ms  min {min(times_ns) / 1e6:10.2f} ms"
        if name in prev:
            ratio = mean / prev[name]["mean_ns"]
            line += f"  vs prev {ratio:5.2f}x"
            if ratio > 1 + args.tolerance:
                failures.append(f"{name}: {ratio:.2f}x slower than previous log")
                line += "  REGRESSED"
        print(line, flush=True)

    if not wanted or "instrument-overhead" in wanted:
        try:
            ov = _instrument_overhead(args.quick, args.trials)
        except Exception as e:
            print(f"instrument-overhead FAILED: {e}", file=sys.stderr)
            failures.append(f"instrument-overhead: failed ({e})")
        else:
            results["instrument-overhead"] = ov
            line = (
                f"{'instrument-overhead':15s} ratio {ov['ratio']:5.2f}x "
                f"({ov['instrumented_ns'] / 1e6:.1f} ms vs "
                f"{ov['base_ns'] / 1e6:.1f} ms, {ov['tasks']} tasks)"
            )
            if ov["ratio"] > args.instrument_tolerance:
                failures.append(
                    f"instrument-overhead: instrument=True is "
                    f"{ov['ratio']:.2f}x slower (bound "
                    f"{args.instrument_tolerance:.2f}x) - the recorder is "
                    "taxing the hot path"
                )
                line += "  REGRESSED"
            print(line, flush=True)

    if not wanted or "ingress-overhead" in wanted:
        try:
            io = _ingress_overhead(args.quick, args.trials)
        except Exception as e:
            print(f"ingress-overhead FAILED: {e}", file=sys.stderr)
            failures.append(f"ingress-overhead: failed ({e})")
        else:
            results["ingress-overhead"] = io
            line = (
                f"{'ingress-overhead':15s} ratio {io['ratio']:5.2f}x "
                f"({io['tenant_ns'] / 1e6:.1f} ms 1-tenant vs "
                f"{io['base_ns'] / 1e6:.1f} ms plain, {io['tasks']} "
                f"tasks, bit-identical)"
            )
            if io["ratio"] > args.ingress_tolerance:
                failures.append(
                    f"ingress-overhead: the 1-tenant front door is "
                    f"{io['ratio']:.2f}x slower than the plain stream "
                    f"(bound {args.ingress_tolerance:.2f}x) - the WRR "
                    "poll is taxing the round loop"
                )
                line += "  REGRESSED"
            print(line, flush=True)

    if not wanted or "checkpoint-overhead" in wanted:
        try:
            co = _checkpoint_overhead(args.quick, args.trials)
        except Exception as e:
            print(f"checkpoint-overhead FAILED: {e}", file=sys.stderr)
            failures.append(f"checkpoint-overhead: failed ({e})")
        else:
            results["checkpoint-overhead"] = co
            line = (
                f"{'checkpoint-overhead':15s} ratio {co['ratio']:5.2f}x "
                f"(stride-{co['stride']} {co['stride_ratio']:5.2f}x; "
                f"{co['checkpoint_ns'] / 1e6:.1f} ms vs "
                f"{co['base_ns'] / 1e6:.1f} ms, {co['nodes']} nodes; "
                f"quiesce lag {co['quiesce_lag_tasks']} tasks, strided "
                f"{co['stride_lag_tasks']})"
            )
            if co["ratio"] > args.checkpoint_tolerance:
                failures.append(
                    f"checkpoint-overhead: checkpoint=True (idle) is "
                    f"{co['ratio']:.2f}x slower (bound "
                    f"{args.checkpoint_tolerance:.2f}x) - the quiesce "
                    "word is taxing the round loop"
                )
                line += "  REGRESSED"
            if co["stride_ratio"] > args.checkpoint_tolerance:
                # The stride knob exists to CUT the enabled-idle tax; a
                # strided build pricier than the bound means the poll
                # skip is broken, not just slow.
                failures.append(
                    f"checkpoint-overhead: quiesce_stride={co['stride']} "
                    f"(idle) is {co['stride_ratio']:.2f}x slower (bound "
                    f"{args.checkpoint_tolerance:.2f}x) - the strided "
                    "poll is not skipping DMAs"
                )
                line += "  STRIDE-REGRESSED"
            if co["quiesce_lag_tasks"] > 8:
                failures.append(
                    f"checkpoint-overhead: quiesce landed "
                    f"{co['quiesce_lag_tasks']} tasks past the requested "
                    "round - the boundary latency contract (<= one batch "
                    "width) regressed"
                )
                line += "  LAG-REGRESSED"
            if co["stride_lag_tasks"] > 8 + co["stride"] - 1:
                failures.append(
                    f"checkpoint-overhead: strided quiesce landed "
                    f"{co['stride_lag_tasks']} tasks past the requested "
                    f"round (contract: one batch width + stride-1 = "
                    f"{8 + co['stride'] - 1})"
                )
                line += "  STRIDE-LAG-REGRESSED"
            print(line, flush=True)

    if not wanted or "forasync-tile" in wanted:
        try:
            fa = _forasync_tile(args.quick, args.trials)
        except Exception as e:
            print(f"forasync-tile FAILED: {e}", file=sys.stderr)
            failures.append(f"forasync-tile: failed ({e})")
        else:
            results["forasync-tile"] = fa
            line = (
                f"{'forasync-tile':15s} tier vs host "
                f"{fa['tier_vs_host']:5.2f}x (vs device-scalar "
                f"{fa['tier_vs_device_scalar']:5.2f}x, occupancy "
                f"{fa['occupancy']:.2f}, {fa['tiles']} tiles, "
                "bit-identical)"
            )
            if fa["tier_vs_host"] < args.forasync_floor:
                failures.append(
                    f"forasync-tile: tile tier is only "
                    f"{fa['tier_vs_host']:.2f}x the host scalar-spawn arm "
                    f"(floor {args.forasync_floor:.2f}x) - the device "
                    "tier collapsed"
                )
                line += "  REGRESSED"
            if fa["occupancy"] < args.forasync_occupancy:
                failures.append(
                    f"forasync-tile: batch-lane occupancy "
                    f"{fa['occupancy']:.2f} under bound "
                    f"{args.forasync_occupancy:.2f} - the tile loop "
                    "stopped batching"
                )
                line += "  OCC-REGRESSED"
            print(line, flush=True)

    if not wanted or "frontier-batch" in wanted:
        try:
            fb = _frontier_batch(args.quick, args.trials)
        except Exception as e:
            print(f"frontier-batch FAILED: {e}", file=sys.stderr)
            failures.append(f"frontier-batch: failed ({e})")
        else:
            results["frontier-batch"] = fb
            line = (
                f"{'frontier-batch':15s} batched/scalar "
                f"{fb['batched_vs_scalar']:5.2f}x "
                f"({fb['batched_teps']:,} vs {fb['scalar_teps']:,} TEPS, "
                f"occupancy {fb['occupancy']:.2f}, partial age "
                f"{fb['lane_partial_age']}, {fb['age_fires']} age fires, "
                f"starved age {fb['max_starved_age']}<="
                f"{fb['lane_max_age']}, bit-identical)"
            )
            if fb["batched_vs_scalar"] < args.frontier_floor:
                failures.append(
                    f"frontier-batch: batched frontier is "
                    f"{fb['batched_vs_scalar']:.2f}x the scalar arm "
                    f"(floor {args.frontier_floor:.2f}x) - the frontier "
                    "tier collapsed"
                )
                line += "  REGRESSED"
            if fb["lane_partial_age"] > args.frontier_age_ceiling:
                failures.append(
                    f"frontier-batch: lane_partial_age "
                    f"{fb['lane_partial_age']} over ceiling "
                    f"{args.frontier_age_ceiling:.0f} - the firing "
                    "policy stopped bounding lane starvation"
                )
                line += "  AGE-REGRESSED"
            print(line, flush=True)

    if not wanted or "priority-tier" in wanted:
        try:
            pt = _priority_tier(args.quick, args.trials)
        except Exception as e:
            print(f"priority-tier FAILED: {e}", file=sys.stderr)
            failures.append(f"priority-tier: failed ({e})")
        else:
            results["priority-tier"] = pt
            line = (
                f"{'priority-tier':15s} expand "
                f"{pt['expand_ratio']:5.2f}x "
                f"({pt['expanded_bucketed']} vs "
                f"{pt['expanded_unordered']} EXPANDs, teps "
                f"{pt['teps_ratio']:.2f}x, pr live "
                f"{pt['pr_live_ratio']:.2f}x "
                f"({pt['pr_live_bucketed']} vs "
                f"{pt['pr_live_unordered']} rows), "
                f"{pt['bucket_inversions']} inversions, bit-identical)"
            )
            if pt["expand_ratio"] > args.priority_expand_ceiling:
                failures.append(
                    f"priority-tier: delta-stepping executed "
                    f"{pt['expand_ratio']:.2f}x the label-correction "
                    f"EXPAND count (ceiling "
                    f"{args.priority_expand_ceiling:.2f}x) - ordered "
                    "retirement stopped cutting re-relaxation"
                )
                line += "  EXPAND-REGRESSED"
            if pt["pr_live_ratio"] > args.priority_live_ceiling:
                failures.append(
                    f"priority-tier: bounded-frontier PageRank peak "
                    f"live set is {pt['pr_live_ratio']:.2f}x the "
                    f"unordered arm (ceiling "
                    f"{args.priority_live_ceiling:.2f}x) - the "
                    "magnitude-band ordering stopped bounding the "
                    "frontier"
                )
                line += "  LIVE-REGRESSED"
            print(line, flush=True)

    if not wanted or "program-cache" in wanted:
        try:
            pg = _program_cache(args.quick, args.trials)
        except Exception as e:
            print(f"program-cache FAILED: {e}", file=sys.stderr)
            failures.append(f"program-cache: failed ({e})")
        else:
            results["program-cache"] = pg
            line = (
                f"{'program-cache':15s} warm "
                f"{pg['speedup']:5.2f}x "
                f"({pg['cold_ns']/1e6:.1f}ms cold vs "
                f"{pg['warm_ns']/1e6:.1f}ms warm first build, "
                f"off {pg['off_ns']/1e6:.1f}ms, bit-identical, "
                f"eviction-correct)"
            )
            if pg["speedup"] < args.progcache_floor:
                failures.append(
                    f"program-cache: warm first build only "
                    f"{pg['speedup']:.2f}x faster than cold (floor "
                    f"{args.progcache_floor:.2f}x) - the cache "
                    "stopped killing the compile tax"
                )
                line += "  REGRESSED"
            print(line, flush=True)

    if not wanted or "telemetry-overhead" in wanted:
        try:
            to = _telemetry_overhead(args.quick, args.trials)
        except Exception as e:
            print(f"telemetry-overhead FAILED: {e}", file=sys.stderr)
            failures.append(f"telemetry-overhead: failed ({e})")
        else:
            results["telemetry-overhead"] = to
            line = (
                f"{'telemetry-overhead':15s} ratio {to['ratio']:5.2f}x "
                f"({to['telemetry_ns'] / 1e6:.1f} ms on vs "
                f"{to['base_ns'] / 1e6:.1f} ms off, {to['tasks']} "
                f"tasks, bit-identical, off-text-identical)"
            )
            if to["ratio"] > args.telemetry_tolerance:
                failures.append(
                    f"telemetry-overhead: the telemetry plane is "
                    f"{to['ratio']:.2f}x slower than the off stream "
                    f"(bound {args.telemetry_tolerance:.2f}x) - the "
                    "histogram fold is taxing the round loop"
                )
                line += "  REGRESSED"
            print(line, flush=True)

    if not wanted or "dyngraph-incremental" in wanted:
        try:
            dy = _dyngraph_incremental(args.quick, args.trials)
        except Exception as e:
            print(f"dyngraph-incremental FAILED: {e}", file=sys.stderr)
            failures.append(f"dyngraph-incremental: failed ({e})")
        else:
            results["dyngraph-incremental"] = dy
            line = (
                f"{'dyngraph-incr':15s} expand "
                f"{dy['expand_ratio']:5.2f}x "
                f"({dy['incr_expands']} incremental vs "
                f"{dy['full_expands']} from-scratch EXPANDs, "
                f"{dy['updates_applied']}/{dy['updates']} splices, "
                "bit-identical)"
            )
            if dy["expand_ratio"] > args.dyngraph_expand_ceiling:
                failures.append(
                    f"dyngraph-incremental: the update-only rerun "
                    f"re-expanded {dy['expand_ratio']:.2f}x the "
                    f"from-scratch EXPAND count (ceiling "
                    f"{args.dyngraph_expand_ceiling:.2f}x) - "
                    "incremental recompute stopped paying for itself"
                )
                line += "  REGRESSED"
            print(line, flush=True)

    if args.device:
        import jax

        if jax.default_backend() != "tpu":
            print("--device: no TPU attached, skipping device suite",
                  file=sys.stderr)
        else:
            for name, fn, unit in _device_suite(args.trials):
                if wanted and name not in wanted:
                    continue
                try:
                    val = fn()
                    if val is None:  # dependent entry whose producer
                        print(f"{name:20s} SKIPPED (no data)",  # didn't run
                              file=sys.stderr)
                        continue
                    rate = float(val)
                except Exception as e:  # one engine must not sink the log
                    print(f"{name:20s} FAILED: {e}", file=sys.stderr)
                    failures.append(f"{name}: failed ({e})")
                    continue
                results[name] = {"rate": rate, "unit": unit}
                line = f"{name:20s} rate {rate:14.3e} {unit}"
                if name in prev and "rate" in prev[name]:
                    ratio = rate / prev[name]["rate"]
                    line += f"  vs prev {ratio:5.2f}x"
                    if ratio < 1 - args.tolerance:
                        failures.append(
                            f"{name}: {1/ratio:.2f}x slower than previous log"
                        )
                        line += "  REGRESSED"
                print(line, flush=True)

    ts = int(time.time())
    if args.multichip:
        from hclib_tpu.device import stress

        fs_kw = (
            stress.FOREST_STEAL_QUICK if args.quick
            else stress.FOREST_STEAL_BENCH
        )
        mc = [
            ("mc-forest-steal", lambda: stress.forest_steal(**fs_kw)),
            # The batched arm of the SAME workload (ISSUE 7): fib fires
            # through per-device lanes between steal rounds; its rate and
            # occupancy feed the mesh-batch-dispatch guard below, which
            # is why both arms share the one config dict.
            ("mc-forest-steal-batch", lambda: stress.forest_steal(
                batch_width=8, **fs_kw
            )),
            ("mc-unified-resident", lambda: stress.unified_load(
                ndev=8,
                n=8 if args.quick else 10,
                fadds=8 if args.quick else 32,
                capacity=256 if args.quick else 1024,
            )),
        ]
        os.makedirs(args.log_dir, exist_ok=True)
        for name, fn in mc:
            if wanted and name not in wanted:
                continue
            try:
                info = fn()  # exact totals asserted inside
            except Exception as e:
                print(f"{name:20s} FAILED: {e}", file=sys.stderr)
                failures.append(f"{name}: failed ({e})")
                continue
            rate = info["tasks_per_sec"]
            results[name] = {
                "rate": rate, "unit": "tasks/s",
                "tasks": info["tasks"], "seconds": info["seconds"],
                "devices_used": info["devices_used"],
                "imbalance": round(info["imbalance"], 3),
            }
            line = (
                f"{name:20s} {info['tasks']:>8,} tasks in "
                f"{info['seconds']:7.2f} s  ({rate:12,.0f} tasks/s, "
                f"{info['devices_used']} devices, imbalance "
                f"{info['imbalance']:.2f}x)"
            )
            if "min_occupancy" in info:
                results[name]["min_occupancy"] = round(
                    info["min_occupancy"], 3
                )
                results[name]["mean_occupancy"] = round(
                    info["mean_occupancy"], 3
                )
                results[name]["spilled"] = info["spilled"]
                line += (
                    f"  occ {info['mean_occupancy']:.2f} "
                    f"(min {info['min_occupancy']:.2f}), "
                    f"{info['spilled']} lane spills"
                )
            with open(os.path.join(
                    args.log_dir, f"{ts}.{name}.json"), "w") as f:
                json.dump(info, f, indent=1)
            if name in prev and "rate" in prev[name]:
                ratio = rate / prev[name]["rate"]
                line += f"  vs prev {ratio:5.2f}x"
                if ratio < 1 - args.tolerance:
                    failures.append(
                        f"{name}: {1/ratio:.2f}x slower than previous log"
                    )
                    line += "  REGRESSED"
            print(line, flush=True)

        # mesh-batch-dispatch guard (ISSUE 7): the batched forest-steal
        # arm must hold a tasks/s floor against the scalar arm measured
        # in the SAME run (no cross-run weather), and its per-device
        # lane occupancy must not collapse - either failing means the
        # mesh multiplier silently regressed.
        sc = results.get("mc-forest-steal")
        bt = results.get("mc-forest-steal-batch")
        if sc and bt and "rate" in sc and "rate" in bt:
            ratio = bt["rate"] / sc["rate"]
            occ = bt.get("min_occupancy", 0.0)
            results["mesh-batch-dispatch"] = {
                "batch_vs_scalar": round(ratio, 3),
                "min_occupancy": occ,
            }
            line = (
                f"{'mesh-batch-dispatch':20s} batched/scalar "
                f"{ratio:5.2f}x  min occupancy {occ:.2f}"
            )
            if ratio < args.mesh_batch_floor:
                failures.append(
                    f"mesh-batch-dispatch: batched forest-steal is "
                    f"{ratio:.2f}x the scalar mesh (floor "
                    f"{args.mesh_batch_floor:.2f}x) - the mesh batch "
                    "tier collapsed"
                )
                line += "  REGRESSED"
            if occ < args.mesh_batch_occupancy:
                failures.append(
                    f"mesh-batch-dispatch: min per-device occupancy "
                    f"{occ:.2f} under bound "
                    f"{args.mesh_batch_occupancy:.2f} - the mesh stopped "
                    "exposing same-kind width to the lanes"
                )
                line += "  OCC-REGRESSED"
            print(line, flush=True)

    os.makedirs(args.log_dir, exist_ok=True)
    out_path = os.path.join(args.log_dir, f"{ts}.json")
    with open(out_path, "w") as f:
        json.dump({"quick": args.quick, "apps": results}, f, indent=1)
    print(f"log written: {out_path}")
    if failures:
        print("REGRESSIONS:\n  " + "\n  ".join(failures), file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
