"""Lesson 20: the request/response serving loop.

Lesson 13 built the ingress half of a service: typed admission into
weighted tenant lanes. This lesson closes the loop with the EGRESS half
(device/egress.py): how a caller who submitted a request gets its
result back - at sustained load, across preemption, without ever
wedging.

- **Submit returns a Future**: on an egress-enabled table every
  ``submit()``'s ``Admission`` carries a typed ``Future``;
  ``future.result(timeout=)`` blocks until exactly ONE terminal rung of
  the degradation ladder: RESULT (the payload), EXPIRED (deadline),
  POISONED (aborted/cancelled/validator), or PREEMPTED carrying a
  ``resume_token`` that reattaches after the stream resumes.
- **The completion mailbox**: each device owns a small ring of EGR
  result rows (result slot, tenant, fn, status, cursors). The kernel
  publishes at task retirement inside the round loop; the host drains
  it at every entry boundary. A FULL mailbox is explicit backpressure:
  the retiring row parks (counted, TR_EGRESS-traced) and an install
  credit gate throttles new installs - results are NEVER dropped, and
  there is no overflow abort by construction.
- **Wedge-proof by model checking**: the same bounded-interleaving
  explorer that certifies the inject/credit protocols (lesson 18)
  explores ``EgressMailboxModel`` - a full mailbox with a dead poller
  still quiesces and drains (tools/hclint.py runs it in CI).
- **Conservation**: the ledger's identity
  ``submitted == resolved + expired + poisoned (+ pending)`` closes
  exactly - across checkpoint cuts, resumes, and mesh reshards
  (tools/chaos_soak.py --serve soaks it; bench.py --serve prices it).

Ordering rule worth memorizing: after a preemption cut, ``reattach``
a resume token only AFTER the resumed stream has re-adopted the
snapshot (i.e. after ``run_stream(resume_state=...)``) - the fresh
ledger learns the outstanding tokens from the snapshot's ``etok``
block. Off path (``egress=`` unset / ``egress=False``) the kernel
lowers bit-identically to the pre-egress build: you pay nothing.

Env spelling for wrapper scripts: ``HCLIB_TPU_EGRESS_DEPTH=N`` (0=off)
and ``HCLIB_TPU_EGRESS_BACKOFF_S`` (the ``result()`` poll backoff).
"""

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from hclib_tpu.device.descriptor import (  # noqa: E402
    RING_ROW,
    TEN_TOKEN,
    TaskGraphBuilder,
)
from hclib_tpu.device.egress import (  # noqa: E402
    EgressSpec,
    FutureExpired,
    FuturePoisoned,
    FuturePreempted,
    HostMailbox,
)
from hclib_tpu.device.inject import StreamingMegakernel  # noqa: E402
from hclib_tpu.device.megakernel import Megakernel  # noqa: E402
from hclib_tpu.device.tenants import (  # noqa: E402
    TenantSpec,
    TenantTable,
    wrr_poll_reference,
)

BUMP = 0


def _mk(checkpoint=False):
    def bump(ctx):
        ctx.set_value(0, ctx.value(0) + ctx.arg(0))

    return Megakernel(
        kernels=[("bump", bump)], capacity=256, num_values=8,
        succ_capacity=8, interpret=True, checkpoint=checkpoint,
    )


def _table(egress=EgressSpec(depth=16), region=32, clock=None):
    return TenantTable(
        [TenantSpec("gold", weight=2), TenantSpec("std")],
        region, egress=egress,
        **({"clock": clock} if clock else {}),
    )


def part_one_submit_futures():
    """The happy path: submit -> Future -> RESULT, conservation exact."""
    table = _table()
    sm = StreamingMegakernel(_mk(), ring_capacity=64, tenants=table)
    futs = []
    for i in range(6):
        adm = sm.submit("gold" if i % 2 else "std", BUMP, args=[i + 1])
        assert adm.accepted and adm.future.token > 0
        futs.append(adm.future)
    sm.close()
    b = TaskGraphBuilder()
    b.add(BUMP, args=[100])
    iv, info = sm.run_stream(b)
    assert int(iv[0]) == 100 + sum(range(1, 7))
    for f in futs:
        assert isinstance(f.result(timeout=2.0), int)
        assert f.state == "RESULT" and f.latency_s() is not None
    cons = table.futures.conservation()
    assert cons["ok"] and cons["resolved"] == 6, cons
    print(f"  6 futures resolved RESULT through the mailbox; "
          f"ledger closes: {cons['resolved']} resolved / "
          f"{cons['submitted']} submitted")


def part_two_backpressure():
    """A depth-2 mailbox under a poller consuming ONE row per step:
    sustained backpressure parks (counted), loses nothing."""
    spec = EgressSpec(depth=2)
    table = _table(egress=spec, region=32, clock=lambda: 100.0)
    box = HostMailbox(spec, park_cap=24)
    ring = np.zeros((2 * 32, RING_ROW), np.int32)
    futs = {}
    for i in range(24):
        adm = table.submit(i % 2, BUMP, args=[i])
        futs[adm.future.token] = (adm.future, 3 * i)
    drained, rnd = 0, 0
    while drained < len(futs):
        tctl = table.pump(ring)
        rows = wrr_poll_reference(ring, tctl, 32, rnd, 1 << 20)
        table.absorb(tctl)
        box.publish([(int(r[TEN_TOKEN]), 0, BUMP,
                      0, futs[int(r[TEN_TOKEN])][1]) for r in rows])
        drained += len(box.drain(futures=table.futures, limit=1))
        rnd += 1
    assert box.park_events() > 0, "the tiny mailbox never parked"
    for f, payload in futs.values():
        assert f.result(timeout=1.0) == payload and f.state == "RESULT"
    print(f"  24 results through a depth-2 mailbox, slow poller: "
          f"{box.park_events()} park events, zero loss, {rnd} steps")


def part_three_degradation_ladder():
    """Every failure is a TYPED terminal state, never a hang: deadline
    -> EXPIRED, abort -> POISONED."""
    clk = [100.0]
    table = _table(region=32, clock=lambda: clk[0])
    ring = np.zeros((2 * 32, RING_ROW), np.int32)
    doomed = table.submit("gold", BUMP, args=[1],
                          deadline_s=0.01).future
    clk[0] += 1.0  # the deadline lapses before the pump pops the row
    table.absorb(table.pump(ring))
    try:
        doomed.result(timeout=1.0)
        raise AssertionError("expected FutureExpired")
    except FutureExpired:
        assert doomed.state == "EXPIRED"
    sm = StreamingMegakernel(_mk(), ring_capacity=64, tenants=_table())
    poisoned = [sm.submit("std", BUMP, args=[1]).future
                for _ in range(3)]
    sm.abort("client disconnect")
    try:
        sm.run_stream(TaskGraphBuilder())
    except Exception as e:
        assert "abort" in str(e)
    for f in poisoned:
        try:
            f.result(timeout=1.0)
            raise AssertionError("expected FuturePoisoned")
        except FuturePoisoned:
            assert f.state == "POISONED"
    print("  deadline -> FutureExpired; abort -> FuturePoisoned "
          "(typed raises, nothing hangs)")


def part_four_preempt_reattach():
    """A checkpoint cut with futures in flight: PREEMPTED + resume
    token; reattach AFTER the resumed stream re-adopts the snapshot."""
    def fresh():
        return StreamingMegakernel(
            _mk(checkpoint=True), ring_capacity=64,
            tenants=_table(egress=EgressSpec(depth=64)),
        )

    sm = fresh()
    futs = [sm.submit("gold", BUMP, args=[1]).future for _ in range(8)]
    sm.quiesce(after_executed=3)
    _, info = sm.run_stream(TaskGraphBuilder())
    assert info["quiesced"] and "etok" in info["state"]
    tokens = []
    for f in futs:
        if f.state == "PREEMPTED":
            try:
                f.result()
            except FuturePreempted as e:
                assert e.resume_token == f.resume_token
            tokens.append(f.resume_token)
        else:
            assert f.state == "RESULT"
    sm2 = fresh()
    sm2.close()
    sm2.run_stream(resume_state=info["state"])  # re-adopts etok
    done = [sm2.tenants.reattach(tok) for tok in tokens]  # THEN attach
    for f in done:
        assert f.result(timeout=2.0) is not None and f.state == "RESULT"
    cons = sm2.tenants.futures.conservation()
    assert cons["ok"] and cons["reattached"] == len(tokens)
    print(f"  cut at 3 tasks: {len(tokens)} futures PREEMPTED with "
          f"resume tokens, all reattached and resolved after resume")


if __name__ == "__main__":
    part_one_submit_futures()
    part_two_backpressure()
    part_three_degradation_ladder()
    part_four_preempt_reattach()
    print("lesson 20 OK")
