"""Lesson 14: the forasync device tier - data-parallel loops on batch lanes.

Lesson 3 ran forasync on the HOST: the loop tiles into ranges, each tile
becomes a host task, and a dist func places tiles on locales. This lesson
lowers the same construct onto the DEVICE (device/forasync_tier.py):

- **A tile IS a same-kind batch.** Every flat tile becomes one task
  descriptor of one kernel kind, so the whole loop rides the lesson-7
  batch lanes: each round fires up to ``width`` tiles through ONE tiled
  Pallas body, with the double-buffered operand prefetch loading the
  next batch's slabs under the current batch's compute.
- **The body is a slab pipeline.** A ``TileKernel`` declares operand
  slabs (windows of named HBM buffers addressed by the tile's loop
  offsets), a pure compute function on the loaded values, and output
  slabs - the tier derives the scalar-dispatch kernel, the batched body,
  and its prefetch drain from that one declaration, which is why the
  two device spellings are bit-identical by construction.
- **Placement is data, not code.** On a mesh, a JSON placement
  descriptor (or a classic dist func) resolved against
  ``locality_graphs/*.json`` maps each flat tile to a device, seeding
  the per-device ready rings; the machine graph also orders the steal
  scan near-neighbors-first (``steal_hop_order``), so a skewed or stale
  placement degrades into recoverable work stealing.

Env spelling for wrapper scripts: ``HCLIB_TPU_FORASYNC_WIDTH`` sets the
default batch width.
"""

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
# The mesh part wants 4 virtual devices; harmless if already set wider.
os.environ.setdefault(
    "XLA_FLAGS", "--xla_force_host_platform_device_count=4"
)

import numpy as np  # noqa: E402

import hclib_tpu as hc  # noqa: E402
from hclib_tpu.device.forasync_tier import run_forasync_device  # noqa: E402
from hclib_tpu.device.megakernel import C_EXECUTED  # noqa: E402
from hclib_tpu.device.workloads import (  # noqa: E402
    map_body,
    map_data,
    map_loop,
    map_reference,
    stencil_body,
    stencil_data,
    stencil_loop,
    stencil_reference,
)
from hclib_tpu.runtime.locality import MeshPlacement  # noqa: E402

H, W = 16, 512  # 2x4 tiles of (8, 128)


def part_one_host_vs_device():
    """The same 2D Jacobi-style stencil three ways - host forasync,
    scalar device dispatch, and the batched tile tier - bit-identical."""
    tk, bounds, tile = stencil_loop(H, W)
    gin, gout = stencil_data(H, W)
    ref = stencil_reference(gin)

    ghost = gout.copy()

    def main():
        hc.forasync(stencil_body(gin, ghost), bounds, tile=tile)

    hc.launch(main, nworkers=2)
    assert np.array_equal(ghost, ref)

    d_scalar, _ = run_forasync_device(
        tk, bounds, tile, {"gin": gin, "gout": gout.copy()}, width=0
    )
    assert np.array_equal(np.asarray(d_scalar["gout"]), ref)

    # place="device" is the forasync spelling of the same call; the body
    # is the TileKernel and the result comes back as (data, info).
    d_tile, info = hc.forasync(
        tk, bounds, tile=tile, place="device",
        data={"gin": gin, "gout": gout.copy()}, width=4,
    )
    assert np.array_equal(np.asarray(d_tile["gout"]), ref)
    t = info["tiers"]
    print(f"  stencil: {t['batch_tasks']} tiles in {t['batch_rounds']} "
          f"batch rounds, occupancy {t['batch_occupancy']:.2f}, "
          f"{t['prefetch_hits']} prefetch hits - three arms bit-identical")


def part_two_map_loop():
    """Map-style batched apply (the batched-inference shape): 1D loop,
    one (8,128) block per tile, prefetch hiding the operand loads."""
    T = 16
    tk, bounds, tile = map_loop(T)
    vin, vout = map_data(T)
    ref = map_reference(vin)

    vh = vout.copy()

    def main():
        hc.forasync(map_body(vin, vh), bounds, tile=tile)

    hc.launch(main, nworkers=2)
    assert np.array_equal(vh, ref)

    d, info = hc.forasync(
        tk, bounds, tile=tile, place="device",
        data={"vin": vin, "vout": vout.copy()}, width=8,
    )
    assert np.array_equal(np.asarray(d["vout"]), ref)
    print(f"  map: {info['tiers']['batch_tasks']} tiles, occupancy "
          f"{info['tiers']['batch_occupancy']:.2f}")


def part_three_mesh_placement():
    """Placement as data: a JSON descriptor seeds the per-device ready
    rings; the machine graph orders the steal scan; a deliberately
    skewed placement still completes exactly via stealing."""
    tk, bounds, tile = stencil_loop(H, W)
    gin, gout = stencil_data(H, W)
    ref = stencil_reference(gin)

    block = MeshPlacement.from_file(
        os.path.join(_REPO, "locality_graphs", "v5e_4.place_block.json")
    )
    print(f"  graph-derived steal scan order: {block.hop_order()} "
          "(2x2 ICI ring: hop 2 is the direct neighbor)")
    d, info = run_forasync_device(
        tk, bounds, tile, {"gin": gin, "gout": gout.copy()},
        width=4, placement=block, quantum=2, window=4,
    )
    assert np.array_equal(np.asarray(d["gout"]), ref)
    print(f"  block placement seeded {info['placement_counts']} tiles/dev")

    skew = MeshPlacement.from_file(
        os.path.join(_REPO, "locality_graphs", "v5e_4.place_skew.json")
    )
    d, info = run_forasync_device(
        tk, bounds, tile, {"gin": gin, "gout": gout.copy()},
        width=4, placement=skew, quantum=1, window=4,
    )
    assert np.array_equal(np.asarray(d["gout"]), ref)
    per_dev = np.asarray(info["per_device_counts"])[:, C_EXECUTED]
    assert int((per_dev > 0).sum()) > 1
    print(f"  skewed placement [8,0,0,0] executed as "
          f"{per_dev.tolist()} - recovered by locality-ordered stealing")


if __name__ == "__main__":
    print("host vs device, bit-identical:")
    part_one_host_vs_device()
    print("map loop:")
    part_two_map_loop()
    print("mesh placement + stealing:")
    part_three_mesh_placement()
    print("lesson 14 OK")
