"""Lesson 8: cross-process ranks and in-kernel ICI work stealing.

The distributed story at two levels:

1. **ProcWorld** - ranks as real OS processes wired by jax.distributed:
   two-sided send/recv, allreduce/barrier, a symmetric heap with
   one-sided put/get served by a per-process progress thread, and named
   active-message handlers - all over the coordination service the
   multi-controller runtime already establishes. (The reference needs
   mpirun + MPI/OpenSHMEM for this surface.) This lesson SPAWNS two real
   processes and runs a put/get/allreduce exchange between them.

2. **In-kernel ICI steal** - per-device resident schedulers that
   exchange surplus task descriptors by remote DMA between their SMEM
   task tables, with semaphore credits for flow control and a ring
   allreduce as the termination collective - the whole multi-device run
   is one kernel launch per device, no host round-trips. Here it runs on
   a 2-device simulated mesh (Mosaic TPU interpret mode emulates the
   remote DMAs + semaphores); identical code compiles for a real slice.
"""

import os
import socket
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

# -- 1. two real processes exchanging through ProcWorld ------------------

WORKER = textwrap.dedent("""
    import sys
    import numpy as np
    import jax
    pid, port = int(sys.argv[1]), sys.argv[2]
    jax.distributed.initialize(f"localhost:{port}", num_processes=2, process_id=pid)
    sys.path.insert(0, %r)
    from hclib_tpu.modules.procworld import ProcWorld
    w = ProcWorld(timeout_s=30.0)
    w.alloc("cell", (2,), np.int32)
    for r in range(2):  # one-sided write of my slot into EVERY rank's cell
        w.put(r, "cell", np.array([10 + pid]), offset=pid)
    w.fence(1 - pid)
    w.barrier()
    total = w.allreduce(np.int32(w.heap("cell").sum()))
    assert int(total) == 2 * (10 + 0 + 10 + 1), total
    w.quiet(); w.barrier(); w.close()
    jax.distributed.shutdown()
    print(f"rank {pid} OK", flush=True)
""") % (REPO,)

with socket.socket() as s:
    s.bind(("localhost", 0))
    port = str(s.getsockname()[1])
# The ranks are CPU-only coordination processes: pin PYTHONPATH to the repo
# so no site hook (e.g. a TPU-tunnel PJRT plugin injected via the parent's
# PYTHONPATH) initializes accelerator state in every rank - two ranks
# fighting over one tunneled chip wedges the coordination service. The
# engine also tolerates transient service errors (see
# tests/test_procworld_unit.py), but a demo should not rely on retries.
env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
env.pop("XLA_FLAGS", None)
procs = [
    subprocess.Popen([sys.executable, "-c", WORKER, str(pid), port], env=env)
    for pid in range(2)
]
for p in procs:
    assert p.wait(timeout=120) == 0
print("procworld: 2 processes exchanged put/get + allreduce")

# -- 2. in-kernel ICI steal on a simulated 2-device mesh -----------------

from hclib_tpu.device.descriptor import TaskGraphBuilder
from hclib_tpu.device.ici_steal import ICIStealMegakernel
from hclib_tpu.device.megakernel import Megakernel
from hclib_tpu.parallel.mesh import cpu_mesh

BUMP = 0


def bump(ctx):
    ctx.set_value(0, ctx.value(0) + ctx.arg(0))


mesh = cpu_mesh(2, axis_name="queues")
mk = Megakernel(kernels=[("bump", bump)], capacity=128, num_values=4,
                succ_capacity=8, interpret=True)
smk = ICIStealMegakernel(mk, mesh, migratable_fns=[BUMP], window=8)
builders = [TaskGraphBuilder() for _ in range(2)]
for i in range(16):
    builders[0].add(BUMP, args=[i + 1])  # all work lands on device 0
iv, _, info = smk.run(builders, quantum=4)
assert int(iv[:, 0].sum()) == 16 * 17 // 2
per_dev = info["per_device_counts"][:, 5]
assert per_dev[1] > 0, "device 1 stole nothing"
print(f"ici steal: skewed load executed as {per_dev.tolist()} across devices "
      f"in {info['steal_rounds']} resident rounds")

print("lesson 8 OK")
