"""Lesson 12: elastic autoscaling - serving through a preempt storm.

Lesson 11 survived ONE preemption. Production serving faces storms of
them - plus chip death and load swings - and the autoscaler
(runtime/autoscaler.py) is the control loop that rides them: it slices
a resident mesh into bounded runs (quiesce is the slicing mechanism),
observes each slice through the MetricsRegistry-shaped signals, and
live-reshapes the mesh via quiesce -> ``CheckpointBundle.reshard(M)``
-> resume:

- **scale out** when ready backlog per device stays high (hysteresis:
  N consecutive slices, so one spiky slice never resizes);
- **scale in** when the mesh idles (plus a post-resize cooldown - the
  no-flap guarantee);
- **evacuate** a quarantined chip immediately (fault recovery must not
  wait out a flap guard) - reshard around it before the watchdog
  escalates;
- **checkpoint, then stop** on a preemption notice, resumable at any
  mesh size.

Every decision is a typed ``ScaleEvent``: in ``Autoscaler.events``, in
the MetricsRegistry (``autoscale.*``), and as a TR_SCALE record that
``Autoscaler.trace_info()`` exposes for the Perfetto timeline.

The policy is a PURE function of observations - this lesson drives it
headless first (no mesh, runs on any jax), then runs the real
autoscaled mesh when the Mosaic interpret mode is available.
"""

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
# The mesh part wants virtual CPU devices (no-op without Mosaic).
if "xla_force_host_platform_device_count" not in os.environ.get(
    "XLA_FLAGS", ""
):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import hclib_tpu as hc


def part_one_policy_headless() -> None:
    """The decision function, no mesh attached: hysteresis, cooldown,
    and the evacuation fast path."""
    policy = hc.AutoscalerPolicy(
        min_devices=1, max_devices=8,
        scale_out_backlog=16.0, scale_in_backlog=2.0,
        hysteresis=2, cooldown=1,
    )
    # A single hot slice holds (streak 1/2); a SUSTAINED backlog scales.
    hot = hc.Observation(ndev=2, backlog=[40, 40])
    for expect_kind in ("hold", "scale_out"):
        target, kind, reason = policy.decide(hot)
        print(f"  hot slice -> {kind} (target {target}): {reason}")
        assert kind == expect_kind, (kind, expect_kind)
    # Cooldown right after the resize: even a hot observation holds.
    target, kind, _ = policy.decide(hc.Observation(4, [40] * 4))
    assert kind == "hold" and target == 4
    print("  post-resize slice -> hold (cooldown): no flapping")
    # Evacuation bypasses both gates: a quarantined chip reshard-around
    # happens at the FIRST observation naming it.
    target, kind, reason = policy.decide(
        hc.Observation(4, [5, 5, 5, 0], quarantined=[3])
    )
    assert kind == "evacuate" and target == 2, (kind, target)
    print(f"  dead chip -> {kind} to {target} devices: {reason}")


def part_two_events_and_telemetry() -> None:
    """ScaleEvents are data: metrics counters + a host flight-recorder
    ring in the same ABI device traces use."""
    from hclib_tpu.device.tracebuf import TR_SCALE, records_of

    reg = hc.MetricsRegistry()
    asc = hc.Autoscaler(
        lambda ndev: (_ for _ in ()).throw(RuntimeError("unused")),
        hc.AutoscalerPolicy(),
        metrics=reg,
    )
    asc._event(hc.ScaleEvent("scale_out", 0, 2, 4, "demo backlog"))
    asc._event(hc.ScaleEvent("evacuate", 1, 4, 2, "demo dead chip",
                             resize_latency_s=0.012))
    snap = reg.snapshot()["metrics"]
    assert snap["autoscale.scale_out.count"] == 1.0
    assert snap["autoscale.evacuate.last.to_ndev"] == 2.0
    recs = records_of(asc.trace_info(), TR_SCALE)
    assert len(recs) == 2
    frm, to = int(recs[1][2]) >> 8, int(recs[1][2]) & 0xFF
    print(f"  {len(recs)} TR_SCALE records; last: {frm} -> {to} "
          "(feed asc.trace_info() to tools/timeline.py --perfetto)")


def part_three_autoscaled_mesh() -> None:
    """The real loop: a 2-device UTS mesh scales in on its idle tail,
    totals exact across the resize. Needs the Mosaic interpret mode."""
    from hclib_tpu.jaxcompat import has_mosaic_interpret

    if not has_mosaic_interpret():
        print("  (skipped: the resident mesh needs the Mosaic TPU "
              "interpret mode, jax >= 0.5)")
        return
    import numpy as np

    from hclib_tpu.device.descriptor import TaskGraphBuilder
    from hclib_tpu.device.resident import ResidentKernel
    from hclib_tpu.device.workloads import UTS_NODE, make_uts_megakernel
    from hclib_tpu.parallel.mesh import cpu_mesh

    def make_kernel(ndev):
        mk = make_uts_megakernel(max_depth=6, interpret=True,
                                 checkpoint=True)
        return ResidentKernel(
            mk, cpu_mesh(ndev, axis_name="q"),
            migratable_fns=[UTS_NODE], window=4, homed=False,
        )

    def builders(ndev):
        bs = [TaskGraphBuilder() for _ in range(ndev)]
        for d in range(ndev):
            bs[d].add(UTS_NODE, args=[d + 1, 0])
        return bs

    iv_f, _, info_f = make_kernel(2).run(builders(2), quantum=8,
                                         max_rounds=1 << 14)
    total = int(np.asarray(iv_f)[:, 0].sum())
    asc = hc.Autoscaler(
        make_kernel,
        hc.AutoscalerPolicy(min_devices=1, max_devices=2,
                            scale_out_backlog=1e9, scale_in_backlog=2.0,
                            hysteresis=1, cooldown=0),
        slice_rounds=8,
    )
    iv, _, info = asc.run(builders(2), quantum=8)
    assert int(np.asarray(iv)[:, 0].sum()) == total
    kinds = [e["kind"] for e in info["scale_events"]]
    print(f"  {info['executed']} tasks, events {kinds}, final mesh "
          f"{info['ndev_final']} device(s), totals exact ({total})")


def part_four_quiesce_stride() -> None:
    """The poll-every-N-rounds knob: checkpoint builds re-read the
    quiesce word from HBM each round by default; quiesce_stride=N
    amortizes that DMA N-fold for at most N-1 rounds of extra latency
    (perf_regression's checkpoint-overhead guard bounds both sides)."""
    from hclib_tpu.device.descriptor import TaskGraphBuilder
    from hclib_tpu.device.workloads import (
        UTS_NODE, device_uts_mk, make_uts_megakernel,
    )

    kw = dict(max_depth=7, interpret=True)
    nodes, _ = device_uts_mk(**kw)
    mk = make_uts_megakernel(checkpoint=True, quiesce_stride=4, **kw)
    b = TaskGraphBuilder()
    b.add(UTS_NODE, args=[1, 0])
    _, _, info = mk.run(b, quiesce=nodes // 2)
    assert info["quiesced"] is True
    iv, _, done = mk.resume(info["state"])
    assert int(iv[0]) == nodes
    print(f"  stride-4 build: cut at {info['quiesce']['executed_at']} "
          f"(requested {nodes // 2}), resumed to {nodes} nodes - exact")


if __name__ == "__main__":
    print("policy, headless:")
    part_one_policy_headless()
    print("telemetry:")
    part_two_events_and_telemetry()
    print("autoscaled mesh:")
    part_three_autoscaled_mesh()
    print("quiesce stride:")
    part_four_quiesce_stride()
    print("lesson 12 OK")
