"""Lesson 13: the multi-tenant streaming front door.

Lesson 7 streamed tasks into a running kernel through ONE anonymous
injection ring. Serving millions of users means many concurrent
producers with different priorities, quotas, and deadlines - and a
single greedy or misbehaving stream must not starve (or wedge) the
rest. ``StreamingMegakernel(tenants=...)`` splits the ingress into N
prioritized tenant lanes (device/tenants.py):

- **Admission is typed, never a wedge**: every ``submit()`` returns an
  ``Admission`` verdict - ACCEPTED (within the lane's in-flight
  budget), QUEUED (over budget, host backlog has room), or
  REJECTED(reason) with a machine-readable reason (``rate`` /
  ``backlog`` / ``ring`` / ``expired`` / ``quarantined`` /
  ``cancelled`` / ``closed``). ``submit(wait=True)`` turns the
  transient rejections into a bounded blocking wait.
- **Weighted round-robin on the device**: the in-kernel poll visits
  lane ring regions WRR - ``weight`` rows per lane per round - so
  relative throughput under contention is weight-proportional, and
  total installs are bounded by live scheduler headroom (a full task
  table becomes ring backpressure, not an overflow abort).
- **Deadlines ride CancelScope**: a submission expires at admission,
  in the host queue, or lazily on the ring (the poll drops marked rows,
  counted); a lane over its deadline budget is cancelled - siblings
  keep flowing.
- **Poison isolation**: a tenant whose rows keep failing terminally is
  throttled (weight -> 1) then quarantined; everyone else is untouched.
- **Survivability**: tenant identity rides the ring row (TEN_ID), so
  checkpoint/resume and reshard conserve per-tenant counts exactly
  (lesson 11's machinery, now per tenant).

Observability: ``info['tenants']`` / ``stats_dict()['tenants']`` carry
per-tenant counters; a MetricsRegistry surfaces them as
``tenant.<id>.*`` series; TR_TENANT trace records land on a dedicated
Perfetto track. Env spelling for wrapper scripts: ``HCLIB_TPU_TENANTS=N``
(+ ``HCLIB_TPU_TENANT_WEIGHTS/_RATE/_BURST/_INFLIGHT/_DEADLINE_S``).
"""

import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from hclib_tpu.device.descriptor import TaskGraphBuilder  # noqa: E402
from hclib_tpu.device.inject import StreamingMegakernel  # noqa: E402
from hclib_tpu.device.megakernel import Megakernel  # noqa: E402
from hclib_tpu.device.tenants import (  # noqa: E402
    TenantSpec,
    TenantTable,
    build_row,
    per_tenant_ring_counts,
    wrr_poll_reference,
)

BUMP = 0


def _mk(checkpoint=False):
    def bump(ctx):
        ctx.set_value(0, ctx.value(0) + ctx.arg(0))

    return Megakernel(
        kernels=[("bump", bump)], capacity=256, num_values=8,
        succ_capacity=8, interpret=True, checkpoint=checkpoint,
    )


def part_one_admission_and_quotas():
    """Typed admission: the greedy tenant gets pushback, not a wedge."""
    sm = StreamingMegakernel(
        _mk(), ring_capacity=192,
        tenants=[
            TenantSpec("gold", weight=2),
            TenantSpec("free", max_in_flight=2, queue_capacity=4),
        ],
    )
    expect = 0
    for k in range(10):
        adm = sm.submit("gold", BUMP, args=[k + 1])
        assert adm.accepted
        expect += k + 1
    verdicts = {"ACCEPTED": 0, "QUEUED": 0, "REJECTED": 0}
    for _ in range(20):
        adm = sm.submit("free", BUMP, args=[1])
        verdicts[adm.status] += 1
        if adm:
            expect += 1
        else:
            assert adm.reason == "backlog"  # explicit backpressure
    sm.close()
    b = TaskGraphBuilder()
    b.add(BUMP, args=[0])
    iv, info = sm.run_stream(b)
    assert int(iv[0]) == expect
    ten = info["tenants"]
    assert ten["gold"]["completed"] == 10
    print(f"  gold completed {ten['gold']['completed']}; free saw "
          f"{verdicts} (admitted ones all completed: "
          f"{ten['free']['completed']})")


def part_two_wrr_fairness():
    """The WRR reference model (the executable spec of the in-kernel
    poll): 4:2:1 weights drain saturated lanes in exact proportion."""
    table = TenantTable(
        [TenantSpec("gold", weight=4), TenantSpec("silver", weight=2),
         TenantSpec("bronze")],
        16, clock=lambda: 0.0,
    )
    ring = np.zeros((3 * 16, 256), np.int32)
    for lane in range(3):
        for i in range(14):
            table.admit(lane, build_row(BUMP, [i]))
    tctl = table.pump(ring)
    for r in range(2):  # two WRR cycles
        wrr_poll_reference(ring, tctl, 16, r, 1 << 20)
    table.absorb(tctl)
    done = {t: s["completed"] for t, s in table.stats().items()}
    assert done == {"gold": 8, "silver": 4, "bronze": 2}
    print(f"  2 WRR cycles at weights 4:2:1 -> installs {done}")


def part_three_deadlines_and_poison():
    """Deadline admission + the poison ladder, with exact isolation."""
    # Deadlines: a dead-on-arrival submission is rejected on the spot.
    sm = StreamingMegakernel(
        _mk(), ring_capacity=96,
        tenants=[TenantSpec("slow", deadline_s=30.0), "steady"],
    )
    doa = sm.submit("slow", BUMP, args=[1], deadline_s=-0.001)
    assert doa.rejected and doa.reason == "expired"
    # Poison: a validator that always explodes climbs the ladder.
    def explode(row):
        raise RuntimeError("corrupt payload")

    sm2 = StreamingMegakernel(
        _mk(), ring_capacity=96,
        tenants=[
            TenantSpec("poison", validator=explode, poison_throttle=1,
                       poison_quarantine=2),
            TenantSpec("steady"),
        ],
    )
    for _ in range(4):
        sm2.submit("poison", BUMP, args=[999])
    expect = 0
    for k in range(8):
        sm2.submit("steady", BUMP, args=[10])
        expect += 10
    sm2.close()
    b = TaskGraphBuilder()
    b.add(BUMP, args=[0])
    iv, info = sm2.run_stream(b)
    assert int(iv[0]) == expect  # not one poisoned row executed
    ten = info["tenants"]
    assert ten["poison"]["quarantined"] == 1
    assert ten["steady"]["completed"] == 8
    print(f"  poison tenant quarantined "
          f"({ten['poison']['quarantine_reason']}); steady completed "
          f"{ten['steady']['completed']} exactly")


def part_four_survivability():
    """Checkpoint/resume with tenants live: per-tenant counts conserved
    across the cut (lesson 11's bundle machinery, per tenant)."""
    def fresh():
        return StreamingMegakernel(
            _mk(checkpoint=True), ring_capacity=96,
            tenants=["a", "b", "c"],
        )

    sm = fresh()
    subs = {"a": 8, "b": 5, "c": 3}
    expect = 0
    for i, (tid, n) in enumerate(subs.items()):
        for _ in range(n):
            sm.submit(tid, BUMP, args=[i + 1])
            expect += i + 1
    sm.quiesce(after_executed=4)  # preemption notice, checkpoint-at-4
    t0 = time.monotonic()
    _, info = sm.run_stream(TaskGraphBuilder())
    cut_ms = (time.monotonic() - t0) * 1e3
    assert info["quiesced"] is True
    residue = per_tenant_ring_counts(info["state"]["ring_rows"])
    sm2 = fresh()
    sm2.close()
    iv2, info2 = sm2.run_stream(resume_state=info["state"])
    assert int(iv2[0]) == expect
    for tid, n in subs.items():
        assert info2["tenants"][tid]["completed"] == n
    print(f"  cut at {info['executed']} tasks ({cut_ms:.0f} ms), "
          f"tenant-tagged residue {dict(sorted(residue.items()))}, "
          f"resumed to exact per-tenant totals {subs}")


if __name__ == "__main__":
    print("admission + quotas:")
    part_one_admission_and_quotas()
    print("WRR fairness:")
    part_two_wrr_fairness()
    print("deadlines + poison isolation:")
    part_three_deadlines_and_poison()
    print("survivability:")
    part_four_survivability()
    print("lesson 13 OK")
