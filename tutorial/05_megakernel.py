"""Lesson 5: the device path - a resident scheduler on the TPU core.

The megakernel is the reference's work-stealing worker loop re-imagined as
one long-running Pallas kernel: a SMEM task table + ready ring, kernel
dispatch by table index (``lax.switch``), dependency counters for DDF
wakeups, and descriptor/value-block recycling so bounded tables run
unbounded dynamic graphs. You describe work as task descriptors; the
device schedules them without returning to the host.

Runs in interpret mode on CPU; the same code compiles to a real kernel on
a TPU.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from hclib_tpu.device.descriptor import TaskGraphBuilder
from hclib_tpu.device.megakernel import Megakernel
from hclib_tpu.device.workloads import device_fib


def static_dag() -> None:
    """A 4-task diamond: A -> B0, B1 -> C, scheduled by dep counters."""

    def add_kernel(ctx) -> None:
        ctx.set_out(ctx.value(ctx.arg(0)) + ctx.value(ctx.arg(1)))

    mk = Megakernel(
        kernels=[("add", add_kernel)], capacity=16, num_values=16,
        succ_capacity=8, interpret=True,
    )
    b = TaskGraphBuilder()
    a = b.add(0, args=[0, 1], out=2)            # v2 = v0 + v1
    b0 = b.add(0, args=[2, 0], out=3, deps=[a])  # v3 = v2 + v0
    b1 = b.add(0, args=[2, 1], out=4, deps=[a])  # v4 = v2 + v1
    b.add(0, args=[3, 4], out=5, deps=[b0, b1])  # v5 = v3 + v4
    iv = np.zeros(16, np.int32)
    iv[0], iv[1] = 10, 20
    ivalues, _, info = mk.run(b, ivalues=iv)
    assert ivalues[5] == (30 + 10) + (30 + 20) == 90
    assert info["executed"] == 4
    print("static DAG: 4 tasks -> v5 =", int(ivalues[5]))


def dynamic_spawn() -> None:
    """fib(12) spawns its own task tree ON DEVICE - ~700 tasks through a
    64-row table (descriptor rows and value blocks recycle, so only the
    live set must fit)."""
    v, info = device_fib(12, capacity=64, interpret=True)
    assert v == 144
    print(
        f"dynamic fib(12): {info['executed']} device tasks, "
        f"table high-water {info['allocated']} rows"
    )


def main() -> None:
    static_dag()
    dynamic_spawn()


if __name__ == "__main__":
    main()
