"""Lesson 19: priority-bucketed dispatch - ordered work as raw speed.

Lessons 7 and 15 built the batch lanes: per-kind rings popped FIFO/LIFO.
That pop order was purely a performance lever - until now. A whole
workload class gets *asymptotically less work* from ORDERED retirement:

- **Delta-stepping SSSP.** Label correction (lesson 15) is exact under
  any order, but a bad order relaxes vertices at stale distances and
  re-expands them later. ``priority_buckets=B`` routes every EXPAND
  into bucket ring ``dist // delta`` and the scheduler retires the
  lowest non-empty bucket first - most relaxations then happen at
  FINAL distances, so the executed-EXPAND count (and TEPS) improves
  while the fixpoint stays bit-identical.
- **Bounded-frontier PageRank.** FIFO lanes make the push breadth-first
  and the live descriptor set balloons. Bucketing by residual magnitude
  (small deliveries first - they FOLD, freeing rows) collapses each
  subtree before the next large delivery splits: same exact ranks,
  far smaller peak live set (``info['allocated']``).
- **Branch-and-bound.** Best-first (highest optimistic bound first)
  finds a good incumbent early, so the bound test prunes subtrees an
  unordered run would explore. Here priority IS the speedup.

Three invariants to keep in mind (device/megakernel.py):

- Priorities are a HINT: every kernel must be schedule-independent,
  and ``describe()['schedule_independence']`` certifies the bucketed
  pop order itself (analysis/model.py runs it beside the random
  permutations).
- The bucket id is a pure function of the descriptor's own arg words
  (``BatchSpec.priority``), so spilled/stolen/resharded residue
  re-buckets on its next routing pop - checkpoint and steal invariants
  are untouched.
- The lesson-15 age-fire guard is reused verbatim: a high bucket
  starved behind a continuously refilled low bucket fires at
  ``lane_max_age`` - the one legal bucket-order inversion, counted in
  ``tiers['bucket_inversions']``.

``priority_buckets=0``/unset compiles none of this - byte-identical to
a build with no priorities at all.
"""

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from hclib_tpu.device.bnb import (  # noqa: E402
    host_knapsack_opt,
    make_knapsack,
    run_bnb,
)
from hclib_tpu.device.frontier import (  # noqa: E402
    Graph,
    host_pagerank_push,
    host_sssp,
    run_frontier,
)
from hclib_tpu.device.workloads import rmat_edges  # noqa: E402

n, src, dst, w = rmat_edges(5, efactor=6, seed=3)
g = Graph(n, src, dst, w)
print(f"graph: {g.n} vertices, {g.m} edges")

# One bucketed SSSP build shared by part one (the run) and part four
# (the certificate) - each distinct megakernel build is an XLA compile.
from hclib_tpu.device.frontier import (  # noqa: E402
    _KINDS,
    make_frontier_megakernel,
)

SSSP_BUCKETED = make_frontier_megakernel(
    _KINDS["sssp"](), g, width=4, interpret=True, priority_buckets=4,
)


def part_one_delta_stepping():
    """Ordered relaxation does less work than label correction - same
    bit-exact distances."""
    ref = host_sssp(g, 0)
    d_u, iu = run_frontier("sssp", g, 0, width=4, interpret=True)
    d_b, ib = run_frontier(
        "sssp", g, 0, mk=SSSP_BUCKETED, interpret=True,
    )
    assert np.array_equal(d_u, ref) and np.array_equal(d_b, ref)
    assert ib["executed"] <= iu["executed"]
    print(
        f"sssp: unordered executed {iu['executed']} EXPANDs, "
        f"delta-stepping {ib['executed']} "
        f"({ib['executed'] / iu['executed']:.2f}x) - identical distances"
    )


def part_two_bounded_pagerank():
    """Small residuals fold first: exact ranks, smaller peak live set."""
    m0, reps = 1 << 12, 64
    twin, _ = host_pagerank_push(g, m0=m0, reps=reps)
    r_u, pu = run_frontier(
        "pagerank", g, width=8, m0=m0, reps=reps, interpret=True,
        capacity=768,
    )
    r_b, pb = run_frontier(
        "pagerank", g, width=8, m0=m0, reps=reps, interpret=True,
        capacity=768, priority_buckets=4,
    )
    assert np.array_equal(r_u, twin) and np.array_equal(r_b, twin)
    print(
        f"pagerank: peak live rows {pu['allocated']} unordered -> "
        f"{pb['allocated']} bucketed (exact ranks both ways)"
    )


def part_three_branch_and_bound():
    """Best-first search: the proven optimum is order-free; the node
    count is not - that asymmetry is the whole feature."""
    kp = make_knapsack(11, seed=5)
    opt = host_knapsack_opt(kp)
    best_u, iu = run_bnb(kp, width=4, interpret=True)
    best_b, ib = run_bnb(kp, width=4, interpret=True, priority_buckets=8)
    assert best_u == best_b == opt
    assert ib["executed"] < iu["executed"]
    print(
        f"bnb: optimum {opt} proven by both arms; best-first expanded "
        f"{ib['executed']} nodes vs {iu['executed']} unordered "
        f"({ib['pruned']} vs {iu['pruned']} pruned)"
    )


def part_four_certificate():
    """The exactness gate: the bucketed pop order is certified
    schedule-independent at describe() time."""
    cert = SSSP_BUCKETED.describe()["schedule_independence"]
    assert cert["status"] == "certified", cert
    print(
        f"certificate: {cert['kind']} over {cert['orders']} pop orders "
        f"(incl. the bucketed one) -> {cert['status']}"
    )


if __name__ == "__main__":
    part_one_delta_stepping()
    part_two_bounded_pagerank()
    part_three_branch_and_bound()
    part_four_certificate()
    print("lesson 19 OK")
