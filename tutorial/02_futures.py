"""Lesson 2: data-driven futures.

``async_future`` spawns a task and returns a Future for its result;
``Promise`` is the single-assignment cell behind it. A task that waits on
a future does not block its worker: ready tasks run in its place
(help-first work-shifting), so dataflow graphs schedule themselves by
data availability - the reference's DDF model.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import hclib_tpu as hc


def main() -> None:
    result = {}

    def body() -> None:
        # A small dataflow diamond: c consumes a and b.
        fa = hc.async_future(lambda: 20)
        fb = hc.async_future(lambda: 22)

        def join():
            return fa.wait() + fb.wait()

        fc = hc.async_future(join)
        result["c"] = fc.wait()

        # Promises directly: producer/consumer decoupled from task results.
        p = hc.Promise()
        hc.async_(lambda: p.put("ready"))
        result["p"] = p.future.wait()

    hc.launch(body, nworkers=2)
    assert result["c"] == 42
    assert result["p"] == "ready"
    print("dataflow diamond ->", result["c"], "| promise ->", result["p"])


if __name__ == "__main__":
    main()
