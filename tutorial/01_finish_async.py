"""Lesson 1: structured task parallelism.

``launch`` brings up the runtime (worker threads, locality graph, modules)
and runs your root function as a task; ``async_`` spawns a child task;
``finish()`` is a scope that blocks until every task spawned inside it -
transitively - has completed. This is the reference's
finish/async model (a task may outlive its spawner, but never its
enclosing finish).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import hclib_tpu as hc


def fib(n: int, out: list, slot: int) -> None:
    if n < 2:
        out[slot] = n
        return
    part = [0, 0]
    with hc.finish():  # wait for BOTH children (and their subtrees)
        hc.async_(fib, n - 1, part, 0)
        hc.async_(fib, n - 2, part, 1)
    out[slot] = part[0] + part[1]


def main() -> None:
    out = [0]
    # nworkers=4: four work-stealing workers; stats=True prints per-worker
    # executed/spawned/steal counters at exit.
    hc.launch(lambda: fib(16, out, 0), nworkers=4, stats=True)
    assert out[0] == 987, out[0]
    print("fib(16) =", out[0], "computed by a tree of dynamic tasks")


if __name__ == "__main__":
    main()
