"""Lesson 15: graph analytics - frontier expansion on the batch lanes.

UTS (lesson 11) proved dynamic trees; this lesson traverses a GRAPH: an
adjacency kept in HBM, walked by dynamically-spawned EXPAND tasks
(device/frontier.py). Three ideas:

- **Blocked CSR.** Every vertex's edge run pads to 128-edge blocks, so
  one EXPAND descriptor names one block and its edge slab is a STATIC
  DMA shape. A hub vertex (the R-MAT skew) is simply many same-kind
  descriptors - skew becomes batch occupancy, not a ragged transfer.
- **The frontier IS a batch lane.** Every EXPAND of one traversal is
  the same kernel kind, so each round's frontier groups onto one batch
  lane and fires ``width`` at a time through ONE tiled body, with the
  double-buffered prefetch streaming the next batch's edge slabs under
  the current batch's relax loop. Relaxation is monotone label
  correction (BFS/SSSP) or exact mass routing (push PageRank), so the
  RESULT is independent of schedule, batching, and migration - the
  bit-identity across arms is by construction.
- **The age-triggered firing policy.** Frontier expansion keeps the
  ready ring hot (every batch deposits a fan-out of children), which
  starves lanes under the old ring-drain-first rule. The ISSUE 10 fix:
  ``Megakernel(lane_max_age=N)`` / ``HCLIB_TPU_LANE_MAX_AGE`` lets a
  lane that held entries for N rounds jump the ring and fire - frontier
  builds default it to ``4 * width``. Watch ``tiers['age_fires']`` and
  the bounded ``tiers['max_starved_age']`` gauge.

The headline metric is TEPS (traversed edges/s): ``info['edges']``
counts every edge each EXPAND relaxed - ``bench.py --graph`` reports it
beside the UTS nodes/s number.
"""

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
# The mesh part wants 4 virtual devices; harmless if already set wider.
os.environ.setdefault(
    "XLA_FLAGS", "--xla_force_host_platform_device_count=4"
)

import numpy as np  # noqa: E402

from hclib_tpu.device.frontier import (  # noqa: E402
    Graph,
    host_bfs,
    host_pagerank_push,
    host_sssp,
    run_frontier,
)
from hclib_tpu.device.workloads import rmat_edges  # noqa: E402
from hclib_tpu.runtime.locality import MeshPlacement  # noqa: E402

# A seeded R-MAT-style graph: skewed degrees, deterministic from the seed.
n, src, dst, w = rmat_edges(5, efactor=6, seed=3)
g = Graph(n, src, dst, w)
print(f"graph: {g.n} vertices, {g.m} edges, max degree {int(g.deg.max())}")


def part_one_bfs_two_arms():
    """Scalar dispatch vs the batched frontier: bit-identical distances."""
    ref = host_bfs(g, 0)
    d_scalar, _ = run_frontier("bfs", g, 0, width=0, interpret=True)
    d_batch, info = run_frontier("bfs", g, 0, width=4, interpret=True)
    assert np.array_equal(d_scalar, ref) and np.array_equal(d_batch, ref)
    t = info["tiers"]
    print(
        f"bfs: {info['edges']} edges traversed, occupancy "
        f"{t['batch_occupancy']:.2f}, {t['prefetch_hits']} prefetch hits, "
        f"{t['age_fires']} age fires (max starved age "
        f"{t['max_starved_age']} <= lane_max_age)"
    )


def part_two_sssp_and_pagerank():
    """Weighted SSSP (exact) and push PageRank (exact integer twin)."""
    d, _ = run_frontier("sssp", g, 0, width=4, interpret=True)
    assert np.array_equal(d, host_sssp(g, 0))
    m0, reps = 1 << 12, 64
    twin, _ = host_pagerank_push(g, m0=m0, reps=reps)
    r, info = run_frontier(
        "pagerank", g, width=8, m0=m0, reps=reps, interpret=True,
        capacity=768,
    )
    assert np.array_equal(r, twin)
    assert twin.sum() == g.n * m0  # mass conserves exactly
    print(f"sssp exact; pagerank: {info['relaxations']} deliveries, "
          f"mass conserved ({g.n * m0} units)")


def part_three_mesh():
    """4-device mesh: seeds placed by descriptor, dynamic EXPANDs spread
    by stealing, per-device distance caches min-combine - still exact."""
    d, info = run_frontier(
        "bfs", g, 0, width=4, interpret=True, capacity=256,
        placement=MeshPlacement(4, policy="single", device=0),
        quantum=2, window=4,
    )
    assert np.array_equal(d, host_bfs(g, 0))
    from hclib_tpu.device.megakernel import C_EXECUTED

    per_dev = np.asarray(info["per_device_counts"])[:, C_EXECUTED]
    print(f"mesh bfs exact from skewed seeds; per-device executed "
          f"{per_dev.tolist()} (stealing spread the frontier)")


if __name__ == "__main__":
    part_one_bfs_two_arms()
    part_two_sssp_and_pagerank()
    part_three_mesh()
    print("lesson 15 OK")
