"""Lesson 24: the dynamic graph service - mutable adjacency, served.

Lesson 15's frontier tier traversed a FROZEN blocked-CSR graph: the
adjacency was data, never mutated. The dynamic-graph subsystem
(device/dyngraph.py) makes it mutable WHILE traversals run:

- **Spare blocks**: every vertex's blocked-CSR rows are followed by
  ``spare_blocks`` pristine edge blocks; an in-kernel UPDATE(u, v, w)
  splices the new edge into u's tail block (or claims a fresh spare
  off the per-vertex cursor) with a single-writer DMA, then relaxes v
  with u's CURRENT label - no rebuild, no host round trip.
- **Incremental recompute**: because bfs/sssp label correction is
  monotone, the post-storm fixpoint is BIT-IDENTICAL to a from-scratch
  run on the mutated graph (``host_dyngraph``), for EVERY interleaving
  of updates and expansions - ``host_incremental`` is the pure-python
  twin that replays any permutation, and the certifier
  (``certify_claim``) sweeps K of them.
- **Serving**: ``serve_dyngraph`` runs the storm through lesson 13's
  multi-tenant front door - updates and queries submit as Futures,
  query results come back through the egress mailbox, and the splice
  count rides the flight recorder as a TR_SPLICE record.
- **Lint**: hclint's ``check_splice`` proves at build time that every
  routed lane runs prefetch-off (a splice can land between slab fetch
  and use) and that blind DMA stores only ever target spare rows.

Off path: importing dyngraph lowers ZERO new device words into static
frontier builds (tests/test_dyngraph.py pins the lowered text hash).
Env knobs: ``HCLIB_TPU_DYNGRAPH_SPARE_BLOCKS``,
``HCLIB_TPU_DYNGRAPH_UPDATE_PRIORITY`` (see ``runtime/env.py``).
"""

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from hclib_tpu.analysis import certify_claim, check_splice  # noqa: E402
from hclib_tpu.device.dyngraph import (  # noqa: E402
    DynGraph,
    host_dyngraph,
    host_incremental,
    make_dyngraph_megakernel,
    run_dyngraph,
    serve_dyngraph,
)
from hclib_tpu.device.tracebuf import TR_SPLICE, records_of  # noqa: E402
from hclib_tpu.device.workloads import rmat_edges  # noqa: E402

N, SRC, DST, W = rmat_edges(5, efactor=4, seed=9)
UPDATES = [(1, 5, 3), (2, 7, 1), (0, 9, 2), (4, 3, 6)]


def _graph(**kw):
    kw.setdefault("spare_blocks", 2)
    kw.setdefault("upd_cap", 16)
    return DynGraph(N, SRC, DST, W, **kw)


def part_one_update_storm_is_exact():
    """An UPDATE storm races an SSSP traversal; the fixpoint lands
    bit-identical to recomputing the mutated graph from scratch - and
    the pure-python twin agrees under a shuffled interleaving."""
    g = _graph()
    res, info = run_dyngraph(
        "sssp", g, 0, updates=UPDATES, queries=[0, 5, 9], width=0,
        interpret=True,
    )
    ref = host_dyngraph("sssp", g)  # from-scratch, mutated adjacency
    assert np.array_equal(res, ref)
    assert info["updates_applied"] == len(UPDATES)
    assert info["dropped"] == 0
    assert info["queries"] == 3
    # Any permutation of the op pool converges to the same fixpoint
    # (monotone label correction) - here, updates FIRST.
    order = list(range(1, 1 + len(UPDATES))) + [0]
    assert np.array_equal(host_incremental("sssp", g, 0, order=order), ref)
    print(f"  {info['updates_applied']} splices ({info['spare_in_use']} "
          f"spare blocks claimed), {info['queries']} queries, "
          f"{info['edges']} edges relaxed - bit-identical to the "
          "from-scratch mutated-graph run, under reordering too")


def part_two_served_multi_tenant():
    """The same storm through the streaming front door: per-request
    Futures, query results via the egress mailbox, the splice tally on
    the flight recorder."""
    g = _graph()
    res, info = serve_dyngraph(
        "sssp", g, src=0, updates=UPDATES, queries=[0, 5, 9], width=0,
        interpret=True, ring_capacity=64, egress_depth=32,
        max_rounds=512,
    )
    assert np.array_equal(res, host_dyngraph("sssp", g))
    assert all(f.state == "RESULT" for f in info["update_futures"])
    assert all(f.state == "RESULT" for f in info["query_futures"])
    # Served queries drained AFTER the fixpoint: exact, not tentative.
    assert info["query_results"] == info["query_values"]
    assert info["query_results"][0] == 0  # dist(src, src)
    egress = info["serve_stats"]["egress"]
    assert egress["resolved"] == egress["submitted"]
    r = records_of(info["splice_trace"], TR_SPLICE)
    applied, dropped = int(r[0, 2]) >> 16, int(r[0, 2]) & 0xFFFF
    assert (applied, dropped) == (len(UPDATES), 0)
    print(f"  {egress['resolved']}/{egress['submitted']} futures "
          f"resolved through the egress mailbox; TR_SPLICE says "
          f"{applied} applied / {dropped} dropped; exact query "
          f"results {info['query_results']}")


def part_three_lint_and_certification():
    """Build-time: check_splice proves the prefetch/spare-row protocol.
    Post-run: certify_claim replays the registered update stream under
    K permutations against the from-scratch reference."""
    g = _graph()
    mk = make_dyngraph_megakernel(
        "sssp", g, width=4, capacity=256, interpret=True,
    )
    assert not check_splice(mk).errors()
    cert0 = certify_claim(mk)
    assert cert0["status"].startswith("unbound")  # no stream bound yet
    run_dyngraph("sssp", g, 0, updates=UPDATES[:2], mk=mk,
                 interpret=True)
    cert = certify_claim(mk)
    assert cert["status"] == "certified", cert
    assert cert["updates"] == 2 and cert["orders"] >= 4
    print(f"  check_splice clean; schedule-independence certified "
          f"over {cert['orders']} interleavings of {cert['updates']} "
          "updates + seed expansion")


if __name__ == "__main__":
    part_one_update_storm_is_exact()
    part_two_served_multi_tenant()
    part_three_lint_and_certification()
    print("lesson 24 OK")
