"""Lesson 18: the whole-program concurrency model checker (hclint v2).

Lesson 16's verifier proves PER-BODY properties (slot disjointness,
prefetch pairing, the layout table). Nothing there speaks about
LIVENESS: a wait cycle, a credit wedge, or a quiesce that exports one
thing while the poll consumes another all still fail only at runtime -
as a ``StallError``, or by wedging a mesh. Before the completion-promise
serving loop lands (ROADMAP direction 1: ``TenantTable.submit()``
returning a ``Future`` satisfied by an on-device flag write), the
analysis package grows three whole-program analyses - all host-only,
zero Pallas builds, compiled programs byte-identical verify-on-vs-off:

1. **Wait-graph deadlock detection** (``analysis/waits.py``). The new
   on-device promise ops - ``ctx.satisfy(slot)`` (one flag write) and
   ``ctx.wait_value(slot)`` (a bounded in-body spin) - are recorded by
   the same shim pass that classifies kinds, and construction proves
   the per-kind waits-on graph cycle-free or refuses with the cycle's
   kind chain.
2. **Bounded interleaving exploration** (``analysis/explore.py``). The
   WRR inject poll (via ``wrr_poll_reference`` - the executable spec
   itself), the steal-credit exchange, and the quiesce freeze explored
   over EVERY schedule of a small seeded configuration: termination,
   conservation, and freeze-exactness checked at each terminal state,
   with the violating action prefix as witness.
3. **Schedule-independence certification** (``analysis/model.py``).
   Kernels that CLAIM order-independence (frontier BFS/SSSP/PageRank,
   forasync tiles) run their abstract body to the fixpoint under K
   permuted pop orders; identical states certify (surfaced in
   ``Megakernel.describe()``), divergent ones are refused with the two
   schedules shown.
"""

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from jax.experimental import pallas as pl  # noqa: E402

from hclib_tpu.analysis import (  # noqa: E402
    AnalysisError, CreditExchangeModel, certify_frontier_schedule,
    explore,
)
from hclib_tpu.device.descriptor import TaskGraphBuilder  # noqa: E402
from hclib_tpu.device.frontier import (  # noqa: E402
    INF, FrontierKernel, _spawn_blocks,
)
from hclib_tpu.device.megakernel import Megakernel  # noqa: E402

# ---- 1. a wait cycle is caught AT CONSTRUCTION -------------------------


def kind_a(ctx):
    ctx.wait_value(5)   # spin on the flag only kind_b writes ...
    ctx.satisfy(6)


def kind_b(ctx):
    ctx.wait_value(6)   # ... which spins on the flag only kind_a writes
    ctx.satisfy(5)


try:
    Megakernel(kernels=[("a", kind_a), ("b", kind_b)], capacity=32,
               num_values=16, succ_capacity=8, interpret=True,
               verify=True)
    raise SystemExit("the deadlock went unnoticed!")
except AnalysisError as e:
    print("wait cycle refused:", str(e).splitlines()[1].strip()[:72])

# The acyclic handshake builds AND runs: the satisfier fires first
# (LIFO owner-side pops), the waiter's bounded spin observes the flag.
mk = Megakernel(
    kernels=[("sat", lambda ctx: ctx.satisfy(5, v=7)),
             ("wait", lambda ctx: ctx.set_value(0, ctx.wait_value(5)))],
    capacity=32, num_values=16, succ_capacity=8, interpret=True,
    verify=True,
)
b = TaskGraphBuilder()
b.add(1)
b.add(0)
iv, _, _ = mk.run(b)
assert int(iv[0]) == 7
print("acyclic promise handshake: built, gated, ran ->", int(iv[0]))

# ---- 2. the explorer finds the credit wedge ----------------------------

# Seeded fault: the victim's first grant DROPS its credit (the
# DeviceFaultPlan fault) and regeneration is off - the thief's owed
# wait can never fire. Some interleaving wedges; the explorer finds it
# and hands back the exact action prefix.
res = explore(CreditExchangeModel((3, 0), drop_credit=0, regen=False,
                                  max_steals=2))
assert res.violations
print("credit wedge found:", res.violations[0].message[:60], "...")
print("  interleaving:", list(res.violations[0].witness)[:4], "...")

# The shipped recovery (credit regeneration) explores clean on EVERY
# schedule - that is the difference between a test and a proof-shaped
# sweep of the bounded configuration.
assert explore(CreditExchangeModel((3, 0), drop_credit=0, regen=True,
                                   max_steals=2)).clean
print("with regeneration: every schedule terminates + conserves")

# ---- 3. schedule-independence certificates -----------------------------

cert = certify_frontier_schedule("bfs")
print("bfs certificate:", cert["status"],
      f"({cert['orders']} permuted orders, {cert['tasks']} tasks)")
assert cert["status"] == "certified"


# A visit-order labeling (DFS-vs-BFS numbering) is genuinely order-
# dependent - certification is REFUSED with both schedules shown.
def visit_order_relax(fk, kctx, u, w, carry):
    st = fk.st_base + u
    first = kctx.ivalues[st] == INF

    @pl.when(first)
    def _():
        n = kctx.ivalues[1] + 1
        kctx.ivalues[1] = n
        kctx.ivalues[st] = n
        _spawn_blocks(kctx, u, 0)


try:
    certify_frontier_schedule("bfs", fk=FrontierKernel(
        "fr_visit", visit_order_relax, weighted=False, state0=INF))
    raise SystemExit("order dependence went unnoticed!")
except AnalysisError as e:
    print("visit-order labeling refused:",
          "two schedules in the witness:", "schedule_a" in str(e))

print("lesson 18 OK")
