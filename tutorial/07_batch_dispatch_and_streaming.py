"""Lesson 7: the batch-dispatch vector tier and streaming task injection.

Two round-2 capabilities of the megakernel:

1. **Batch dispatch** - a recursive, reduction-shaped task family
   (fib, n-queens, tree searches) declared as a ``VectorTaskSpec`` runs
   its whole subtree wide over VPU lanes: per-lane tail-call DFS stacks,
   and *lane-level work stealing* - starved lanes claim a donor lane's
   bottom stack frame under a rotating ring permutation. One seed
   descriptor in the scalar table fans out to thousands of tasks per
   vector step (~0.5 ns/task on v5e vs ~126 ns on the scalar tier).

2. **Streaming injection** - a resident scheduler's task supply can be
   open-ended: the host appends descriptors to an HBM ring that the
   kernel polls mid-run (write rows, then publish the tail - the
   release/acquire contract), so work can arrive while earlier work
   executes (the reference's analogue is an active message materializing
   a task on a running PE).

Runs on the CPU backend in interpret mode; identical code drives the TPU.
"""

import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from hclib_tpu.device.descriptor import TaskGraphBuilder
from hclib_tpu.device.inject import StreamingMegakernel
from hclib_tpu.device.megakernel import Megakernel
from hclib_tpu.device.vector_engine import fib_spec, nqueens_spec


def fib(n):
    a, b = 0, 1
    for _ in range(n):
        a, b = b, a + b
    return a


# -- 1. batch dispatch: one seed descriptor, a whole subtree over lanes --

# A kernel-table entry can BE a VectorTaskSpec: the scalar scheduler pops
# the seed task and dispatches the entire recursion tree across lanes.
mk = Megakernel(
    kernels=[
        ("vfib", fib_spec(max_n=14, lanes=(1, 8))),
        ("vnqueens", nqueens_spec(5, lanes=(1, 8))),
    ],
    capacity=16, num_values=8, succ_capacity=8, interpret=True,
)
b = TaskGraphBuilder()
b.add(0, args=[12], out=0)  # fib(12) - 465 tasks
b.add(1, args=[0], out=1)   # 5-queens - 10 solutions
b.reserve_values(2)
ivalues, _, info = mk.run(b)
assert int(ivalues[0]) == fib(12), ivalues[0]
assert int(ivalues[1]) == 10, ivalues[1]
print(f"batch dispatch: fib(12)={int(ivalues[0])}, 5-queens={int(ivalues[1])}, "
      f"{info['executed']} tasks through 2 seed descriptors")

# -- 2. streaming injection: the host feeds a running scheduler ---------

BUMP = 0


def bump(ctx):
    ctx.set_value(0, ctx.value(0) + ctx.arg(0))


sm = StreamingMegakernel(
    Megakernel(kernels=[("bump", bump)], capacity=64, num_values=4,
               succ_capacity=8, interpret=True),
    ring_capacity=64,
)
seed = TaskGraphBuilder()
seed.add(BUMP, args=[1000])


def feeder():
    for i in range(6):
        sm.inject(BUMP, args=[i + 1])  # thread-safe, any time
        time.sleep(0.002)
    sm.close()  # no more work: the stream drains and returns


t = threading.Thread(target=feeder)
t.start()
iv, sinfo = sm.run_stream(seed)
t.join()
assert int(iv[0]) == 1000 + 6 * 7 // 2, iv[0]
print(f"streaming: {sinfo['executed']} tasks total, "
      f"{sinfo['injected']} injected while the scheduler ran")

print("lesson 7 OK")
