"""Lesson 23: the live telemetry plane - histograms, scrape, SLO burn.

Lesson 20's serving loop measured submit->result latency HOST-side
(Future wall stamps) and surfaced device counters only after a run
exited. The telemetry plane (device/telemetry.py) moves the
measurement ON-DEVICE and makes it scrapeable MID-RUN:

- **Lifecycle stamps**: the host pump stamps each ring row's
  TEN_ADMIT_ROUND transport word with the round counter it last saw;
  the kernel stamps install and fire rounds per row (retire == fire -
  dispatch and completion are atomic within one inner round), and the
  egress publish carries the span back (EGR_T_ADMIT / EGR_T_SPANS).
- **On-device histograms**: every tracked retirement bumps one log2
  bucket of (retire - admit) in a per-tenant histogram row of the
  ``tele`` block, which rides the ctl-echo discipline - so every entry
  boundary re-exports it and a ``TelemetryPoller`` thread snapshots a
  LIVE stream without stopping it.
- **Units**: everything device-side is in scheduler ROUNDS (there is
  no device wall clock); the host converts rounds->ns with the
  ``EpochBracket`` wall bracket around each entry.
- **SLO engine**: ``runtime/slo.py`` turns cumulative histogram
  snapshots into streaming quantiles and multi-window burn rates; the
  autoscaler policy grows a typed ``slo_out`` rung that fires BEFORE
  the deadline watchdog when the error budget drains.

Off path: telemetry unset compiles ZERO new device words - the
lowered text is byte-identical (tests/test_telemetry.py pins it).
Env spelling for wrapper scripts: ``HCLIB_TPU_TELEMETRY=1`` plus the
SLO knobs (see ``runtime/env.py`` registry).
"""

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

import hclib_tpu as hc  # noqa: E402
from hclib_tpu.device.descriptor import TaskGraphBuilder  # noqa: E402
from hclib_tpu.device.egress import EgressSpec  # noqa: E402
from hclib_tpu.device.inject import StreamingMegakernel  # noqa: E402
from hclib_tpu.device.megakernel import Megakernel  # noqa: E402
from hclib_tpu.device.telemetry import (  # noqa: E402
    LAT_BUCKETS,
    TelemetryBlock,
    TelemetryPoller,
    bucket_of,
)
from hclib_tpu.device.tenants import TenantSpec, TenantTable  # noqa: E402
from hclib_tpu.runtime.slo import SloEstimator  # noqa: E402

BUMP = 0


def _mk():
    def bump(ctx):
        ctx.set_value(0, ctx.value(0) + ctx.arg(0))

    return Megakernel(
        kernels=[("bump", bump)], capacity=256, num_values=8,
        succ_capacity=8, interpret=True,
    )


def _stream(region=32, depth=64):
    table = TenantTable(
        [TenantSpec("gold", weight=2, queue_capacity=64),
         TenantSpec("std", queue_capacity=64)],
        region, egress=EgressSpec(depth=depth),
    )
    return StreamingMegakernel(
        _mk(), ring_capacity=64, tenants=table, telemetry=True,
    )


def part_one_on_device_histograms():
    """Submit through two tenants; the device folds every tracked
    retirement into a per-tenant log2 histogram, and the per-row
    stamps reconcile with it exactly."""
    sm = _stream()
    for i in range(9):
        assert sm.submit("gold" if i % 3 else "std", BUMP, args=[1])
    sm.close()
    b = TaskGraphBuilder()
    b.add(BUMP, args=[100])
    sm.run_stream(b)
    snap = sm.telemetry_snapshot()
    blk = TelemetryBlock(snap["tele"], snap.get("ns_per_round"))
    g = blk.gauges()
    assert g["retires"] == blk.total() == 9, g
    # The spans are the histogram's witnesses: refolding the per-row
    # (fire - admit) deltas reproduces the device's bucket counts.
    spans = sm.telemetry_spans()
    assert len(spans) == 9
    refold = np.zeros(LAT_BUCKETS, np.int64)
    for admit, install, fire in spans.values():
        assert admit <= install <= fire
        refold[bucket_of(fire - admit)] += 1
    assert np.array_equal(refold, blk.hist()), (refold, blk.hist())
    p50, p99 = blk.quantile(0.5), blk.quantile(0.99)
    npr = snap.get("ns_per_round")
    print(f"  9 retirements across 2 tenant histograms "
          f"(gold {blk.total(0)}, std {blk.total(1)}); p50 <= {p50:.0f} "
          f"rounds, p99 <= {p99:.0f} rounds, "
          f"~{npr / 1e3 if npr else 0:.0f}us/round - spans refold "
          "bit-exactly")


def part_two_midrun_scrape():
    """A TelemetryPoller thread snapshots the echoed block while the
    stream RUNS; the scrape feeds the Prometheus exposition."""
    sm = _stream()
    for i in range(40):
        assert sm.submit(i % 2, BUMP, args=[1])
    sm.close()
    poller = TelemetryPoller(sm.telemetry_snapshot,
                             interval_s=0.002).start()
    b = TaskGraphBuilder()
    b.add(BUMP, args=[100])
    # A small per-entry round budget: the stream re-enters the kernel
    # many times, and every entry boundary re-exports the echo blocks
    # the poller is watching.
    sm.run_stream(b, max_rounds=8)
    poller.stop(final_poll=True)  # never miss the final state
    seqs = [s["seq"] for s in poller.snapshots]
    assert seqs and seqs == sorted(seqs), seqs
    totals = [int(np.asarray(s["tele"])[1:].sum())
              for s in poller.snapshots]
    assert totals == sorted(totals) and totals[-1] == 40, totals
    # The scrape is what a dashboard sees: cumulative bucket counts
    # per tenant in Prometheus text form (tools/metrics_serve.py
    # serves this over HTTP from a stdlib http.server).
    reg = hc.MetricsRegistry()
    reg.record_latency(poller.latest_block())
    text = reg.to_prometheus()
    assert "hclib_latency_bucket" in text and 'le="+Inf"' in text
    lines = [ln for ln in text.splitlines() if "latency" in ln]
    print(f"  {len(poller.snapshots)} mid-run snapshots, monotone "
          f"({totals[0]} -> {totals[-1]} retirements); "
          f"{len(lines)} Prometheus latency lines")


def part_three_slo_burn():
    """Histogram deltas -> streaming burn rates -> a typed slo_out
    scale-out, fired before any deadline has expired."""
    est = SloEstimator(objective_rounds=64, quantile=0.99,
                       windows_s=(5.0, 30.0))
    counts, t = np.zeros(LAT_BUCKETS, np.int64), 0.0
    for phase, (lo, hi) in enumerate([(4, 32), (256, 4096)]):
        for _ in range(6):
            for d in np.random.default_rng(int(t)).integers(
                    lo, hi, size=16):
                counts[bucket_of(int(d))] += 1
            t += 1.0
            est.observe(counts.copy(), t)
        if phase == 0:
            assert est.latency_pressure(t) < 2.0
    pressure = est.latency_pressure(t)
    assert pressure >= 2.0, est.stats()
    policy = hc.AutoscalerPolicy(
        min_devices=1, max_devices=8, scale_out_backlog=1e9,
        scale_in_backlog=4.0, hysteresis=2, cooldown=3, slo_burn=2.0,
    )
    obs = hc.Observation(2, [4, 4], executed_delta=8, slice_s=1.0,
                         latency_pressure=pressure)
    target, kind, reason = policy.decide(obs)
    assert kind == "slo_out" and target == 4, (kind, reason)
    print(f"  tail walked past the 64-round objective: burn "
          f"{pressure:.1f}x budget -> '{kind}' 2->4 ({reason[:40]}...)")


if __name__ == "__main__":
    part_one_on_device_histograms()
    part_two_midrun_scrape()
    part_three_slo_burn()
    print("lesson 23 OK")
