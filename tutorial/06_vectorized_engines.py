"""Lesson 6: throughput engines and multi-device scheduling.

Task-per-node scheduling has a per-task floor (~100 ns even on-device).
When the workload is regular enough, the TPU-first answer is to vectorize
the *algorithm* across VPU lanes instead: thousands of lanes each run an
independent traversal, balanced through a shared work queue - the
work-stealing idea recast as data-parallel claims. And for multi-device,
per-device megakernel queues exchange surplus tasks over the ICI ring
between bulk-synchronous rounds.

Uses a virtual 8-device CPU mesh (env set below); on real hardware the
same code runs over the chips of a slice.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np

import jax


def vectorized_uts() -> None:
    """Exact UTS tree count, thousands of DFS lanes + shared root queue."""
    from hclib_tpu.device.uts_vec import NLANES, uts_vec
    from hclib_tpu.models.uts import T3, count_seq

    r = uts_vec(T3, target_roots=64, device=jax.devices("cpu")[0])
    want_nodes, want_leaves, want_depth = count_seq(T3)
    assert (r["nodes"], r["leaves"], r["max_depth"]) == (
        want_nodes, want_leaves, want_depth,
    )
    print(f"UTS T3: {r['nodes']} nodes counted exactly by {NLANES} lanes")


def fused_smith_waterman() -> None:
    """Batched alignment scores from the fused Pallas row sweep."""
    from hclib_tpu.device.sw_pallas import sw_scores_pallas
    from hclib_tpu.models.smithwaterman import random_seq, sw_seq

    B = 4
    A = np.stack([random_seq(96, i) for i in range(B)])
    Bs = np.stack([random_seq(128, 100 + i) for i in range(B)])
    got = sw_scores_pallas(A, Bs, interpret=True)
    want = [int(sw_seq(A[i], Bs[i]).max()) for i in range(B)]
    assert list(got) == want
    print("Smith-Waterman scores", list(got), "match the sequential DP")


def sharded_megakernel() -> None:
    """Per-device task queues + bulk-synchronous stealing over the ring."""
    from hclib_tpu.device.descriptor import TaskGraphBuilder
    from hclib_tpu.device.megakernel import Megakernel
    from hclib_tpu.device.sharded import ShardedMegakernel
    from hclib_tpu.parallel.mesh import cpu_mesh

    def bump(ctx):
        ctx.set_value(0, ctx.value(0) + ctx.arg(0))

    mesh = cpu_mesh(8, axis_name="queues")
    mk = Megakernel(kernels=[("bump", bump)], capacity=64, num_values=8,
                    succ_capacity=8, interpret=True)
    smk = ShardedMegakernel(mk, mesh, migratable_fns=[0])
    builders = [TaskGraphBuilder() for _ in range(8)]
    for _ in range(32):  # all work starts on device 0...
        builders[0].add(0, args=[1])
    iv, _, info = smk.run(builders, steal=True, quantum=4, window=8)
    assert info["pending"] == 0 and int(iv[:, 0].sum()) == 32
    spread = int((iv[:, 0] > 0).sum())
    print(f"sharded megakernel: 32 tasks stole across {spread} devices in "
          f"{info['steal_rounds']} rounds")


def main() -> None:
    vectorized_uts()
    fused_smith_waterman()
    sharded_megakernel()


if __name__ == "__main__":
    main()
