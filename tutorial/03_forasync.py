"""Lesson 3: parallel loops and reducers.

``forasync`` runs a body over an index space, chunked into tile tasks.
FLAT mode makes one task per tile up front; RECURSIVE splits the range
in half until tiles are small (better locality + load balance for
irregular bodies). Reducers give race-free accumulation: each worker
accumulates privately and the values merge at the end.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import hclib_tpu as hc


def main() -> None:
    n = 10_000
    data = list(range(n))
    out = [0] * n

    def body() -> None:
        hc.forasync(lambda i: out.__setitem__(i, data[i] * 2), (n,))

        # 2D iteration space + an explicit mode and tile size.
        grid = [[0] * 8 for _ in range(8)]
        hc.forasync(
            lambda i, j: grid[i].__setitem__(j, i * 8 + j),
            (8, 8),
            mode=hc.RECURSIVE,
            tile=(2, 2),
        )
        assert grid[7][7] == 63

        # Worker-local reduction (the reference's atomic_sum_t): each
        # worker accumulates privately; gather() merges at read time.
        total = hc.SumReducer(0)
        hc.forasync(lambda i: total.add(i), (1000,))
        assert total.gather() == 499500

    hc.launch(body, nworkers=4)
    assert out[: 5] == [0, 2, 4, 6, 8] and out[-1] == 2 * (n - 1)
    print("forasync doubled", n, "elements; reduced sum(0..999) = 499500")


if __name__ == "__main__":
    main()
