"""Lesson 9: the unified resident kernel - migration, atomics, locks.

Lesson 8 showed per-device schedulers stealing *independent* tasks. The
unified resident kernel (`device/resident.py`) is the full composition -
the device-side analogue of the reference's one-scheduler-many-modules
architecture (reference inc/hclib-module.h:79-97): ONE kernel per device
that steals, puts, runs active messages, applies remote atomics, grants
locks, and polls an injection ring in the same round loop. Two pieces are
new in this lesson:

1. **Migration of dependency-bearing tasks.** The reference thief takes
   ANY task from a victim's deque - finish scopes and dependency edges
   included (reference src/hclib-deque.c:75-106) - because shared memory
   makes its pointers valid anywhere. On a TPU mesh, successor links are
   device-local row indices, so migration is re-designed as a *home-link
   protocol*: an exported row leaves a proxy at home (links intact) and
   ships a copy naming the proxy; whoever ends the remote continuation
   chain sends the result home in a remote-completion active message,
   which fires the proxy's successors exactly as if the task had run at
   home. A skewed recursive fib graph - every task carrying successor
   links - therefore rebalances across the mesh with exact results.

2. **Remote atomics and locks.** Owner-computes over the active-message
   path: fetch-add / compare-swap are applied by the slot's owner (the
   per-device scheduler is serial, so owner-side application IS the
   atomicity), with replies that wake parked continuation rows; a FIFO
   lock grants parked rows in arrival order (the reference SHMEM layer's
   AMO + lock surface, modules/openshmem/src/hclib_openshmem.cpp).

Runs on the CPU backend (Mosaic interpret mode emulates remote DMA +
semaphores); identical code compiles for a real slice.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()


from hclib_tpu.device.descriptor import TaskGraphBuilder
from hclib_tpu.device.megakernel import Megakernel, VBLOCK
from hclib_tpu.device.resident import ResidentKernel, lock_block_slots
from hclib_tpu.device.workloads import FIB, SUM, make_fib_megakernel
from hclib_tpu.models.fib import fib_seq, task_count
from hclib_tpu.parallel.mesh import cpu_mesh

# -- 1. dependency-bearing tasks migrate ---------------------------------
#
# Device 0 seeds fib(8): every FIB task spawns two children and a SUM
# continuation wired by real dependency edges. migratable_fns marks both
# kernels exportable; SUM's args 0 and 1 are value-slot references, which
# the export path dereferences (they are final - the row was ready) and
# rehydrates into thief-local slots on arrival.

ndev, n = 2, 8
capacity = 96
mk = make_fib_megakernel(
    capacity=capacity,
    interpret=True,
    # migration reserves one result slot per row at the top of the value
    # buffer: row-owned blocks + host slots + result slots
    num_values=VBLOCK * capacity + 16 + capacity,
)
rk = ResidentKernel(
    mk, cpu_mesh(ndev, axis_name="q"),
    migratable_fns={FIB: (), SUM: (0, 1)},
    window=8, am_window=8,
)
builders = [TaskGraphBuilder() for _ in range(ndev)]
builders[0].add(FIB, args=[n], out=0)
iv, _, info = rk.run(builders, quantum=8)

t = task_count(n)
expect_exec = t + (t - 1) // 2  # FIB nodes + one SUM per internal node
assert info["pending"] == 0
assert int(iv[:, 0].sum()) == fib_seq(n), iv[:, 0]
assert info["executed"] == expect_exec
per_dev = info["per_device_counts"][:, 5]
assert all(c > 0 for c in per_dev), per_dev  # both devices really worked
print(f"fib({n}) = {fib_seq(n)}: {expect_exec} dependency-bearing tasks "
      f"rebalanced as {list(per_dev)} across {ndev} devices")

# -- 2. remote atomics and a distributed lock ----------------------------
#
# Every device fetch-adds into device 0's slot 5 (owner-computes: exact
# sum), and bumps a counter under a FIFO lock on device 0 (the lock
# serializes the critical-section tasks; each runs only when granted).

FADD, LOCKER, CSECT = 0, 1, 2
LBASE, SLOT, CX = 16, 5, 8
qcap = ndev


def fadd_kernel(ctx):
    ctx.pgas.fadd(0, SLOT, 1 + ctx.pgas.me)  # fire-and-forget


def locker(ctx):
    row = ctx.spawn(CSECT, dep_count=1)  # parked until the lock grants it
    ctx.pgas.lock(0, LBASE, row, qcap)


def csect(ctx):
    ctx.pgas.fadd(0, CX, 1)
    ctx.pgas.unlock(0, LBASE, qcap)


amk = Megakernel(
    kernels=[("fadd", fadd_kernel), ("locker", locker), ("csect", csect)],
    capacity=64, num_values=64, succ_capacity=8, interpret=True,
)
ark = ResidentKernel(amk, cpu_mesh(ndev, axis_name="q"), steal=False)
builders = [TaskGraphBuilder() for _ in range(ndev)]
for d in range(ndev):
    builders[d].add(FADD)
    builders[d].add(LOCKER)
    # the lock block lives in the owner's value slots; declare the zero
    # presets so staging covers them
    builders[d].reserve_values(LBASE + lock_block_slots(qcap))
iv, _, info = ark.run(builders, quantum=8)
assert info["pending"] == 0
assert int(iv[0, SLOT]) == sum(1 + d for d in range(ndev)), iv[0, SLOT]
assert int(iv[0, CX]) == ndev  # every critical section ran exactly once
assert int(iv[0, LBASE]) == 0  # lock ends released
print(f"remote fetch-adds summed exactly ({int(iv[0, SLOT])}); "
      f"{ndev} lock-protected critical sections serialized")

print("lesson 09 OK")
