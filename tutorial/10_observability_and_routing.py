"""Lesson 10: observability and the auto-routed fast path.

Three production-facing features close the tour:

1. **Tracing and reports.** The runtime records per-worker START/END task
   events into binary double-buffered logs (the reference's instrument
   framework, but LIVE - the reference's recorder is stubbed,
   reference src/hclib-instrument.c:211-252), and exposes worker counters
   incl. the steal matrix as a dict. ``tools/timeline.py`` renders both:
   a density timeline (one row per worker, shade = busy fraction) and a
   load/steal report - the analogue of the reference's tools/timeline.py
   station.

2. **Auto-routing to the batch-dispatch tier.** A recursive,
   reduction-shaped task family (lesson 7) can be named in
   ``Megakernel(auto_route=...)``: tasks of that kernel NAME then run as
   whole subtrees across the VPU lanes instead of one ~100 ns descriptor
   at a time, while the rest of the DAG stays on the scalar tier -
   dependencies, value slots, and counts all behave identically.

3. **The device flight recorder.** ``Megakernel(trace=N)`` compiles a
   fixed-width trace ring into the scheduler's round loop
   (device/tracebuf.py): every dispatch is a record, the host brackets
   the launch with its wall clock, and ``tools/timeline.py --perfetto``
   merges host events + device rounds into one zoomable timeline.
"""

import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import hclib_tpu as hc


def part_one_tracing(tmpdir: str) -> None:
    rt = hc.Runtime(nworkers=4, instrument=True)

    def body():
        with hc.finish():
            for _ in range(60):
                hc.async_(lambda: time.sleep(0.0005))

    rt.run(body)
    dump = rt.event_log.dump(tmpdir)
    stats = rt.stats_dict()

    sys.path.insert(0, os.path.join(_REPO, "tools"))
    import timeline

    text = timeline.render_dump(dump)
    assert "per-worker timeline" in text and "task" in text
    print(text.split("\n\n")[1])  # the timeline block
    report = timeline.render_stats(stats)
    assert "executed=" in report
    print(report)
    executed = sum(w["executed"] for w in stats["workers"])
    assert executed >= 61, executed
    print(f"traced {executed} tasks across {stats['nworkers']} workers\n")


def part_two_auto_route() -> None:
    from hclib_tpu.device.descriptor import TaskGraphBuilder
    from hclib_tpu.device.megakernel import Megakernel
    from hclib_tpu.device.vector_engine import fib_spec
    from hclib_tpu.device.workloads import _fib_kernel, _sum_kernel

    def report(ctx):
        ctx.set_value(1, ctx.value(0) * 10)

    mk = Megakernel(
        kernels=[
            ("fib", _fib_kernel),   # the scalar semantic definition
            ("sum", _sum_kernel),
            ("report", report),
        ],
        # Route the 'fib' FAMILY to the vector tier: its whole recursion
        # tree expands across the lanes from one descriptor.
        auto_route={"fib": fib_spec(max_n=16, lanes=(1, 8))},
        capacity=32,
        num_values=16,
        succ_capacity=16,
        interpret=True,
    )
    b = TaskGraphBuilder()
    t0 = b.add(0, args=[14], out=0)     # routed: 1219-node subtree
    b.add(2, deps=[t0])                 # scalar successor reads its out
    b.reserve_values(2)
    iv, _, info = mk.run(b)
    assert iv[0] == 377 and iv[1] == 3770
    assert info["executed"] > 1000      # the tree, not 2 descriptors
    assert info["allocated"] == 2       # ...from just 2 descriptor rows
    print(
        f"auto-routed fib(14): {info['executed']} tasks expanded on the "
        f"vector tier from {info['allocated']} descriptors; "
        f"result {iv[0]}, scalar successor saw {iv[1]}"
    )


def part_three_flight_recorder(tmpdir: str) -> None:
    from hclib_tpu.device.descriptor import TaskGraphBuilder
    from hclib_tpu.device.tracebuf import TR_FIRE_SCALAR, records_of
    from hclib_tpu.device.workloads import FIB, make_fib_megakernel

    # trace=256: a 256-record ring rides out of the kernel; every
    # scheduler round appends records from INSIDE the device loop.
    mk = make_fib_megakernel(256, interpret=True, trace=256)
    b = TaskGraphBuilder()
    b.add(FIB, args=[10], out=0)
    iv, _, info = mk.run(b)
    assert int(iv[0]) == 55
    ring = info["trace"]["rings"][0]
    fires = records_of(info["trace"], TR_FIRE_SCALAR)
    # Overflow is counted, never fatal: the ring keeps the LAST records.
    print(
        f"flight recorder: {ring['written']} records written "
        f"({ring['dropped']} dropped past the {ring['capacity']}-record "
        f"ring), {len(fires)} scalar dispatch fires kept"
    )
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    import timeline

    out = os.path.join(tmpdir, "lesson10.perfetto.json")
    doc = timeline.export_perfetto(out, traces=[info["trace"]])
    assert len(doc["traceEvents"]) > 0
    print(
        f"perfetto: {len(doc['traceEvents'])} events -> {out} "
        "(open at https://ui.perfetto.dev)\n"
    )


def main() -> None:
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        part_one_tracing(d)
        part_three_flight_recorder(d)
    part_two_auto_route()
    print("lesson 10 OK")


if __name__ == "__main__":
    main()
