"""Lesson 22: the program cache - killing the compile tax.

Every lesson so far paid the full JAX trace -> lower -> compile
pipeline the first time its megakernel ran, even when a byte-identical
program was built by the previous cell. That tax is the whole price of
a serving cold start and of an autoscaler resize onto a shape this
process ever built before. `runtime/progcache.py` is a process-wide
registry of JITTED EXECUTABLES keyed on a content fingerprint of
everything that shapes the program:

- the kernel table positionally PLUS each body's code fingerprint
  (bytecode, consts, closure cell values - arrays hash by content);
- routed BatchSpecs, buffer shapes, and every device-word knob
  (checkpoint, quiesce_stride, lane_max_age, priority_buckets, trace);
- the runner's static variant (mesh shape + device order + hop order,
  steal windows, quantum, injection-ring/tenant/egress shape);
- the hclint layout-table fingerprint, so ANY device-word ABI drift
  invalidates the whole cache.

A hit hands the new instance the very callable a cache-off build would
have produced: `jax.jit` tracing is lazy and cached per-callable, so a
content-identical second instance's FIRST run does zero trace/lower
work. The cache changes WHEN a program is built, never WHAT - lowered
text is byte-identical by construction, which is why it defaults ON
(`HCLIB_TPU_PROGRAM_CACHE=0` forces off, CAP bounds the LRU).
"""

import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from hclib_tpu.device.descriptor import TaskGraphBuilder  # noqa: E402
from hclib_tpu.device.megakernel import Megakernel  # noqa: E402
from hclib_tpu.runtime import progcache  # noqa: E402


def _mark_mk(bump=0):
    """One tiny kernel; ``bump`` rides a closure cell, so bump=1 is a
    DIFFERENT program by content even though the code object matches."""

    def mark(ctx):
        ctx.set_value(ctx.arg(1), ctx.arg(0) + bump)

    return Megakernel(
        kernels=[("mark", mark)], capacity=64, num_values=24,
        succ_capacity=8, interpret=True,
    )


def _run(mk, n=16):
    b = TaskGraphBuilder()
    for i in range(n):
        b.add(0, args=[i + 1, i + 1])
    t0 = time.perf_counter()
    iv, _, info = mk.run(b)
    return time.perf_counter() - t0, np.asarray(iv).tobytes(), info


def part_one_cold_vs_warm():
    """A content-identical second instance's first run is a cache hit:
    same bytes, a fraction of the wall."""
    progcache.reset()
    cold_s, cold_bytes, info = _run(_mark_mk())
    assert info["program_cache"]["hit"] is False
    warm_s, warm_bytes, info = _run(_mark_mk())  # a FRESH instance
    assert info["program_cache"]["hit"] is True
    assert info["program_cache"]["build_s"] == 0.0
    assert warm_bytes == cold_bytes, "a hit is bit-identical"
    s = progcache.cache_stats()
    assert (s["hits"], s["misses"], s["entries"]) == (1, 1, 1)
    print(f"  cold first run {cold_s*1e3:7.1f}ms, warm first run "
          f"{warm_s*1e3:6.1f}ms ({cold_s/warm_s:.0f}x) - "
          "zero trace/lower work on the hit")


def part_two_content_is_the_key():
    """Change anything that shapes the program - a closure constant, a
    knob - and the key provably misses; runtime facts do not key."""
    progcache.reset()
    _run(_mark_mk())
    _, _, info = _run(_mark_mk(bump=1))  # closure cell differs
    assert info["program_cache"]["hit"] is False, "bump=1 is new content"
    fp0 = progcache.megakernel_fingerprint(_mark_mk())
    for kw in ({"checkpoint": True}, {"trace": 4096},
               {"quiesce_stride": 4}):
        mk = Megakernel(
            kernels=[("mark", _mark_mk().kernel_fns[0])], capacity=64,
            num_values=24, succ_capacity=8, interpret=True, **kw,
        )
        assert progcache.megakernel_fingerprint(mk) != fp0, kw
    print("  closure constants, knobs, layout drift: all miss; "
          "per-run words (fuel, quiesce, tctl) never key")


def part_three_off_switch_and_cap():
    """The off switch proves byte-identity; the LRU cap proves an
    eviction is only ever a rebuild."""
    progcache.reset()
    _, on_bytes, _ = _run(_mark_mk())
    before = progcache.cache_stats()
    os.environ["HCLIB_TPU_PROGRAM_CACHE"] = "0"
    try:
        _, off_bytes, info = _run(_mark_mk())
        assert info["program_cache"]["hit"] is False
        assert off_bytes == on_bytes, "cache off = same bytes, just slower"
        assert progcache.cache_stats() == before, "off moves no counters"
    finally:
        del os.environ["HCLIB_TPU_PROGRAM_CACHE"]
    os.environ["HCLIB_TPU_PROGRAM_CACHE_CAP"] = "1"
    try:
        progcache.reset()
        _, first, _ = _run(_mark_mk())
        _run(_mark_mk(bump=1))           # second program evicts the first
        assert progcache.cache_stats()["evictions"] >= 1
        _, again, info = _run(_mark_mk())
        assert info["program_cache"]["hit"] is False  # honest rebuild
        assert again == first, "post-eviction rebuild is bit-identical"
    finally:
        del os.environ["HCLIB_TPU_PROGRAM_CACHE_CAP"]
    print("  off-switch bytes == on-switch bytes; cap=1 evicts, "
          "rebuild bit-identical (counters: "
          f"{progcache.cache_stats()})")


if __name__ == "__main__":
    try:
        part_one_cold_vs_warm()
        part_two_content_is_the_key()
        part_three_off_switch_and_cap()
    finally:
        progcache.reset()
    print("lesson 22 OK")
