"""Lesson 4: the module layer - distributed primitives on rank worlds.

Modules plug new capabilities into the runtime (the reference's dlopen'd
module system, redesigned as registered Python classes). The comm modules
give you a "rank world" - one rank per mesh device - with MPI-style
two-sided messaging, SHMEM-style one-sided puts/gets/atomics on a
symmetric heap, and active messages that run a function at another rank.
Everything here runs single-host over a virtual device mesh; the same
code spans real chips when the mesh does.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
# Virtual 8-device CPU mesh so the rank world has devices to live on
# (must be set before jax initializes).
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np

import hclib_tpu as hc
from hclib_tpu.modules import CommModule, OneSidedModule, async_remote, symm_array
from hclib_tpu.modules import comm as C
from hclib_tpu.modules import oneside as O
from hclib_tpu.parallel.mesh import cpu_mesh, mesh_locality_graph


def two_sided() -> None:
    def body():
        out = []
        with hc.finish():
            hc.async_(lambda: C.send(np.arange(4), dst=1, tag=7))
            hc.async_(lambda: out.append(C.recv(tag=7, rank=1)))
        np.testing.assert_array_equal(np.asarray(out[0]), np.arange(4))
        # Nonblocking variants return futures.
        fut = C.irecv(tag=1, rank=0)
        C.isend("hello", dst=0, tag=1)
        assert fut.wait() == "hello"

    hc.register_module(CommModule())
    hc.launch(body, locality_graph=mesh_locality_graph(cpu_mesh(2), nworkers=3))
    hc.unregister_all_modules()  # registrations are global: clean between runs
    print("two-sided: send/recv + isend/irecv futures OK")


def one_sided() -> None:
    def body():
        heap = symm_array(4, np.int32)  # one copy per rank
        O.put(heap, rank=1, value=7, index=2)
        assert O.get(heap, rank=1, index=2) == 7
        assert O.get(heap, rank=0, index=2) == 0  # distinct copies
        assert O.fetch_add(heap, rank=0, delta=5) == 0
        # Signal-driven task: fires when rank 0's flag becomes 42.
        flag = symm_array(1, np.int32)
        fut = O.async_when(flag, "eq", 42, rank=0, index=0)
        hc.async_(lambda: O.put(flag, rank=0, value=42, index=0))
        fut.wait()

    hc.register_module(OneSidedModule())
    hc.launch(body, locality_graph=mesh_locality_graph(cpu_mesh(2), nworkers=3))
    hc.unregister_all_modules()
    print("one-sided: symmetric heap put/get/AMO + wait-set OK")


def active_messages() -> None:
    def body():
        y = 40
        assert async_remote(lambda x: x + y, 0, 2).wait() == 42

    hc.register_module(OneSidedModule())
    hc.launch(body, locality_graph=mesh_locality_graph(cpu_mesh(2), nworkers=3))
    hc.unregister_all_modules()
    print("active message ran at rank 0 ->", 42)


def main() -> None:
    two_sided()
    one_sided()
    active_messages()


if __name__ == "__main__":
    main()
