"""Lesson 17: mesh-wide tenancy + tenant/deadline-aware autoscaling.

Lesson 13 gave ONE device a multi-tenant front door; lesson 12 gave the
mesh an autoscaler that watched raw backlog LEVELS. This lesson closes
both residuals as one elasticity story (ISSUE 13):

- **Mesh-wide TenantTable** (``MeshTenantTable``, device/tenants.py):
  the same tenant roster spans every device of a resident mesh - each
  device's injection ring is partitioned into the same per-tenant
  regions (one tctl echo block per device; the in-kernel WRR poll is
  lesson 13's, unchanged, per device), and ``submit()`` ROUTES each
  admission to a device by placement/backlog while the typed Admission
  ladder stays verbatim. Rate quotas are mesh-wide; the poison ladder
  and deadline budget are enforced on AGGREGATE counts, so a tenant
  cannot evade isolation by spreading failures across devices.

- **Deadline survival**: a checkpoint cut exports each residue row's
  REMAINING deadline budget (``TEN_DEADLINE_MS``, a transport word on
  the row itself) and resume re-arms it - the old "residue resumes
  deadline-free" caveat is gone.

- **Tenant/deadline-aware autoscaling**: the policy now reads live
  per-slice rate DELTAS (a backlog rising while the executed rate is
  flat scales out before the level threshold trips) and per-tenant
  deadline-budget drain: a tenant burning >= ``tenant_pressure`` of
  its budget in one slice triggers an immediate typed ``deadline_out``
  scale-out - no hysteresis, no cooldown - so the controller beats the
  watchdog's strike ladder (budget exhaustion cancels the lane).
  Scale-in NEVER strands a tenant: while any lane has in-flight ring
  residue the decision is a typed ``strand_hold``.

Everything below runs on the numpy WRR reference model (the executable
spec of the in-kernel poll), so the lesson is exact and fast with no
TPU and no Mosaic interpret; ``ResidentKernel(tenants=...)`` +
``run(tenant_table=...)`` is the compiled spelling of the same
machinery.
"""

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from hclib_tpu.device.descriptor import RING_ROW  # noqa: E402
from hclib_tpu.device.tenants import (  # noqa: E402
    MeshTenantTable,
    TenantSpec,
    wrr_poll_reference,
)
from hclib_tpu.runtime.autoscaler import (  # noqa: E402
    AutoscalerPolicy,
    Observation,
)

BUMP = 0
REGION = 16

# A deterministic clock: every admission/deadline decision becomes a
# pure function of the script.
t_now = [100.0]
clock = lambda: t_now[0]  # noqa: E731


def drive(table, rings, polls=2, start=0):
    """One mesh entry: pump every device's lanes, run ``polls`` WRR
    reference rounds per device, absorb the echo."""
    tctl = table.pump(rings)
    for r in range(start, start + polls):
        for d in range(table.ndev):
            wrr_poll_reference(rings[d], tctl[d], REGION, r, 1 << 20)
    table.absorb(tctl)


# ---------------------------------------------------------------- 1
# Routing: the least-backlogged replica of a tenant's lane wins.
print("== mesh-wide admission routing ==")
specs = [
    TenantSpec("gold", weight=2, queue_capacity=64,
               deadline_budget=20),
    TenantSpec("std", queue_capacity=64),
]
table = MeshTenantTable(specs, ndev=4, region_rows=REGION, clock=clock)
rings = np.zeros((4, 2 * REGION, RING_ROW), np.int32)
routed = [table.submit("gold", BUMP, args=[i]).device for i in range(8)]
print("gold admissions routed to devices:", routed)
assert routed == [0, 1, 2, 3, 0, 1, 2, 3]  # backlog-balanced
pinned = table.submit("gold", BUMP, args=[9], device=2)
assert pinned and pinned.device == 2

# ---------------------------------------------------------------- 2
# The WRR poll per device is lesson 13's poll, unchanged: weight
# proportion holds on every device of the mesh.
for d in range(4):
    for i in range(8):
        assert table.submit("gold", BUMP, args=[i], device=d)
        if i < 4:
            assert table.submit("std", BUMP, args=[i], device=d)
drive(table, rings, polls=4)
snap = table.stats()
print("completed after 4 WRR rounds:",
      {t: s["completed"] for t, s in snap.items()})
# gold (w=2) installs exactly twice std's rows per cycle, mesh-wide.
assert snap["gold"]["completed"] == 2 * snap["std"]["completed"] > 0

# ---------------------------------------------------------------- 3
# A live reshard cut 4 -> 2: export (deadline-stamped, tenant-tagged
# residue + aggregate counter blocks), resume on the smaller mesh -
# per-tenant counts conserved exactly.
print("== live reshard cut 4 -> 2 ==")
for i in range(6):
    assert table.submit("std", BUMP, args=[i], deadline_s=30.0)
accepted_before = {t: s["accepted"] for t, s in table.stats().items()}
table2, state = table.reshard(rings, 2)
rings2 = np.zeros((2, 2 * REGION, RING_ROW), np.int32)
t_now[0] += 1.0  # the 30 s budgets re-arm with ~29 s left
for r in range(32):
    drive(table2, rings2, polls=2, start=r)
    if table2.drained():
        break
assert table2.drained()
for tid, s in table2.stats().items():
    assert s["accepted"] == accepted_before[tid]
    assert s["accepted"] == s["completed"] + s["expired"] + s["dropped"]
    print(f"  {tid}: accepted {s['accepted']} == completed "
          f"{s['completed']} + expired {s['expired']} + dropped "
          f"{s['dropped']}  (conserved across the cut)")

# ---------------------------------------------------------------- 4
# Deadline-pressure autoscaling: a storm drains the gold budget; the
# policy fires a typed deadline_out BEFORE the lane's budget exhausts
# (the watchdog rung), even mid-cooldown.
print("== tenant/deadline-aware policy ==")
policy = AutoscalerPolicy(min_devices=1, max_devices=8,
                          scale_out_backlog=1e9, scale_in_backlog=4.0,
                          hysteresis=2, cooldown=3, tenant_pressure=0.25)
policy._cooling = 3  # prove the pressure path does not wait it out
ndev = 2
obs0 = Observation(ndev, [4] * ndev, executed_delta=8, slice_s=1.0,
                   tenants=table2.pressure())
print("slice 0:", policy.decide(obs0)[1:])
# The storm: 8 doomed gold rows expire inside one slice (8/20 = 40%).
for i in range(8):
    assert table2.submit("gold", BUMP, args=[i], deadline_s=0.01)
t_now[0] += 1.0
table2.absorb(table2.pump(rings2))
obs1 = Observation(ndev, [4] * ndev, executed_delta=8, slice_s=1.0,
                   tenants=table2.pressure())
target, kind, reason = policy.decide(obs1)
print(f"slice 1: {kind} -> {target} devices ({reason})")
assert kind == "deadline_out" and target == 2 * ndev
assert table2.stats()["gold"]["expired"] < 20  # budget NOT exhausted:
# the controller beat the watchdog's strike ladder to the punch.

# ---------------------------------------------------------------- 5
# Strand refusal: idle backlog but in-flight ring residue -> the
# scale-in decision is a typed strand_hold until the residue drains.
ndev = target
policy._cooling = 0
assert table2.submit("gold", BUMP, args=[0], deadline_s=1e6)
table2.absorb(table2.pump(rings2))  # published, not yet consumed
busy = Observation(ndev, [0] * ndev, tenants=table2.pressure())
kinds = [policy.decide(busy)[1] for _ in range(2)]
print("idle-with-residue decisions:", kinds)
assert kinds == ["hold", "strand_hold"]
drive(table2, rings2, polls=2, start=100)  # drain the straggler
done = Observation(ndev, [0] * ndev, tenants=table2.pressure())
target, kind, _ = policy.decide(done)
print(f"drained decision: {kind} -> {target} devices")
assert kind == "scale_in" and target == ndev // 2

print("lesson 17 OK: mesh-wide tenancy + tenant-aware elasticity")
