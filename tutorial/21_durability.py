"""Lesson 21: durable checkpoints - the crash-safe generational store.

Lesson 8 saved ONE `CheckpointBundle` to ONE directory. That is enough
for a demo and exactly wrong for production: a preemption can land
mid-save (a torn artifact is now your only copy), disks flip bits, and
an operator wants to roll back a bad generation without archaeology.
This lesson is `runtime/checkpoint.BundleStore` - the durability layer
the autoscaler's preempt rung writes through.

- **Crash-safe publish**: `store.save(bundle)` stages the whole
  `gen-NNNNNN` directory under a temp name, fsyncs, and publishes with
  a single atomic rename; the `CURRENT` pointer moves LAST. A crash at
  ANY instant leaves either the old store or the new one - never a
  half-written generation. The ordering is model-checked:
  `analysis/explore.py`'s `BundleStoreModel` explores every
  save x crash x concurrent-load interleaving and proves no schedule
  exposes a partial generation (and catches the planted
  publish-before-manifest bug if you flip the ordering).
- **Self-healing restore**: `load_latest()` walks generations
  newest-first, validates each (magic, version, kernel table, sha256
  of the npz), and QUARANTINES anything torn or corrupt into
  `root/quarantine/` with a typed `BundleFault` - then resumes from
  the newest generation that validates. Only a store with NO valid
  generation raises, naming every fault, so outstanding serving
  futures poison through the degradation ladder instead of wedging.
- **Bounded retention**: `keep=K` (default 3, `HCLIB_TPU_CKPT_KEEP`)
  prunes the oldest generations at publish; the store never grows
  without bound.
- **Reshard with pending waits**: exported wait tables now RE-HOME
  across mesh sizes - needs are rebased to arrivals-since-entry at
  export, so `reshard(M)` re-deals parked rows with their wait entries
  re-pointed, conserving wait counts and per-channel need sums. The
  one refusal left: a wait whose *satisfier* sits in unexported host
  residue (`meta['host_residue']`).

Env spelling for wrapper scripts: `HCLIB_TPU_CKPT_DIR` (roots
`hc.default_store()`), `HCLIB_TPU_CKPT_KEEP`, `HCLIB_TPU_CKPT_FSYNC=0`
(trade durability for publish latency, e.g. under a test harness).
`tools/chaos_soak.py --durability` soaks the whole crash-point matrix.
"""

import os
import shutil
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from hclib_tpu.device.descriptor import (  # noqa: E402
    DESC_WORDS,
    F_DEP,
    F_FN,
    F_HOME,
    NO_TASK,
)
from hclib_tpu.runtime.checkpoint import (  # noqa: E402
    BundleStore,
    CheckpointBundle,
    CheckpointError,
)
from hclib_tpu.runtime.metrics import MetricsRegistry  # noqa: E402


def _bundle(seed, ndev=4, cap=8, live=2, parked=(), residue=None):
    """A hand-built resident bundle (same shape the mesh exports):
    ``live`` ready link-free rows per device plus optional wait-parked
    rows - each ``parked`` triple (device, channel, need) parks a row
    carrying one dep bump with its entry in the exported wait table."""
    tasks = np.zeros((ndev, cap, DESC_WORDS), np.int32)
    tasks[:, :, 2:4] = NO_TASK
    tasks[:, :, F_HOME] = NO_TASK
    ready = np.full((ndev, cap), NO_TASK, np.int32)
    counts = np.zeros((ndev, 8), np.int32)
    waits = np.zeros((ndev, 5, 3), np.int32)
    for d in range(ndev):
        for i in range(live):
            tasks[d, i, F_FN] = 1
            ready[d, i] = i
        npk = 0
        for (pd, ch, need) in parked:
            if pd != d:
                continue
            slot = live + npk
            tasks[d, slot, F_FN] = 2
            tasks[d, slot, F_DEP] = 1
            w = int(waits[d, 0, 0])
            waits[d, 1 + w] = (ch, need, slot)
            waits[d, 0, 0] = w + 1
            npk += 1
        counts[d, 1] = live
        counts[d, 2] = counts[d, 3] = live + npk
        counts[d, 4] = 2
    meta = {"ndev": ndev, "channels": ["left", "right"]}
    if residue:
        meta["host_residue"] = dict(residue)
    rng = np.random.default_rng(seed)
    return CheckpointBundle("resident", meta, {
        "tasks": tasks,
        "succ": np.full((ndev, 8), -1, np.int32),
        "ready": ready, "counts": counts,
        "ivalues": rng.integers(0, 1 << 20, (ndev, 16)).astype(np.int32),
        "waits": waits,
    })


def part_one_generations(root):
    """Publish is atomic; retention is bounded; reload is exact."""
    reg = MetricsRegistry()
    store = BundleStore(root, keep=3, fsync=False, metrics=reg)
    bundles = [_bundle(seed=i) for i in range(5)]
    for b in bundles:
        store.save(b)
    assert store.generations() == [3, 4, 5], "keep=3 pruned gens 1-2"
    back = store.load_latest()
    assert back.generation == 5
    assert back.diff(bundles[-1])["equal"], "bit-identical reload"
    m = reg.snapshot()["metrics"]
    assert m["checkpoint.save.count"] == 5
    print(f"  5 saves -> generations {store.generations()} (keep=3), "
          f"load_latest() == newest save bit-exactly")


def part_two_self_healing(root):
    """Corrupt the newest generation on disk; the store heals itself."""
    npz = os.path.join(root, "gen-%06d" % 5, "state.npz")
    blob = open(npz, "rb").read()
    with open(npz, "wb") as f:  # one flipped bit, mid-payload
        f.write(blob[:64] + bytes([blob[64] ^ 0x10]) + blob[65:])
    healer = BundleStore(root, keep=3, fsync=False)
    back = healer.load_latest()
    assert back.generation == 4, "fell back to the newest VALID gen"
    (fault,) = healer.faults
    assert fault.generation == 5 and fault.reason == "corrupt"
    assert os.path.isdir(fault.path) and "quarantine" in fault.path
    assert healer.generations() == [3, 4], "bad gen moved aside"
    print(f"  flipped one bit in gen 5: quarantined as "
          f"{fault.reason!r}, resumed from gen {back.generation}")
    return back


def part_three_unrecoverable(root):
    """A store with NO valid generation raises - poison, don't wedge."""
    for g in BundleStore(root, fsync=False).generations():
        os.remove(os.path.join(root, "gen-%06d" % g, "manifest.json"))
    try:
        BundleStore(root, fsync=False).load_latest()
    except CheckpointError as e:
        assert "unrecoverable" in str(e) and "poison" in str(e)
        print("  all manifests gone: load_latest raises the poison "
              "diagnostic (futures fail fast through the ladder)")
    else:
        raise AssertionError("expected CheckpointError")


def part_four_reshard_waits():
    """Pending waits re-home across mesh sizes; only satisfier-in-
    residue refuses - with one whole-program diagnostic."""
    parked = [(0, 0, 3), (1, 1, 2), (2, 0, 1), (3, 1, 4)]
    b = _bundle(seed=9, parked=parked)

    def needs(waits):
        acc = {}
        for d in range(waits.shape[0]):
            for i in range(int(waits[d, 0, 0])):
                ch, need, _ = (int(x) for x in waits[d, 1 + i])
                acc[ch] = acc.get(ch, 0) + need
        return acc

    want = needs(b.arrays["waits"])
    for m in (2, 8):
        out = b.reshard(m)
        w = np.asarray(out.arrays["waits"])
        assert int(w[:, 0, 0].sum()) == len(parked)
        assert needs(w) == want, "per-channel need sums conserved"
    bad = _bundle(seed=9, parked=parked, residue={"left": 2})
    try:
        bad.reshard(2)
    except CheckpointError as e:
        assert "host residue" in str(e) and "'left'" in str(e)
        print(f"  4 waits re-home onto 2 and 8 devices (needs {want} "
              f"conserved); satisfier-in-residue refuses by name")
    else:
        raise AssertionError("expected the residue refusal")


if __name__ == "__main__":
    root = tempfile.mkdtemp(prefix="hclib-lesson21-")
    try:
        part_one_generations(root)
        part_two_self_healing(root)
        part_three_unrecoverable(root)
        part_four_reshard_waits()
    finally:
        shutil.rmtree(root, ignore_errors=True)
    print("lesson 21 OK")
