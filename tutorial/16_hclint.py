"""Lesson 16: hclint - the build-time program verifier.

Every earlier lesson leaned on contracts that live only in docstrings:
batch slots "remain responsible for writing disjoint data" (lesson 7),
forasync tiles must store disjoint output windows (lesson 14), a
prefetch body "MUST issue exactly the starts the tier announces", and
reshard moves link-free rows only (lesson 11). Violations surface at
runtime as corrupt buffers - or NEVER: interpret mode serializes DMAs,
so a real slab race can still land the right bytes on CPU and corrupt
on hardware.

``hclib_tpu.analysis`` checks those contracts when the program is
BUILT. ``Megakernel(verify=True)`` (or ``HCLIB_TPU_VERIFY=1``;
default-on under pytest) runs four host-only analyses over the
assembled Python objects - no Pallas build, no Mosaic, byte-identical
compiled programs either way:

1. **Batch-slot race detection.** Kernel bodies are plain Python
   emitting device code, so the verifier RUNS each routed batch body
   once over a synthetic slot-distinct batch with recording fake
   buffers, then proves the recorded store windows pairwise disjoint.
   For forasync TileKernels with known bounds it goes further and
   proves disjointness over the whole concrete tile space - the
   witness is the two colliding tile coordinates.
2. **Prefetch-protocol conformance.** The same recorded trace must
   match every DMA start with a wait, and the residual (prefetch)
   starts must be exactly what ``drain`` retires.
3. **Word-layout consistency.** One table of shared ABI words
   (descriptor fields, ring-row transport words, counter rows)
   cross-checked against every module that hard-codes them.
4. **Reshard classification.** Each kernel kind classes link-free vs
   home-linked from what its body does (spawns with successors?
   continuation transfer?); ``describe()`` surfaces it and checkpoint
   bundles carry it so ``reshard`` can name every offending kind
   upfront.

``tools/hclint.py`` runs the same checks over every in-repo builder
from the CLI (CI gates on it, next to tools/lint.py).
"""

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.experimental import pallas as pl  # noqa: E402

from hclib_tpu.analysis import (  # noqa: E402
    AnalysisError, check_layout, check_tile_windows,
)
from hclib_tpu.device.forasync_tier import (  # noqa: E402
    Slab, TileKernel, make_forasync_megakernel, run_forasync_device,
)
from hclib_tpu.device.workloads import make_fib_megakernel  # noqa: E402

import numpy as np  # noqa: E402

N, TS = 64, 8
SPECS = {
    "x": jax.ShapeDtypeStruct((N,), jnp.int32),
    "y": jax.ShapeDtypeStruct((N,), jnp.int32),
}

# ---- 1. a clean tile loop builds and runs, verified --------------------

good = TileKernel(
    loads=[Slab("xin", "x", lambda a: (pl.ds(a[1], TS),), (TS,))],
    stores=[Slab("yout", "y", lambda a: (pl.ds(a[1], TS),), (TS,))],
    compute=lambda ins: {"yout": ins["xin"] * 3 + 7},
    data_specs=SPECS,
)
mk = make_forasync_megakernel(good, width=4, interpret=True, verify=True)
assert mk.verify and mk.analysis is not None
assert mk.analysis.errors() == []
x = np.arange(N, dtype=np.int32)
out, _ = run_forasync_device(
    good, [N], [TS], {"x": x, "y": np.zeros(N, np.int32)},
    width=4, mk=mk,
)
assert (out["y"] == x * 3 + 7).all()
print("clean tile loop: verified at build, correct at run")

# ---- 2. a planted batch-slot race is caught AT BUILD TIME --------------

# The classic copy-paste bug: the store index ignores the tile's
# descriptor args, so every tile writes window [0, TS).
racy = TileKernel(
    loads=[Slab("xin", "x", lambda a: (pl.ds(a[1], TS),), (TS,))],
    stores=[Slab("yout", "y", lambda a: (pl.ds(0, TS),), (TS,))],
    compute=lambda ins: {"yout": ins["xin"]},
    data_specs=SPECS,
)
try:
    make_forasync_megakernel(racy, width=4, interpret=True, verify=True)
    raise SystemExit("the race went unnoticed!")
except AnalysisError as e:
    print("caught at construction:",
          str(e).splitlines()[1].strip()[:72], "...")

# The bounds-aware spelling gives the concrete colliding tiles:
rep = check_tile_windows(racy, [N], [TS])
w = rep.findings[0].witness
print(f"colliding tiles: {w['tile_a']} vs {w['tile_b']} "
      f"both store {w['window_a']} of 'y'")
assert rep.findings[0].rule == "tile-race"

# ---- 3. layout table + classification ----------------------------------

assert check_layout(force=True).findings == []
fib = make_fib_megakernel(128, interpret=True)
kinds = fib.describe()["kinds"]
assert kinds["fib"]["classification"] == "home-linked"
assert kinds["sum"]["classification"] == "link-free"
print("classification:",
      {k: v["classification"] for k, v in kinds.items()})
print("lesson 16 OK")
