"""Lesson 11: checkpoint/restore - surviving preemption.

A resident megakernel that runs for minutes is exactly what TPU
preemption kills: a SIGTERM or maintenance event used to lose the whole
task graph. The checkpoint subsystem (runtime/checkpoint.py) closes that
gap in three moves:

1. **Quiesce.** Build the megakernel with ``checkpoint=True`` and the
   scheduler polls a host-writable *quiesce word* inside its round loop
   (the abort word's checkpoint twin). On observing it, workers stop
   popping at the next round boundary - batch lanes spill back to the
   ready ring, in-flight prefetches drain - and the kernel returns with
   its LIVE scheduler state (task table, ready ring, counters, value
   heap) instead of discarding it: ``info['quiesced']`` + ``info['state']``.

2. **Bundle.** ``snapshot_megakernel(mk, info).save(path)`` serializes
   that state into a versioned on-disk artifact (``state.npz`` + a
   sha256-checksummed ``manifest.json``); ``CheckpointBundle.load``
   verifies integrity and version before handing anything back.

3. **Restore.** ``restore_megakernel(path, mk2)`` validates the manifest
   against a freshly built (same-code) kernel and relaunches MID-GRAPH.
   For a deterministic workload the continued run is bit-identical to
   the uninterrupted one - asserted below.

Preemption wiring: ``hc.checkpoint_on_preempt(stream)`` binds a running
injection stream to the process preemption hooks - SIGTERM (after
``resilience.install_preempt_handler()``), ``HCLIB_TPU_PREEMPT=1``, or
the watchdog's checkpoint rung (``HCLIB_TPU_WATCHDOG_CHECKPOINT=1``) -
so a preemption notice checkpoints the stream instead of losing it.

Caveat (stated, like every caveat in this repo): only DEVICE scheduler
state is captured. Host-side tasks and help-first host execution are not
in the bundle - checkpoint the device layer and re-enter the host
program idempotently.
"""

import os
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import hclib_tpu as hc
from hclib_tpu.device.descriptor import TaskGraphBuilder
from hclib_tpu.device.workloads import (
    UTS_NODE,
    device_uts_mk,
    make_uts_megakernel,
)


def part_one_quiesce_mid_tree() -> int:
    """Quiesce a seeded UTS traversal mid-tree; the exported state is a
    complete, resumable scheduler snapshot."""
    kw = dict(max_depth=8, interpret=True)
    nodes, _ = device_uts_mk(**kw)
    print(f"uninterrupted traversal: {nodes} nodes")

    mk = make_uts_megakernel(checkpoint=True, **kw)
    b = TaskGraphBuilder()
    b.add(UTS_NODE, args=[1, 0])
    # quiesce=k: stop at the first round boundary after k tasks - the
    # deterministic spelling. A preemption handler would pass
    # quiesce=True ("now") instead.
    _, _, info = mk.run(b, quiesce=nodes // 3)
    assert info["quiesced"] is True
    print(
        f"quiesced at {info['quiesce']['executed_at']} tasks with "
        f"{info['pending']} still pending - state exported, not lost"
    )
    return nodes


def part_two_bundle_and_restore(nodes: int) -> None:
    """Serialize the quiesced state to disk, then restore it on a fresh
    kernel and run to completion - bit-identical to never stopping."""
    kw = dict(max_depth=8, interpret=True)
    mk = make_uts_megakernel(checkpoint=True, **kw)
    b = TaskGraphBuilder()
    b.add(UTS_NODE, args=[1, 0])
    _, _, info = mk.run(b, quiesce=nodes // 3)

    path = os.path.join(tempfile.mkdtemp(), "ckpt")
    stats = hc.snapshot_megakernel(mk, info).save(path)
    print(
        f"bundle: {stats['bundle_bytes']} bytes, sha256 "
        f"{stats['sha256'][:12]}..., saved in {stats['save_s'] * 1e3:.1f} ms"
    )

    # A new process would rebuild the SAME program and load the bundle;
    # the manifest guards against restoring onto a different kernel
    # table (descriptors index it positionally).
    mk2 = make_uts_megakernel(checkpoint=True, **kw)
    iv, _, info2 = hc.restore_megakernel(path, mk2)
    assert int(iv[0]) == nodes, (int(iv[0]), nodes)
    assert info2["pending"] == 0
    print(f"restored + drained: {int(iv[0])} nodes - exact")


def part_three_preempt_a_stream() -> None:
    """The operational path: a live injection stream, a preemption
    notice, a checkpoint instead of a loss."""
    from hclib_tpu.device.inject import StreamingMegakernel
    from hclib_tpu.device.megakernel import Megakernel
    from hclib_tpu.runtime import resilience

    def bump(ctx):
        ctx.set_value(0, ctx.value(0) + ctx.arg(0))

    def make_sm():
        return StreamingMegakernel(
            Megakernel(kernels=[("bump", bump)], capacity=256,
                       num_values=16, succ_capacity=8, interpret=True,
                       checkpoint=True),
            ring_capacity=256,
        )

    resilience.reset_preempt()
    sm = make_sm()
    b = TaskGraphBuilder()
    n = 40
    for i in range(n):
        sm.inject(0, args=[i + 1])
    # Simulate the preemption notice BEFORE the stream runs: register-
    # then-replay means even that ordering checkpoints cleanly. (A real
    # deployment calls resilience.install_preempt_handler() once and
    # lets SIGTERM do this.)
    resilience.fire_preempt("maintenance event (simulated)")
    with hc.checkpoint_on_preempt(sm, after_executed=10):
        iv, info = sm.run_stream(b, quantum=8, deadline_s=120.0)
    assert info["quiesced"] is True
    print(
        f"stream preempted after {info['executed']} tasks; "
        f"{info['pending']} pending + ring residue ride the snapshot"
    )
    resilience.reset_preempt()

    sm2 = make_sm()
    sm2.close()  # drain-and-exit on the restored stream
    iv2, info2 = sm2.run_stream(resume_state=info["state"],
                                deadline_s=120.0)
    want = n * (n + 1) // 2
    assert int(iv2[0]) == want, (int(iv2[0]), want)
    print(f"restored stream drained: sum {int(iv2[0])} == {want} - exact")


if __name__ == "__main__":
    nodes = part_one_quiesce_mid_tree()
    part_two_bundle_and_restore(nodes)
    part_three_preempt_a_stream()
    print("lesson 11 OK")
