"""forasync (1D/2D/3D, flat + recursive) and locality-graph tests, mirroring
test/c/forasync*{Ch,Rec} and the locality-graph loader."""

import threading


import hclib_tpu as hc
from hclib_tpu.runtime.locality import graph_from_dict


def _concurrent_marker(n):
    lock = threading.Lock()
    hits = set()

    def fn(*idx):
        with lock:
            hits.add(idx if len(idx) > 1 else idx[0])

    return fn, hits, lock


def test_forasync_1d_flat():
    fn, hits, _ = _concurrent_marker(100)

    def main():
        hc.forasync(fn, [100], tile=16, mode=hc.FLAT)

    hc.launch(main, nworkers=3)
    assert hits == set(range(100))


def test_forasync_1d_recursive():
    fn, hits, _ = _concurrent_marker(100)

    def main():
        hc.forasync(fn, [100], tile=8, mode=hc.RECURSIVE)

    hc.launch(main, nworkers=3)
    assert hits == set(range(100))


def test_forasync_2d():
    fn, hits, _ = _concurrent_marker(None)

    def main():
        hc.forasync(fn, [12, 9], tile=[4, 3], mode=hc.FLAT)

    hc.launch(main, nworkers=2)
    assert hits == {(i, j) for i in range(12) for j in range(9)}


def test_forasync_3d_recursive():
    fn, hits, _ = _concurrent_marker(None)

    def main():
        hc.forasync(fn, [4, 5, 6], tile=2, mode=hc.RECURSIVE)

    hc.launch(main, nworkers=2)
    assert hits == {(i, j, k) for i in range(4) for j in range(5) for k in range(6)}


def test_forasync_bounds_pairs_and_autotile():
    fn, hits, _ = _concurrent_marker(None)

    def main():
        hc.forasync(fn, [(10, 20)])

    hc.launch(main, nworkers=2)
    assert hits == set(range(10, 20))


def test_forasync_future():
    fn, hits, _ = _concurrent_marker(None)

    def main():
        fut = hc.forasync_future(fn, [50], tile=10)
        fut.wait()
        assert hits == set(range(50))

    hc.launch(main, nworkers=2)


def test_forasync_dist_func():
    """Every tile routed to the central locale via a dist func
    (reference: loop_dist_func, inc/hclib-forasync.h:349-380)."""
    placed = []

    def main():
        rt = hc.current_runtime()
        central = rt.graph.central_locale()

        def dist(ndim, tile, total):
            placed.append(tile)
            return central

        hc.forasync(lambda i: None, [40], tile=10, dist_func=dist)

    hc.launch(main, nworkers=2)
    assert sorted(placed) == [0, 1, 2, 3]


def test_recursive_dist_func_matches_flat():
    """Cross-mode placement determinism (ISSUE 9 satellite): a flat-index
    dist func sees the SAME tile -> locale mapping in RECURSIVE mode as
    in FLAT mode. Power-of-two tile counts make the recursion land
    exactly on the flat tile grid, so the (flat, locale) call sets must
    be identical - previously RECURSIVE ignored the dist func entirely."""
    import threading

    lock = threading.Lock()
    calls = {}

    def run(mode):
        calls[mode] = set()

        def main():
            rt = hc.current_runtime()
            locales = rt.graph.locales_of_type("L1")

            def dist(ndim, flat, total):
                loc = locales[flat % len(locales)]
                with lock:
                    calls[mode].add((flat, total, loc.name))
                return loc

            hc.forasync(lambda i, j: None, [8, 8], tile=[2, 2],
                        mode=mode, dist_func=dist)

        hc.launch(main, nworkers=2)

    run(hc.FLAT)
    run(hc.RECURSIVE)
    assert calls[hc.FLAT] == calls[hc.RECURSIVE]
    assert len(calls[hc.FLAT]) == 16  # every flat tile placed exactly once


def test_recursive_dist_func_unaligned_consistent():
    """When recursion does NOT land on the flat grid (non-pow2 counts),
    leaves still key placement by the flat tile covering their low
    corner: every flat index used is in range and the full iteration
    space executes exactly once."""
    import threading

    lock = threading.Lock()
    flats = []
    fn, hits, _ = _concurrent_marker(None)

    def main():
        central = hc.current_runtime().graph.central_locale()

        def dist(ndim, flat, total):
            with lock:
                flats.append((flat, total))
            return central

        hc.forasync(fn, [24], tile=8, mode=hc.RECURSIVE, dist_func=dist)

    hc.launch(main, nworkers=2)
    assert hits == set(range(24))
    assert all(0 <= f < t and t == 3 for f, t in flats)


def test_arrayadd_forasync():
    """Reference: test/forasync/arrayadd - c = a + b elementwise."""
    n = 1000
    a = list(range(n))
    b = list(range(0, 2 * n, 2))
    c = [0] * n

    def main():
        def body(i):
            c[i] = a[i] + b[i]

        hc.forasync(body, [n], tile=64)

    hc.launch(main, nworkers=4)
    assert c == [3 * i for i in range(n)]


# ---------------------------------------------------------------- locality


def test_default_graph_shape():
    g = hc.generate_default_graph(4)
    assert g.nworkers == 4
    assert g.central_locale().type == "sysmem"
    assert len(g.locales_of_type("L1")) == 4
    for w in range(4):
        assert g.closest_locale(w).name == f"L1_{w}"


def test_reference_schema_load():
    """Parse a reference-format locality JSON with $(id) interpolation
    (schema: locality_graphs/davinci.json)."""
    doc = {
        "nworkers": 4,
        "declarations": ["sysmem", "L2_0", "L2_1", "GPU0", "Interconnect"],
        "reachability": [
            ["sysmem", "L2_0"],
            ["sysmem", "L2_1"],
            ["sysmem", "GPU0"],
            ["sysmem", "Interconnect"],
        ],
        "pop_paths": {"default": ["L2_$(id / 2)", "sysmem"]},
        "steal_paths": {"default": ["L2_$(id % 2)", "sysmem"]},
    }
    g = graph_from_dict(doc)
    assert g.nworkers == 4
    assert [g.locale(i).name for i in g.pop_paths[3]] == ["L2_1", "sysmem"]
    assert [g.locale(i).name for i in g.steal_paths[3]] == ["L2_1", "sysmem"]
    assert g.locale(g.pop_paths[0][0]).name == "L2_0"
    gpu = g.locales_of_type("GPU")
    assert len(gpu) == 1
    assert g.closest_of_type(0, "GPU").name == "GPU0"
    nic = g.by_name["Interconnect"]
    nic.mark_special("COMM")
    assert nic.is_special("COMM")


def test_run_with_custom_graph():
    doc = {
        "nworkers": 2,
        "declarations": ["sysmem", "L1_0", "L1_1"],
        "reachability": [["sysmem", "L1_0"], ["sysmem", "L1_1"]],
        "pop_paths": {"default": ["L1_$(id % 2)", "sysmem"]},
        "steal_paths": {"default": ["sysmem", "L1_0", "L1_1"]},
    }
    g = graph_from_dict(doc)
    hits = []

    def main():
        with hc.finish():
            for i in range(20):
                hc.async_(hits.append, i)

    hc.launch(main, locality_graph=g)
    assert len(hits) == 20


def test_reducers():
    def main():
        s = hc.SumReducer()
        m = hc.MaxReducer()
        hc.forasync(lambda i: (s.add(i), m.put(i)), [100], tile=10)
        assert s.gather() == sum(range(100))
        assert m.gather() == 99

    hc.launch(main, nworkers=3)
