"""Workload model tests (reference acceptance suite at test sizes)."""

import numpy as np
import pytest

import hclib_tpu as hc
from hclib_tpu.models import arrayadd, cholesky, fib, smithwaterman, uts


def test_fib_finish():
    r = fib.run(16, variant="finish", nworkers=3)
    assert r["value"] == 987


def test_fib_finish_cutoff():
    r = fib.run(20, variant="finish", nworkers=3, cutoff=10)
    assert r["value"] == 6765


def test_fib_ddf():
    r = fib.run(16, variant="ddf", nworkers=3)
    assert r["value"] == 987


def test_uts_t3_parallel_matches_sequential():
    seq = uts.count_seq(uts.T3)
    par = uts.count_parallel(uts.T3, nworkers=4)
    assert par == seq
    assert seq[0] == 1279  # pinned: detects any RNG/shape drift


def test_uts_grain_batching():
    seq = uts.count_seq(uts.T3)
    assert uts.count_parallel(uts.T3, nworkers=4, grain=32) == seq


def test_uts_canonical_root_children():
    """The canonical trees' first-level structure is fixed by the SHA-1 RNG;
    T1 root (seed 19, b0=4) child count is deterministic."""
    s = uts.root_state(uts.T1.root_seed)
    n = uts.num_children(uts.T1, s, 0)
    assert 0 <= n <= 100
    # Re-derivation must be stable.
    assert n == uts.num_children(uts.T1, s, 0)


def test_cholesky_small():
    r = cholesky.run(n=128, tile=32)
    assert r["ok"], r


def test_cholesky_uneven_rejected():
    a = cholesky.make_spd(100)
    with pytest.raises(ValueError):
        cholesky.cholesky_tiled(a, 32)


def test_smithwaterman_matches_sequential():
    a = smithwaterman.random_seq(150, 1)
    b = smithwaterman.random_seq(130, 2)
    h_par = smithwaterman.sw_tiled(a, b, tile=32)
    h_seq = smithwaterman.sw_seq(a, b)
    assert np.array_equal(h_par, h_seq)


def test_arrayadd_models():
    arrayadd.arrayadd_1d(10_000, tile=1000)
    arrayadd.arrayadd_2d(50, 40, tile=(16, 16))
    arrayadd.arrayadd_1d(5_000, tile=500, mode=hc.RECURSIVE)
