"""forasync device tier (ISSUE 9): tile loops lowered onto batch lanes,
data-driven mesh placement from locality_graphs/, locality-ordered
stealing, checkpoint mid-loop, and the partial-batch starvation detector.

The acceptance spine: stencil and map-loop results bit-identical across
host forasync, scalar device dispatch, and the tile tier (single device
and the 4-device interpret mesh), with placement as data and skew
recovered by stealing.
"""

import os

import numpy as np
import pytest
from jax.experimental import pallas as pl

import hclib_tpu as hc
from hclib_tpu.device.descriptor import F_A0, TaskGraphBuilder
from hclib_tpu.device.forasync_tier import (
    FA_TILE,
    make_forasync_megakernel,
    place_tiles,
    run_forasync_device,
    seed_tiles,
    tile_args,
    tile_grid,
)
from hclib_tpu.device.megakernel import (
    C_EXECUTED,
    C_HEAD,
    C_TAIL,
    Megakernel,
)
from hclib_tpu.device.workloads import (
    batch_of,
    map_body,
    map_data,
    map_loop,
    map_reference,
    stencil_body,
    stencil_data,
    stencil_loop,
    stencil_reference,
)
from hclib_tpu.runtime.locality import (
    MeshPlacement,
    load_locality_file,
    resolve_placement,
    steal_hop_order,
)

GRAPHS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "locality_graphs",
)

# One small stencil configuration shared by every arm in this file: 8
# tiles of (8, 128) so a width-4 batch tier fires full rounds, kept tiny
# because each distinct megakernel build is an XLA compile.
H, W = 16, 512
TK, BOUNDS, TILE = stencil_loop(H, W)
GIN, GOUT0 = stencil_data(H, W)
REF = stencil_reference(GIN)
TOTAL = 8


# ------------------------------------------------------------ tiling math


def test_tile_grid_math():
    dims, tdims, counts, total = tile_grid([16, 512], [8, 128])
    assert (dims, tdims, counts, total) == (
        [(0, 16), (0, 512)], [8, 128], [2, 4], 8
    )
    # Flat order is row-major; args carry [flat, lo0, lo1, lo2].
    assert tile_args(dims, tdims, counts, 0) == [0, 0, 0, 0]
    assert tile_args(dims, tdims, counts, 5) == [5, 8, 128, 0]
    # (lo, hi) bounds offset the lo corner.
    dims2, td2, c2, t2 = tile_grid([(4, 12)], 4)
    assert tile_args(dims2, td2, c2, 1) == [1, 8, 0, 0]
    # Ragged tiling is a device-path error, not a silent clamp.
    with pytest.raises(ValueError, match="divide the bounds exactly"):
        tile_grid([10], [4])
    with pytest.raises(ValueError, match="1-3 dimensions"):
        tile_grid([2, 2, 2, 2], 1)


def test_place_arguments_validated():
    with pytest.raises(ValueError, match="mode=FLAT"):
        hc.forasync(TK, BOUNDS, tile=TILE, mode=hc.RECURSIVE,
                    place="device")
    with pytest.raises(ValueError, match="explicit tile"):
        hc.forasync(TK, BOUNDS, place="device")
    with pytest.raises(ValueError, match="unknown forasync place"):
        hc.forasync(lambda i: None, [4], place="gpu")
    with pytest.raises(TypeError, match="place='device'"):
        hc.forasync(lambda i: None, [4], width=4)
    with pytest.raises(ValueError, match="synchronous"):
        hc.forasync(TK, BOUNDS, tile=TILE, place="device",
                    blocking=False)


# ------------------------------------------------- three-arm bit-identity


def test_stencil_three_arms_bit_identical():
    # Host forasync arm.
    ghost = GOUT0.copy()

    def main():
        hc.forasync(stencil_body(GIN, ghost), BOUNDS, tile=TILE)

    hc.launch(main, nworkers=3)
    assert np.array_equal(ghost, REF)

    # Scalar device dispatch arm (width=0: one tile per lax.switch).
    d_sc, info_sc = run_forasync_device(
        TK, BOUNDS, TILE, {"gin": GIN, "gout": GOUT0.copy()}, width=0
    )
    assert np.array_equal(np.asarray(d_sc["gout"]), ghost)
    assert info_sc["executed"] == TOTAL

    # Tile tier arm: batch lanes + double-buffered operand prefetch.
    d_bt, info_bt = run_forasync_device(
        TK, BOUNDS, TILE, {"gin": GIN, "gout": GOUT0.copy()}, width=4
    )
    assert np.array_equal(np.asarray(d_bt["gout"]), ghost)
    t = info_bt["tiers"]
    assert t["batch_tasks"] == TOTAL and t["scalar_tasks"] == 0
    assert t["batch_rounds"] > 0 and t["batch_occupancy"] == 1.0
    # The cross-round prefetch engaged: every batch past the first had
    # its operand slabs in flight one round early.
    assert t["prefetch_hits"] == TOTAL - 4


def test_map_three_arms_bit_identical():
    T = 16
    tkm, mb, mt = map_loop(T)
    vin, vout = map_data(T)
    mref = map_reference(vin)

    vh = vout.copy()

    def main():
        hc.forasync(map_body(vin, vh), mb, tile=mt)

    hc.launch(main, nworkers=2)
    assert np.array_equal(vh, mref)

    d_sc, _ = hc.forasync(
        tkm, mb, tile=mt, place="device",
        data={"vin": vin, "vout": vout.copy()}, width=0,
    )
    assert np.array_equal(np.asarray(d_sc["vout"]), mref)

    d_bt, info = hc.forasync(
        tkm, mb, tile=mt, place="device",
        data={"vin": vin, "vout": vout.copy()}, width=8,
    )
    assert np.array_equal(np.asarray(d_bt["vout"]), mref)
    assert info["tiers"]["batch_tasks"] == T
    assert info["tiers"]["batch_occupancy"] == 1.0


# --------------------------------------------------- placement as data


def test_placement_policies_counts():
    p = MeshPlacement(4, policy="block")
    assert p.counts(8) == [2, 2, 2, 2]
    assert [p.device_of(f, 8) for f in range(8)] == [0, 0, 1, 1, 2, 2, 3, 3]
    assert MeshPlacement(4, policy="cyclic").counts(10) == [3, 3, 2, 2]
    w = MeshPlacement(4, policy="weights", weights=[4, 2, 1, 1])
    assert w.counts(8) == [4, 2, 1, 1]
    s = MeshPlacement(4, policy="single", device=2)
    assert s.counts(8) == [0, 0, 8, 0]
    # Closed-form counts agree with the per-tile mapping (incl. a
    # zero-weight device, which owns no tiles).
    z = MeshPlacement(3, policy="weights", weights=[2, 0, 1])
    brute = [0, 0, 0]
    for f in range(9):
        brute[z.device_of(f, 9)] += 1
    assert z.counts(9) == brute and brute[1] == 0
    # dist-func spelling agrees with device_of.
    df = w.dist_func()
    assert [df(2, f, 8) for f in range(8)] == [
        w.device_of(f, 8) for f in range(8)
    ]
    with pytest.raises(ValueError, match="unknown placement policy"):
        MeshPlacement(4, policy="zigzag")
    with pytest.raises(ValueError, match="wants 4 weights"):
        MeshPlacement(4, policy="weights", weights=[1, 2])


def test_placement_descriptor_files():
    p = MeshPlacement.from_file(
        os.path.join(GRAPHS, "v5e_4.place_block.json")
    )
    assert p.ndev == 4 and p.policy == "block" and p.graph is not None
    assert p.hop_order() == [2, 1]
    skew = MeshPlacement.from_file(
        os.path.join(GRAPHS, "v5e_4.place_skew.json")
    )
    assert skew.counts(8) == [8, 0, 0, 0]
    with pytest.raises(ValueError, match="describes 4 devices"):
        resolve_placement(p, ndev=8)
    with pytest.raises(ValueError, match="'devices' or a 'graph'"):
        MeshPlacement.from_dict({"policy": "block"})
    with pytest.raises(ValueError, match="has 4 tpu locales"):
        MeshPlacement.from_dict(
            {"graph": os.path.join(GRAPHS, "v5e_4.json"), "devices": 8}
        )


def test_steal_hop_order_from_graphs():
    # 2x2 ICI ring: every hop-2 partner is a direct neighbor, half the
    # hop-1 partners are diagonal - the graph flips the default scan.
    assert steal_hop_order(os.path.join(GRAPHS, "v5e_4.json")) == [2, 1]
    g8 = load_locality_file(os.path.join(GRAPHS, "v5e_8.json"))
    order = steal_hop_order(g8)
    assert sorted(order) == [1, 2, 4]
    with pytest.raises(ValueError, match="tpu devices"):
        steal_hop_order(g8, ndev=4)
    # A 1-device roster has no hops: the descriptor hands back None so
    # runners fall back to their default instead of an empty override.
    one = MeshPlacement.from_dict(
        {"graph": os.path.join(GRAPHS, "v5e_1.json")}
    )
    assert one.ndev == 1 and one.hop_order() is None


def test_placement_swap_changes_ring_seeding():
    """Swapping the descriptor changes per-device initial tile counts as
    specified; totals are conserved (each flat tile placed exactly once)."""
    for placement, expect in [
        (MeshPlacement(4, policy="block"), [2, 2, 2, 2]),
        (MeshPlacement(4, policy="cyclic"), [2, 2, 2, 2]),
        (MeshPlacement(4, policy="weights", weights=[4, 2, 1, 1]),
         [4, 2, 1, 1]),
        (os.path.join(GRAPHS, "v5e_4.place_skew.json"), [8, 0, 0, 0]),
        (lambda ndim, flat, total: 3 - flat % 4, [2, 2, 2, 2]),
    ]:
        builders = [TaskGraphBuilder() for _ in range(4)]
        counts = place_tiles(builders, BOUNDS, TILE, placement)
        assert counts == expect, placement
        assert sum(counts) == TOTAL
        assert [b.num_tasks for b in builders] == expect
    # Block vs cyclic seed the same counts but DIFFERENT tiles: the
    # descriptor controls which flat index lands where.
    bb = [TaskGraphBuilder() for _ in range(4)]
    place_tiles(bb, BOUNDS, TILE, MeshPlacement(4, policy="block"))
    cb = [TaskGraphBuilder() for _ in range(4)]
    place_tiles(cb, BOUNDS, TILE, MeshPlacement(4, policy="cyclic"))
    bf = [r[F_A0] for r in bb[0]._rows]
    cf = [r[F_A0] for r in cb[0]._rows]
    assert bf == [0, 1] and cf == [0, 4]


# ------------------------------------------------------------- mesh arms


@pytest.fixture(scope="module")
def mesh_kernel():
    """One batch-tier megakernel + sharded runner shared by the mesh
    tests (the 4-device steal build is the expensive compile here)."""
    from hclib_tpu.device.sharded import ShardedMegakernel
    from hclib_tpu.parallel.mesh import cpu_mesh

    mk = make_forasync_megakernel(TK, width=4, capacity=64, interpret=True)
    smk = ShardedMegakernel(mk, cpu_mesh(4, axis_name="q"),
                            migratable_fns=[FA_TILE])
    return mk, smk


def _run_mesh(smk, placement, hop_order, quantum=2):
    builders = [TaskGraphBuilder() for _ in range(4)]
    counts = place_tiles(builders, BOUNDS, TILE, placement)
    stacked = {
        "gin": np.broadcast_to(GIN, (4,) + GIN.shape).copy(),
        "gout": np.zeros((4,) + GIN.shape, np.int32),
    }
    _, data, info = smk.run(
        builders, data=stacked, steal=True, quantum=quantum, window=4,
        hop_order=hop_order,
    )
    gout = np.asarray(data["gout"]).sum(axis=0, dtype=np.int32)
    return counts, gout, info


def test_mesh_stencil_bit_identical_with_batch_rounds(mesh_kernel):
    _, smk = mesh_kernel
    p = MeshPlacement.from_file(
        os.path.join(GRAPHS, "v5e_4.place_block.json")
    )
    counts, gout, info = _run_mesh(smk, p, p.hop_order())
    assert counts == p.counts(TOTAL)
    assert np.array_equal(gout, REF)  # bit-identical to the single-device arms
    assert info["executed"] == TOTAL and info["pending"] == 0
    per_dev = np.asarray(info["per_device_counts"])[:, C_EXECUTED]
    tiers = info["tiers"]
    for d in range(4):
        if per_dev[d] > 0:
            assert tiers[d]["batch_rounds"] > 0, (d, tiers[d])
    assert sum(t["batch_tasks"] for t in tiers) == TOTAL
    assert sum(t["scalar_tasks"] for t in tiers) == 0


def test_mesh_skewed_placement_completes_by_stealing(mesh_kernel):
    """A deliberately skewed placement (every tile on device 0) still
    completes exactly: tiles are successor-free, so the locality-ordered
    steal exchange spreads them - misplacement is recoverable, not
    fatal."""
    _, smk = mesh_kernel
    skew = MeshPlacement.from_file(
        os.path.join(GRAPHS, "v5e_4.place_skew.json")
    )
    # Same quantum as the identity test so both share ONE compiled steal
    # kernel (quantum is part of the jit cache key).
    counts, gout, info = _run_mesh(smk, skew, skew.hop_order(), quantum=2)
    assert counts == [TOTAL, 0, 0, 0]
    assert np.array_equal(gout, REF)
    per_dev = np.asarray(info["per_device_counts"])[:, C_EXECUTED]
    assert int((per_dev > 0).sum()) > 1, per_dev.tolist()
    assert int(per_dev.sum()) == TOTAL


# ------------------------------------------------- checkpoint mid-loop


def test_checkpoint_mid_loop_resume_bit_identical():
    mk = make_forasync_megakernel(
        TK, width=4, capacity=64, interpret=True, checkpoint=True
    )
    b = TaskGraphBuilder()
    seed_tiles(b, BOUNDS, TILE)
    _, full, _ = mk.run(b, data={"gin": GIN, "gout": GOUT0.copy()})
    full_gout = np.asarray(full["gout"])
    assert np.array_equal(full_gout, REF)

    b2 = TaskGraphBuilder()
    seed_tiles(b2, BOUNDS, TILE)
    _, _, q = mk.run(
        b2, data={"gin": GIN, "gout": GOUT0.copy()}, quiesce=TOTAL // 2
    )
    assert q["quiesced"] and q["pending"] > 0
    state = q["state"]
    # Lane spill discipline: the export sees ONLY ring rows - every
    # pending tile sits in the exported ready window (a lane-resident
    # descriptor here would be invisible to restore and lose a tile).
    counts = state["counts"]
    head, tail = int(counts[C_HEAD]), int(counts[C_TAIL])
    cap = mk.capacity
    rows = [int(state["ready"][i % cap]) for i in range(head, tail)]
    flats = sorted(int(state["tasks"][r][F_A0]) for r in rows)
    assert len(flats) == q["pending"] == len(set(flats))
    assert set(flats) <= set(range(TOTAL))
    # Resume runs the remainder; the final grid is bit-identical to the
    # uninterrupted run.
    _, data_r, info_r = mk.resume(state)
    assert info_r["pending"] == 0
    # C_EXECUTED stages from the exported counts, so the resumed entry
    # reports the CUMULATIVE total across the cut.
    assert info_r["executed"] == TOTAL
    assert np.array_equal(np.asarray(data_r["gout"]), full_gout)


# ------------------------------- partial-batch starvation watch item


PUMP, PTILE = 0, 1


def _pump_kernel(ctx):
    """Dynamic spawner that keeps the ready ring hot: each PUMP spawns
    one batch-routed PTILE and chains the next PUMP behind it, so under
    ring-drain-first firing the lane never holds more than one entry -
    the forasync-style dynamic-producer shape the ROADMAP lane-policy
    watch item predicts will starve partial batches."""
    d = ctx.arg(0)

    @pl.when(d > 0)
    def _():
        nxt = ctx.spawn(PUMP, [d - 1], dep_count=1, nargs=1)
        ctx.spawn(PTILE, [d], succ0=nxt, nargs=1)


def _ptile_kernel(ctx):
    ctx.set_value(0, ctx.value(0) + 1)


def test_lane_partial_age_detector_fires():
    depth = 24
    mk = Megakernel(
        kernels=[("pump", _pump_kernel), ("ptile", _ptile_kernel)],
        route={"ptile": batch_of(_ptile_kernel, width=4)},
        capacity=128, num_values=16, succ_capacity=8,
        interpret=True, trace=4096,
    )
    b = TaskGraphBuilder()
    b.add(PUMP, args=[depth])
    iv, _, info = mk.run(b)
    assert int(iv[0]) == depth
    t = info["tiers"]
    # Every tile fired as a width-1 partial batch: the detector reports
    # a long consecutive-partial streak for the PTILE lane.
    assert t["batch_tasks"] == depth and t["full_rounds"] == 0
    assert t["lane_partial_ages"][PTILE] >= 16, t
    assert t["lane_partial_age"] == t["lane_partial_ages"][PTILE]

    # The gauge rides MetricsRegistry.add_run_info beside lane_occupancy.
    reg = hc.MetricsRegistry()
    reg.add_run_info("pumped", info)
    snap = reg.snapshot()["metrics"]
    assert snap["pumped.lane_partial_age.0"] >= 16
    assert "pumped.lane_occupancy.0" in snap


def test_lane_partial_age_quiet_on_static_tiles():
    """A static tile set (the forasync lowering's shape) fires full
    batches: the detector stays at/near zero - the gauge separates
    healthy loops from starved ones instead of alarming on both."""
    mk = Megakernel(
        kernels=[("pump", _pump_kernel), ("ptile", _ptile_kernel)],
        route={"ptile": batch_of(_ptile_kernel, width=4)},
        capacity=128, num_values=16, succ_capacity=8,
        interpret=True, trace=4096,
    )
    b = TaskGraphBuilder()
    for k in range(8):
        b.add(PTILE, args=[k + 1])
    iv, _, info = mk.run(b)
    assert int(iv[0]) == 8
    t = info["tiers"]
    assert t["full_rounds"] == t["batch_rounds"] == 2
    assert t["lane_partial_age"] == 0


# --------------------------------------- resident ready-ring seeding

from hclib_tpu.jaxcompat import has_mosaic_interpret  # noqa: E402

needs_mosaic = pytest.mark.skipif(
    not has_mosaic_interpret(),
    reason="needs pltpu.InterpretParams (jax >= 0.5)",
)


@needs_mosaic
def test_resident_ring_seeding_follows_placement():
    """place_tiles seeds the RESIDENT runner's per-device ready rings the
    same way (placement is runner-agnostic data): with stealing disabled
    for the tile kind, each device executes exactly its seeded count."""
    from hclib_tpu.device.resident import ResidentKernel
    from hclib_tpu.parallel.mesh import cpu_mesh

    mk = Megakernel(
        kernels=[("fa_tile", _ptile_kernel)],
        capacity=64, num_values=16, succ_capacity=8, interpret=True,
    )
    rk = ResidentKernel(mk, cpu_mesh(4, axis_name="q"),
                        migratable_fns=[], window=4)
    builders = [TaskGraphBuilder() for _ in range(4)]
    counts = place_tiles(
        builders, [12], [1],
        MeshPlacement(4, policy="weights", weights=[6, 3, 2, 1]),
    )
    assert counts == [6, 3, 2, 1]
    iv, _, info = rk.run(builders, quantum=4)
    assert info["pending"] == 0
    per_dev = np.asarray(info["per_device_counts"])[:, C_EXECUTED]
    assert per_dev.tolist() == counts
    assert int(np.asarray(iv)[:, 0].sum()) == 12
