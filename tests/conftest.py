"""Test configuration.

Force the CPU backend with 8 virtual devices so multi-chip sharding paths are
exercised without TPU hardware (the driver separately dry-runs the multichip
path); must be set before jax initializes.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Persistent XLA compilation cache (works for the CPU backend too): the
# suite's one-time engine compiles (~40-120 s each for the UTS engines and
# the big interpret kernels) are disk-cached under the repo, so repeated
# suite runs on one machine skip them (measured 41 s -> 17 s for a single
# UTS test). Tutorial subprocesses inherit the env. Cold runs are
# unaffected; the cache directory is gitignored.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".jax_cache"),
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")
# NOTE: do not be tempted to speed the suite up with non-default
# InterpretParams (eager DMA / unchecked OOB reads): both variants
# sporadically deadlock the Mosaic interpreter's io_callback machinery
# on 1-vCPU hosts (see megakernel.interpret_mode).

import faulthandler  # noqa: E402

import pytest  # noqa: E402

# Stack dumps must BYPASS pytest's stderr capture (captured output dies
# with the os._exit the watchdog fires), so they go to an on-disk log
# next to this file; the handle stays open for the whole session.
_WEDGE_LOG = open(
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 ".wedge_traceback.log"),
    "w",
)


@pytest.fixture(autouse=True)
def _wedge_watchdog():
    """Hard per-test ceiling (15 min; the slowest test is ~2 min loaded).

    The Mosaic interpreter's io_callback machinery can SPORADICALLY wedge
    on 1-vCPU hosts even with the strict default InterpretParams (device
    threads park in buffer allocation; observed roughly once per hundreds
    of multi-device kernel runs). pytest-timeout isn't available in this
    image, and a thread-based timeout can't interrupt parked threads -
    faulthandler's timer CAN: it dumps every thread's stack (to
    tests/.wedge_traceback.log, see above) and exits, so a wedged run
    fails loudly with evidence instead of hanging forever."""
    faulthandler.dump_traceback_later(900, exit=True, file=_WEDGE_LOG)
    yield
    faulthandler.cancel_dump_traceback_later()


@pytest.fixture(autouse=True)
def _clean_modules():
    """Each test sees the module registries as it found them."""
    from hclib_tpu.runtime import module

    saved_modules = list(module._modules)
    saved_mem = {k: dict(v) for k, v in module._mem_fns.items()}
    saved_factories = list(module._per_worker_factories)
    yield
    module._modules[:] = saved_modules
    module._mem_fns.clear()
    module._mem_fns.update(saved_mem)
    module._per_worker_factories[:] = saved_factories


def timeline_mod():
    """Import tools/timeline.py (shared by the observability tests so the
    sys.path dance lives in ONE place)."""
    import sys

    tools = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
    )
    sys.path.insert(0, tools)
    try:
        import timeline
    finally:
        sys.path.remove(tools)
    return timeline
