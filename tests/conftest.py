"""Test configuration.

Force the CPU backend with 8 virtual devices so multi-chip sharding paths are
exercised without TPU hardware (the driver separately dry-runs the multichip
path); must be set before jax initializes.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_modules():
    """Each test starts with an empty module registry."""
    from hclib_tpu.runtime import module

    saved = list(module._modules)
    yield
    module._modules[:] = saved
