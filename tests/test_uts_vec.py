"""Vectorized UTS tests (CPU backend; exactness vs the sequential spec)."""

import jax
import pytest

from hclib_tpu.device.uts_vec import (
    child_threshold_table,
    child_thresholds,
    depth_cap,
    uts_vec,
)
from hclib_tpu.models.uts import (
    CYCLIC,
    EXPDEC,
    FIXED,
    LINEAR,
    T3,
    UTSParams,
    count_seq,
    num_children,
)


def _cpu():
    return jax.devices("cpu")[0]


def test_thresholds_exact_against_scalar_formula():
    """count(r) = #{k: r >= t_k} must reproduce num_children for many r."""
    b0 = 4.0
    ts = child_thresholds(b0)
    params = UTSParams(shape=FIXED, gen_mx=100, b0=b0, root_seed=1)
    import struct

    for r in [0, 1, 429496729, 1073741824, 1717986918, 2147483646,
              2147483647, 214748364, 2100000000]:
        state = b"\x00" * 16 + struct.pack(">I", r)
        want = num_children(params, state, 1)
        got = int((r >= ts).sum())
        assert got == want, (r, got, want)


def test_uts_vec_t3_exact():
    r = uts_vec(T3, target_roots=64, device=_cpu(), stack_pad=8)
    assert (r["nodes"], r["leaves"], r["max_depth"]) == count_seq(T3)


def test_uts_vec_deeper_tree_exact():
    p = UTSParams(shape=FIXED, gen_mx=7, b0=4.0, root_seed=19)
    r = uts_vec(p, target_roots=256, device=_cpu(), stack_pad=8)
    assert (r["nodes"], r["leaves"], r["max_depth"]) == count_seq(p)


def test_uts_vec_tiny_tree_host_only():
    """A tree smaller than target_roots is fully consumed by the host BFS."""
    p = UTSParams(shape=FIXED, gen_mx=2, b0=1.0, root_seed=3)
    r = uts_vec(p, target_roots=10_000, device=_cpu())
    assert (r["nodes"], r["leaves"], r["max_depth"]) == count_seq(p)


def test_threshold_table_matches_scalar_formula_per_depth():
    """Every table row must reproduce num_children at its depth (the f64
    shape functions, reference test/uts/uts.c:171-221)."""
    import struct

    for shape in (LINEAR, EXPDEC, CYCLIC):
        p = UTSParams(shape=shape, gen_mx=6, b0=4.0, root_seed=1)
        cap = depth_cap(p) or 30
        tab = child_threshold_table(p, cap)
        for d in [0, 1, 2, 5, cap // 2, cap]:
            row = tab[d]
            for r in [0, 1, 1073741824, 1717986918, 2147483646, 2147483647]:
                state = b"\x00" * 16 + struct.pack(">I", r)
                want = num_children(p, state, d)
                got = int(((row >= 0) & (r >= row)).sum())
                assert got == want, (shape, d, r, got, want)


@pytest.mark.parametrize(
    "shape,gen_mx,b0,seed",
    [
        (LINEAR, 8, 4.0, 34),
        (CYCLIC, 1, 6.0, 502),
        (EXPDEC, 3, 3.0, 502),
    ],
)
def test_uts_vec_depth_varying_shapes_exact(shape, gen_mx, b0, seed):
    """LINEAR/EXPDEC/CYCLIC trees count exactly vs the sequential spec
    (VERDICT r1 item 6; reference trees T5/T2 are these shapes at scale).
    Shallow parameterizations on purpose: compile time grows steeply with
    the per-lane stack height (= depth cap), and the CYCLIC gen_mx=1 tree
    still spans the full period of its threshold table."""
    p = UTSParams(shape=shape, gen_mx=gen_mx, b0=b0, root_seed=seed)
    # A tight EXPDEC bound keeps the per-lane stack (and with it compile
    # time) small; the engine raises if the tree ever reaches it.
    kw = {"depth_bound": 9} if shape == EXPDEC else {}
    # stack_pad + table_cols land every parameterization on ONE
    # padded-shape engine (one XLA compile for the whole matrix).
    r = uts_vec(p, target_roots=128, device=_cpu(), stack_pad=10,
                table_cols=100, **kw)
    assert (r["nodes"], r["leaves"], r["max_depth"]) == count_seq(p)


def test_uts_vec_expdec_depth_bound_raises():
    """An EXPDEC tree that reaches the configured depth bound must fail
    loudly, never silently truncate."""
    p = UTSParams(shape=EXPDEC, gen_mx=3, b0=3.0, root_seed=502)
    _, _, true_maxd = count_seq(p)
    # target_roots small enough that the engine (not the host BFS) does
    # the deep traversal - a large target consumes this 217-node tree on
    # the host and nothing ever reaches the bound.
    with pytest.raises(RuntimeError, match="depth bound"):
        uts_vec(p, target_roots=8, device=_cpu(), stack_pad=10,
                table_cols=100, depth_bound=max(2, true_maxd - 2))
