"""Vectorized UTS tests (CPU backend; exactness vs the sequential spec)."""

import jax
import pytest

from hclib_tpu.device.uts_vec import child_thresholds, uts_vec
from hclib_tpu.models.uts import FIXED, LINEAR, T3, UTSParams, count_seq, num_children, root_state


def _cpu():
    return jax.devices("cpu")[0]


def test_thresholds_exact_against_scalar_formula():
    """count(r) = #{k: r >= t_k} must reproduce num_children for many r."""
    b0 = 4.0
    ts = child_thresholds(b0)
    params = UTSParams(shape=FIXED, gen_mx=100, b0=b0, root_seed=1)
    import struct

    for r in [0, 1, 429496729, 1073741824, 1717986918, 2147483646,
              2147483647, 214748364, 2100000000]:
        state = b"\x00" * 16 + struct.pack(">I", r)
        want = num_children(params, state, 1)
        got = int((r >= ts).sum())
        assert got == want, (r, got, want)


def test_uts_vec_t3_exact():
    r = uts_vec(T3, target_roots=64, device=_cpu())
    assert (r["nodes"], r["leaves"], r["max_depth"]) == count_seq(T3)


def test_uts_vec_deeper_tree_exact():
    p = UTSParams(shape=FIXED, gen_mx=7, b0=4.0, root_seed=19)
    r = uts_vec(p, target_roots=256, device=_cpu())
    assert (r["nodes"], r["leaves"], r["max_depth"]) == count_seq(p)


def test_uts_vec_tiny_tree_host_only():
    """A tree smaller than target_roots is fully consumed by the host BFS."""
    p = UTSParams(shape=FIXED, gen_mx=2, b0=1.0, root_seed=3)
    r = uts_vec(p, target_roots=10_000, device=_cpu())
    assert (r["nodes"], r["leaves"], r["max_depth"]) == count_seq(p)


def test_uts_vec_rejects_non_fixed_shape():
    p = UTSParams(shape=LINEAR, gen_mx=5, b0=4.0, root_seed=1)
    with pytest.raises(NotImplementedError):
        uts_vec(p, device=_cpu())
