"""Elastic autoscaling (ISSUE 6): the metrics-driven quiesce -> reshard
-> resume control loop.

The policy half is PURE (observation in, decision out) and is tested
headless - hysteresis, cooldown, the no-flap guarantee, and the
evacuation fast path need no mesh and no Mosaic. The control loop's
telemetry (typed ScaleEvents -> MetricsRegistry + TR_SCALE host ring ->
Perfetto) is host-only too. The end-to-end mesh runs (scale out under
backlog, dead-chip evacuation mid-stream, preemption checkpoint of an
autoscaled deployment, totals bit-identical to an uninterrupted run)
need the Mosaic interpret mode and ride the chaos marker like the other
mesh tests.
"""

import threading

import numpy as np
import pytest

import hclib_tpu as hc
from hclib_tpu.device.tracebuf import TR_SCALE, records_of
from hclib_tpu.jaxcompat import has_mosaic_interpret
from hclib_tpu.runtime import resilience

needs_mosaic = pytest.mark.skipif(
    not has_mosaic_interpret(),
    reason="needs the Mosaic TPU interpret mode (pltpu.InterpretParams, "
           "jax >= 0.5): the ICI mesh kernels simulate remote DMA + "
           "semaphores on CPU",
)


# ---------------------------------------------------------- policy, pure


def _policy(**kw):
    base = dict(min_devices=1, max_devices=8, scale_out_backlog=16.0,
                scale_in_backlog=2.0, hysteresis=2, cooldown=2)
    base.update(kw)
    return hc.AutoscalerPolicy(**base)


def test_policy_hysteresis_gates_scale_out():
    p = _policy()
    hot = hc.Observation(2, [40, 40])
    assert p.decide(hot)[1] == "hold"  # streak 1/2
    target, kind, reason = p.decide(hot)
    assert (target, kind) == (4, "scale_out")
    assert "2 slices" in reason


def test_policy_one_spike_never_resizes():
    """An alternating hot/cold load (the classic flap inducer) never
    builds a streak, so the mesh size never moves."""
    p = _policy()
    for _ in range(6):
        assert p.decide(hc.Observation(4, [50] * 4))[1] == "hold"
        assert p.decide(hc.Observation(4, [0] * 4))[1] == "hold"


def test_policy_cooldown_blocks_back_to_back_resizes():
    p = _policy(hysteresis=1, cooldown=2)
    assert p.decide(hc.Observation(2, [40, 40]))[1] == "scale_out"
    # Cooldown: two slices hold even under sustained pressure...
    assert p.decide(hc.Observation(4, [40] * 4))[1] == "hold"
    assert p.decide(hc.Observation(4, [40] * 4))[1] == "hold"
    # ...then the streak machinery re-engages.
    assert p.decide(hc.Observation(4, [40] * 4))[1] == "scale_out"


def test_policy_scale_in_waits_for_empty_inject_backlog():
    p = _policy(hysteresis=1, cooldown=0)
    idle_but_queued = hc.Observation(4, [0] * 4, inject_backlog=9)
    assert p.decide(idle_but_queued)[1] == "hold"
    target, kind, _ = p.decide(hc.Observation(4, [0] * 4))
    assert (target, kind) == (2, "scale_in")


def test_policy_bounds_respected():
    p = _policy(min_devices=2, max_devices=4, hysteresis=1, cooldown=0)
    assert p.decide(hc.Observation(4, [99] * 4))[1] == "hold"  # at max
    assert p.decide(hc.Observation(2, [0, 0]))[1] == "hold"  # at min
    with pytest.raises(ValueError, match="power of two"):
        hc.AutoscalerPolicy(min_devices=3)
    with pytest.raises(ValueError, match="oscillate|must be <"):
        hc.AutoscalerPolicy(scale_out_backlog=4.0, scale_in_backlog=8.0)


def test_policy_evacuation_bypasses_gates():
    """A quarantined chip reshard-around fires at the FIRST observation
    naming it - during cooldown, with zero streak - and drops to the
    largest pof2 that fits the survivors."""
    p = _policy(hysteresis=2, cooldown=3)
    p.decide(hc.Observation(8, [40] * 8))  # prime a streak + no resize
    target, kind, reason = p.decide(
        hc.Observation(8, [1] * 8, quarantined=[5])
    )
    assert (target, kind) == (4, "evacuate")
    assert "quarantined" in reason
    # At min_devices there is nowhere to evacuate TO: hold, and say why.
    p2 = _policy(min_devices=1)
    target, kind, reason = p2.decide(
        hc.Observation(1, [5], quarantined=[0])
    )
    assert (target, kind) == (1, "hold") and "watchdog" in reason


def test_observation_from_info_reads_counts_and_quarantine():
    from hclib_tpu.device.megakernel import C_HEAD, C_TAIL

    counts = np.zeros((2, 8), np.int32)
    counts[0, C_TAIL] = 7
    counts[1, C_HEAD], counts[1, C_TAIL] = 2, 5
    info = {
        "per_device_counts": counts,
        "pending": 11,
        "executed": 30,
        "fault_stats": [
            {"quarantined": [1]}, {"quarantined": []},
        ],
        "inject_ctl": np.array(
            [[4, 1, 1, 0, 0, 0, 0, 0], [2, 1, 2, 0, 0, 0, 0, 0]],
            np.int32,
        ),
    }
    obs = hc.Observation.from_info(2, info, executed_before=10,
                                   slice_s=0.5)
    assert obs.backlog == [7, 3]
    assert obs.pending == 11
    assert obs.executed_delta == 20
    assert obs.inject_backlog == 3  # (4-1) + (2-2)
    assert obs.quarantined == (1,)
    assert obs.backlog_per_device == (7 + 3 + 3) / 2


# ------------------------------------------------- events and telemetry


def test_scale_events_metrics_and_trace_ring():
    reg = hc.MetricsRegistry()
    asc = hc.Autoscaler(lambda n: None, _policy(), metrics=reg)
    asc._event(hc.ScaleEvent("scale_out", 0, 2, 4, "r1"))
    asc._event(hc.ScaleEvent("hold", 1, 4, 4, "r2"))
    asc._event(hc.ScaleEvent("evacuate", 2, 4, 2, "r3",
                             resize_latency_s=0.01))
    snap = reg.snapshot()["metrics"]
    assert snap["autoscale.scale_out.count"] == 1.0
    assert snap["autoscale.evacuate.last.from_ndev"] == 4.0
    assert snap["autoscale.state.events"] == 3.0
    assert snap["autoscale.state.resizes"] == 2.0
    tr = asc.trace_info()
    recs = records_of(tr, TR_SCALE)
    assert len(recs) == 3
    assert int(recs[0][2]) == (2 << 8) | 4
    assert [int(r[1]) for r in recs] == [0, 1, 2]  # slice timebase
    # The Perfetto exporter renders the host ring (no dump needed).
    import os
    import sys

    sys.path.insert(
        0, os.path.join(os.path.dirname(__file__), "..", "tools")
    )
    import timeline

    doc = timeline.export_perfetto("", traces=[tr])
    names = [e.get("name", "") for e in doc["traceEvents"]]
    assert any(n.startswith("scale out 2→4") for n in names), names
    assert any(n.startswith("evacuate 4→2") for n in names), names


def test_autoscaler_close_unregisters_gauge():
    """A retired controller must not stay reachable through the
    registry: close() removes the live gauge source."""
    reg = hc.MetricsRegistry()
    asc = hc.Autoscaler(lambda n: None, _policy(), metrics=reg)
    assert "autoscale.state.ndev" in reg.snapshot()["metrics"]
    asc.close()
    assert "autoscale.state.ndev" not in reg.snapshot()["metrics"]


def test_scale_event_validation_and_shape():
    with pytest.raises(ValueError, match="kind"):
        hc.ScaleEvent("embiggen", 0, 1, 2, "no")
    ev = hc.ScaleEvent("scale_in", 5, 4, 2, "idle", backlog=3,
                       pending=7, executed=100, resize_latency_s=0.25)
    d = ev.as_dict()
    assert d["kind"] == "scale_in" and d["resize_latency_s"] == 0.25
    assert ev.resized and not hc.ScaleEvent("hold", 0, 2, 2, "x").resized


# ------------------------------------------------------------- off-path


def test_autoscaler_off_path_is_inert():
    """ACCEPTANCE: the autoscaler is pure host-side composition - no
    controller thread is spawned by construction or by policy decisions,
    a non-checkpoint kernel factory is refused up front (never half-run),
    and a Megakernel run outside the autoscaler carries no autoscale
    state (byte-identical PR 5 behavior - the checkpoint-off device path
    is covered by test_checkpoint's off-path bit-identity test)."""
    from hclib_tpu.device.workloads import device_uts_mk

    before = set(threading.enumerate())
    reg = hc.MetricsRegistry()
    asc = hc.Autoscaler(lambda n: None, _policy(), metrics=reg)
    for _ in range(4):
        asc.policy.decide(hc.Observation(2, [1, 1]))
    assert set(threading.enumerate()) == before  # no controller thread

    class FakeRK:
        class mk:
            checkpoint = False

        ndev = 2

    with pytest.raises(ValueError, match="checkpoint=True"):
        hc.Autoscaler(lambda n: FakeRK(), _policy())._kernel_for(2)
    with pytest.raises(ValueError, match="exactly one"):
        asc.run()

    n1, i1 = device_uts_mk(max_depth=6, interpret=True)
    assert "scale_events" not in i1  # plain runs carry no autoscale state
    n2, i2 = device_uts_mk(max_depth=6, interpret=True)
    assert n1 == n2 and i1["executed"] == i2["executed"]


# ------------------------------------------------------- mesh end-to-end


def _uts_kernel_factory(depth, dead_on_4=None, seed=0):
    from hclib_tpu.device.resident import ResidentKernel
    from hclib_tpu.device.workloads import UTS_NODE, make_uts_megakernel
    from hclib_tpu.parallel.mesh import cpu_mesh

    def make_kernel(ndev):
        plan = None
        if dead_on_4 is not None and ndev == 4:
            plan = hc.DeviceFaultPlan(
                seed=seed, dead_device=dead_on_4, dead_round=2,
                heartbeat_timeout=2,
            )
        mk = make_uts_megakernel(seed=19 + seed, max_depth=depth,
                                 interpret=True, checkpoint=True)
        return ResidentKernel(
            mk, cpu_mesh(ndev, axis_name="q"),
            migratable_fns=[UTS_NODE], window=4, homed=False,
            fault_plan=plan,
        )

    return make_kernel


def _uts_builders(ndev, roots=8):
    from hclib_tpu.device.descriptor import TaskGraphBuilder
    from hclib_tpu.device.workloads import UTS_NODE

    bs = [TaskGraphBuilder() for _ in range(ndev)]
    for d in range(ndev):
        for r in range(roots):
            bs[d].add(UTS_NODE, args=[d * roots + r + 1, 0])
    return bs


@needs_mosaic
@pytest.mark.chaos
def test_autoscale_storm_evacuates_dead_chip_totals_exact():
    """ACCEPTANCE (the storm): an autoscaled UTS mesh scales OUT under
    seeded backlog, the dead chip on the 4-device mesh is quarantined
    and EVACUATED mid-stream, the idle tail scales IN - >= 3 typed
    ScaleEvents including the evacuation - and the final totals are
    bit-identical to an uninterrupted fault-free run (zero task loss)."""
    make_kernel = _uts_kernel_factory(6, dead_on_4=3)
    iv_f, _, info_f = _uts_kernel_factory(6)(2).run(
        _uts_builders(2), quantum=8, max_rounds=1 << 14,
    )
    total = int(np.asarray(iv_f)[:, 0].sum())

    reg = hc.MetricsRegistry()
    asc = hc.Autoscaler(
        make_kernel,
        hc.AutoscalerPolicy(min_devices=1, max_devices=4,
                            scale_out_backlog=4.0, scale_in_backlog=1.0,
                            hysteresis=1, cooldown=1),
        slice_rounds=8, metrics=reg,
    )
    iv, _, info = asc.run(_uts_builders(2), quantum=8)
    assert info["pending"] == 0
    assert int(np.asarray(iv)[:, 0].sum()) == total
    assert info["executed"] == info_f["executed"]
    kinds = [e["kind"] for e in info["scale_events"]]
    assert len(info["scale_events"]) >= 3, kinds
    assert "evacuate" in kinds, kinds
    ev = next(e for e in info["scale_events"] if e["kind"] == "evacuate")
    assert ev["from_ndev"] == 4 and ev["to_ndev"] == 2
    assert ev["resize_latency_s"] is not None
    snap = reg.snapshot()["metrics"]
    assert snap["autoscale.evacuate.count"] >= 1.0
    recs = records_of(asc.trace_info(), TR_SCALE)
    assert len(recs) == len(info["scale_events"])


@needs_mosaic
@pytest.mark.chaos
def test_autoscale_preempt_checkpoints_and_resumes():
    """Preemption of an autoscaled deployment: the notice lands between
    slices, the controller checkpoints (bundle on disk) and stops; a
    fresh Autoscaler continues from the bundle and the totals are
    exact."""
    import os
    import tempfile

    make_kernel = _uts_kernel_factory(6, seed=1)
    iv_f, _, info_f = make_kernel(2).run(
        _uts_builders(2), quantum=8, max_rounds=1 << 14,
    )
    total = int(np.asarray(iv_f)[:, 0].sum())

    resilience.reset_preempt()
    ckdir = tempfile.mkdtemp(prefix="hclib-autoscale-")
    asc = hc.Autoscaler(
        make_kernel,
        hc.AutoscalerPolicy(min_devices=1, max_devices=2,
                            scale_out_backlog=1e9,
                            scale_in_backlog=0.0, hysteresis=1),
        slice_rounds=4, checkpoint_dir=ckdir,
    )
    try:
        resilience.fire_preempt("test preemption")
        iv, _, info = asc.run(_uts_builders(2), quantum=2)
    finally:
        resilience.reset_preempt()
    assert info.get("preempted") is True
    assert info["pending"] > 0  # genuinely mid-graph
    assert os.path.isdir(info["bundle_path"])
    assert [e["kind"] for e in info["scale_events"]][-1] == "checkpoint"

    asc2 = hc.Autoscaler(make_kernel, hc.AutoscalerPolicy(
        min_devices=1, max_devices=2, scale_out_backlog=1e9,
        scale_in_backlog=0.0, hysteresis=1,
    ), slice_rounds=1 << 12)
    iv2, _, info2 = asc2.run(resume_bundle=info["bundle_path"],
                             quantum=8)
    assert info2["pending"] == 0
    assert int(np.asarray(iv2)[:, 0].sum()) == total
    assert info2["executed"] == info_f["executed"]


# ------------------- tenant/deadline-aware policy (ISSUE 13), pure


def _pressure(expired, budget=20.0, in_flight=0.0, backlog=0.0):
    return {"expired": float(expired), "budget": float(budget),
            "in_flight": float(in_flight), "ring_residue": in_flight,
            "backlog": float(backlog),
            "pressure": min(1.0, expired / budget) if budget else 0.0}


def test_policy_deadline_pressure_beats_cooldown_and_watchdog():
    """ACCEPTANCE: a tenant draining >= tenant_pressure of its deadline
    budget in ONE slice triggers an immediate typed ``deadline_out``
    scale-out - during cooldown, with zero streak (before the watchdog
    rung: budget exhaustion would cancel the lane). The drain is a
    DELTA: a resumed deployment's cumulative expiry count is not a
    fresh storm, and a stable count never re-fires."""
    p = _policy(hysteresis=3, cooldown=3, tenant_pressure=0.25)
    p._cooling = 3  # mid-cooldown: the pressure path must not wait
    # First observation: cumulative expired=10 is BASELINE, not drain.
    base = hc.Observation(2, [1, 1], tenants={"t": _pressure(10)})
    assert p.decide(base)[1] == "hold"
    # 6 new expirations on a budget of 20 = 30% drained in one slice.
    target, kind, reason = p.decide(
        hc.Observation(2, [1, 1], tenants={"t": _pressure(16)})
    )
    assert (target, kind) == (4, "deadline_out"), (target, kind, reason)
    assert "watchdog" in reason and "'t'" in reason
    # Stable cumulative count after the resize: no re-fire (the resize
    # set a cooldown; and with zero drain there is no pressure at all;
    # backlog held in band so only the pressure path could resize).
    for _ in range(6):
        assert p.decide(
            hc.Observation(4, [5] * 4, tenants={"t": _pressure(16)})
        )[1] == "hold"
    # At max_devices the pressure path cannot help: it falls through
    # to the ordinary machinery (here: in-band hold), never a loop.
    p2 = _policy(max_devices=2, tenant_pressure=0.25)
    p2.decide(hc.Observation(2, [5, 5], tenants={"t": _pressure(0)}))
    assert p2.decide(
        hc.Observation(2, [5, 5], tenants={"t": _pressure(19)})
    )[1] == "hold"


def test_policy_delta_scale_out_below_level_threshold():
    """The live-delta arm: a backlog RISING by >= scale_out_delta per
    slice scales out after hysteresis even while the LEVEL is still
    under scale_out_backlog - the storm is caught while it builds."""
    p = _policy(hysteresis=2, cooldown=0, scale_out_delta=4.0)
    # Levels 2 -> 8 -> 14 per device: always far below the 16 level
    # threshold, but rising 6/slice with a flat executed rate.
    assert p.decide(hc.Observation(2, [2, 2], executed_delta=80,
                                   slice_s=1.0))[1] == "hold"
    assert p.decide(hc.Observation(2, [8, 8], executed_delta=80,
                                   slice_s=1.0))[1] == "hold"  # streak 1
    target, kind, reason = p.decide(
        hc.Observation(2, [14, 14], executed_delta=80, slice_s=1.0)
    )
    assert (target, kind) == (4, "scale_out"), (target, kind, reason)
    assert "rising" in reason
    # A rising backlog WITH a rising rate is ramp-up, not a storm.
    p2 = _policy(hysteresis=1, cooldown=0, scale_out_delta=4.0)
    p2.decide(hc.Observation(2, [2, 2], executed_delta=10, slice_s=1.0))
    assert p2.decide(
        hc.Observation(2, [8, 8], executed_delta=200, slice_s=1.0)
    )[1] == "hold"


def test_policy_strand_refusal_then_scale_in():
    """ACCEPTANCE: scale-in NEVER strands a tenant's in-flight quota or
    ring residue - the refusal is a typed ``strand_hold`` that keeps
    the streak armed, so the mesh shrinks at the first drained slice."""
    p = _policy(hysteresis=2, cooldown=0)
    idle_busy = hc.Observation(
        4, [0] * 4, tenants={"t": _pressure(0, in_flight=3)}
    )
    assert p.decide(idle_busy)[1] == "hold"          # streak 1/2
    for _ in range(3):                               # typed, repeated
        target, kind, reason = p.decide(idle_busy)
        assert (target, kind) == (4, "strand_hold"), (kind, reason)
        assert "'t'" in reason
    drained = hc.Observation(
        4, [0] * 4, tenants={"t": _pressure(0, in_flight=0)}
    )
    assert p.decide(drained)[:2] == (2, "scale_in")


def test_policy_no_flap_two_competing_tenants():
    """No-flap proof with two tenants trading small budget drains and
    an oscillating backlog: neither the pressure path (drains below
    threshold) nor the streak machinery (alternating hot/cold) ever
    resizes."""
    p = _policy(hysteresis=2, cooldown=2, tenant_pressure=0.5)
    exp_a = exp_b = 0.0
    for i in range(12):
        # Each slice one tenant expires 2 rows (10% of its budget) and
        # the backlog flips between busy and idle-with-residue.
        if i % 2:
            exp_a += 2
            obs = hc.Observation(4, [40] * 4, tenants={
                "a": _pressure(exp_a), "b": _pressure(exp_b),
            })
        else:
            exp_b += 2
            obs = hc.Observation(4, [0] * 4, tenants={
                "a": _pressure(exp_a, in_flight=1),
                "b": _pressure(exp_b),
            })
        target, kind, _ = p.decide(obs)
        assert target == 4, (i, kind)
        assert kind in ("hold", "strand_hold"), (i, kind)


def test_scale_event_new_kinds_ride_trace_and_metrics():
    """The new typed kinds (deadline_out / strand_hold) ride TR_SCALE,
    the metrics registry, and the Perfetto exporter - one SC_NAMES
    edit, no drifting copies."""
    from hclib_tpu.device.tracebuf import (
        SC_DEADLINE_OUT, SC_STRAND_HOLD,
    )

    reg = hc.MetricsRegistry()
    asc = hc.Autoscaler(lambda n: None, _policy(), metrics=reg)
    asc._event(hc.ScaleEvent("deadline_out", 0, 2, 4, "pressure"))
    asc._event(hc.ScaleEvent("strand_hold", 1, 4, 4, "residue"))
    snap = reg.snapshot()["metrics"]
    assert snap["autoscale.deadline_out.count"] == 1.0
    assert snap["autoscale.strand_hold.count"] == 1.0
    recs = records_of(asc.trace_info(), TR_SCALE)
    assert [int(r[3]) for r in recs] == [SC_DEADLINE_OUT, SC_STRAND_HOLD]
    import os
    import sys

    sys.path.insert(
        0, os.path.join(os.path.dirname(__file__), "..", "tools")
    )
    import timeline

    doc = timeline.export_perfetto("", traces=[asc.trace_info()])
    names = [e.get("name", "") for e in doc["traceEvents"]]
    assert any(n.startswith("deadline out 2→4") for n in names), names
    assert any(n.startswith("strand hold") for n in names), names
    with pytest.raises(ValueError, match="kind"):
        hc.ScaleEvent("strand", 0, 1, 1, "typo")


# ----------------------------------- program cache across resizes (ISSUE 18)


def test_scale_event_carries_cache_hit():
    """cache_hit rides the typed event: set on resizes, None elsewhere,
    present in as_dict (the flattener drops None, so non-resize events
    cost no gauge)."""
    ev = hc.ScaleEvent("scale_in", 5, 4, 2, "idle",
                       resize_latency_s=0.1, cache_hit=True)
    assert ev.as_dict()["cache_hit"] is True
    assert hc.ScaleEvent("hold", 0, 2, 2, "x").cache_hit is None


def test_program_cached_probe_reads_process_cache():
    """ResidentKernel.program_cached: False cold; True on a DIFFERENT
    content-identical instance once the (mk, variant) program is in the
    process-wide registry; parameter changes miss. Host-only - the probe
    never builds."""
    from hclib_tpu.device.resident import ResidentKernel
    from hclib_tpu.device.workloads import UTS_NODE, make_uts_megakernel
    from hclib_tpu.parallel.mesh import cpu_mesh
    from hclib_tpu.runtime import progcache

    def rk():
        mk = make_uts_megakernel(seed=19, max_depth=4, interpret=True,
                                 checkpoint=True)
        return ResidentKernel(
            mk, cpu_mesh(2, axis_name="q"), migratable_fns=[UTS_NODE],
            window=4, homed=False,
        )

    progcache.reset()
    try:
        a = rk()
        assert a.program_cached(quantum=8) is False
        key = (8, 1 << 14, a._hop_bits(None))
        _, stats = progcache.shared_build(
            a.mk, a._cache_variant(key), object
        )
        assert stats["hit"] is False
        assert rk().program_cached(quantum=8) is True
        assert rk().program_cached(quantum=16) is False
    finally:
        progcache.reset()


@needs_mosaic
@pytest.mark.chaos
def test_autoscale_resizes_with_both_shapes_warm_hit_cache():
    """ACCEPTANCE (ISSUE 18): with both mesh shapes pre-warmed by
    content-identical kernels, every controller resize reports
    cache_hit=True and the whole autoscaled run performs ZERO new
    trace/lower work (the process-wide miss counter does not move)."""
    from hclib_tpu.runtime import progcache

    make_kernel = _uts_kernel_factory(6)
    progcache.reset()
    try:
        # Pre-warm BOTH shapes with fresh instances (their private jit
        # tables die with them; only the process cache carries over).
        for ndev in (2, 4):
            make_kernel(ndev).run(
                _uts_builders(ndev), quantum=8, max_rounds=1 << 14,
            )
        warm = progcache.cache_stats()
        assert warm["misses"] >= 2 and warm["entries"] >= 2

        asc = hc.Autoscaler(
            make_kernel,
            hc.AutoscalerPolicy(min_devices=1, max_devices=4,
                                scale_out_backlog=4.0,
                                scale_in_backlog=1.0,
                                hysteresis=1, cooldown=1),
            slice_rounds=8,
        )
        iv, _, info = asc.run(_uts_builders(2), quantum=8)
        assert info["pending"] == 0
        resizes = [
            e for e in info["scale_events"]
            if e["from_ndev"] != e["to_ndev"]
        ]
        assert resizes, info["scale_events"]
        assert all(e["cache_hit"] is True for e in resizes), resizes
        # Zero rebuilds anywhere in the run: every slice's program came
        # from the registry (hits moved, misses did not).
        after = progcache.cache_stats()
        assert after["misses"] == warm["misses"]
        assert after["hits"] > warm["hits"]
    finally:
        progcache.reset()
