"""Shipped locality_graphs/*.json machine configs: load + execute on both
the Python host runtime and the native C++ runtime (the reference ships 21
machine JSONs consumed by its graph loader; ours describe TPU machines)."""

import glob
import os
import shutil

import pytest

import hclib_tpu as hc
from hclib_tpu.runtime.locality import load_locality_file

CONFIG_DIR = os.path.join(os.path.dirname(__file__), "..", "locality_graphs")
_ALL = sorted(glob.glob(os.path.join(CONFIG_DIR, "*.json")))
# Machine graphs vs mesh-placement descriptors (ISSUE 9) share the
# directory; ".place_" in the name marks the descriptor schema.
CONFIGS = [p for p in _ALL if ".place_" not in os.path.basename(p)]
PLACEMENTS = [p for p in _ALL if ".place_" in os.path.basename(p)]


def test_configs_present():
    names = {os.path.basename(p) for p in CONFIGS}
    assert {"v5e_1.json", "v5e_4.json", "v5e_8.json", "v4_8.json",
            "dcn_2host.json"} <= names
    assert {os.path.basename(p) for p in PLACEMENTS} >= {
        "v5e_4.place_block.json", "v5e_4.place_skew.json",
    }


@pytest.mark.parametrize("path", PLACEMENTS, ids=os.path.basename)
def test_placement_descriptor_loads(path):
    """Shipped placement descriptors resolve: the referenced graph loads,
    the roster is dense, and the mapping covers a tile range exactly."""
    from hclib_tpu.runtime.locality import MeshPlacement

    p = MeshPlacement.from_file(path)
    assert p.ndev >= 1
    counts = p.counts(2 * p.ndev)
    assert sum(counts) == 2 * p.ndev
    if p.graph is not None:
        assert p.hop_order(), "graph-backed descriptor must order hops"


@pytest.mark.parametrize("path", CONFIGS, ids=os.path.basename)
def test_config_loads_and_is_wellformed(path):
    g = load_locality_file(path)
    assert g.nworkers >= 1
    assert len(g.pop_paths) == g.nworkers
    assert len(g.steal_paths) == g.nworkers
    # Every worker must reach a drainable locale; every path entry resolves.
    for w in range(g.nworkers):
        assert g.pop_paths[w] and g.steal_paths[w]
    # Type derivation: device/comm locales present as declared.
    types = {l.type for l in g.locales}
    assert "sysmem" in types


@pytest.mark.parametrize("path", CONFIGS, ids=os.path.basename)
def test_config_runs_host_runtime(path):
    g = load_locality_file(path)
    out = []

    def main():
        with hc.finish():
            for i in range(20):
                hc.async_(lambda i=i: out.append(i))

    hc.launch(main, locality_graph=g)
    assert sorted(out) == list(range(20))


def test_device_worker_services_tpu_locale():
    """A task spawned at the tpu locale runs on a worker whose path covers
    it (the reference's 'GPU worker is just a path' design)."""
    g = load_locality_file(os.path.join(CONFIG_DIR, "v5e_1.json"))
    tpu = g.by_name["tpu_0"]
    seen = []

    def main():
        with hc.finish():
            hc.async_(lambda: seen.append(hc.current_worker()), at=tpu)

    hc.launch(main, locality_graph=g)
    assert seen and seen[0] == 3  # worker 3's pop path leads with tpu_0


@pytest.mark.skipif(
    shutil.which(os.environ.get("CXX", "g++")) is None,
    reason="no C++ compiler",
)
@pytest.mark.parametrize("name", ["v5e_1.json", "v5e_8.json"])
def test_config_runs_native_runtime(name):
    from hclib_tpu.native import NativeRuntime

    g = load_locality_file(os.path.join(CONFIG_DIR, name))
    with NativeRuntime(graph=g) as rt:
        assert rt.nlocales == len(g.locales)
        assert rt.fib(18) == 2584
