"""One-sided device PGAS (device/pgas_kernel.py): put / AM / wait-until on
data between resident schedulers, on simulated multi-device meshes (Mosaic
TPU interpret mode emulates the remote DMAs + semaphores) plus a TPU-gated
1-device compile.

Reference parity targets: one-sided put + wait-until on user data
(/root/reference/modules/openshmem/src/hclib_openshmem.cpp:136-920) and
active messages at a chosen PE
(/root/reference/modules/openshmem-am/src/hclib_openshmem-am.cpp:64-123).
"""

import jax
import numpy as np
import pytest

from hclib_tpu.device.descriptor import TaskGraphBuilder
from hclib_tpu.device.megakernel import Megakernel
from hclib_tpu.device.pgas_kernel import PGASMegakernel
from hclib_tpu.parallel.mesh import cpu_mesh

ROWS = 16
COLS = 128

# kernel ids
PUT = 0
CONSUME = 1
BUMP = 2
SERVE = 3
NOP = 4


def _mk(interpret=True, ndev=8, capacity=256, batch_width=0):
    """Kernel table used by every test in this file.

    PUT: put my heap row arg2 to device arg0's row arg1 on channel arg3.
    CONSUME: record the channel-0 arrival count into value slot arg0.
    BUMP: ivalues[arg0] += arg1 (the classic AM side effect).
    SERVE: the 'get' responder - put my row arg1 back to requester arg0's
           row arg2 on channel arg3 (reply channel).
    """

    def put(ctx):
        def b(c):
            def go():
                ctx.pgas.put(ctx.arg(0), c, ctx.arg(1), ctx.arg(2))

            return go

        # channel id must be static: branch on the arg
        from jax.experimental import pallas as pl

        for c in range(ctx.pgas.nchan):
            @pl.when(ctx.arg(3) == c)
            def _(go=b(c)):
                go()

    def consume(ctx):
        ctx.set_value(ctx.arg(0), ctx.pgas.count(0))

    def bump(ctx):
        ctx.set_value(ctx.arg(0), ctx.value(ctx.arg(0)) + ctx.arg(1))

    def serve(ctx):
        ctx.pgas.put(ctx.arg(0), 1, ctx.arg(2), ctx.arg(1))

    def nop(ctx):
        pass

    # batch_width > 0 routes BUMP (the AM payload kind) through the
    # batched same-kind tier - slot_ctx re-applies the pgas ctx_hook, so
    # a batched AM task sees the same facilities scalar dispatch gives it.
    from hclib_tpu.device.workloads import batch_of

    return Megakernel(
        kernels=[("put", put), ("consume", consume), ("bump", bump),
                 ("serve", serve), ("nop", nop)],
        data_specs={"heap": jax.ShapeDtypeStruct((ROWS, COLS), np.int32)},
        capacity=capacity,
        num_values=64,
        succ_capacity=64,
        interpret=interpret,
        route={"bump": batch_of(bump, width=batch_width)}
        if batch_width else None,
    )


def _heap(ndev):
    """Device d's row r prefilled with 1000*d + r."""
    h = np.zeros((ndev, ROWS, COLS), np.int32)
    for d in range(ndev):
        for r in range(ROWS):
            h[d, r, :] = 1000 * d + r
    return h


def test_put_wakes_parked_consumer_across_devices():
    """Device 0 puts two rows into every other device; each target's
    consumer task is parked on wait_until(chan 0, need 2) and runs only
    after both arrive - the signal-driven wakeup the reference implements
    as SHMEM wait-sets."""
    ndev = 4
    mesh = cpu_mesh(ndev, axis_name="queues")
    mk = _mk(ndev=ndev, capacity=128)
    pg = PGASMegakernel(
        mk, mesh, channels={"c0": ("heap", 1), "reply": ("heap", 1)}
    )
    builders = [TaskGraphBuilder() for _ in range(ndev)]
    waits = [[] for _ in range(ndev)]
    for d in range(1, ndev):
        # device 0: two puts at target d (rows d and d+8 <- rows 1 and 2)
        builders[0].add(PUT, args=[d, d % ROWS, 1, 0])
        builders[0].add(PUT, args=[d, (d + 8) % ROWS, 2, 0])
        # device d: parked consumer, one wait-dep
        t = builders[d].add(CONSUME, args=[0], out=0)
        waits[d].append((0, 2, t))
    iv, data, info = pg.run(builders, data={"heap": _heap(ndev)}, waits=waits)
    heap = np.asarray(data["heap"])
    for d in range(1, ndev):
        assert (heap[d, d % ROWS] == 1).all(), heap[d, d % ROWS][:4]
        assert (heap[d, (d + 8) % ROWS] == 2).all()
        # the consumer observed both arrivals when it ran
        assert iv[d, 0] == 2, (d, iv[d, :2])
    assert info["pending"] == 0 and not info["overflow"]


def test_am_targets_specific_device_mid_run():
    """Every device AMs a BUMP at every other device (all-to-all, more
    messages than one round's window cap so the outbox pacing runs):
    device d ends with the sum of all senders' payloads - tasks pushed at
    a *chosen* device, not a steal partner."""
    ndev = 4
    mesh = cpu_mesh(ndev, axis_name="queues")
    mk = _mk(ndev=ndev, capacity=128)
    pg = PGASMegakernel(
        mk, mesh, channels={"c0": ("heap", 1), "reply": ("heap", 1)},
        # am_window 2 < the 4 messages each sender queues, so the
        # outbox's capped-head carry-over path actually runs.
        am_window=2,
    )

    SEND = 5

    def send_all(ctx):
        # AM a bump at every device (including self: loopback rides the
        # same inbox path).
        me = ctx.pgas.me

        for d in range(ndev):
            ctx.pgas.am(d, BUMP, args=[0, 1 + me])

    mk.kernel_names.append("send_all")
    mk.kernel_fns.append(send_all)
    builders = [TaskGraphBuilder() for _ in range(ndev)]
    for d in range(ndev):
        builders[d].add(SEND)
    iv, _, info = pg.run(builders, data={"heap": _heap(ndev)})
    expect = sum(1 + s for s in range(ndev))
    for d in range(ndev):
        assert iv[d, 0] == expect, (d, iv[d, 0])
    assert info["executed"] == ndev + ndev * ndev
    assert info["pending"] == 0


def test_get_composes_am_and_reply_put():
    """The SHMEM 'get': device 0 AMs a SERVE task at each owner d, which
    puts its heap row back on the reply channel; device 0's consumer is
    parked until all replies land (request/response over one-sided
    primitives, the reference's AM-over-SHMEM composition)."""
    ndev = 4
    mesh = cpu_mesh(ndev, axis_name="queues")
    mk = _mk(ndev=ndev)
    pg = PGASMegakernel(
        mk, mesh, channels={"c0": ("heap", 1), "reply": ("heap", 1)}
    )
    GET_ROW = 3  # fetch row 3 of each owner
    REQUEST = 5  # appended below after the 5 base kernels
    builders = [TaskGraphBuilder() for _ in range(ndev)]
    waits = [[] for _ in range(ndev)]
    for d in range(1, ndev):
        # am(SERVE) at owner d: serve(requester=0, src_row=GET_ROW,
        # dst_row=d) -> reply channel. Issued from a task on device 0.
        builders[0].add(REQUEST, args=[d])
    # consumer on device 0 parked until ndev-1 replies
    t = builders[0].add(CONSUME, args=[1])
    waits[0].append((1, ndev - 1, t))

    def request(ctx):
        d = ctx.arg(0)
        ctx.pgas.am(d, SERVE, args=[0, GET_ROW, d, 0])

    # SERVE args: (requester, src_row, dst_row, unused) -> uses channel 1
    mk.kernel_names.append("request")
    mk.kernel_fns.append(request)
    iv, data, info = pg.run(builders, data={"heap": _heap(ndev)}, waits=waits)
    heap = np.asarray(data["heap"])
    for d in range(1, ndev):
        # owner d's row GET_ROW (value 1000*d+3) landed in requester row d
        assert (heap[0, d] == 1000 * d + GET_ROW).all(), heap[0, d][:4]
    assert info["pending"] == 0


def test_wait_until_device_side_spawn():
    """A task spawns a parked child and registers the wait itself
    (device-side wait_until, not host-declared): child runs after the
    producer's put lands."""
    ndev = 2
    mesh = cpu_mesh(ndev, axis_name="queues")
    mk = _mk(ndev=ndev)
    pg = PGASMegakernel(
        mk, mesh, channels={"c0": ("heap", 1), "reply": ("heap", 1)}
    )

    SPAWNER = 5

    def spawner(ctx):
        row = ctx.spawn(CONSUME, args=[2], dep_count=1)
        ctx.pgas.wait_until(0, 1, row)

    mk.kernel_names.append("spawner")
    mk.kernel_fns.append(spawner)
    builders = [TaskGraphBuilder() for _ in range(ndev)]
    builders[0].add(PUT, args=[1, 0, 5, 0])  # put my row 5 -> dev1 row 0
    builders[1].add(SPAWNER)
    iv, data, info = pg.run(builders, data={"heap": _heap(ndev)})
    assert iv[1, 2] == 1  # consumer ran, saw one arrival
    assert (np.asarray(data["heap"])[1, 0] == 5).all()
    assert info["pending"] == 0


def test_pgas_race_free_under_detector():
    """Mosaic interpret race detection over the one-sided protocol: the
    counting discipline (wait total arrivals before any inbox read) must
    induce a happens-before order with no data race - this detector is
    what caught the shared-semaphore per-source-wait race during
    development."""
    from jax.experimental.pallas import tpu as pltpu

    ndev = 2
    mesh = cpu_mesh(ndev, axis_name="queues")
    mk = _mk(ndev=ndev)
    pg = PGASMegakernel(
        mk, mesh, channels={"c0": ("heap", 1), "reply": ("heap", 1)},
        am_window=4,
    )

    SEND = 5

    def send_all(ctx):
        for d in range(ndev):
            ctx.pgas.am(d, BUMP, args=[0, 1 + ctx.pgas.me])
        ctx.pgas.put((ctx.pgas.me + 1) % ndev, 0, 0, 1)

    mk.kernel_names.append("send_all")
    mk.kernel_fns.append(send_all)
    # pof2 meshes delegate to the resident kernel: patch the build that
    # will actually run.
    target = pg._resident if pg._resident is not None else pg
    orig = target._build

    def build_with_detector(quantum, max_rounds):
        import unittest.mock as m

        real = pltpu.InterpretParams
        with m.patch.object(
            pltpu, "InterpretParams",
            # Ignore kwargs: if interpret_mode() ever grows non-default
            # InterpretParams variants, they must not silently alter
            # race-detection semantics (same in test_resident/test_ici).
            lambda **kw: real(detect_races=True),
        ):
            return orig(quantum, max_rounds)

    target._build = build_with_detector
    builders = [TaskGraphBuilder() for _ in range(ndev)]
    for d in range(ndev):
        builders[d].add(SEND)
    iv, data, info = pg.run(builders, data={"heap": _heap(ndev)})
    expect = sum(1 + s for s in range(ndev))
    for d in range(ndev):
        assert iv[d, 0] == expect
        assert (np.asarray(data["heap"])[d, 0] == 1000 * ((d + 1) % ndev) + 1).all()


@pytest.mark.skipif(jax.default_backend() != "tpu", reason="needs TPU")
def test_pgas_compiles_and_runs_on_tpu():
    """1-device self-loop: the identical kernel compiles for real hardware
    and the full put + AM + wait-until protocol runs (remote DMA to self)."""
    mesh_devs = jax.devices()[:1]
    from jax.sharding import Mesh

    mesh = Mesh(np.array(mesh_devs), ("queues",))
    mk = _mk(interpret=False, ndev=1)
    pg = PGASMegakernel(
        mk, mesh, channels={"c0": ("heap", 1), "reply": ("heap", 1)}
    )

    SPAWNER = 5

    def spawner(ctx):
        row = ctx.spawn(CONSUME, args=[2], dep_count=1)
        ctx.pgas.wait_until(0, 1, row)
        ctx.pgas.am(0, BUMP, args=[3, 7])

    mk.kernel_names.append("spawner")
    mk.kernel_fns.append(spawner)
    builders = [TaskGraphBuilder()]
    builders[0].add(PUT, args=[0, 0, 5, 0])  # self-put row 5 -> row 0
    builders[0].add(SPAWNER)
    iv, data, info = pg.run(builders, data={"heap": _heap(1)})
    assert iv[0, 2] == 1
    assert iv[0, 3] == 7
    assert (np.asarray(data["heap"])[0, 0] == 5).all()
    assert info["pending"] == 0


# --------------------------------- batched dispatch under PGAS/AM (ISSUE 7)

from hclib_tpu.jaxcompat import has_mosaic_interpret  # noqa: E402

needs_mosaic = pytest.mark.skipif(
    not has_mosaic_interpret(),
    reason="needs pltpu.InterpretParams (Mosaic TPU interpret mode)",
)


@needs_mosaic
def test_pgas_batch_routed_am_bumps_exact():
    """ISSUE 7 acceptance (PGAS arm): AM-delivered BUMP tasks fire through
    the batched same-kind tier - the lane scratch binds positionally at
    the end of the PGAS body's 23-ref scratch tail, so this is the
    coverage that a _build edit misplacing lanes/lstate/tstats fails
    loudly. Every device AMs a BUMP at every other device; batched
    delivery must land the exact all-senders sum on each device (slot_ctx
    carries the pgas ctx_hook, so a batched AM task behaves exactly like
    scalar dispatch), and tier counters reconcile with the executed
    count."""
    ndev = 4
    mesh = cpu_mesh(ndev, axis_name="queues")
    mk = _mk(ndev=ndev, capacity=128, batch_width=4)
    pg = PGASMegakernel(
        mk, mesh, channels={"c0": ("heap", 1), "reply": ("heap", 1)},
        am_window=2,
    )

    SEND = 5

    def send_all(ctx):
        me = ctx.pgas.me
        for d in range(ndev):
            ctx.pgas.am(d, BUMP, args=[0, 1 + me])

    mk.kernel_names.append("send_all")
    mk.kernel_fns.append(send_all)
    builders = [TaskGraphBuilder() for _ in range(ndev)]
    for d in range(ndev):
        builders[d].add(SEND)
    iv, _, info = pg.run(builders, data={"heap": _heap(ndev)})
    expect = sum(1 + s for s in range(ndev))
    for d in range(ndev):
        assert iv[d, 0] == expect, (d, iv[d, 0])
    assert info["executed"] == ndev + ndev * ndev
    assert info["pending"] == 0
    tiers = info["tiers"]
    assert len(tiers) == ndev
    batched = sum(t["batch_tasks"] for t in tiers)
    scalar = sum(t["scalar_tasks"] for t in tiers)
    assert batched + scalar == info["executed"], (batched, scalar)
    assert batched > 0, tiers
