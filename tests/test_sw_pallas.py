"""Fused-Pallas Smith-Waterman (device/sw_pallas.py): exactness vs the
sequential DP, including batch/length padding paths (interpret mode)."""

import numpy as np

from hclib_tpu.device.sw_pallas import sw_scores_pallas
from hclib_tpu.device.sw_vec import sw_scores
from hclib_tpu.models.smithwaterman import random_seq, sw_seq


def test_sw_pallas_exact_vs_sequential():
    B, n, m = 6, 97, 128  # odd n exercises the multiple-of-8 padding
    A = np.stack([random_seq(n, i) for i in range(B)])
    Bs = np.stack([random_seq(m, 100 + i) for i in range(B)])
    got = sw_scores_pallas(A, Bs, interpret=True)
    want = [int(sw_seq(A[i], Bs[i]).max()) for i in range(B)]
    assert list(got) == want


def test_sw_pallas_matches_xla_engine():
    B, n, m = 9, 64, 256  # B=9 exercises lane-block padding (128-multiple)
    rng = np.random.default_rng(3)
    A = rng.integers(0, 4, (B, n)).astype(np.int32)
    Bs = rng.integers(0, 4, (B, m)).astype(np.int32)
    got = sw_scores_pallas(A, Bs, interpret=True)
    want = np.asarray(sw_scores(A, Bs))
    assert list(got) == list(want)
