"""Batched same-kind dispatch tier: per-F_FN lane partitioning, batch
bodies, cross-round prefetch, tier counters, and the SW / Cholesky wirings
(batch-vs-scalar results must be bit-identical)."""

import numpy as np
import pytest
from jax.experimental import pallas as pl

from hclib_tpu.device.descriptor import TaskGraphBuilder
from hclib_tpu.device.megakernel import BatchSpec, Megakernel
from hclib_tpu.runtime.resilience import StallError

DOUBLE, NEG = 0, 1


def _scalar_double(ctx):
    ctx.set_out(ctx.arg(0) * 2)


def _scalar_neg(ctx):
    ctx.set_out(-ctx.arg(0))


def _batch_double(ctx):
    for s in range(ctx.width):
        @pl.when(ctx.live(s))
        def _(s=s):
            ctx.set_out(s, ctx.arg(s, 0) * 2)


def _toy_mk(width=4, capacity=64):
    return Megakernel(
        kernels=[("double", _scalar_double), ("neg", _scalar_neg)],
        route={"double": BatchSpec(_batch_double, width=width)},
        capacity=capacity,
        num_values=64,
        interpret=True,
    )


def _toy_graph():
    """6 independent doubles; 3 negs each gated on one double; a second
    wave of 5 doubles gated on all negs - same-kind groups separated by a
    foreign kind, so routing, batching, and scalar dispatch all engage."""
    b = TaskGraphBuilder()
    first = [b.add(DOUBLE, args=[i], out=i) for i in range(6)]
    negs = [
        b.add(NEG, args=[10 + i], out=6 + i, deps=[first[i]])
        for i in range(3)
    ]
    b2 = [b.add(DOUBLE, args=[100 + i], out=9 + i, deps=negs) for i in range(5)]
    del b2
    return b


def test_lane_partitioning_results_and_counters():
    mk = _toy_mk()
    iv, _, info = mk.run(_toy_graph())
    assert list(iv[:6]) == [0, 2, 4, 6, 8, 10]
    assert list(iv[6:9]) == [-10, -11, -12]
    assert list(iv[9:14]) == [200, 202, 204, 206, 208]
    t = info["tiers"]
    # Every 'double' went through the batch tier, every 'neg' scalar.
    assert t["batch_tasks"] == 11
    assert t["routed"] == 11
    assert t["scalar_tasks"] == 3
    assert t["spilled"] == 0
    assert 0 < t["batch_occupancy"] <= 1.0
    assert t["batch_rounds"] * t["batch_width"] >= t["batch_tasks"]
    assert info["executed"] == 14
    # stats_dict() mirrors the last run's info for harness consumers.
    assert mk.stats_dict()["tiers"]["batch_tasks"] == 11


def test_batch_width_one_still_batches():
    mk = _toy_mk(width=1)
    iv, _, info = mk.run(_toy_graph())
    assert list(iv[:6]) == [0, 2, 4, 6, 8, 10]
    t = info["tiers"]
    assert t["batch_tasks"] == 11
    assert t["batch_rounds"] == 11
    assert t["full_rounds"] == 11


def test_fuel_exhaustion_spills_lanes_and_stalls_cleanly():
    """Fuel running out mid-lane must spill unrun entries back to the ring
    and surface as a StallError with the right pending count - tasks are
    never silently lost in a lane."""
    mk = _toy_mk(width=2)
    b = TaskGraphBuilder()
    for i in range(10):
        b.add(DOUBLE, args=[i], out=i)
    with pytest.raises(StallError) as ei:
        mk.run(b, fuel=3)
    # 2 batch rounds of 2 ran (the second crosses the fuel bound); the
    # other 6 stay pending.
    assert ei.value.stats["pending"] == 6
    assert ei.value.stats["executed"] == 4


def test_batchspec_validation():
    with pytest.raises(ValueError, match="drain"):
        BatchSpec(_batch_double, width=2, prefetch=True)
    with pytest.raises(ValueError, match="width"):
        BatchSpec(_batch_double, width=0)
    with pytest.raises(ValueError, match="route"):
        Megakernel(
            kernels=[("a", _scalar_double)],
            route={"b": BatchSpec(_batch_double)},
            interpret=True,
        )


def test_sw_batched_tier_matches_scalar_tile_engine():
    """Per-tile SW on the 3-neighbor DAG, grouped by the scheduler's lane:
    H and score bit-identical to the scalar tile engine; executed counts
    tiles; tier counters see every tile."""
    from hclib_tpu.device.smithwaterman import device_sw, device_sw_batched
    from hclib_tpu.models.smithwaterman import random_seq

    a, b = random_seq(256, 3), random_seq(384, 4)
    score_s, h_s, info_s = device_sw(a, b, interpret=True)
    score_b, h_b, info_b = device_sw_batched(a, b, interpret=True)
    assert np.array_equal(h_b, h_s)
    assert score_b == score_s
    assert info_b["executed"] == info_s["executed"] == 6
    t = info_b["tiers"]
    assert t["batch_tasks"] == 6
    assert t["scalar_tasks"] == 0


def test_sw_wave_chunked_prefetch_engages_and_stays_exact():
    """Anti-diagonals wider than one batch (chunk=1, width=2 on a 4x4 tile
    grid: mid-waves queue 3-4 descriptors): the cross-round double-
    buffered prefetch must engage (hits > 0) and the full H matrix must
    stay bit-identical to the scalar tile engine - prefetched operands are
    the same bytes the on-demand path loads."""
    from hclib_tpu.device.smithwaterman import (
        build_sw_wave_graph,
        device_sw,
        make_sw_wave_megakernel,
        sw_wave_buffers,
    )
    from hclib_tpu.models.smithwaterman import random_seq

    a, b = random_seq(512, 5), random_seq(512, 6)
    _, h_s, _ = device_sw(a, b, interpret=True)
    mk = make_sw_wave_megakernel(4, 4, interpret=True, chunk=1, width=2)
    data = sw_wave_buffers(a, b)
    data["htiles"] = np.zeros((4, 4, 128, 128), np.int32)
    iv, out, info = mk.run(build_sw_wave_graph(4, 4, chunk=1), data=data)
    h_w = np.asarray(out["htiles"]).swapaxes(1, 2).reshape(512, 512)
    assert np.array_equal(h_w, h_s)
    assert int(iv[0]) == int(h_s.max())
    t = info["tiers"]
    assert t["prefetch_hits"] > 0
    assert t["batch_tasks"] == mk.stats_dict()["tiers"]["batch_tasks"]


def test_cholesky_batched_updrow_bit_identical():
    """The batched trailing-update tier (resident L-split pipelined across
    slots) must produce the bit-identical factor of the scalar dispatch."""
    from hclib_tpu.device.cholesky import (
        device_cholesky,
        make_cholesky_megakernel,
    )
    from hclib_tpu.models.cholesky import make_spd

    a = make_spd(512).astype(np.float32)  # nt=4: 6 updrow tasks
    L_b, info_b = device_cholesky(a, interpret=True)
    mk_s = make_cholesky_megakernel(4, interpret=True, batch_updrow=False)
    L_s, info_s = device_cholesky(a, interpret=True, mk=mk_s)
    assert np.array_equal(L_b, L_s)
    assert info_b["executed"] == info_s["executed"]
    rel = np.max(np.abs(L_b @ L_b.T - a)) / np.max(np.abs(a))
    assert rel < 1e-5
    t = info_b["tiers"]
    assert t["batch_tasks"] == 6  # every updrow batched
    assert t["scalar_tasks"] == 4 + 3  # potrf + trsmcol stay scalar
    assert "tiers" not in info_s


# --------------------------------------------- mesh batch dispatch (ISSUE 7)


def _forest_run(batch_width, ndev=4, roots=10, n=8, quantum=16, window=8,
                capacity=1024):
    """Skewed fib forest (all roots on device 0) through the sharded steal
    runner, batch-routed when batch_width > 0."""
    from hclib_tpu.device.megakernel import VBLOCK
    from hclib_tpu.device.sharded import ShardedMegakernel
    from hclib_tpu.device.workloads import FIB, make_fib_megakernel
    from hclib_tpu.parallel.mesh import cpu_mesh

    mk = make_fib_megakernel(
        capacity=capacity, interpret=True,
        num_values=VBLOCK * capacity + max(64, roots),
        batch_width=batch_width or None,
    )
    smk = ShardedMegakernel(
        mk, cpu_mesh(ndev, axis_name="q"), migratable_fns=[FIB]
    )
    builders = [TaskGraphBuilder() for _ in range(ndev)]
    for r in range(roots):
        builders[0].add(FIB, args=[n], out=r)
    for b in builders:
        b.reserve_values(roots)
    iv, _, info = smk.run(
        builders, steal=True, quantum=quantum, window=window
    )
    return np.asarray(iv), info, roots, n


def test_mesh_forest_batch_bit_identical_to_scalar():
    """THE ISSUE 7 acceptance (sharded arm): the batch-routed forest-steal
    mesh computes bit-identical per-root results to the scalar mesh, with
    exact totals, nonzero batch rounds on every device that executed
    work, and tier counters reconciling with the executed count."""
    from hclib_tpu.models.fib import fib_seq, task_count

    iv_s, info_s, roots, n = _forest_run(0)
    iv_b, info_b, _, _ = _forest_run(8)
    # A migrated root writes its out slot on the thief's value buffer:
    # the per-root result is the column sum across the mesh, and it must
    # be bit-identical between the arms (placement may differ).
    per_root_s = iv_s[:, :roots].sum(axis=0)
    per_root_b = iv_b[:, :roots].sum(axis=0)
    assert np.array_equal(per_root_b, per_root_s)
    assert int(per_root_b.sum()) == roots * fib_seq(n)
    per_call = task_count(n)
    per_call += (per_call - 1) // 2
    assert info_b["executed"] == info_s["executed"] == roots * per_call
    assert "tiers" not in info_s
    tiers = info_b["tiers"]
    per_dev = np.asarray(info_b["per_device_counts"])[:, 5]  # C_EXECUTED
    batched = sum(t["batch_tasks"] for t in tiers)
    scalar = sum(t["scalar_tasks"] for t in tiers)
    assert batched + scalar == info_b["executed"]
    assert batched > 0
    for d, t in enumerate(tiers):
        if per_dev[d] > 0:
            # Every device that executed work fired same-kind batches:
            # the tier engaged mesh-wide, not just on the seed device.
            assert t["batch_rounds"] > 0, (d, t)


def test_mesh_lane_spill_at_steal_boundary():
    """A stolen row that was lane-resident on the victim: with a small
    quantum the victim's sched() exits every round with unrun lane
    entries, which spill to the ready ring's cold (head) end - exactly
    the window the steal exchange scans - so the forest still spreads
    and totals stay exact. The spilled counter proves rows crossed a
    steal boundary through a lane."""
    from hclib_tpu.models.fib import fib_seq

    iv, info, roots, n = _forest_run(8, roots=16, n=7, quantum=8)
    tiers = info["tiers"]
    per_dev = np.asarray(info["per_device_counts"])[:, 5]
    # The victim (seed device 0) spilled lane entries at steal
    # boundaries, and the load still spread beyond it.
    assert tiers[0]["spilled"] > 0, tiers[0]
    assert int((per_dev > 0).sum()) >= 2, per_dev
    assert int(iv[:, :roots].sum(dtype=np.int64)) == roots * fib_seq(n)
    assert info["pending"] == 0


def test_megakernel_quiesce_with_lanes_resumes_bit_identical():
    """Checkpoint with lanes active on the single-device scheduler: a
    quiesce cut spills lane-resident descriptors to the ready ring's
    cold end (C_HEAD walks negative), the exported state restages the
    wrapped window, and the resumed run completes bit-identically to the
    uninterrupted one."""
    from hclib_tpu.device.megakernel import C_HEAD, VBLOCK
    from hclib_tpu.device.workloads import FIB, make_fib_megakernel
    from hclib_tpu.models.fib import fib_seq, task_count

    def mk_of():
        cap = 512
        return make_fib_megakernel(
            capacity=cap, interpret=True,
            num_values=VBLOCK * cap + 16,
            batch_width=4, checkpoint=True,
        )

    def builder():
        b = TaskGraphBuilder()
        b.add(FIB, args=[10], out=0)
        return b

    iv_f, _, info_f = mk_of().run(builder())
    assert int(iv_f[0]) == fib_seq(10)

    mk = mk_of()
    iv_q, _, info_q = mk.run(builder(), quiesce=40)
    assert info_q["quiesced"] is True
    assert info_q["pending"] > 0
    st = info_q["state"]
    if info_q["tiers"]["spilled"] > 0:
        # Lane spills insert at the ring's cold end: the head walks
        # below zero and stage() must widen its restage copy over the
        # wrapped window (asserted implicitly by the exact resume).
        assert int(st["counts"][C_HEAD]) < 0
    iv_r, _, info_r = mk.resume(st)
    assert info_r["pending"] == 0
    assert int(iv_r[0]) == fib_seq(10)
    t = task_count(10)
    assert info_r["executed"] == t + (t - 1) // 2 == info_f["executed"]


def test_vector_and_batch_tiers_coexist():
    """One megakernel can route different kinds to different tiers: a
    vector-tier fib family next to a batch-tier kind, both feeding scalar
    join tasks."""
    from hclib_tpu.device.vector_engine import fib_spec

    def scalar_fib_stub(ctx):  # semantic definition, replaced by routing
        ctx.set_out(0)

    def scalar_sum(ctx):
        ctx.set_out(ctx.value(ctx.arg(0)) + ctx.value(ctx.arg(1)))

    mk = Megakernel(
        kernels=[
            ("fib", scalar_fib_stub),
            ("double", _scalar_double),
            ("sum", scalar_sum),
        ],
        route={
            "fib": fib_spec(max_n=12, lanes=(1, 8)),
            "double": BatchSpec(_batch_double, width=2),
        },
        capacity=64,
        num_values=64,
        interpret=True,
    )
    b = TaskGraphBuilder()
    f = b.add(0, args=[10], out=0)  # fib(10) = 55 via the vector tier
    d = b.add(1, args=[21], out=1, deps=[])  # 42 via the batch tier
    b.add(2, args=[0, 1], out=2, deps=[f, d])  # 97 via the scalar tier
    iv, _, info = mk.run(b)
    assert iv[0] == 55 and iv[1] == 42 and iv[2] == 97
    assert info["tiers"]["batch_tasks"] == 1
