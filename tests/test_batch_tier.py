"""Batched same-kind dispatch tier: per-F_FN lane partitioning, batch
bodies, cross-round prefetch, tier counters, and the SW / Cholesky wirings
(batch-vs-scalar results must be bit-identical)."""

import numpy as np
import pytest
from jax.experimental import pallas as pl

from hclib_tpu.device.descriptor import TaskGraphBuilder
from hclib_tpu.device.megakernel import BatchSpec, Megakernel
from hclib_tpu.runtime.resilience import StallError

DOUBLE, NEG = 0, 1


def _scalar_double(ctx):
    ctx.set_out(ctx.arg(0) * 2)


def _scalar_neg(ctx):
    ctx.set_out(-ctx.arg(0))


def _batch_double(ctx):
    for s in range(ctx.width):
        @pl.when(ctx.live(s))
        def _(s=s):
            ctx.set_out(s, ctx.arg(s, 0) * 2)


def _toy_mk(width=4, capacity=64):
    return Megakernel(
        kernels=[("double", _scalar_double), ("neg", _scalar_neg)],
        route={"double": BatchSpec(_batch_double, width=width)},
        capacity=capacity,
        num_values=64,
        interpret=True,
    )


def _toy_graph():
    """6 independent doubles; 3 negs each gated on one double; a second
    wave of 5 doubles gated on all negs - same-kind groups separated by a
    foreign kind, so routing, batching, and scalar dispatch all engage."""
    b = TaskGraphBuilder()
    first = [b.add(DOUBLE, args=[i], out=i) for i in range(6)]
    negs = [
        b.add(NEG, args=[10 + i], out=6 + i, deps=[first[i]])
        for i in range(3)
    ]
    b2 = [b.add(DOUBLE, args=[100 + i], out=9 + i, deps=negs) for i in range(5)]
    del b2
    return b


def test_lane_partitioning_results_and_counters():
    mk = _toy_mk()
    iv, _, info = mk.run(_toy_graph())
    assert list(iv[:6]) == [0, 2, 4, 6, 8, 10]
    assert list(iv[6:9]) == [-10, -11, -12]
    assert list(iv[9:14]) == [200, 202, 204, 206, 208]
    t = info["tiers"]
    # Every 'double' went through the batch tier, every 'neg' scalar.
    assert t["batch_tasks"] == 11
    assert t["routed"] == 11
    assert t["scalar_tasks"] == 3
    assert t["spilled"] == 0
    assert 0 < t["batch_occupancy"] <= 1.0
    assert t["batch_rounds"] * t["batch_width"] >= t["batch_tasks"]
    assert info["executed"] == 14
    # stats_dict() mirrors the last run's info for harness consumers.
    assert mk.stats_dict()["tiers"]["batch_tasks"] == 11


def test_batch_width_one_still_batches():
    mk = _toy_mk(width=1)
    iv, _, info = mk.run(_toy_graph())
    assert list(iv[:6]) == [0, 2, 4, 6, 8, 10]
    t = info["tiers"]
    assert t["batch_tasks"] == 11
    assert t["batch_rounds"] == 11
    assert t["full_rounds"] == 11


def test_fuel_exhaustion_spills_lanes_and_stalls_cleanly():
    """Fuel running out mid-lane must spill unrun entries back to the ring
    and surface as a StallError with the right pending count - tasks are
    never silently lost in a lane."""
    mk = _toy_mk(width=2)
    b = TaskGraphBuilder()
    for i in range(10):
        b.add(DOUBLE, args=[i], out=i)
    with pytest.raises(StallError) as ei:
        mk.run(b, fuel=3)
    # 2 batch rounds of 2 ran (the second crosses the fuel bound); the
    # other 6 stay pending.
    assert ei.value.stats["pending"] == 6
    assert ei.value.stats["executed"] == 4


def test_batchspec_validation():
    with pytest.raises(ValueError, match="drain"):
        BatchSpec(_batch_double, width=2, prefetch=True)
    with pytest.raises(ValueError, match="width"):
        BatchSpec(_batch_double, width=0)
    with pytest.raises(ValueError, match="route"):
        Megakernel(
            kernels=[("a", _scalar_double)],
            route={"b": BatchSpec(_batch_double)},
            interpret=True,
        )


def test_sw_batched_tier_matches_scalar_tile_engine():
    """Per-tile SW on the 3-neighbor DAG, grouped by the scheduler's lane:
    H and score bit-identical to the scalar tile engine; executed counts
    tiles; tier counters see every tile."""
    from hclib_tpu.device.smithwaterman import device_sw, device_sw_batched
    from hclib_tpu.models.smithwaterman import random_seq

    a, b = random_seq(256, 3), random_seq(384, 4)
    score_s, h_s, info_s = device_sw(a, b, interpret=True)
    score_b, h_b, info_b = device_sw_batched(a, b, interpret=True)
    assert np.array_equal(h_b, h_s)
    assert score_b == score_s
    assert info_b["executed"] == info_s["executed"] == 6
    t = info_b["tiers"]
    assert t["batch_tasks"] == 6
    assert t["scalar_tasks"] == 0


def test_sw_wave_chunked_prefetch_engages_and_stays_exact():
    """Anti-diagonals wider than one batch (chunk=1, width=2 on a 4x4 tile
    grid: mid-waves queue 3-4 descriptors): the cross-round double-
    buffered prefetch must engage (hits > 0) and the full H matrix must
    stay bit-identical to the scalar tile engine - prefetched operands are
    the same bytes the on-demand path loads."""
    from hclib_tpu.device.smithwaterman import (
        build_sw_wave_graph,
        device_sw,
        make_sw_wave_megakernel,
        sw_wave_buffers,
    )
    from hclib_tpu.models.smithwaterman import random_seq

    a, b = random_seq(512, 5), random_seq(512, 6)
    _, h_s, _ = device_sw(a, b, interpret=True)
    mk = make_sw_wave_megakernel(4, 4, interpret=True, chunk=1, width=2)
    data = sw_wave_buffers(a, b)
    data["htiles"] = np.zeros((4, 4, 128, 128), np.int32)
    iv, out, info = mk.run(build_sw_wave_graph(4, 4, chunk=1), data=data)
    h_w = np.asarray(out["htiles"]).swapaxes(1, 2).reshape(512, 512)
    assert np.array_equal(h_w, h_s)
    assert int(iv[0]) == int(h_s.max())
    t = info["tiers"]
    assert t["prefetch_hits"] > 0
    assert t["batch_tasks"] == mk.stats_dict()["tiers"]["batch_tasks"]


def test_cholesky_batched_updrow_bit_identical():
    """The batched trailing-update tier (resident L-split pipelined across
    slots) must produce the bit-identical factor of the scalar dispatch."""
    from hclib_tpu.device.cholesky import (
        device_cholesky,
        make_cholesky_megakernel,
    )
    from hclib_tpu.models.cholesky import make_spd

    a = make_spd(512).astype(np.float32)  # nt=4: 6 updrow tasks
    L_b, info_b = device_cholesky(a, interpret=True)
    mk_s = make_cholesky_megakernel(4, interpret=True, batch_updrow=False)
    L_s, info_s = device_cholesky(a, interpret=True, mk=mk_s)
    assert np.array_equal(L_b, L_s)
    assert info_b["executed"] == info_s["executed"]
    rel = np.max(np.abs(L_b @ L_b.T - a)) / np.max(np.abs(a))
    assert rel < 1e-5
    t = info_b["tiers"]
    assert t["batch_tasks"] == 6  # every updrow batched
    assert t["scalar_tasks"] == 4 + 3  # potrf + trsmcol stay scalar
    assert "tiers" not in info_s


def test_vector_and_batch_tiers_coexist():
    """One megakernel can route different kinds to different tiers: a
    vector-tier fib family next to a batch-tier kind, both feeding scalar
    join tasks."""
    from hclib_tpu.device.vector_engine import fib_spec

    def scalar_fib_stub(ctx):  # semantic definition, replaced by routing
        ctx.set_out(0)

    def scalar_sum(ctx):
        ctx.set_out(ctx.value(ctx.arg(0)) + ctx.value(ctx.arg(1)))

    mk = Megakernel(
        kernels=[
            ("fib", scalar_fib_stub),
            ("double", _scalar_double),
            ("sum", scalar_sum),
        ],
        route={
            "fib": fib_spec(max_n=12, lanes=(1, 8)),
            "double": BatchSpec(_batch_double, width=2),
        },
        capacity=64,
        num_values=64,
        interpret=True,
    )
    b = TaskGraphBuilder()
    f = b.add(0, args=[10], out=0)  # fib(10) = 55 via the vector tier
    d = b.add(1, args=[21], out=1, deps=[])  # 42 via the batch tier
    b.add(2, args=[0, 1], out=2, deps=[f, d])  # 97 via the scalar tier
    iv, _, info = mk.run(b)
    assert iv[0] == 55 and iv[1] == 42 and iv[2] == 97
    assert info["tiers"]["batch_tasks"] == 1
