"""Host -> resident-kernel task injection (device/inject.py).

Reference counterpart: materializing work on a running runtime from outside
(/root/reference/modules/openshmem-am/src/hclib_openshmem-am.cpp:64-123)."""

import threading
import time

import jax
import pytest

from hclib_tpu.device.descriptor import TaskGraphBuilder
from hclib_tpu.device.inject import StreamingMegakernel
from hclib_tpu.device.megakernel import Megakernel
from hclib_tpu.device.workloads import FIB, make_fib_megakernel

BUMP = 0


def _bump_kernel(ctx):
    ctx.set_value(0, ctx.value(0) + ctx.arg(0))


def _bump_mk(interpret=True):
    return Megakernel(
        kernels=[("bump", _bump_kernel)],
        capacity=128, num_values=4, succ_capacity=8, interpret=interpret,
    )


def fib(n):
    a, b = 0, 1
    for _ in range(n):
        a, b = b, a + b
    return a


def tree_tasks(n):
    if n < 2:
        return 1
    return 1 + tree_tasks(n - 1) + tree_tasks(n - 2)


def test_ring_rows_discovered_by_in_kernel_poll():
    """Injected rows are NEVER staged with the graph - they can only enter
    through the in-kernel ring poll; exact totals prove that path."""
    sm = StreamingMegakernel(_bump_mk(), ring_capacity=64)
    b = TaskGraphBuilder()
    b.add(BUMP, args=[1000])
    for i in range(20):
        sm.inject(BUMP, args=[i + 1])
    sm.close()
    iv, info = sm.run_stream(b)
    assert info["executed"] == 21
    assert info["injected"] == 20
    assert int(iv[0]) == 1000 + 20 * 21 // 2


def test_concurrent_feeder_thread():
    """A host thread appends fib seeds while the stream runs; every seed's
    value lands in its out slot and the task totals are exact."""
    mk = make_fib_megakernel(capacity=768, interpret=True)
    sm = StreamingMegakernel(mk, ring_capacity=32)
    b = TaskGraphBuilder()
    b.add(FIB, args=[10], out=0)
    b.reserve_values(10)
    ns = [5, 7, 8, 9, 11, 6, 4, 12]

    def feeder():
        for i, n in enumerate(ns):
            sm.inject(FIB, args=[n], out=1 + i)
            time.sleep(0.02)
        sm.close()

    t = threading.Thread(target=feeder)
    t.start()
    iv, info = sm.run_stream(b, quantum=64)
    t.join()
    assert int(iv[0]) == fib(10)
    for i, n in enumerate(ns):
        assert int(iv[1 + i]) == fib(n), (i, n)
    assert info["injected"] == len(ns)
    # Scalar-tier fib counts FIB nodes plus SUM joins: t + (t-1)//2.
    scalar_tasks = lambda n: tree_tasks(n) + (tree_tasks(n) - 1) // 2
    assert info["executed"] == sum(scalar_tasks(n) for n in [10] + ns)


def test_inject_after_close_raises():
    sm = StreamingMegakernel(_bump_mk(), ring_capacity=8)
    sm.close()
    with pytest.raises(RuntimeError):
        sm.inject(BUMP, args=[1])


@pytest.mark.skipif(jax.default_backend() != "tpu", reason="needs TPU")
def test_streaming_on_tpu():
    """The ring poll + install path through real Mosaic lowering."""
    sm = StreamingMegakernel(_bump_mk(interpret=False), ring_capacity=64)
    b = TaskGraphBuilder()
    b.add(BUMP, args=[7])
    for i in range(10):
        sm.inject(BUMP, args=[i + 1])
    sm.close()
    iv, info = sm.run_stream(b)
    assert info["executed"] == 11
    assert int(iv[0]) == 7 + 55
