"""Device Cholesky (MXU tiles) and Smith-Waterman (VPU wavefront) tests."""

import jax
import numpy as np
import pytest

from hclib_tpu.device.cholesky import build_cholesky_graph, device_cholesky
from hclib_tpu.device.smithwaterman import device_sw
from hclib_tpu.models.cholesky import make_spd
from hclib_tpu.models.smithwaterman import random_seq, sw_seq

on_tpu = jax.default_backend() == "tpu"


def test_cholesky_graph_structure():
    # fused default: 4 potrf + 3 column TRSM streams + 6 row updates
    b = build_cholesky_graph(4)
    assert b.num_tasks == 4 + 3 + 6
    _, _, ring, counts = b.finalize(capacity=32, succ_capacity=128)
    assert counts[1] == 1  # only potrf(0) initially ready
    # tile-level TRSM (the reference's granularity): one task per tile
    b2 = build_cholesky_graph(4, fused_trsm=False)
    assert b2.num_tasks == 4 + 6 + 6
    _, _, _, counts2 = b2.finalize(capacity=32, succ_capacity=128)
    assert counts2[1] == 1


def test_device_cholesky_interpret():
    a = make_spd(256).astype(np.float32)
    L, info = device_cholesky(a, interpret=True)
    rel = np.max(np.abs(L @ L.T - a)) / np.max(np.abs(a))
    assert rel < 1e-5
    assert info["executed"] == 4


def test_device_cholesky_interpret_blocked_potrf():
    """tile=256 with factor_base=128 exercises the recursive 2x2 blocked
    factor_and_inv path (panel/update/inverse as block algebra) - the
    default base of min(tile, 256) would factor a 256 tile directly."""
    from hclib_tpu.device.cholesky import make_cholesky_megakernel

    a = make_spd(512).astype(np.float32)
    mk = make_cholesky_megakernel(2, interpret=True, tile=256,
                                  factor_base=128)
    L, info = device_cholesky(a, interpret=True, tile=256, mk=mk)
    rel = np.max(np.abs(L @ L.T - a)) / np.max(np.abs(a))
    assert rel < 1e-5
    assert info["executed"] == 4


def test_device_sw_interpret_multi_tile():
    a, b = random_seq(256, 3), random_seq(384, 4)
    score, h, info = device_sw(a, b, interpret=True)
    ref = sw_seq(a, b)[1:, 1:]
    assert np.array_equal(h, ref)
    assert score == int(ref.max())
    assert info["executed"] == 6


def test_device_sw_rejects_unaligned():
    with pytest.raises(ValueError):
        device_sw(random_seq(100, 1), random_seq(128, 2), interpret=True)


def test_device_sw_wave_interpret_exact():
    """The wave-batched SW engine (VERDICT r3 #4: the tile wavefront
    riding the vector tier - up to 8 anti-diagonal tiles as stacked VPU
    planes per task, wave chunks chained by real dependencies): exact
    against the sequential DP, and 'executed' counts tiles."""
    from hclib_tpu.device.smithwaterman import device_sw_wave

    a, b = random_seq(256, 3), random_seq(384, 4)
    score, h, info = device_sw_wave(a, b, interpret=True)
    ref = sw_seq(a, b)[1:, 1:]
    assert np.array_equal(h, ref)
    assert score == int(ref.max())
    assert info["executed"] == 6  # 2x3 tiles


@pytest.mark.skipif(not on_tpu, reason="needs TPU")
def test_device_sw_wave_tpu_matches_tile_engine():
    """On hardware, with anti-diagonals wider than one wave chunk (10x10
    tiles -> two chunks on the middle diagonals): the wave engine's full H
    matrix equals the tile-at-a-time engine's."""
    from hclib_tpu.device.smithwaterman import device_sw_wave

    a, b = random_seq(1280, 7), random_seq(1280, 8)
    score_t, h_t, info_t = device_sw(a, b, interpret=False)
    score_w, h_w, info_w = device_sw_wave(a, b, interpret=False)
    assert np.array_equal(h_w, h_t)
    assert score_w == score_t
    assert info_w["executed"] == info_t["executed"] == 100  # tiles


@pytest.mark.skipif(not on_tpu, reason="needs TPU")
def test_device_cholesky_tpu():
    a = make_spd(512).astype(np.float32)
    L, info = device_cholesky(a, interpret=False)
    rel = np.max(np.abs(L @ L.T - a)) / np.max(np.abs(a))
    assert rel < 1e-5, rel


@pytest.mark.skipif(not on_tpu, reason="needs TPU")
def test_device_cholesky_tpu_tile512():
    """The bench configuration's tile size: recursion depth 2 in
    factor_and_inv (512 -> 256 -> 128 base), residual checked on hardware
    (MXU precision differs from the interpret path)."""
    a = make_spd(1024).astype(np.float32)
    L, info = device_cholesky(a, interpret=False, tile=512)
    rel = np.max(np.abs(L @ L.T - a)) / np.max(np.abs(a))
    assert rel < 1e-5, rel
    assert info["executed"] == 4


@pytest.mark.skipif(not on_tpu, reason="needs TPU")
def test_device_sw_tpu():
    a, b = random_seq(256, 5), random_seq(256, 6)
    score, h, info = device_sw(a, b, interpret=False)
    ref = sw_seq(a, b)[1:, 1:]
    assert np.array_equal(h, ref)
