"""Cross-process comm (modules/procworld.py): 2 REAL processes wired by
jax.distributed, exchanging send/recv, allreduce, barrier, symmetric-heap
put/get, and active messages through the coordination service.

Reference counterpart: modules/mpi + modules/openshmem(+-am) under mpirun
(/root/reference/modules/mpi/src/hclib_mpi.cpp:107-286)."""

import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "procworld_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _run_world(n: int, timeout: int = 180) -> None:
    port = str(_free_port())
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(pid), str(n), port],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in range(n)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {pid} failed:\n{out}"
        assert f"rank {pid}: OK" in out, out


def test_two_process_world():
    _run_world(2)


def test_four_process_world():
    """Non-trivial fan-out: recursive-doubling allreduce (4 = full
    doubling), bulk collective bridge, and the module layer, across 4 real
    processes."""
    _run_world(4)
