"""Observability: event instrumentation, state timer, stats, watchdog.

The reference's instrumentation recorder is stubbed (src/hclib-instrument.c:
211-252); here it must actually record and round-trip.
"""

import time

import numpy as np

import hclib_tpu as hc
from hclib_tpu.runtime.instrument import END, START, load_dump, register_event_type
from hclib_tpu.runtime.timer import IDLE, WORK, StateTimer


def test_event_log_records_and_dumps(tmp_path):
    rt = hc.Runtime(nworkers=2, instrument=True)

    def body():
        with hc.finish():
            for _ in range(10):
                hc.async_(lambda: None)

    rt.run(body)
    path = rt.event_log.dump(str(tmp_path))
    names, per_worker = load_dump(path)
    assert "task" in names
    events = np.concatenate(list(per_worker.values()))
    starts = events[events["transition"] == START]
    ends = events[events["transition"] == END]
    # every executed task produced a START/END pair with matching ids
    assert len(starts) >= 11 and len(ends) == len(starts)
    assert set(starts["id"]) == set(ends["id"])
    # timestamps are monotonic per worker
    for w, ev in per_worker.items():
        ts = ev["ts_ns"]
        assert np.all(np.diff(ts) >= 0)


def test_event_log_double_buffer_overflow(tmp_path):
    from hclib_tpu.runtime.instrument import EventLog

    log = EventLog(1, capacity=8)
    t = register_event_type("x")
    for i in range(30):
        log.record(0, t, 2, i)
    path = log.dump(str(tmp_path))
    _, per_worker = load_dump(path)
    assert len(per_worker[0]) == 30
    assert list(per_worker[0]["id"]) == list(range(30))


def test_custom_event_type_ids_stable():
    a = register_event_type("my_phase")
    b = register_event_type("my_phase")
    assert a == b


def test_state_timer_accumulates():
    st = StateTimer(1)
    st.set_state(0, WORK)
    time.sleep(0.02)
    st.set_state(0, IDLE)
    time.sleep(0.01)
    st.finalize()
    totals = st.totals_ns()[0]
    assert totals["WORK"] >= 15_000_000
    assert totals["IDLE"] >= 5_000_000
    assert st.avg_time_ns(WORK) == totals["WORK"]
    assert "WORK".lower() in st.format().lower()


def test_runtime_timer_marks_work_and_search():
    rt = hc.Runtime(nworkers=2, timer=True)

    def body():
        with hc.finish():
            for _ in range(20):
                hc.async_(lambda: time.sleep(0.001))

    rt.run(body)
    totals = rt.state_timer.totals_ns()
    assert sum(t["WORK"] for t in totals) > 0


def test_watchdog_reports_stall(caplog):
    """A task that sleeps while holding the only path to progress triggers
    the stall report (the hazard test/deadlock/README documents), routed
    through logging so tests can assert on it (escalation to StallError
    is covered in test_resilience.py)."""
    import logging

    rt = hc.Runtime(nworkers=1, watchdog_s=0.2, watchdog_escalate=False)

    def body():
        time.sleep(0.7)  # outstanding work, no task transitions

    with caplog.at_level(logging.WARNING, logger="hclib_tpu.resilience"):
        rt.run(body)
    assert rt.stall_reports >= 1
    assert any("watchdog" in r.message for r in caplog.records)


def test_watchdog_quiet_on_healthy_run():
    rt = hc.Runtime(nworkers=2, watchdog_s=5.0)

    def body():
        with hc.finish():
            for _ in range(5):
                hc.async_(lambda: None)

    rt.run(body)
    assert rt.stall_reports == 0


def test_stats_format_contains_steals():
    rt = hc.Runtime(nworkers=2, stats=False)

    def body():
        with hc.finish():
            for _ in range(50):
                hc.async_(lambda: time.sleep(0.0005))

    rt.run(body)
    text = rt.format_stats()
    assert "executed=" in text and "steals=" in text
    executed = sum(st.executed for st in rt.worker_stats)
    assert executed >= 51


def test_windowed_trials_stats_survive_sheared_trials():
    """Slope-based trials can land nonpositive under clock shear; stats()
    must exclude them from the pool but still count them in n_trials, and
    degrade to a 0.0 'all-sheared' summary (never None) when every trial
    sheared - bench.py formats median/best unconditionally."""
    from hclib_tpu.runtime.clockprobe import WindowedTrials

    class FakeProbe:
        best = 50.0

        def sample(self, note=""):
            return 50.0

        def is_fast(self, v):
            return v > 40

    wt = WindowedTrials("sheared", probe=FakeProbe(), log_dir=None)
    for v in (-1.0, -2.0):
        wt.run(lambda v=v: v)
    s = wt.stats()
    assert s["window"] == "all-sheared"
    assert s["median"] == 0.0 and s["best"] == 0.0
    assert s["n_trials"] == 2 and s["n_used"] == 0

    wt2 = WindowedTrials("mixed", probe=FakeProbe(), log_dir=None)
    for v in (5.0, -1.0, 7.0):
        wt2.run(lambda v=v: v)
    s2 = wt2.stats()
    assert s2["median"] == 6.0
    assert s2["n_trials"] == 3 and s2["n_used"] == 2 and s2["n_fast"] == 2


def test_event_log_external_lane_counts_non_worker_records(tmp_path):
    """Records from non-worker threads (module init, watchdog, procworld
    engines) used to vanish; they now land in the external lane and are
    counted (the satellite fix)."""
    from hclib_tpu.runtime.instrument import EventLog, load_manifest

    log = EventLog(2, capacity=16)
    t = register_event_type("ext_evt")
    log.record(0, t, 2, 1)      # worker lane
    log.record(-1, t, 2, 2)     # main/module context (no identity)
    log.record(99, t, 2, 3)     # out-of-range id
    assert log.external_records == 2
    path = log.dump(str(tmp_path))
    names, per_worker = load_dump(path)
    man = load_manifest(path)
    assert man["external_lane"] == 2 and man["external_records"] == 2
    assert len(per_worker[2]) == 2
    assert sorted(per_worker[2]["id"]) == [2, 3]


def test_watchdog_stall_event_lands_in_external_lane(tmp_path, caplog):
    import logging

    rt = hc.Runtime(nworkers=1, watchdog_s=0.15, watchdog_escalate=False,
                    instrument=True)

    def body():
        time.sleep(0.5)

    with caplog.at_level(logging.WARNING, logger="hclib_tpu.resilience"):
        rt.run(body)
    assert rt.stall_reports >= 1
    # The watchdog thread's 'stall' records route to the external lane
    # (writing worker 0's lock-free buffer from another thread was a
    # race).
    assert rt.event_log.external_records >= 1


def _timeline():
    from conftest import timeline_mod

    return timeline_mod()


def test_spans_from_events_empty_and_open_paths(tmp_path):
    timeline = _timeline()
    from hclib_tpu.runtime.instrument import _EVENT_DTYPE, EventLog

    # Empty input: no spans, no crash.
    assert timeline.spans_from_events(np.zeros(0, _EVENT_DTYPE)) == []
    # Open span (START without END): kept, flagged, closed at last ts.
    ev = np.zeros(3, _EVENT_DTYPE)
    ev[0] = (100, 0, START, 1)   # never ends
    ev[1] = (200, 0, START, 2)
    ev[2] = (300, 0, END, 2)
    spans = timeline.spans_from_events(ev)
    open_ = [s for s in spans if s.get("open")]
    assert len(spans) == 2 and len(open_) == 1
    assert open_[0]["t0"] == 100 and open_[0]["t1"] == 300
    # Empty-dump render path.
    log = EventLog(1, capacity=4)
    path = log.dump(str(tmp_path))
    text = timeline.render_dump(path)
    assert "(no events recorded)" in text
    # render_stats / render_device_report degrade on empty inputs.
    assert "0 tasks executed" in timeline.render_stats({"workers": []})
    assert "(no per_device_counts in info)" in (
        timeline.render_device_report({"executed": 1})
    )


def test_render_dump_density_vectorization_matches_bruteforce():
    """The np.add.at density must equal the old O(spans*width) loop."""
    timeline = _timeline()
    rng = np.random.default_rng(3)
    width, t_lo, total = 37, 1000, 50000
    bucket = total / width
    spans = []
    for _ in range(200):
        a = int(rng.integers(t_lo, t_lo + total))
        b = int(rng.integers(a, t_lo + total + 1))
        spans.append({"type": 0, "id": 0, "t0": a, "t1": b})
    got = timeline._density(spans, t_lo, bucket, width)
    want = np.zeros(width)
    for s in spans:
        b0 = (s["t0"] - t_lo) / bucket
        b1 = max((s["t1"] - t_lo) / bucket, b0 + 1e-9)
        for bk in range(int(b0), min(int(np.ceil(b1)), width)):
            want[bk] += max(0.0, min(b1, bk + 1) - max(b0, bk))
    assert np.allclose(got, want, atol=1e-6)


def test_render_dump_labels_unknown_types_and_top(tmp_path):
    timeline = _timeline()
    from hclib_tpu.runtime.instrument import EventLog

    log = EventLog(1, capacity=16)
    # A type id past the manifest (simulates a foreign/stale dump).
    log.record(0, 999, START, 1)
    log.record(0, 999, END, 1)
    path = log.dump(str(tmp_path))
    text = timeline.render_dump(path, top=2)
    assert "type<999>" in text
    assert "top 1 spans by duration" in text


def test_metrics_registry_snapshot_delta_and_exports():
    from hclib_tpu.runtime.metrics import MetricsRegistry

    reg = MetricsRegistry()
    live = {"executed": 10, "nested": {"a": 1.5, "flag": True}}
    reg.register("rt", lambda: live)
    reg.record("run", {"tasks": 100, "skip_me": "string", "arr": [1, 2]})
    s1 = reg.snapshot()
    m = s1["metrics"]
    assert m["rt.executed"] == 10.0
    assert m["rt.nested.a"] == 1.5
    assert m["rt.nested.flag"] == 1.0
    assert m["run.tasks"] == 100.0
    assert m["run.arr.0"] == 1.0 and m["run.arr.1"] == 2.0
    assert "run.skip_me" not in m  # strings are not metrics
    live["executed"] = 25
    s2 = reg.snapshot()
    d = MetricsRegistry.delta(s1, s2)
    assert d["metrics"]["rt.executed"] == 15.0
    assert d["metrics"]["run.tasks"] == 0.0
    assert d["t"] >= 0.0
    # JSON export round-trips; Prometheus text is well-formed gauges.
    import json as _json

    assert _json.loads(reg.to_json(s2))["metrics"]["rt.executed"] == 25.0
    prom = reg.to_prometheus(s2)
    assert "# TYPE hclib_tpu_rt_executed gauge" in prom
    assert "hclib_tpu_rt_executed 25.0" in prom
    # A raising live source degrades to an error flag, not a crash.
    reg.register("bad", lambda: (_ for _ in ()).throw(RuntimeError("x")))
    assert reg.snapshot()["metrics"]["bad.error"] == 1.0


def test_metrics_registry_add_run_info_summarizes_device_shapes():
    from hclib_tpu.device import tracebuf as tb
    from hclib_tpu.runtime.metrics import MetricsRegistry

    import numpy as _np

    trace = {
        "epoch": {"t0_ns": 0, "t1_ns": 10},
        "rings": [{
            "written": 3, "dropped": 1, "capacity": 2,
            "records": _np.array(
                [[tb.TR_FIRE_SCALAR, 0, 0, 0],
                 [tb.TR_ROUND_END, 1, 1, 0]], dtype=_np.int64),
        }],
    }
    info = {
        "executed": 7,
        "tiers": {"batch_tasks": 5},
        "per_device_counts": _np.zeros((2, 8), _np.int32),
        "extra_outputs": [object()],  # must be dropped, not flattened
        "trace": trace,
    }
    reg = MetricsRegistry()
    reg.add_run_info("dev", info)
    m = reg.snapshot()["metrics"]
    assert m["dev.executed"] == 7.0
    assert m["dev.tiers.batch_tasks"] == 5.0
    assert m["dev.trace.fire_scalar"] == 1.0
    assert m["dev.trace.dropped"] == 1.0
    assert m["dev.per_device_executed.0"] == 0.0
    assert not any(k.startswith("dev.extra_outputs") for k in m)


def test_metrics_lane_occupancy_gauge_per_device():
    """Batch-routed runs export one lane-occupancy gauge per device
    (mesh runs return ``tiers`` as a per-device list; a single-device
    dict normalizes to a one-entry list) - the ROADMAP lane-firing-
    policy detector, readable straight off a Prometheus scrape."""
    from hclib_tpu.runtime.metrics import MetricsRegistry

    reg = MetricsRegistry()
    reg.add_run_info("mesh", {
        "executed": 12,
        "tiers": [
            {"batch_occupancy": 0.75, "batch_tasks": 6},
            {"batch_occupancy": 0.5, "batch_tasks": 2},
        ],
    })
    reg.add_run_info("solo", {
        "executed": 3,
        "tiers": {"batch_occupancy": 1.0, "batch_tasks": 3},
    })
    reg.add_run_info("scalar", {"executed": 1})  # no tiers: no gauge
    m = reg.snapshot()["metrics"]
    assert m["mesh.lane_occupancy.0"] == 0.75
    assert m["mesh.lane_occupancy.1"] == 0.5
    assert m["solo.lane_occupancy.0"] == 1.0
    assert not any(k.startswith("scalar.lane_occupancy") for k in m)
    prom = reg.to_prometheus()
    assert "hclib_tpu_mesh_lane_occupancy_1 0.5" in prom


def test_runtime_metrics_wiring():
    rt = hc.Runtime(nworkers=2, metrics=True)

    def body():
        with hc.finish():
            for _ in range(10):
                hc.async_(lambda: None)

    rt.run(body)
    m = rt.metrics.snapshot()["metrics"]
    assert sum(
        v for k, v in m.items()
        if k.startswith("runtime.workers.") and k.endswith(".executed")
    ) >= 11


def test_timeline_renders_dump_and_reports(tmp_path):
    """tools/timeline.py turns a dump + info/stats dicts into readable
    reports (the reference's tools/timeline.py + instrument parser
    station)."""
    timeline = _timeline()

    rt = hc.Runtime(nworkers=2, instrument=True)

    def body():
        with hc.finish():
            for _ in range(25):
                hc.async_(lambda: time.sleep(0.0002))

    rt.run(body)
    stats = rt.stats_dict()
    path = rt.event_log.dump(str(tmp_path))

    text = timeline.render_dump(path)
    assert "per-worker timeline" in text
    assert "task" in text  # the registered event type shows up
    assert "w0" in text and "w1" in text
    assert "% busy" in text

    # START/END pairing: spans exist and have nonnegative durations
    names, by_worker = load_dump(path)
    spans = [
        s
        for w, ev in by_worker.items()
        for s in timeline.spans_from_events(ev)
    ]
    assert len(spans) >= 26
    assert all(s["t1"] >= s["t0"] for s in spans)

    # host stats report incl. steal matrix layout
    stext = timeline.render_stats(stats)
    assert "executed=" in stext and "w0" in stext

    # device report from a resident-style info dict
    info = {
        "name": "uts steal",
        "executed": 1000,
        "rounds": 7,
        "seconds": 0.5,
        "per_device_counts": [
            [0, 0, 200, 0, 4, 300, 0, 7],
            [0, 0, 180, 0, 4, 700, 0, 7],
        ],
    }
    dtext = timeline.render_device_report(info)
    assert "dev0" in dtext and "dev1" in dtext
    assert "1,000 tasks" in dtext
    assert "imbalance" in dtext

    # CLI round-trips via files
    import json as _json

    f = tmp_path / "info.json"
    f.write_text(_json.dumps(info))
    rc = timeline.main([str(path), "--device", str(f)])
    assert rc == 0


def test_tenant_metrics_series_live_and_recorded():
    """SATELLITE (multi-tenant ingress): a live TenantTable source and a
    recorded run info both surface the canonical ``tenant.<id>.*``
    series (accepted/rejected/expired/completed/backlog) - the fairness
    numbers a dashboard rates - and Prometheus export carries them."""
    from hclib_tpu.device.tenants import TenantSpec, TenantTable
    from hclib_tpu.runtime.metrics import MetricsRegistry

    table = TenantTable(
        [TenantSpec("alice"), TenantSpec("bob")], 16,
        clock=lambda: 0.0,
    )
    import numpy as _np
    from hclib_tpu.device.tenants import build_row

    for i in range(3):
        table.admit("alice", build_row(0, [i]))
    table.admit("bob", build_row(0, [9]))
    reg = MetricsRegistry()
    reg.register("tenant", table.metrics)
    m = reg.snapshot()["metrics"]
    assert m["tenant.alice.accepted"] == 3.0
    assert m["tenant.bob.accepted"] == 1.0
    assert m["tenant.alice.backlog"] == 3.0
    assert "tenant.alice.quarantine_reason" not in m  # strings dropped
    prom = reg.to_prometheus()
    assert "hclib_tpu_tenant_alice_accepted 3.0" in prom
    # add_run_info mirrors a run's info['tenants'] under the SAME prefix
    # even when the run landed under another name.
    reg2 = MetricsRegistry()
    reg2.add_run_info("stream", {
        "executed": 4,
        "tenants": {"alice": {"accepted": 3, "completed": 2,
                              "expired": 1, "backlog": 0,
                              "quarantine_reason": None}},
    })
    m2 = reg2.snapshot()["metrics"]
    assert m2["stream.executed"] == 4.0
    assert m2["tenant.alice.completed"] == 2.0
    assert m2["tenant.alice.expired"] == 1.0
    # One canonical series: no duplicate under the run-info name.
    assert not any(k.startswith("stream.tenants.") for k in m2)


def test_tr_tenant_perfetto_render(tmp_path):
    """SATELLITE: TR_TENANT records land on a dedicated 'tenant ingress'
    track with lane id, installs, and lazy expired drops decoded."""
    import json

    import numpy as np

    from hclib_tpu.device import tracebuf as tb
    from tools import timeline

    trace = {
        "epoch": {"t0_ns": 1_000_000, "t1_ns": 2_000_000},
        "rings": [{
            "written": 3, "dropped": 0, "capacity": 8,
            "records": np.array(
                [[tb.TR_TENANT, 0, (0 << 16) | 4, 0],
                 [tb.TR_TENANT, 0, (1 << 16) | 2, 0],
                 [tb.TR_TENANT, 1, (2 << 16) | 0, 3]],
                dtype=np.int64),
        }],
    }
    out = tmp_path / "tenants.perfetto.json"
    doc = timeline.export_perfetto(str(out), traces=[trace])
    evs = [e for e in doc["traceEvents"]
           if e.get("cat") == "device" and e["name"].startswith("t")]
    assert len(evs) == 3
    by_lane = {e["args"]["lane"]: e for e in evs}
    assert by_lane[0]["args"]["installed"] == 4
    assert by_lane[1]["name"] == "t1 +2"
    assert by_lane[2]["args"]["expired"] == 3
    assert "expired" in by_lane[2]["name"]
    tracks = [e for e in doc["traceEvents"]
              if e.get("name") == "thread_name"
              and e["args"]["name"] == "tenant ingress"]
    assert tracks, "tenant ingress track must be named"
    json.loads(out.read_text())  # the file is valid Chrome-trace JSON
