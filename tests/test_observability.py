"""Observability: event instrumentation, state timer, stats, watchdog.

The reference's instrumentation recorder is stubbed (src/hclib-instrument.c:
211-252); here it must actually record and round-trip.
"""

import sys
import time

import numpy as np

import hclib_tpu as hc
from hclib_tpu.runtime.instrument import END, START, load_dump, register_event_type
from hclib_tpu.runtime.timer import IDLE, WORK, StateTimer


def test_event_log_records_and_dumps(tmp_path):
    rt = hc.Runtime(nworkers=2, instrument=True)

    def body():
        with hc.finish():
            for _ in range(10):
                hc.async_(lambda: None)

    rt.run(body)
    path = rt.event_log.dump(str(tmp_path))
    names, per_worker = load_dump(path)
    assert "task" in names
    events = np.concatenate(list(per_worker.values()))
    starts = events[events["transition"] == START]
    ends = events[events["transition"] == END]
    # every executed task produced a START/END pair with matching ids
    assert len(starts) >= 11 and len(ends) == len(starts)
    assert set(starts["id"]) == set(ends["id"])
    # timestamps are monotonic per worker
    for w, ev in per_worker.items():
        ts = ev["ts_ns"]
        assert np.all(np.diff(ts) >= 0)


def test_event_log_double_buffer_overflow(tmp_path):
    from hclib_tpu.runtime.instrument import EventLog

    log = EventLog(1, capacity=8)
    t = register_event_type("x")
    for i in range(30):
        log.record(0, t, 2, i)
    path = log.dump(str(tmp_path))
    _, per_worker = load_dump(path)
    assert len(per_worker[0]) == 30
    assert list(per_worker[0]["id"]) == list(range(30))


def test_custom_event_type_ids_stable():
    a = register_event_type("my_phase")
    b = register_event_type("my_phase")
    assert a == b


def test_state_timer_accumulates():
    st = StateTimer(1)
    st.set_state(0, WORK)
    time.sleep(0.02)
    st.set_state(0, IDLE)
    time.sleep(0.01)
    st.finalize()
    totals = st.totals_ns()[0]
    assert totals["WORK"] >= 15_000_000
    assert totals["IDLE"] >= 5_000_000
    assert st.avg_time_ns(WORK) == totals["WORK"]
    assert "WORK".lower() in st.format().lower()


def test_runtime_timer_marks_work_and_search():
    rt = hc.Runtime(nworkers=2, timer=True)

    def body():
        with hc.finish():
            for _ in range(20):
                hc.async_(lambda: time.sleep(0.001))

    rt.run(body)
    totals = rt.state_timer.totals_ns()
    assert sum(t["WORK"] for t in totals) > 0


def test_watchdog_reports_stall(caplog):
    """A task that sleeps while holding the only path to progress triggers
    the stall report (the hazard test/deadlock/README documents), routed
    through logging so tests can assert on it (escalation to StallError
    is covered in test_resilience.py)."""
    import logging

    rt = hc.Runtime(nworkers=1, watchdog_s=0.2, watchdog_escalate=False)

    def body():
        time.sleep(0.7)  # outstanding work, no task transitions

    with caplog.at_level(logging.WARNING, logger="hclib_tpu.resilience"):
        rt.run(body)
    assert rt.stall_reports >= 1
    assert any("watchdog" in r.message for r in caplog.records)


def test_watchdog_quiet_on_healthy_run():
    rt = hc.Runtime(nworkers=2, watchdog_s=5.0)

    def body():
        with hc.finish():
            for _ in range(5):
                hc.async_(lambda: None)

    rt.run(body)
    assert rt.stall_reports == 0


def test_stats_format_contains_steals():
    rt = hc.Runtime(nworkers=2, stats=False)

    def body():
        with hc.finish():
            for _ in range(50):
                hc.async_(lambda: time.sleep(0.0005))

    rt.run(body)
    text = rt.format_stats()
    assert "executed=" in text and "steals=" in text
    executed = sum(st.executed for st in rt.worker_stats)
    assert executed >= 51


def test_windowed_trials_stats_survive_sheared_trials():
    """Slope-based trials can land nonpositive under clock shear; stats()
    must exclude them from the pool but still count them in n_trials, and
    degrade to a 0.0 'all-sheared' summary (never None) when every trial
    sheared - bench.py formats median/best unconditionally."""
    from hclib_tpu.runtime.clockprobe import WindowedTrials

    class FakeProbe:
        best = 50.0

        def sample(self, note=""):
            return 50.0

        def is_fast(self, v):
            return v > 40

    wt = WindowedTrials("sheared", probe=FakeProbe(), log_dir=None)
    for v in (-1.0, -2.0):
        wt.run(lambda v=v: v)
    s = wt.stats()
    assert s["window"] == "all-sheared"
    assert s["median"] == 0.0 and s["best"] == 0.0
    assert s["n_trials"] == 2 and s["n_used"] == 0

    wt2 = WindowedTrials("mixed", probe=FakeProbe(), log_dir=None)
    for v in (5.0, -1.0, 7.0):
        wt2.run(lambda v=v: v)
    s2 = wt2.stats()
    assert s2["median"] == 6.0
    assert s2["n_trials"] == 3 and s2["n_used"] == 2 and s2["n_fast"] == 2


def test_timeline_renders_dump_and_reports(tmp_path):
    """tools/timeline.py turns a dump + info/stats dicts into readable
    reports (the reference's tools/timeline.py + instrument parser
    station)."""
    import os

    tools = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
    )
    sys.path.insert(0, tools)
    try:
        import timeline
    finally:
        sys.path.remove(tools)

    rt = hc.Runtime(nworkers=2, instrument=True)

    def body():
        with hc.finish():
            for _ in range(25):
                hc.async_(lambda: time.sleep(0.0002))

    rt.run(body)
    stats = rt.stats_dict()
    path = rt.event_log.dump(str(tmp_path))

    text = timeline.render_dump(path)
    assert "per-worker timeline" in text
    assert "task" in text  # the registered event type shows up
    assert "w0" in text and "w1" in text
    assert "% busy" in text

    # START/END pairing: spans exist and have nonnegative durations
    names, by_worker = load_dump(path)
    spans = [
        s
        for w, ev in by_worker.items()
        for s in timeline.spans_from_events(ev)
    ]
    assert len(spans) >= 26
    assert all(s["t1"] >= s["t0"] for s in spans)

    # host stats report incl. steal matrix layout
    stext = timeline.render_stats(stats)
    assert "executed=" in stext and "w0" in stext

    # device report from a resident-style info dict
    info = {
        "name": "uts steal",
        "executed": 1000,
        "rounds": 7,
        "seconds": 0.5,
        "per_device_counts": [
            [0, 0, 200, 0, 4, 300, 0, 7],
            [0, 0, 180, 0, 4, 700, 0, 7],
        ],
    }
    dtext = timeline.render_device_report(info)
    assert "dev0" in dtext and "dev1" in dtext
    assert "1,000 tasks" in dtext
    assert "imbalance" in dtext

    # CLI round-trips via files
    import json as _json

    f = tmp_path / "info.json"
    f.write_text(_json.dumps(info))
    rc = timeline.main([str(path), "--device", str(f)])
    assert rc == 0
