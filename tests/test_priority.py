"""Priority-bucketed dispatch tier (ISSUE 15): bucket rings over the
per-kind batch lanes, popped lowest-nonempty-first.

The acceptance spine: delta-stepping SSSP bit-identical to the
unordered frontier arm (scalar / batched / 4-device sharded mesh) with
a measured executed-EXPAND reduction; bounded-frontier PageRank
bit-identical to the integer twin with a smaller peak live row set;
branch-and-bound returning the proven optimum with pruning counted;
``priority_buckets`` off-path byte-identical; checkpoint/reshard
conserving per-bucket residue (the bucket id is a pure function of
descriptor words, so residue re-buckets on its next routing pop).
"""

import numpy as np
import pytest
from jax.experimental import pallas as pl

import hclib_tpu as hc
from hclib_tpu.device.bnb import (
    host_bnb,
    host_knapsack_opt,
    make_bnb_megakernel,
    make_knapsack,
    run_bnb,
)
from hclib_tpu.device.descriptor import TaskGraphBuilder
from hclib_tpu.device.frontier import (
    _KINDS,
    Graph,
    host_pagerank_push,
    host_sssp,
    make_frontier_megakernel,
    priority_bucket,
    run_frontier,
)
from hclib_tpu.device.megakernel import (
    BK_MAX,
    BatchContext,
    BatchSpec,
    Megakernel,
)
from hclib_tpu.device.workloads import rmat_edges
from hclib_tpu.runtime.locality import MeshPlacement

# The shared seeded weighted graph for every frontier arm here.
N, SRC, DST, W = rmat_edges(5, efactor=6, seed=3)
G = Graph(N, SRC, DST, W)
SSSP_REF = host_sssp(G, 0)
M0, REPS = 1 << 14, 64

KP = make_knapsack(12, seed=5)
KP_OPT = host_knapsack_opt(KP)


@pytest.fixture(scope="session")
def sssp_pair():
    """Unordered + bucketed batched SSSP builds over the same graph
    (the shared-build discipline of test_frontier)."""
    return {
        "unordered": make_frontier_megakernel(
            _KINDS["sssp"](), G, width=4, interpret=True
        ),
        "bucketed": make_frontier_megakernel(
            _KINDS["sssp"](), G, width=4, interpret=True,
            priority_buckets=8,
        ),
    }


@pytest.fixture(scope="session")
def bnb_pair():
    return {
        "unordered": make_bnb_megakernel(
            KP, width=4, interpret=True, capacity=1024
        ),
        "bucketed": make_bnb_megakernel(
            KP, width=4, interpret=True, capacity=1024,
            priority_buckets=8,
        ),
    }


# ------------------------------------------------ the tier's mechanics


def _seq_kernel(ctx):
    """Record retirement order: value 0 is a cursor, values 2.. the
    observed arg sequence."""
    seq = ctx.value(0)
    ctx.set_value(2 + seq, ctx.arg(0))
    ctx.set_value(0, seq + 1)


def _seq_body(ctx: BatchContext):
    for s in range(ctx.width):
        @pl.when(ctx.live(s))
        def _(s=s):
            _seq_kernel(ctx.slot_ctx(s))


def _seq_mk(buckets, priority, trace=None, lane_max_age=0):
    # The order recorder deliberately funnels every slot through one
    # cursor-indexed write (the shim can't see the cursor dependency,
    # so the batch-race rule fires) - suppressed on the spec, the
    # documented spelling for a deliberate violation.
    return Megakernel(
        kernels=[("k", lambda ctx: None)],
        route={"k": BatchSpec(_seq_body, width=2, priority=priority,
                              verify_suppress=("batch-race",))},
        capacity=64, num_values=64, succ_capacity=8, interpret=True,
        priority_buckets=buckets, trace=trace, lane_max_age=lane_max_age,
    )


def _run_seq(mk, args=(7, 1, 5, 3, 0, 6, 2, 4)):
    b = TaskGraphBuilder()
    for a in args:
        b.add(0, args=[a])
    iv, _, info = mk.run(b)
    n = int(iv[0])
    return [int(x) for x in iv[2 : 2 + n]], info


def test_bucketed_pops_retire_in_priority_order():
    order, info = _run_seq(_seq_mk(4, lambda arg: arg(0) // 2))
    assert order == sorted(order), order
    t = info["tiers"]
    # All eight descriptors retired through bucket rings; three of the
    # four fired rounds came from a nonzero bucket.
    assert t["batch_tasks"] == 8 and t["bucket_fires"] == 3
    assert t["bucket_inversions"] == 0


def test_off_path_byte_identical_and_priority_ignored():
    """priority_buckets=0 with priority fns compiles the EXACT program
    a priority-free build compiles (lowered text equality - the ISSUE
    15 off-path gate), and behaves identically."""
    mk_p = _seq_mk(0, lambda arg: arg(0) // 2)
    mk_n = _seq_mk(0, None)
    lowered_p = mk_p._build_raw(1 << 20).lower(
        *_seq_args(mk_p)
    ).as_text()
    lowered_n = mk_n._build_raw(1 << 20).lower(
        *_seq_args(mk_n)
    ).as_text()
    assert lowered_p == lowered_n
    o_p, i_p = _run_seq(mk_p)
    o_n, i_n = _run_seq(mk_n)

    def device_tiers(info):
        # build_s / cache_lookup_s are host-side program-cache timings,
        # not device counters - never comparable across arms.
        return {
            k: v for k, v in info["tiers"].items()
            if k not in ("build_s", "cache_lookup_s")
        }

    assert o_p == o_n and device_tiers(i_p) == device_tiers(i_n)
    assert i_p["tiers"]["bucket_fires"] == 0
    assert i_p["tiers"]["bucket_inversions"] == 0


def _seq_args(mk):
    import jax

    b = TaskGraphBuilder()
    for a in (1, 2):
        b.add(0, args=[a])
    tasks, succ, ring, counts = b.finalize(
        capacity=mk.capacity, succ_capacity=mk.succ_capacity
    )
    iv = np.zeros(mk.num_values, np.int32)
    return [
        jax.ShapeDtypeStruct(np.asarray(x).shape, np.asarray(x).dtype)
        for x in (tasks, succ, ring, counts, iv)
    ]


def test_knob_validation_and_env(monkeypatch):
    with pytest.raises(ValueError, match="priority_buckets"):
        _seq_mk(1, None)
    with pytest.raises(ValueError, match="priority_buckets"):
        _seq_mk(BK_MAX + 1, None)
    with pytest.raises(ValueError, match="priority"):
        BatchSpec(_seq_body, width=2, priority=3)
    monkeypatch.setenv("HCLIB_TPU_PRIORITY_BUCKETS", "4")
    mk = _seq_mk(None, None)
    assert mk.priority_buckets == 4
    # The process-wide spelling reaches the workload builders too (they
    # must resolve it themselves: bucketed builds disable the
    # cross-round prefetch and rescale the age default).
    fmk = make_frontier_megakernel(
        _KINDS["sssp"](), G, width=4, interpret=True
    )
    assert fmk.priority_buckets == 4
    assert fmk.si_claim[3] == 4  # the bucketed 5-tuple claim
    bmk = make_bnb_megakernel(KP, width=4, interpret=True)
    assert bmk.priority_buckets == 4
    monkeypatch.setenv("HCLIB_TPU_PRIORITY_BUCKETS", "banana")
    with pytest.raises(ValueError):
        _seq_mk(None, None)
    monkeypatch.delenv("HCLIB_TPU_PRIORITY_BUCKETS")
    # The scalar frontier arm has no lanes to bucket.
    with pytest.raises(ValueError, match="batched arm"):
        make_frontier_megakernel(
            _KINDS["sssp"](), G, width=0, interpret=True,
            priority_buckets=4,
        )


def test_age_guard_fires_as_bucket_inversion():
    """A high bucket starved behind repeatedly-fired low buckets
    crosses lane_max_age and fires OUT of bucket order - counted in
    bucket_inversions, results unaffected (priorities are a hint)."""
    args = tuple([0] * 20 + [3, 3])  # bucket 0 monopoly + 2 in bucket 3
    mk = _seq_mk(4, lambda arg: arg(0), trace=1024, lane_max_age=3)
    order, info = _run_seq(mk, args)
    t = info["tiers"]
    assert sorted(order) == sorted(args)
    assert t["bucket_inversions"] >= 1
    assert t["max_starved_age"] <= 3 + 4  # N + nrows bound
    # The forced fire happened while bucket 0 still held entries: the
    # 3s retired before the last 0s.
    assert order.index(3) < len(order) - 1
    from hclib_tpu.device.tracebuf import TR_FIRE_BUCKET, records_of

    recs = records_of(info["trace"], TR_FIRE_BUCKET)
    assert len(recs) == t["batch_rounds"]
    assert t["bucket_occupancy"][0] > 0


# ------------------------------------------- delta-stepping SSSP


def test_delta_sssp_bit_identical_with_fewer_expands(sssp_pair):
    d_u, iu = run_frontier(
        "sssp", G, 0, mk=sssp_pair["unordered"], interpret=True
    )
    d_b, ib = run_frontier(
        "sssp", G, 0, mk=sssp_pair["bucketed"], interpret=True
    )
    assert np.array_equal(d_u, SSSP_REF)
    assert np.array_equal(d_b, SSSP_REF)
    # Ordered retirement does less label-correction re-relaxation (the
    # guard of record pins <= 0.8x at scale 8; this small graph just
    # pins the direction).
    assert ib["executed"] <= iu["executed"]
    assert ib["tiers"]["bucket_fires"] > 0
    # The drain-period age default left the order intact.
    assert ib["tiers"]["bucket_inversions"] == 0


def test_delta_sssp_mesh_bit_identical():
    """The 4-device sharded mesh arm: bucketed EXPANDs migrate through
    the steal exchange, re-bucket on their new device's routing pop
    (the bucket is a pure function of descriptor args), and the
    min-combined distances stay bit-identical."""
    d, info = run_frontier(
        "sssp", G, 0, width=4, interpret=True, capacity=256,
        priority_buckets=8,
        placement=MeshPlacement(4, policy="block"), quantum=2, window=4,
    )
    assert np.array_equal(d, SSSP_REF)
    assert info["executed"] > 0


def test_delta_sssp_checkpoint_resume_rebuckets_residue():
    """Quiesce mid-traversal (bucket rings spill to the ready ring -
    the steal/export/checkpoint invariant), resume, and the fixpoint is
    bit-identical: spilled residue re-buckets on the resumed routing
    pops."""
    from hclib_tpu.device.frontier import seed_frontier

    fk = _KINDS["sssp"]()
    mk = make_frontier_megakernel(
        fk, G, width=4, capacity=256, interpret=True, checkpoint=True,
        priority_buckets=8,
    )
    iv = G.preset_values(mk.num_values, fk.state0)
    iv[G.st_base] = 0

    def builder():
        b = TaskGraphBuilder()
        b.reserve_values(G.num_value_slots)
        seed_frontier(b, G, "sssp")
        return b

    data = {"indices": G.indices, "weights": G.weights}
    iv_full, _, info_full = mk.run(
        builder(), data=dict(data), ivalues=iv.copy()
    )
    full = np.asarray(iv_full)[G.st_base : G.st_base + G.n]
    assert np.array_equal(full.astype(np.int32), SSSP_REF)
    _, _, q = mk.run(
        builder(), data=dict(data), ivalues=iv.copy(),
        quiesce=max(2, info_full["executed"] // 2),
    )
    assert q["quiesced"] and q["pending"] > 0
    iv_r, _, info_r = mk.resume(q["state"])
    assert info_r["pending"] == 0
    assert np.array_equal(
        np.asarray(iv_r)[G.st_base : G.st_base + G.n], full
    )


def test_bucketed_kind_keeps_reshard_class(sssp_pair, bnb_pair):
    """The priority callable is routing state, not body code: the
    classification (what reshard/steal filters consult) is identical
    bucketed vs not, and describe() surfaces the priority flag."""
    from hclib_tpu.analysis import classify_megakernel

    cu = classify_megakernel(sssp_pair["unordered"])
    cb = classify_megakernel(sssp_pair["bucketed"])
    assert cu == cb == {"fr_sssp": "link-free"}
    assert classify_megakernel(bnb_pair["bucketed"]) == {
        "bnb_node": "link-free"
    }
    d = sssp_pair["bucketed"].describe()
    assert d["kinds"]["fr_sssp"]["priority"] is True
    assert d["priority_buckets"] == 8
    assert sssp_pair["unordered"].describe()["priority_buckets"] == 0


def test_si_claim_certifies_bucketed_order(sssp_pair):
    cert = sssp_pair["bucketed"].describe()["schedule_independence"]
    assert cert["status"] == "certified"
    assert cert["buckets"] == 8
    # One extra order beyond the random permutations: the bucketed pop.
    assert cert["orders"] >= 3
    # The unbucketed claim stays the 3-tuple spelling.
    assert len(sssp_pair["unordered"].si_claim) == 3
    assert len(sssp_pair["bucketed"].si_claim) == 5


def test_priority_bucket_host_spelling():
    assert priority_bucket("sssp", 17, delta=4) == 4
    assert priority_bucket("bfs", 3, delta=1) == 3
    # PageRank bands ascend with residual magnitude (PR_BAND=2 steps).
    assert priority_bucket("pagerank", 63, reps=64) == 0
    assert priority_bucket("pagerank", 128, reps=64) == 1
    assert priority_bucket("pagerank", 1 << 14, reps=64) == BK_MAX - 1


# ---------------------------------------- bounded-frontier PageRank


def test_bounded_pagerank_bit_identical_smaller_live_set():
    twin, _ = host_pagerank_push(G, m0=M0, reps=REPS)
    r_u, pu = run_frontier(
        "pagerank", G, width=8, m0=M0, reps=REPS, interpret=True,
        capacity=2048,
    )
    r_b, pb = run_frontier(
        "pagerank", G, width=8, m0=M0, reps=REPS, interpret=True,
        capacity=2048, priority_buckets=8,
    )
    assert np.array_equal(r_u, twin) and np.array_equal(r_b, twin)
    # The live-set fix: allocated is the row high-water mark (rows
    # recycle through the free stack, so the bump cursor IS peak live).
    assert pb["allocated"] < pu["allocated"]


def test_bounded_pagerank_fits_where_fifo_overflows():
    """Interpret-scale capacity suffices: a capacity the FIFO
    breadth-first arm overflows runs to completion bucketed."""
    twin, _ = host_pagerank_push(G, m0=M0, reps=REPS)
    cap = 640
    with pytest.raises(RuntimeError, match="task-table rows"):
        run_frontier(
            "pagerank", G, width=8, m0=M0, reps=REPS, interpret=True,
            capacity=cap,
        )
    r_b, _ = run_frontier(
        "pagerank", G, width=8, m0=M0, reps=REPS, interpret=True,
        capacity=cap, priority_buckets=8,
    )
    assert np.array_equal(r_b, twin)


# ------------------------------------------------- branch and bound


def test_bnb_proven_optimum_and_pruning_speedup(bnb_pair):
    assert host_bnb(KP)["best"] == host_bnb(KP, best_first=True)[
        "best"
    ] == KP_OPT
    best_u, iu = run_bnb(KP, mk=bnb_pair["unordered"], interpret=True)
    best_b, ib = run_bnb(KP, mk=bnb_pair["bucketed"], interpret=True)
    assert best_u == best_b == KP_OPT
    assert iu["pruned"] > 0 and ib["pruned"] > 0
    assert iu["leaves"] >= 1 and ib["leaves"] >= 1
    # Priority IS the speedup: best-first finds the incumbent early
    # and prunes subtrees the unordered run explores.
    assert ib["executed"] < iu["executed"]


def test_bnb_certificate_and_instance_guard(bnb_pair):
    cert = bnb_pair["bucketed"].describe()["schedule_independence"]
    assert cert["status"] == "certified"
    assert cert["optimum"] == KP_OPT
    other = make_knapsack(12, seed=6)
    with pytest.raises(ValueError, match="knapsack"):
        run_bnb(other, mk=bnb_pair["bucketed"], interpret=True)
    with pytest.raises(ValueError, match="batched arm"):
        make_bnb_megakernel(KP, width=0, priority_buckets=4)


# ------------------------------------------------------- observability


def test_bucket_gauges_ride_metrics():
    _, info = run_frontier(
        "sssp", G, 0, width=4, interpret=True, priority_buckets=4,
        trace=2048,
    )
    t = info["tiers"]
    assert set(t["bucket_occupancy"]) == {0, 1, 2, 3}
    reg = hc.MetricsRegistry()
    reg.add_run_info("prio", info)
    m = reg.snapshot()["metrics"]
    assert "prio.bucket_inversions.0" in m
    # Per-device then per-bucket (the lane_occupancy discipline):
    # device 0, bucket 0 on this single-device run.
    assert "prio.bucket_occupancy.0.0" in m
    assert m["prio.trace.fire_bucket"] == t["batch_rounds"]
