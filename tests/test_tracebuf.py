"""Device flight recorder (device/tracebuf.py): the trace ring written
from inside the scheduler's round loops.

Acceptance (ISSUE 4): a seeded interpret-mode megakernel run with tracing
ON produces records whose batch-tier round spans reconcile EXACTLY with
``info['tiers']`` (rounds, tasks, prefetch hits) and a valid Perfetto
export; the same run with tracing OFF is bit-identical in outputs with no
trace ring added."""

import json

import numpy as np
import pytest
from jax.experimental import pallas as pl

from hclib_tpu.device.descriptor import TaskGraphBuilder
from hclib_tpu.device.megakernel import BatchSpec, Megakernel
from hclib_tpu.device import tracebuf as tb
from hclib_tpu.runtime.resilience import StallError


def _timeline():
    from conftest import timeline_mod

    return timeline_mod()


DOUBLE, NEG = 0, 1


def _scalar_double(ctx):
    ctx.set_out(ctx.arg(0) * 2)


def _scalar_neg(ctx):
    ctx.set_out(-ctx.arg(0))


def _batch_double(ctx):
    for s in range(ctx.width):
        @pl.when(ctx.live(s))
        def _(s=s):
            ctx.set_out(s, ctx.arg(s, 0) * 2)


def _drain_noop(ctx):
    return None


def _mk(trace=None, width=2, prefetch=False):
    spec = (
        BatchSpec(_batch_double, width=width, prefetch=True,
                  drain=_drain_noop)
        if prefetch
        else BatchSpec(_batch_double, width=width)
    )
    return Megakernel(
        kernels=[("double", _scalar_double), ("neg", _scalar_neg)],
        route={"double": spec},
        capacity=64,
        num_values=64,
        interpret=True,
        trace=trace,
    )


def _graph(n_first=6, n_negs=3, n_second=5):
    b = TaskGraphBuilder()
    first = [b.add(DOUBLE, args=[i], out=i) for i in range(n_first)]
    negs = [
        b.add(NEG, args=[10 + i], out=n_first + i, deps=[first[i]])
        for i in range(n_negs)
    ]
    for i in range(n_second):
        b.add(DOUBLE, args=[100 + i], out=n_first + n_negs + i, deps=negs)
    return b


def test_traced_run_reconciles_exactly_with_tier_counters():
    """The acceptance reconciliation: batch-fire records vs info['tiers'],
    counted and summed EXACTLY (rounds, tasks, prefetch hits), scalar
    fires vs scalar_tasks, prefetch issue/drain bookkeeping consistent."""
    mk = _mk(trace=512, width=2, prefetch=True)
    iv, _, info = mk.run(_graph())
    assert list(iv[:6]) == [0, 2, 4, 6, 8, 10]
    t = info["tiers"]
    tr = info["trace"]
    ring = tr["rings"][0]
    assert ring["dropped"] == 0
    bat = tb.records_of(tr, tb.TR_FIRE_BATCH)
    sca = tb.records_of(tr, tb.TR_FIRE_SCALAR)
    iss = tb.records_of(tr, tb.TR_PREFETCH_ISSUE)
    assert len(bat) == t["batch_rounds"]
    assert int((bat[:, 2] & 0xFFFF).sum()) == t["batch_tasks"]
    assert int(bat[:, 3].sum()) == t["prefetch_hits"]
    assert t["prefetch_hits"] > 0  # queue depth > width engages it
    assert len(sca) == t["scalar_tasks"]
    # Lane id rides the high half of the fire word.
    assert set(bat[:, 2] >> 16) == {DOUBLE}
    # Announcements can only exceed consumed hits by the final round's
    # (possibly unconsumed-at-full-width) issue; both are recorded.
    assert int(iss[:, 3].sum()) >= t["prefetch_hits"]
    # Round brackets: one begin + one end per sched entry (single run()).
    assert len(tb.records_of(tr, tb.TR_ROUND_BEGIN)) == 1
    ends = tb.records_of(tr, tb.TR_ROUND_END)
    assert len(ends) == 1
    assert int(ends[0, 2]) == info["executed"]
    # Record timebase is monotonic.
    assert np.all(np.diff(ring["records"][:, 1]) >= 0)
    # Host epoch bracketed the launch.
    assert tr["epoch"]["t1_ns"] > tr["epoch"]["t0_ns"]


def test_trace_off_is_bit_identical_with_no_ring_output():
    mk_on = _mk(trace=512, width=2, prefetch=True)
    mk_off = _mk(trace=None, width=2, prefetch=True)
    iv_on, _, info_on = mk_on.run(_graph())
    iv_off, _, info_off = mk_off.run(_graph())
    assert np.array_equal(iv_on, iv_off)
    assert "trace" not in info_off
    # Tracing adds the trace key plus the trace-DERIVED tier gauges
    # (lane_partial_age, ISSUE 9); every device-computed number is
    # identical.
    # (program_cache and the tiers build_s/cache_lookup_s keys are
    # host-side program-cache facts - different per build, not device
    # output - so they are excluded from the cross-arm identity.)
    on = {k: v for k, v in info_on.items()
          if k not in ("trace", "program_cache")}
    off = {k: v for k, v in info_off.items() if k != "program_cache"}
    host_keys = ("lane_partial_age", "lane_partial_ages",
                 "build_s", "cache_lookup_s")
    on["tiers"] = {
        k: v for k, v in on["tiers"].items() if k not in host_keys
    }
    off["tiers"] = {
        k: v for k, v in off["tiers"].items() if k not in host_keys
    }
    assert on == off
    assert "lane_partial_age" in info_on["tiers"]
    assert "lane_partial_age" not in info_off["tiers"]
    # No appended ring output on the off build: its pallas out tree is
    # one entry shorter (tasks/ready/counts/ivalues + tstats, no ring).
    assert mk_off.trace is None
    import jax

    b = _graph()
    tasks, succ, ring, counts = b.finalize(
        capacity=mk_off.capacity, succ_capacity=mk_off.succ_capacity
    )
    args = (tasks, succ, ring, counts,
            np.zeros(mk_off.num_values, np.int32))
    n_off = len(jax.eval_shape(mk_off._build_raw(1 << 20), *args))
    n_on = len(jax.eval_shape(mk_on._build_raw(1 << 20), *args))
    assert n_on == n_off + 1


def test_ring_overflow_counted_not_crashed():
    from hclib_tpu.device.workloads import FIB, make_fib_megakernel

    mk = make_fib_megakernel(256, interpret=True, trace=32)
    b = TaskGraphBuilder()
    b.add(FIB, args=[10], out=0)
    iv, _, info = mk.run(b)
    assert int(iv[0]) == 55  # results unharmed by the wrap
    ring = info["trace"]["rings"][0]
    assert ring["dropped"] > 0
    assert ring["written"] == ring["dropped"] + ring["capacity"]
    assert len(ring["records"]) == ring["capacity"]
    # The ring keeps the LAST records: the run's closing round_end
    # survives the wrap (what a stall post-mortem needs).
    assert int(ring["records"][-1, 0]) == tb.TR_ROUND_END


def test_fuel_spill_traced_in_stall_stats():
    """Fuel exhaustion spills lane entries; the StallError's stats carry
    the trace, and the spill records account for every spilled entry."""
    mk = _mk(trace=256, width=2)
    b = TaskGraphBuilder()
    for i in range(10):
        b.add(DOUBLE, args=[i], out=i)
    with pytest.raises(StallError) as ei:
        mk.run(b, fuel=3)
    tr = ei.value.stats["trace"]
    spills = tb.records_of(tr, tb.TR_SPILL)
    assert int(spills[:, 3].sum()) == ei.value.stats["tiers"]["spilled"] > 0


def test_perfetto_export_round_trips(tmp_path):
    timeline = _timeline()
    mk = _mk(trace=512, width=2, prefetch=True)
    _, _, info = mk.run(_graph())
    out = tmp_path / "trace.perfetto.json"
    doc = timeline.export_perfetto(str(out), traces=[info["trace"]])
    loaded = json.loads(out.read_text())  # valid JSON round-trip
    assert loaded == doc
    evs = loaded["traceEvents"]
    dev = [e for e in evs if e.get("cat") == "device"]
    assert dev, "no device events exported"
    # One process (track group) for the single device, named.
    assert {e["pid"] for e in dev} == {1}
    names = [
        e for e in evs
        if e["ph"] == "M" and e["name"] == "process_name"
    ]
    assert [n["args"]["name"] for n in names] == ["device 0"]
    # Monotonic ts within every track.
    for tid in {e["tid"] for e in dev}:
        ts = [e["ts"] for e in dev if e["tid"] == tid]
        assert ts == sorted(ts)
    # The batch lane surfaced as its own thread with occupancy labels,
    # and the EXPORTED events reconcile exactly with info['tiers']: one
    # span per batch round, takes summing to batch_tasks, prefetched
    # args summing to prefetch_hits (the acceptance reconciliation, on
    # the Perfetto side).
    t = info["tiers"]
    lane_evs = [e for e in dev if e["name"].startswith("batch x")]
    assert len(lane_evs) == t["batch_rounds"]
    assert sum(e["args"]["take"] for e in lane_evs) == t["batch_tasks"]
    assert (
        sum(e["args"]["prefetched"] for e in lane_evs)
        == t["prefetch_hits"]
    )
    rounds = [
        e for e in dev if e["tid"] == 0 and e["name"].startswith("round")
    ]
    assert len(rounds) == 1  # one sched bracket for the single run()


def test_perfetto_multi_device_one_track_per_device(tmp_path):
    """A two-ring trace (as a 2-device resident run returns) exports one
    process per device - built synthetically so the multi-device shape is
    covered without Mosaic interpret mode."""
    timeline = _timeline()
    recs0 = np.array([
        [tb.TR_ROUND_BEGIN, 0, 3, 5],
        [tb.TR_FIRE_SCALAR, 1, 0, 7],
        [tb.TR_ROUND_END, 2, 1, 4],
        [tb.TR_XFER, 2, 1, 2],
    ], dtype=np.int64)
    recs1 = np.array([
        [tb.TR_ROUND_BEGIN, 0, 1, 1],
        [tb.TR_ABORT, 1, 1, 0],
        [tb.TR_ROUND_END, 1, 0, 1],
    ], dtype=np.int64)
    trace = {
        "epoch": {"t0_ns": 1_000_000, "t1_ns": 2_000_000},
        "rings": [
            {"written": len(r), "dropped": 0, "capacity": 16,
             "records": r}
            for r in (recs0, recs1)
        ],
    }
    out = tmp_path / "mesh.perfetto.json"
    doc = timeline.export_perfetto(str(out), traces=[trace])
    evs = doc["traceEvents"]
    procs = {
        e["args"]["name"]
        for e in evs
        if e["ph"] == "M" and e["name"] == "process_name"
    }
    assert procs == {"device 0", "device 1"}
    dev_pids = {e["pid"] for e in evs if e.get("cat") == "device"}
    assert dev_pids == {1, 2}
    # Device-round timestamps interpolate INSIDE the host epoch.
    for e in evs:
        if e.get("cat") == "device":
            assert 1_000_000 / 1e3 <= e["ts"] <= 2_000_000 / 1e3
    # jsonable round-trip matches the direct export.
    j = tb.trace_to_jsonable(trace)
    doc2 = timeline.export_perfetto("", traces=[json.loads(json.dumps(j))])
    assert len(doc2["traceEvents"]) == len(evs)


def test_streaming_megakernel_traces_injection():
    from hclib_tpu.device.inject import StreamingMegakernel
    from hclib_tpu.device.workloads import FIB, make_fib_megakernel

    mk = make_fib_megakernel(256, interpret=True, trace=1024)
    sm = StreamingMegakernel(mk, ring_capacity=16)
    b = TaskGraphBuilder()
    b.add(FIB, args=[8], out=0)
    sm.inject(FIB, [6], out=1)
    sm.close()
    iv, info = sm.run_stream(b, quantum=64, max_rounds=8)
    assert int(iv[0]) == 21 and int(iv[1]) == 8
    inj = tb.records_of(info["trace"], tb.TR_INJECT)
    assert int(inj[:, 2].sum()) == 1  # the injected row was recorded


def test_sharded_runner_refuses_trace(monkeypatch):
    import jax
    from jax.sharding import Mesh
    from hclib_tpu.device.sharded import ShardedMegakernel
    from hclib_tpu.device.workloads import make_fib_megakernel

    devs = np.array(jax.devices()[:1])
    mk = _mk(trace=None, width=2)
    mk.batch_specs = []  # scalar-only for the sharded runner
    mk.trace = tb.TraceRing(64)
    with pytest.raises(ValueError, match="trace"):
        ShardedMegakernel(mk, Mesh(devs, ("d",)))
    # Env-derived tracing degrades (warning + local suppression) WITHOUT
    # mutating the shared kernel: other runners keep their ring.
    monkeypatch.setenv("HCLIB_TPU_TRACE", "64")
    mk2 = make_fib_megakernel(256, interpret=True)
    assert mk2.trace is not None and mk2.trace_from_env
    sm = ShardedMegakernel(mk2, Mesh(devs, ("d",)))
    assert sm._suppress_trace and mk2.trace is not None
    with sm._maybe_untraced():
        assert mk2.trace is None  # suppressed only inside builds
    assert mk2.trace is not None


def test_trace_env_enables_recorder(monkeypatch):
    monkeypatch.setenv("HCLIB_TPU_TRACE", "64")
    assert _mk().trace.capacity == 64
    monkeypatch.setenv("HCLIB_TPU_TRACE", "1")
    assert _mk().trace.capacity == 2048  # 1 = on, default capacity
    monkeypatch.setenv("HCLIB_TPU_TRACE", "0")
    assert _mk().trace is None
    monkeypatch.delenv("HCLIB_TPU_TRACE")
    assert _mk().trace is None
    assert _mk(trace=16).trace.capacity == 16  # explicit arg wins


def test_tracering_normalization_and_decode_validation():
    assert tb.TraceRing.of(None) is None
    assert tb.TraceRing.of(True).capacity == 2048
    assert tb.TraceRing.of(False) is None
    assert tb.TraceRing.of(7).capacity == 7
    r = tb.TraceRing(3)
    assert tb.TraceRing.of(r) is r
    assert r.words == tb.HDR + 3 * tb.TR_WORDS
    with pytest.raises(ValueError):
        tb.TraceRing(0)
    # decode of an all-zero row: no records, nothing dropped.
    d = tb.decode_ring(np.zeros(tb.HDR + 8 * tb.TR_WORDS, np.int32))
    assert d["written"] == 0 and d["dropped"] == 0
    assert d["records"].shape == (0, tb.TR_WORDS)


@pytest.mark.chaos
def test_resident_mesh_trace_rings():
    """2-device resident run with the recorder on: per-device rings with
    round records, reconciled against info (needs Mosaic interpret)."""
    import jax
    from jax.sharding import Mesh
    from hclib_tpu.jaxcompat import has_mosaic_interpret

    if not has_mosaic_interpret():
        pytest.skip("needs pltpu.InterpretParams (Mosaic interpret mode)")
    from hclib_tpu.device.resident import ResidentKernel
    from hclib_tpu.device.workloads import (  # noqa: F401
        FIB,
        make_fib_megakernel,
    )

    mk = make_fib_megakernel(256, interpret=True, trace=2048)
    devs = np.array(jax.devices()[:2])
    rk = ResidentKernel(mk, Mesh(devs, ("d",)), steal=True, homed=False)
    builders = []
    for n in (9, 7):
        b = TaskGraphBuilder()
        b.add(FIB, args=[n], out=0)
        builders.append(b)
    iv, _, info = rk.run(builders, quantum=64)
    assert [int(iv[0][0]), int(iv[1][0])] == [34, 13]
    tr = info["trace"]
    assert len(tr["rings"]) == 2
    for d in range(2):
        begins = tb.records_of(tr, tb.TR_ROUND_BEGIN, ring=d)
        ends = tb.records_of(tr, tb.TR_ROUND_END, ring=d)
        # One sched bracket per exchange round on every device.
        assert len(begins) == len(ends) == info["rounds"]
