"""Dynamic graph service (ISSUE 20): mutable blocked-CSR adjacency
with per-vertex spare blocks, UPDATE splices + QUERY reads riding the
scheduler as descriptor kinds, and incremental recompute.

The acceptance spine: the mutated fixpoint is bit-identical to the
from-scratch host reference on the mutated graph across the scalar,
batched, bucketed, and 4-device mesh arms (pagerank: mass conserved
exactly); spare exhaustion DROPS the splice and raises overflow rather
than corrupting static rows; the splice protocol is machine-checked
(hclint ``check_splice``) and the schedule-independence claim
certifies bound streams; static frontier builds compile zero new
device words with the dyngraph module loaded.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from hclib_tpu.analysis.model import certify_claim, certify_dyngraph_schedule
from hclib_tpu.analysis.races import check_splice
from hclib_tpu.device.dyngraph import (
    DynGraph,
    host_dyngraph,
    host_incremental,
    host_incremental_pagerank,
    make_dyngraph_megakernel,
    run_dyngraph,
    serve_dyngraph,
)
from hclib_tpu.device.frontier import EBLOCK, INF
from hclib_tpu.device.tracebuf import TR_SPLICE, records_of
from hclib_tpu.device.workloads import rmat_edges
from hclib_tpu.runtime.locality import MeshPlacement

# One small seeded R-MAT shared by every arm (each distinct build is an
# XLA compile; the program cache dedupes content-identical rebuilds).
N, SRC, DST, W = rmat_edges(5, efactor=4, seed=9)
UPS = [(1, 5, 3), (2, 7, 1), (0, 9, 2), (4, 3, 6)]
M0, REPS = 1 << 12, 64


def _graph(**kw):
    kw.setdefault("spare_blocks", 2)
    kw.setdefault("upd_cap", 16)
    return DynGraph(N, SRC, DST, W, **kw)


# ------------------------------------------------- container + stream


def test_dyngraph_container_layout_and_update_stream():
    g = _graph()
    # Spare rows appended behind the static blocked-CSR rows, pristine.
    assert g.nblocks == g.spare_base + g.n * g.spare
    assert g.indices.shape[0] == g.nblocks
    assert (g.indices[g.spare_base:] == -1).all()
    assert (g.weights[g.spare_base:] == 0).all()
    # Value-slot layout: counters | vt | static counts | flags | state.
    iv = g.preset_values(g.num_value_slots, INF)
    assert np.array_equal(
        iv[g.bcs_base : g.bcs_base + g.n], g.blk_count
    )
    assert (iv[g.flag_base : g.flag_base + g.upd_cap] == 0).all()
    # The stream: uids are dense, endpoints validated.
    assert g.add_update(1, 5, 3) == 0
    assert g.add_update(2, 7) == 1
    with pytest.raises(ValueError, match="out of range"):
        g.add_update(0, g.n)
    with pytest.raises(ValueError, match="weight"):
        g.add_update(0, 1, -2)
    tight = DynGraph(N, SRC, DST, W, spare_blocks=1, upd_cap=1)
    tight.add_update(0, 1)
    with pytest.raises(ValueError, match="upd_cap"):
        tight.add_update(1, 2)
    with pytest.raises(ValueError, match="spare_blocks"):
        DynGraph(N, SRC, DST, W, spare_blocks=-1)
    # The host twin: static edges + the registered stream.
    tw = g.mutated()
    assert int(tw.deg.sum()) == int(g.deg.sum()) + 2
    assert g.spare_needed() <= 2


def test_env_knobs(monkeypatch):
    monkeypatch.setenv("HCLIB_TPU_DYNGRAPH_SPARE_BLOCKS", "3")
    assert DynGraph(N, SRC, DST, W).spare == 3
    monkeypatch.setenv("HCLIB_TPU_DYNGRAPH_SPARE_BLOCKS", "0")
    with pytest.raises(ValueError, match="SPARE_BLOCKS"):
        DynGraph(N, SRC, DST, W)
    monkeypatch.setenv("HCLIB_TPU_DYNGRAPH_SPARE_BLOCKS", "two")
    with pytest.raises(ValueError):
        DynGraph(N, SRC, DST, W)
    monkeypatch.delenv("HCLIB_TPU_DYNGRAPH_SPARE_BLOCKS", raising=False)
    # UPDATE_PRIORITY stamps the bucketed build (clamped to the range).
    monkeypatch.setenv("HCLIB_TPU_DYNGRAPH_UPDATE_PRIORITY", "1")
    mk = make_dyngraph_megakernel(
        "bfs", _graph(), width=4, interpret=True, priority_buckets=2,
    )
    assert mk._dyngraph["update_priority"] == 1
    monkeypatch.setenv("HCLIB_TPU_DYNGRAPH_UPDATE_PRIORITY", "9")
    mk2 = make_dyngraph_megakernel(
        "bfs", _graph(), width=4, interpret=True, priority_buckets=2,
    )
    assert mk2._dyngraph["update_priority"] == 1  # clamped to B-1
    # Bucket rings layer over batch lanes: the scalar arm refuses them.
    with pytest.raises(ValueError, match="batched arm"):
        make_dyngraph_megakernel(
            "bfs", _graph(), width=0, interpret=True, priority_buckets=2,
        )


# ------------------------------------------------ bit-identity arms


def test_scalar_update_storm_bit_identical_and_counters():
    g = _graph()
    res, info = run_dyngraph(
        "sssp", g, 0, updates=UPS, queries=[0, 5, 9], width=0,
        interpret=True,
    )
    ref = host_dyngraph("sssp", g, 0)  # after registration: mutated
    assert np.array_equal(res, ref)
    assert info["updates_applied"] == len(UPS)
    assert info["dropped"] == 0
    assert info["spare_in_use"] == g.spare_needed()
    assert info["queries"] == 3 and len(info["query_values"]) == 3
    # The incremental host twin lands on the same fixpoint.
    assert np.array_equal(host_incremental("sssp", g, src=0), ref)


def test_batched_and_bucketed_arms_bit_identical():
    g = _graph()
    res, info = run_dyngraph(
        "bfs", g, 0, updates=UPS, width=4, interpret=True,
    )
    assert np.array_equal(res, host_dyngraph("bfs", g, 0))
    assert info["updates_applied"] == len(UPS)
    g2 = _graph()
    res2, _ = run_dyngraph(
        "bfs", g2, 0, updates=UPS, width=4, interpret=True,
        priority_buckets=2, update_priority=0,
    )
    assert np.array_equal(res2, host_dyngraph("bfs", g2, 0))


def test_mesh_update_broadcast_bit_identical():
    """4-device mesh: the update stream broadcasts to every replica
    (idempotent splices), EXPANDs migrate, labels min-combine - the
    fixpoint is exactly the mutated single-device result."""
    g = _graph()
    res, info = run_dyngraph(
        "sssp", g, 0, updates=UPS, queries=[3], width=4, capacity=256,
        interpret=True, placement=MeshPlacement(4, policy="block"),
        quantum=4, window=8,
    )
    assert np.array_equal(res, host_dyngraph("sssp", g, 0))
    assert info["updates_applied"] == len(UPS)
    assert info["dropped"] == 0


def test_pagerank_mass_conserved_under_updates():
    g = _graph()
    res, info = run_dyngraph(
        "pagerank", g, updates=UPS, width=0, m0=M0, reps=REPS,
        interpret=True, capacity=768,
    )
    twin, _ = host_incremental_pagerank(g, m0=M0, reps=REPS)
    assert int(res.sum()) == int(twin.sum())
    assert info["updates_applied"] == len(UPS)


def test_spare_exhaustion_drops_and_raises_overflow():
    """A full tail with no spare ordinal left DROPS the splice (flagged
    as engine overflow - the run raises instead of corrupting static
    rows), and the host mirror excludes the drop identically."""
    n = 8
    src = np.concatenate([np.zeros(EBLOCK, np.int64), [1]])
    dst = np.concatenate(
        [1 + np.arange(EBLOCK) % (n - 1), [2]]
    ).astype(np.int64)
    g = DynGraph(n, src, dst, np.ones(len(src), np.int64),
                 spare_blocks=0, upd_cap=4)
    with pytest.raises(RuntimeError, match="overflow"):
        run_dyngraph(
            "bfs", g, 0, updates=[(0, 7, 1), (1, 3, 1)], width=0,
            interpret=True,
        )
    # Host mirror of the drop rule: vertex 0's tail is full (deg ==
    # EBLOCK, spare 0) so its insert is excluded; vertex 1 has slack.
    assert g.spare_needed() == 0
    tw = g.mutated()
    assert int(tw.deg.sum()) == int(g.deg.sum()) + 1
    assert np.array_equal(
        host_incremental("bfs", g, src=0), host_dyngraph("bfs", g, 0)
    )


# ---------------------------------------------- serving front door


def test_serve_two_tenants_update_query_futures():
    rng = np.random.default_rng(3)
    n, m = 24, 80
    g = DynGraph(n, rng.integers(0, n, m), rng.integers(0, n, m),
                 rng.integers(1, 8, m), spare_blocks=2, upd_cap=16)
    res, info = serve_dyngraph(
        "sssp", g, src=0, updates=[(1, 5, 3), (2, 7, 1), (0, 9, 2)],
        queries=[0, 5, 9], interpret=True, ring_capacity=64,
        egress_depth=32, max_rounds=512,
    )
    assert np.array_equal(res, host_dyngraph("sssp", g, src=0))
    assert info["updates_applied"] == 3 and info["queries"] == 3
    assert all(f.state == "RESULT" for f in info["update_futures"])
    assert all(f.state == "RESULT" for f in info["query_futures"])
    # Drained stream: the published labels are exact, and the future
    # resolved to the same out-slot value the run reported.
    assert info["query_results"] == info["query_values"]
    assert info["query_results"][0] == 0  # the source's own label
    eg = info["serve_stats"]["egress"]
    assert eg["resolved"] == eg["submitted"] == 6
    r = records_of(info["splice_trace"], TR_SPLICE)
    assert r.shape[0] == 1 and int(r[0, 2]) >> 16 == 3
    # The stream front door is the scalar arm only.
    with pytest.raises(ValueError, match="scalar arm"):
        serve_dyngraph("sssp", _graph(), width=4, interpret=True)


# ------------------------------------- certification + splice lint


def test_certify_claim_unbound_then_bound():
    g = _graph()
    mk = make_dyngraph_megakernel("sssp", g, width=0, interpret=True)
    cert0 = certify_claim(mk)
    assert cert0["claim"] == "dyngraph"
    assert cert0["status"].startswith("unbound")
    res, _ = run_dyngraph(
        "sssp", g, 0, updates=UPS[:2], width=0, interpret=True, mk=mk,
    )
    assert np.array_equal(res, host_dyngraph("sssp", g, 0))
    cert = certify_claim(mk)
    assert cert["status"] == "certified"
    assert cert["updates"] == 2 and cert["orders"] >= 4


def test_certify_dyngraph_pagerank_conserves_mass():
    cert = certify_dyngraph_schedule(
        "pagerank", updates=UPS[:2], perms=2,
    )
    assert cert["status"] == "certified" and cert["mass"] > 0


def test_check_splice_protocol_and_negatives():
    g = _graph()
    mk = make_dyngraph_megakernel("bfs", g, width=4, interpret=True)
    assert not check_splice(mk).errors()

    # (2) spare-region bounds wiring must be exact.
    mk._dyngraph["total_blocks"] += 1
    rep = check_splice(mk)
    assert any("bounds disagree" in f.message for f in rep.errors())
    mk._dyngraph["total_blocks"] -= 1

    # (1) no lane of a dyngraph build may run the cross-round prefetch.
    upd_spec = next(
        s for fid, s in mk.batch_specs
        if mk.kernel_names[fid] == "dg_update"
    )
    upd_spec.prefetch = True
    rep = check_splice(mk)
    assert any("prefetch" in f.message for f in rep.errors())
    upd_spec.prefetch = False

    # (3) the blind-overwrite exemption is scoped to the spare region:
    # pushing spare_base past the buffer makes the splice's blind
    # spare-row store look like a static-row write, which is refused.
    real = mk._dyngraph["spare_base"]
    mk._dyngraph["spare_base"] = 1 << 40
    rep = check_splice(mk)
    assert any("blind DMA store" in f.message for f in rep.errors())
    mk._dyngraph["spare_base"] = real
    assert not check_splice(mk).errors()


# --------------------------------------------------- off-path purity


_OFFPATH_SCRIPT = """
import hashlib
import numpy as np, jax
{extra}
from hclib_tpu.device.workloads import rmat_edges
from hclib_tpu.device.frontier import _KINDS, Graph, make_frontier_megakernel
from hclib_tpu.device.descriptor import TaskGraphBuilder
n, s, d, w = rmat_edges(4, efactor=3, seed=5)
g = Graph(n, s, d, w)
mk = make_frontier_megakernel(_KINDS["bfs"](), g, width=0, interpret=True)
tasks, succ, ring, counts = TaskGraphBuilder().finalize(
    capacity=mk.capacity, succ_capacity=mk.succ_capacity)
args = [tasks, succ, ring, counts, np.zeros(mk.num_values, np.int32)]
for sp in mk.data_specs.values():
    args.append(np.zeros(sp.shape, sp.dtype))
structs = [jax.ShapeDtypeStruct(np.asarray(x).shape, np.asarray(x).dtype)
           for x in args]
text = mk._build_raw(1 << 12).lower(*structs).as_text()
print(hashlib.sha256(text.encode()).hexdigest())
"""


def _offpath_hash(extra: str) -> str:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("PYTHONPATH", os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    ))
    out = subprocess.run(
        [sys.executable, "-c", _OFFPATH_SCRIPT.format(extra=extra)],
        capture_output=True, text=True, env=env, timeout=300,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    return out.stdout.strip().splitlines()[-1]


def test_static_frontier_lowered_text_unchanged_by_dyngraph():
    """Zero new device words off-path: a STATIC frontier build lowers
    to byte-identical text whether or not the dyngraph module was ever
    imported (the spawn hook defaults compile out entirely)."""
    plain = _offpath_hash("")
    with_dg = _offpath_hash("import hclib_tpu.device.dyngraph")
    assert plain == with_dg
