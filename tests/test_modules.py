"""Module layer: pending-op harness, system module, tpu module.

Mirrors the reference's module tests (modules/cuda/test/{kernel,allocate}.cu,
modules/system usage in test/cpp/copies0.cpp) against the new API.
"""

import numpy as np
import pytest

import hclib_tpu as hc
from hclib_tpu.modules import (
    PendingList,
    PendingOp,
    SystemModule,
    TpuModule,
    World,
    get_closest_cpu_locale,
    get_closest_tpu_locale,
    set_world,
)
from hclib_tpu.modules.tpu import async_device, device_stream, forasync_device
from hclib_tpu.parallel.mesh import cpu_mesh, mesh_locality_graph


@pytest.fixture(autouse=True)
def _reset_world():
    set_world(None)
    yield
    set_world(None)


def test_pending_list_completion_polling():
    """Ops complete when their test fires; promises deliver results."""

    def body():
        counters = {"a": 0, "b": 0}
        pending = PendingList()

        def make_test(key, threshold):
            def test(op):
                counters[key] += 1
                if counters[key] >= threshold:
                    return True, key.upper()
                return False, None

            return test

        from hclib_tpu.runtime.promise import Promise

        fa = pending.append(PendingOp(make_test("a", 3), promise=Promise()))
        fb = pending.append(PendingOp(make_test("b", 5), promise=Promise()))
        assert fa.wait() == "A"
        assert fb.wait() == "B"
        assert len(pending) == 0

    hc.launch(body, nworkers=2)


def test_pending_list_poison_propagates():
    def body():
        from hclib_tpu.runtime.promise import Promise, PromiseError

        pending = PendingList()

        def test(op):
            raise ValueError("transport died")

        f = pending.append(PendingOp(test, promise=Promise()))
        with pytest.raises(PromiseError):
            f.wait()

    hc.launch(body, nworkers=2)


def test_system_module_alloc_memset_copy():
    hc.register_module(SystemModule())

    def body():
        loc = get_closest_cpu_locale()
        buf = hc.allocate_at(((8,), np.float64), loc).wait()
        assert buf.shape == (8,)
        hc.memset_at(buf, 0, loc).wait()
        assert np.all(buf == 0.0)
        src = np.arange(8, dtype=np.float64)
        hc.async_copy(buf, loc, src, loc).wait()
        np.testing.assert_array_equal(buf, src)
        hc.free_at(buf, loc).wait()

    hc.launch(body, nworkers=2)


def test_system_module_alloc_bytes():
    hc.register_module(SystemModule())

    def body():
        loc = get_closest_cpu_locale()
        buf = hc.allocate_at(64, loc).wait()
        assert buf.nbytes == 64

    hc.launch(body, nworkers=1)


def _mesh_runtime_args(ndev=2, nworkers=2):
    mesh = cpu_mesh(ndev)
    return {"locality_graph": mesh_locality_graph(mesh, nworkers=nworkers)}


def test_tpu_module_device_alloc_and_copies():
    hc.register_module(SystemModule())
    hc.register_module(TpuModule())

    def body():
        import jax

        tloc = get_closest_tpu_locale()
        hloc = get_closest_cpu_locale()
        dbuf = hc.allocate_at(((4, 4), np.float32), tloc).wait()
        assert isinstance(dbuf, jax.Array)
        # host->device (MUST_USE beats the system handler)
        src = np.full((4, 4), 3.0, dtype=np.float32)
        dbuf = hc.async_copy(dbuf, tloc, src, hloc).wait()
        # device->host
        out = np.zeros((4, 4), dtype=np.float32)
        hc.async_copy(out, hloc, dbuf, tloc).wait()
        np.testing.assert_array_equal(out, src)

    hc.launch(body, **_mesh_runtime_args())


def test_tpu_module_device_to_device_copy():
    hc.register_module(TpuModule())

    def body():
        rt = hc.current_runtime()
        t0, t1 = rt.graph.locales_of_type("tpu")[:2]
        a = hc.allocate_at(((8,), np.float32), t0).wait()
        b = hc.async_copy(a, t1, a, t0).wait()
        assert b.devices() == {t1.metadata["device"]}

    hc.launch(body, **_mesh_runtime_args())


def test_async_device_runs_on_locale_device():
    hc.register_module(TpuModule())

    def body():
        import jax.numpy as jnp

        tloc = get_closest_tpu_locale()
        f = async_device(lambda x: jnp.sum(x * 2), np.arange(16, dtype=np.float32),
                         locale=tloc)
        assert float(f.wait()) == 240.0

    hc.launch(body, **_mesh_runtime_args())


def test_async_device_stream_ordering():
    """Ops on one stream serialize; results observe program order."""
    hc.register_module(TpuModule())

    def body():
        import jax.numpy as jnp

        tloc = get_closest_tpu_locale()
        st = device_stream(tloc)
        futs = [
            async_device(lambda x, k=k: x + k, np.zeros(4, np.float32),
                         locale=tloc, stream=st)
            for k in range(5)
        ]
        outs = [np.asarray(f.wait()) for f in futs]
        for k, o in enumerate(outs):
            np.testing.assert_array_equal(o, np.full(4, k, np.float32))

    hc.launch(body, **_mesh_runtime_args())


def test_forasync_device_vectorizes():
    hc.register_module(TpuModule())

    def body():
        out = forasync_device(lambda i: i * i, 16).wait()
        np.testing.assert_array_equal(np.asarray(out), np.arange(16) ** 2)

    hc.launch(body, **_mesh_runtime_args())


def test_world_from_mesh_graph():
    def body():
        w = World.from_runtime()
        assert w.size == 2
        assert w.locale_for(0).type == "tpu"
        assert w.device_for(1) is not None

    hc.launch(body, **_mesh_runtime_args())


def test_world_from_default_graph():
    def body():
        w = World.from_runtime()
        assert w.size == 3
        assert w.device_for(0) is None
        assert w.locale_for(2) is not None

    hc.launch(body, nworkers=3)
