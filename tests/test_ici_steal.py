"""In-kernel ICI work stealing (device/ici_steal.py): the fully-resident
multi-device scheduler, exercised under Mosaic's TPU interpret mode (which
simulates remote DMA + semaphores on CPU; the same kernel compiles and runs
on real TPU hardware - see the tpu-gated test).

Reference counterpart: thief-side deque CAS across cores
(/root/reference/src/hclib-locality-graph.c:843-888, src/hclib-deque.c:75-106).
"""

import jax
import numpy as np
import pytest

from hclib_tpu.device.descriptor import TaskGraphBuilder
from hclib_tpu.device.ici_steal import ICIStealMegakernel
from hclib_tpu.device.megakernel import Megakernel
from hclib_tpu.parallel.mesh import cpu_mesh

BUMP = 0


def _bump_kernel(ctx):
    ctx.set_value(0, ctx.value(0) + ctx.arg(0))


def _make_mk(capacity=256):
    return Megakernel(
        kernels=[("bump", _bump_kernel)],
        capacity=capacity,
        num_values=4,
        succ_capacity=8,
        interpret=True,
    )


def _skewed(ndev, ntasks):
    builders = [TaskGraphBuilder() for _ in range(ndev)]
    for i in range(ntasks):
        builders[0].add(BUMP, args=[i + 1])
    return builders


def test_ici_steal_rebalances_skewed_load():
    # (8-device spread coverage lives in the hypercube test below and the
    # resident skewed-fib test; 4 devices keep this one's semantics at a
    # quarter of the interpret cost.)
    ndev, ntasks = 4, 28
    smk = ICIStealMegakernel(
        _make_mk(capacity=64), cpu_mesh(ndev, axis_name="queues"),
        migratable_fns=[BUMP], window=8,
    )
    iv, _, info = smk.run(_skewed(ndev, ntasks), quantum=8)
    assert info["pending"] == 0
    assert info["executed"] == ntasks
    assert int(iv[:, 0].sum()) == ntasks * (ntasks + 1) // 2
    per_dev = info["per_device_counts"][:, 5]
    assert int((per_dev > 0).sum()) >= 3, per_dev


def test_ici_steal_two_devices_exact():
    ndev, ntasks = 2, 16
    smk = ICIStealMegakernel(
        _make_mk(capacity=64), cpu_mesh(ndev, axis_name="queues"),
        migratable_fns=[BUMP], window=8,
    )
    iv, _, info = smk.run(_skewed(ndev, ntasks), quantum=8)
    assert info["pending"] == 0
    assert int(iv[:, 0].sum()) == ntasks * (ntasks + 1) // 2
    assert info["per_device_counts"][1, 5] > 0  # work actually migrated


def test_ici_steal_dependency_graphs_stay_home():
    """Non-whitelisted dynamic graphs (fib spawns with successors) run
    where placed; the steal rounds must not corrupt them."""
    from hclib_tpu.device.workloads import FIB, make_fib_megakernel

    ndev = 2
    mk = make_fib_megakernel(capacity=128, interpret=True)
    smk = ICIStealMegakernel(
        mk, cpu_mesh(ndev, axis_name="queues")
    )  # empty whitelist
    builders = []
    for d, n in enumerate((7, 9)):
        b = TaskGraphBuilder()
        b.add(FIB, args=[n], out=0)
        builders.append(b)
    iv, _, info = smk.run(builders, quantum=64)
    assert info["pending"] == 0
    assert int(iv[0, 0]) == 13 and int(iv[1, 0]) == 34


def test_ici_steal_race_free_under_detector():
    """Mosaic interpret race detection over the full steal protocol - the
    remote DMAs + credit semaphores must induce a happens-before order with
    no data race (an aux capability the reference lacks entirely: its deque
    relies on hand-audited fences, SURVEY.md section 5)."""
    from jax.experimental.pallas import tpu as pltpu

    ndev, ntasks = 2, 12
    smk = ICIStealMegakernel(
        _make_mk(), cpu_mesh(ndev, axis_name="queues"),
        migratable_fns=[BUMP], window=4,
    )
    # Rebuild with the race detector on (pof2 meshes delegate to the
    # resident kernel, so patch the build that will actually run).
    target = smk._resident if smk._resident is not None else smk
    orig = target._build

    def build_with_detector(quantum, max_rounds):
        import unittest.mock as m

        real = pltpu.InterpretParams

        with m.patch.object(
            pltpu, "InterpretParams",
            # Ignore incoming kwargs: the suite's fast-interpret mode
            # (eager DMA, unchecked OOB) must not leak into race
            # detection, which needs the async on_wait DMA model.
            lambda **kw: real(detect_races=True),
        ):
            return orig(quantum, max_rounds)

    target._build = build_with_detector
    iv, _, info = smk.run(_skewed(ndev, ntasks), quantum=4)
    assert int(iv[:, 0].sum()) == ntasks * (ntasks + 1) // 2


@pytest.mark.skipif(jax.default_backend() != "tpu", reason="needs TPU")
def test_ici_steal_compiles_and_runs_on_tpu():
    """The steal kernel on a REAL TPU chip: 1-device mesh, self-loop ring -
    remote DMA + semaphores exercise the actual Mosaic lowering."""
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:1]), ("queues",))
    mk = Megakernel(
        kernels=[("bump", _bump_kernel)],
        capacity=256, num_values=4, succ_capacity=8, interpret=False,
    )
    smk = ICIStealMegakernel(mesh=mesh, mk=mk, migratable_fns=[BUMP])
    ntasks = 100
    iv, _, info = smk.run(_skewed(1, ntasks), quantum=16)
    assert info["pending"] == 0
    assert int(iv[0, 0]) == ntasks * (ntasks + 1) // 2


def test_ici_steal_hypercube_spreads_max_skew_fast():
    """VERDICT round-2 efficiency target: a 48-task skew on 8 devices
    spreads across the whole mesh in a handful of exchange rounds (the
    paired dimension-exchange moves (mine-theirs)/2 per hop, all hops per
    round, vs. one fixed window to a single partner per round)."""
    ndev, ntasks = 8, 48
    smk = ICIStealMegakernel(
        _make_mk(capacity=128), cpu_mesh(ndev, axis_name="queues"),
        migratable_fns=[BUMP], window=16,
    )
    iv, _, info = smk.run(_skewed(ndev, ntasks), quantum=8)
    assert info["pending"] == 0
    assert int(iv[:, 0].sum()) == ntasks * (ntasks + 1) // 2
    per_dev = info["per_device_counts"][:, 5]
    assert int((per_dev > 0).sum()) == ndev, per_dev  # EVERY device worked
    # Round 1's three hops spread 48 -> 6 per device; quantum=8 then
    # drains everyone in about one execution round.
    assert info["steal_rounds"] <= 4, info["steal_rounds"]


def test_ici_steal_2d_mesh_exact():
    """2x2 mesh (VERDICT item 6): the XOR dimension-exchange decomposes
    into per-axis torus hops; totals must be exact and work must reach
    both rows and columns."""
    from hclib_tpu.parallel.mesh import make_mesh

    cpus = jax.devices("cpu")
    mesh = make_mesh((2, 2), ("r", "c"), cpus[:4])
    ntasks = 20
    smk = ICIStealMegakernel(
        _make_mk(capacity=64), mesh, migratable_fns=[BUMP], window=8,
    )
    builders = [TaskGraphBuilder() for _ in range(4)]
    for i in range(ntasks):
        builders[0].add(BUMP, args=[i + 1])
    iv, _, info = smk.run(builders, quantum=8)
    assert info["pending"] == 0
    assert info["executed"] == ntasks
    assert int(iv[:, 0].sum()) == ntasks * (ntasks + 1) // 2
    per_dev = info["per_device_counts"][:, 5]
    assert int((per_dev > 0).sum()) >= 3, per_dev


def test_ici_steal_non_pof2_legacy_ring():
    """3 devices take the cycling-partner + ring-termination path; totals
    stay exact."""
    ndev, ntasks = 3, 18
    smk = ICIStealMegakernel(
        _make_mk(), cpu_mesh(ndev, axis_name="queues"),
        migratable_fns=[BUMP], window=8,
    )
    iv, _, info = smk.run(_skewed(ndev, ntasks), quantum=4)
    assert info["pending"] == 0
    assert int(iv[:, 0].sum()) == ntasks * (ntasks + 1) // 2
    per_dev = info["per_device_counts"][:, 5]
    assert int((per_dev > 0).sum()) >= 2, per_dev


# ------------------------------------- batched dispatch in the ring (ISSUE 7)

from hclib_tpu.jaxcompat import has_mosaic_interpret  # noqa: E402

needs_mosaic = pytest.mark.skipif(
    not has_mosaic_interpret(),
    reason="needs pltpu.InterpretParams (Mosaic TPU interpret mode)",
)


@needs_mosaic
def test_ici_steal_batch_routed_bump_exact():
    """ISSUE 7 acceptance (ICI arm, pof2): a batch-routed mk through
    ICIStealMegakernel on a pof2 mesh - run() delegates to the resident
    kernel's steal-only configuration, so this covers the delegation
    path surfacing info['tiers'] unchanged. Totals stay exact, work
    still spreads (lane residue spills to the ring's cold end before
    every steal round), and tier counters reconcile with the executed
    count."""
    from hclib_tpu.device.workloads import batch_of

    ndev, ntasks = 4, 28
    mk = Megakernel(
        kernels=[("bump", _bump_kernel)],
        capacity=64,
        num_values=4,
        succ_capacity=8,
        interpret=True,
        route={"bump": batch_of(_bump_kernel, width=4)},
    )
    smk = ICIStealMegakernel(
        mk, cpu_mesh(ndev, axis_name="queues"),
        migratable_fns=[BUMP], window=8,
    )
    iv, _, info = smk.run(_skewed(ndev, ntasks), quantum=8)
    assert info["pending"] == 0
    assert info["executed"] == ntasks
    assert int(iv[:, 0].sum()) == ntasks * (ntasks + 1) // 2
    tiers = info["tiers"]
    assert len(tiers) == ndev
    batched = sum(t["batch_tasks"] for t in tiers)
    scalar = sum(t["scalar_tasks"] for t in tiers)
    assert batched + scalar == ntasks, (batched, scalar)
    assert batched > 0, tiers
    per_dev = info["per_device_counts"][:, 5]
    assert int((per_dev > 0).sum()) >= 2, per_dev


@needs_mosaic
def test_ici_steal_batch_routed_non_pof2_ring():
    """The 3-device legacy ring (cycling partner + ring termination) runs
    this class's OWN kernel body - the only reachable one (pof2 meshes
    delegate to ResidentKernel) - so the lane scratch binding behind its
    11-ref scratch tail gets direct coverage here."""
    from hclib_tpu.device.workloads import batch_of

    ndev, ntasks = 3, 18
    mk = Megakernel(
        kernels=[("bump", _bump_kernel)],
        capacity=64,
        num_values=4,
        succ_capacity=8,
        interpret=True,
        route={"bump": batch_of(_bump_kernel, width=4)},
    )
    smk = ICIStealMegakernel(
        mk, cpu_mesh(ndev, axis_name="queues"),
        migratable_fns=[BUMP], window=8,
    )
    iv, _, info = smk.run(_skewed(ndev, ntasks), quantum=4)
    assert info["pending"] == 0
    assert int(iv[:, 0].sum()) == ntasks * (ntasks + 1) // 2
    tiers = info["tiers"]
    batched = sum(t["batch_tasks"] for t in tiers)
    scalar = sum(t["scalar_tasks"] for t in tiers)
    assert batched + scalar == info["executed"], (batched, scalar)
    assert batched > 0, tiers
