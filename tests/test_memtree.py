"""Pinned host-buffer registry (runtime/memtree.py, the reference's
hclib-tree.c role) + its hook in the tpu module copy handler."""

import numpy as np
import pytest

from hclib_tpu.runtime.memtree import MemoryTree, global_tree, lookup, pin, unpin


def test_insert_lookup_remove():
    t = MemoryTree()
    t.insert(0x1000, 0x100, meta="a")
    t.insert(0x3000, 0x80, meta="b")
    assert t.contains(0x1000)
    assert t.contains(0x10FF)
    assert not t.contains(0x1100)
    assert t.lookup(0x3040).meta == "b"
    assert len(t) == 2
    removed = t.remove(0x1050)  # by interior address, like the reference
    assert removed.meta == "a"
    assert not t.contains(0x1000)
    assert len(t) == 1


def test_overlap_rejected():
    t = MemoryTree()
    t.insert(0x1000, 0x100)
    with pytest.raises(ValueError):
        t.insert(0x1080, 0x10)
    with pytest.raises(ValueError):
        t.insert(0x0F80, 0x100)
    t.insert(0x1100, 0x10)  # adjacent is fine


def test_remove_missing_raises():
    t = MemoryTree()
    with pytest.raises(KeyError):
        t.remove(0x42)


def test_pin_unpin_numpy():
    a = np.arange(64, dtype=np.float32)
    entry = pin(a)
    try:
        assert lookup(a) is entry
        # A view starting at the same base address resolves to the entry.
        assert global_tree().contains(a.ctypes.data)
        assert global_tree().contains(a.ctypes.data + a.nbytes - 1)
    finally:
        unpin(a)
    assert lookup(a) is None


def test_noncontiguous_rejected():
    a = np.arange(64, dtype=np.float32)[::2]
    with pytest.raises(ValueError):
        pin(a)


def test_tpu_copy_stages_unpinned_and_not_pinned(monkeypatch):
    """The h2d copy handler must defensively copy unpinned numpy sources
    and pass pinned ones through zero-copy."""
    import hclib_tpu.modules.tpu as tpu_mod
    from hclib_tpu.runtime.locality import Locale

    staged = []
    put_srcs = []

    class _FakeJax:
        @staticmethod
        def device_put(x, dev):
            put_srcs.append(x)
            return x

    monkeypatch.setattr(tpu_mod, "_device_of", lambda loc: None)
    monkeypatch.setitem(__import__("sys").modules, "jax", _FakeJax)

    host = Locale(0, "sysmem", "sysmem")
    dev = Locale(1, "tpu_0", "tpu")

    a = np.arange(16, dtype=np.float32)
    tpu_mod._tpu_copy(None, dev, a, host)
    assert put_srcs[-1] is not a  # staged copy

    b = np.arange(16, dtype=np.float32)
    pin(b)
    try:
        tpu_mod._tpu_copy(None, dev, b, host)
        assert put_srcs[-1] is b  # zero-copy
    finally:
        unpin(b)
