"""Batch-dispatch (vector) tier: exactness, stealing, megakernel bridge.

The reference has no vector tier (its fib is one heap task per call,
test/fib/fib.c); these tests pin the rebuild-specific contract instead:
exact counts/results for the whole family, overflow reporting, and the
scalar<->vector bridge (a vector task firing scalar successors)."""

import jax
import jax.numpy as jnp
import pytest

from hclib_tpu.device.descriptor import TaskGraphBuilder
from hclib_tpu.device.megakernel import Megakernel
from hclib_tpu.device.vector_engine import fib_spec, make_subtree_runner
from hclib_tpu.device.workloads import device_vfib


def fib(n):
    a, b = 0, 1
    for _ in range(n):
        a, b = b, a + b
    return a


def tree_tasks(n):
    # Naive recursion-tree node count: N(n) = 1 + N(n-1) + N(n-2).
    if n < 2:
        return 1
    return 1 + tree_tasks(n - 1) + tree_tasks(n - 2)


@pytest.fixture(scope="module")
def runner():
    spec = fib_spec(max_n=14, lanes=(1, 8))
    run = make_subtree_runner(spec, max_steps=100000)
    jitted = jax.jit(lambda n: run((n,), jnp.where(n >= 2, 2, 0)))
    cpu = jax.devices("cpu")[0]

    # Pin via committed inputs, NOT a default_device context held across
    # the yield - that context would leak into every other test in the
    # module (a TPU-gated test then lowers its kernel for CPU and fails).
    def call(n):
        return jitted(jax.device_put(n, cpu))

    return call


@pytest.mark.parametrize("n", [2, 3, 5, 10, 14])
def test_runner_exact(runner, n):
    nodes, accs, over = runner(jnp.int32(n))
    assert int(accs["value"]) == fib(n)
    assert int(nodes) + 1 == tree_tasks(n)  # +1: the seed task itself
    assert not bool(over)


def test_runner_leaf_seed(runner):
    # Seeds with count 0 do no vector work (the megakernel bridge adds
    # root_contrib for them).
    for n in (0, 1):
        nodes, accs, over = runner(jnp.int32(n))
        assert int(nodes) == 0 and int(accs["value"]) == 0


def test_runner_stack_overflow_flag():
    spec = fib_spec(max_n=3, lanes=(1, 8))  # depth 5: too shallow for 12
    run = make_subtree_runner(spec, max_steps=100000)
    with jax.default_device(jax.devices("cpu")[0]):
        _, _, over = jax.jit(lambda: run((12,), jnp.int32(2)))()
    assert bool(over)


def test_device_vfib_interpret():
    v, info = device_vfib(10, lanes=(1, 8), interpret=True)
    assert v == fib(10)
    assert info["executed"] == tree_tasks(10)


def test_vector_task_fires_scalar_successors():
    # A vfib task's completion must run downstream scalar-tier tasks with
    # its reduced output visible in the out slot.
    spec = fib_spec(max_n=12, lanes=(1, 8))

    def double(ctx):
        ctx.set_value(1, ctx.value(0) * 2)

    mk = Megakernel(
        kernels=[("vfib", spec), ("double", double)],
        capacity=16,
        num_values=8,
        succ_capacity=8,
        interpret=True,
    )
    b = TaskGraphBuilder()
    t0 = b.add(0, args=[9], out=0)
    b.add(1, deps=[t0], out=1)
    b.reserve_values(2)
    ivalues, _, info = mk.run(b)
    assert ivalues[0] == fib(9)
    assert ivalues[1] == 2 * fib(9)
    assert info["executed"] == tree_tasks(9) + 1  # +1: the double task
    assert info["pending"] == 0


KNOWN_NQ = {1: 1, 4: 2, 5: 10, 6: 4, 8: 92}


@pytest.mark.parametrize("n", [1, 4, 5, 6])
def test_nqueens_runner_exact(n):
    """The vector tier is a generic engine, not a fib special case: the
    n-queens family (3-word bitboard frames, data-dependent child counts)
    counts exactly (reference workload test/misc/nqueens)."""
    from hclib_tpu.device.vector_engine import nqueens_spec

    spec = nqueens_spec(n, lanes=(1, 8))
    run = make_subtree_runner(spec, max_steps=200000)
    with jax.default_device(jax.devices("cpu")[0]):
        _, accs, over = jax.jit(
            lambda: run(spec.seed((jnp.int32(0),))[0], n)
        )()
    assert int(accs["solutions"]) == KNOWN_NQ[n]
    assert not bool(over)


def test_device_nqueens_interpret():
    from hclib_tpu.device.workloads import device_nqueens

    v, info = device_nqueens(6, lanes=(1, 8), interpret=True)
    assert v == KNOWN_NQ[6]
    # The host model agrees (it runs under the host runtime).
    from hclib_tpu.models import nqueens as nq

    assert nq.run(6)["value"] == v


@pytest.mark.skipif(jax.default_backend() != "tpu", reason="needs TPU")
def test_device_nqueens_tpu():
    from hclib_tpu.device.workloads import device_nqueens

    v, info = device_nqueens(10)
    assert v == 724


def test_auto_route_irregular_dag_gets_fast_path():
    """auto_route: a scalar fib kernel's family is routed to the
    batch-dispatch tier by NAME (VERDICT r4 #3) - an irregular DAG mixing
    scalar tasks and a routed recursive family runs the family's whole
    subtree on the VPU lanes (executed counts the expanded tree, not one
    descriptor) while dependencies and out slots behave exactly as on the
    scalar tier."""
    from hclib_tpu.device.workloads import _fib_kernel, _sum_kernel

    def seedv(ctx):
        ctx.set_value(0, 7)

    def consume(ctx):
        ctx.set_value(2, ctx.value(1) + ctx.value(0))

    mk = Megakernel(
        kernels=[
            ("seed", seedv),
            ("fib", _fib_kernel),   # scalar definition of the family
            ("sum", _sum_kernel),
            ("consume", consume),
        ],
        auto_route={"fib": fib_spec(max_n=14, lanes=(1, 8))},
        capacity=32,
        num_values=16,
        succ_capacity=16,
        interpret=True,
    )
    b = TaskGraphBuilder()
    t0 = b.add(0)                        # scalar: writes value 0
    t1 = b.add(1, args=[12], deps=[t0], out=1)  # routed family subtree
    b.add(3, deps=[t1])                  # scalar: reads family's out
    b.reserve_values(3)
    ivalues, _, info = mk.run(b)
    assert ivalues[1] == fib(12)
    assert ivalues[2] == fib(12) + 7
    # Proof the fast path ran: executed counts the whole expanded
    # recursion tree (465 nodes for fib(12)), not 3 descriptors - and no
    # SUM continuation descriptors were ever spawned.
    assert info["executed"] == tree_tasks(12) + 2
    assert info["allocated"] == 3
    assert info["pending"] == 0


def test_auto_route_unknown_name_rejected():
    with pytest.raises(ValueError, match="auto_route"):
        Megakernel(
            kernels=[("a", lambda ctx: None)],
            auto_route={"b": fib_spec(max_n=4, lanes=(1, 8))},
            interpret=True,
        )
