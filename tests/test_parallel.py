"""Mesh, collectives, and sharded-megakernel tests (8 virtual CPU devices)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from hclib_tpu.device.descriptor import TaskGraphBuilder
from hclib_tpu.device.sharded import ShardedMegakernel, round_robin_partition
from hclib_tpu.device.workloads import FIB, make_fib_megakernel
from hclib_tpu.jaxcompat import shard_map
from hclib_tpu.parallel import collectives
from hclib_tpu.parallel.mesh import cpu_mesh, mesh_locality_graph


def _mesh(n):
    if len(jax.devices("cpu")) < n:
        pytest.skip(f"needs {n} cpu devices (xla_force_host_platform_device_count)")
    return cpu_mesh(n)


def test_mesh_locality_graph():
    mesh = _mesh(4)
    g = mesh_locality_graph(mesh)
    assert g.nworkers == 4
    tpus = g.locales_of_type("tpu")
    assert len(tpus) == 4
    assert tpus[0].metadata["ordinal"] == 0
    ici = g.by_name["ici"]
    assert ici.is_special("COMM")
    # every tpu locale is on every worker's steal path
    for w in range(4):
        path_types = {g.locale(l).type for l in g.steal_paths[w]}
        assert "tpu" in path_types and "host" in path_types
        assert len([l for l in g.steal_paths[w] if g.locale(l).type == "tpu"]) == 4


def test_collectives_on_mesh():
    mesh = _mesh(4)

    def step(x):
        s = collectives.psum(x[0], "d")
        g = collectives.all_gather(x[0], "d")
        r = collectives.ring_permute(x[0], "d", 1)
        return s[None], g[None], r[None]

    f = jax.jit(
        shard_map(
            step, mesh=mesh, in_specs=(P("d"),), out_specs=(P("d"),) * 3,
            check_vma=False,
        )
    )
    x = jax.device_put(
        np.arange(4, dtype=np.float32).reshape(4, 1), NamedSharding(mesh, P("d"))
    )
    s, g, r = f(x)
    assert np.all(np.asarray(s) == 6.0)  # 0+1+2+3 everywhere
    assert np.asarray(g).shape == (4, 4, 1)
    assert list(np.asarray(r)[:, 0]) == [3, 0, 1, 2]  # rotated shards


def test_composed_collectives():
    """The composed tier (bcast/reduce/exscan/barrier/ring_allreduce -
    MPI_Bcast/Reduce/Exscan/Barrier parity, hclib_mpi.cpp:220-286): exact
    against numpy references, including the explicit ring-step allreduce
    matching psum."""
    mesh = _mesh(8)

    def step(x):
        b = collectives.bcast(x[0], "d", root=3)
        r = collectives.reduce(x[0], "d", root=2)
        e = collectives.exscan(x[0], "d")
        t = collectives.barrier("d")
        ra = collectives.ring_allreduce(x[0], "d")
        return b[None], r[None], e[None], t[None], ra[None]

    f = jax.jit(
        shard_map(
            step, mesh=mesh, in_specs=(P("d"),), out_specs=(P("d"),) * 5,
            check_vma=False,
        )
    )
    x = np.arange(8 * 16, dtype=np.float32).reshape(8, 16)
    b, r, e, t, ra = map(np.asarray, f(x))
    assert (b == x[3]).all()
    assert (r[2] == x.sum(0)).all() and (r[0] == 0).all()
    assert np.allclose(e, np.cumsum(x, axis=0) - x)  # exclusive prefix
    assert (t == 8).all()
    assert np.allclose(ra, np.tile(x.sum(0), (8, 1)))


def test_sharded_megakernel_fib():
    mesh = _mesh(4)
    mk = make_fib_megakernel(capacity=1024, interpret=True)
    smk = ShardedMegakernel(mk, mesh)
    builders = []
    for d in range(4):
        b = TaskGraphBuilder()
        b.add(FIB, args=[9 + d], out=0)
        builders.append(b)
    iv, _, info = smk.run(builders, fuel=1 << 18)
    assert [int(iv[d, 0]) for d in range(4)] == [34, 55, 89, 144]
    assert info["pending"] == 0
    assert not info["overflow"]


def test_sharded_megakernel_with_data_buffers():
    """Exercises the stacked-data path: per-device arrayadd tile tasks over
    per-device HBM buffers."""
    import jax.numpy as jnp
    from jax.experimental.pallas import tpu as pltpu

    from hclib_tpu.device.megakernel import Megakernel
    from hclib_tpu.device.workloads import ADD_TILE, _TILE, _addtile_kernel

    mesh = _mesh(2)
    ntiles = 3
    shape = (ntiles,) + _TILE
    spec = jax.ShapeDtypeStruct(shape, jnp.float32)
    mk = Megakernel(
        kernels=[("add_tile", _addtile_kernel)],
        data_specs={"a": spec, "b": spec, "c": spec},
        scratch_specs={
            "va": pltpu.VMEM(_TILE, jnp.float32),
            "vb": pltpu.VMEM(_TILE, jnp.float32),
            "sems": pltpu.SemaphoreType.DMA((3,)),
        },
        capacity=64,
        num_values=8,
        succ_capacity=8,
        interpret=True,
    )
    smk = ShardedMegakernel(mk, mesh)
    builders = []
    for d in range(2):
        b = TaskGraphBuilder()
        for t in range(ntiles):
            b.add(ADD_TILE, args=[t])
        builders.append(b)
    rng = np.random.default_rng(1)
    a = rng.standard_normal((2,) + shape).astype(np.float32)
    bb = rng.standard_normal((2,) + shape).astype(np.float32)
    c = np.zeros((2,) + shape, np.float32)
    _, data, info = smk.run(builders, data={"a": a, "b": bb, "c": c}, fuel=1 << 12)
    assert info["executed"] == 6
    assert np.allclose(np.asarray(data["c"]), a + bb)


def test_sharded_partition_validation():
    mesh = _mesh(2)
    mk = make_fib_megakernel(capacity=64, interpret=True)
    smk = ShardedMegakernel(mk, mesh)
    with pytest.raises(ValueError, match="partitions"):
        smk.run([TaskGraphBuilder()])


def test_round_robin_partition():
    parts = round_robin_partition(list(range(10)), 3)
    assert parts == [[0, 3, 6, 9], [1, 4, 7], [2, 5, 8]]


def test_graft_entry_dryrun():
    # 2 devices: every phase still executes end-to-end as a regression
    # guard; the driver itself runs the full 8-device dry run each round.
    import __graft_entry__ as ge

    if len(jax.devices("cpu")) < 2:
        pytest.skip("needs virtual cpu devices")
    # smoke-scale phase 5: the full >=100k-task size belongs to the
    # driver's own dry run and perf_regression --multichip, not the suite
    ge.dryrun_multichip(2, benchmark_scale=False)


def test_graft_entry_compiles():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    jax.jit(fn).lower(*args)  # trace/lower must succeed
