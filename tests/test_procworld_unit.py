"""Single-process ProcWorld engine tests over a fake coordination client.

The progress engine's failure taxonomy (transient retry, fatal death with
reply poisoning + tombstones) can't be driven from the integration tests -
you can't make the real coordination service fail on cue. The fake client
runs every rank as a thread over one shared dict and injects errors by
status code. (The reference's comm modules have no equivalent seam: their
failure behavior is abort-only and untested, SURVEY §5.)
"""

import threading
import time

import numpy as np
import pytest

from hclib_tpu.modules.procworld import (
    ProcWorld,
    ProcWorldError,
    _status,
)


class FakeClient:
    """In-process stand-in for jaxlib's coordination-service client.

    Mimics the observed API surface: absent keys raise NOT_FOUND-prefixed
    errors; ``fail`` (op_name, key) -> Exception lets tests inject faults.
    """

    def __init__(self, world_size: int = 1):
        self._kv = {}
        self._ctr = {}
        self._cv = threading.Condition()
        self._barriers = {}
        self.world_size = world_size
        self.fail = None

    def _maybe_fail(self, op, key):
        if self.fail is not None:
            e = self.fail(op, key)
            if e is not None:
                raise e

    def key_value_set_bytes(self, key, val):
        self._maybe_fail("set", key)
        with self._cv:
            self._kv[key] = bytes(val) if not isinstance(val, bytes) else val
            self._cv.notify_all()

    def key_value_try_get_bytes(self, key):
        self._maybe_fail("try_get", key)
        with self._cv:
            if key in self._kv:
                return self._kv[key]
        raise RuntimeError(f"NOT_FOUND: key {key} not found")

    def blocking_key_value_get_bytes(self, key, timeout_ms):
        self._maybe_fail("blocking_get", key)
        deadline = time.monotonic() + timeout_ms / 1000.0
        with self._cv:
            while key not in self._kv:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise RuntimeError(
                        f"DEADLINE_EXCEEDED: GetKeyValue() timed out "
                        f"with key: {key}"
                    )
                self._cv.wait(left)
            return self._kv[key]

    def key_value_delete(self, key):
        with self._cv:
            self._kv.pop(key, None)

    def key_value_increment(self, key, n):
        self._maybe_fail("increment", key)
        with self._cv:
            self._ctr[key] = self._ctr.get(key, 0) + n
            return self._ctr[key]

    def wait_at_barrier(self, bid, timeout_ms, *a, **k):
        deadline = time.monotonic() + timeout_ms / 1000.0
        with self._cv:
            self._barriers[bid] = self._barriers.get(bid, 0) + 1
            self._cv.notify_all()
            while self._barriers[bid] < self.world_size:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise RuntimeError(f"DEADLINE_EXCEEDED: Barrier {bid}")
                self._cv.wait(left)


def _world(client, rank, size, **kw):
    kw.setdefault("timeout_s", 5.0)
    return ProcWorld(_client=client, _rank=rank, _size=size, **kw)


def test_status_classification_is_by_leading_token():
    assert _status(RuntimeError("NOT_FOUND: key x")) == "NOT_FOUND"
    assert _status(RuntimeError("UNAVAILABLE: conn refused")) == "UNAVAILABLE"
    # 'NOT_FOUND' *inside* a message must not classify as NOT_FOUND - the
    # round-2 substring test turned UNAVAILABLE errors into silent death.
    assert _status(
        RuntimeError("INTERNAL: handler for NOT_FOUND missing")
    ) == "INTERNAL"
    assert _status(RuntimeError("weird free-text error")) == "UNKNOWN"


def test_basic_ops_over_fake_client():
    c = FakeClient(world_size=2)
    a, b = _world(c, 0, 2), _world(c, 1, 2)
    try:
        a.send(1, np.arange(3), tag=4)
        assert (b.recv(0, tag=4) == np.arange(3)).all()
        for w in (a, b):
            with w._heap_lock:
                w._heap["x"] = np.zeros(4, np.int32)
        a.put(1, "x", np.array([7, 8]), offset=1)
        a.fence(1)
        assert (b.heap("x") == [0, 7, 8, 0]).all()
        assert (a.get(1, "x", offset=1, size=2) == [7, 8]).all()
    finally:
        a.close()
        b.close()


def test_allreduce_recursive_doubling_all_sizes():
    """Exact allreduce for power-of-two and ragged world sizes (the
    pre/post folding steps), every supported op."""
    for n in (2, 3, 4, 5):
        c = FakeClient(world_size=n)
        worlds = [_world(c, r, n) for r in range(n)]
        results = [None] * n

        def run(r):
            w = worlds[r]
            results[r] = (
                w.allreduce(np.arange(4, dtype=np.int64) + r),
                w.allreduce(np.float64(r), op="max"),
                w.allreduce(np.int32(r + 1), op="prod"),
            )

        ts = [threading.Thread(target=run, args=(r,)) for r in range(n)]
        [t.start() for t in ts]
        [t.join(timeout=30) for t in ts]
        expect_sum = np.arange(4) * n + sum(range(n))
        prod = int(np.prod(np.arange(1, n + 1)))
        for r in range(n):
            s, m, p = results[r]
            assert (s == expect_sum).all(), (n, r, s)
            assert float(m) == n - 1
            assert int(p) == prod
        for w in worlds:
            w.close()


def test_engine_retries_transient_errors():
    """UNAVAILABLE during the poll must not kill the engine (round 2's
    deterministic tutorial-08 failure): it backs off, retries, and applies
    the op once the service recovers."""
    c = FakeClient(world_size=2)
    flaky = {"n": 0}

    def fail(op, key):
        if op == "try_get" and "/op/1/" in key and flaky["n"] < 3:
            flaky["n"] += 1
            return RuntimeError("UNAVAILABLE: failed to connect")
        return None

    a, b = _world(c, 0, 2), _world(c, 1, 2)
    c.fail = fail
    try:
        with b._heap_lock:
            b._heap["x"] = np.zeros(2, np.int32)
        a.put(1, "x", np.array([5]), offset=0)
        deadline = time.monotonic() + 5
        while int(b.heap("x")[0]) != 5:
            assert time.monotonic() < deadline, "put never applied"
            time.sleep(0.01)
        assert flaky["n"] == 3  # the transient path was actually exercised
        assert b.dead is None
    finally:
        c.fail = None
        a.close()
        b.close()


def test_fatal_error_poisons_pending_replies_and_tombstones():
    """A dying engine must fail peers fast: poison queued reply keys and
    publish a tombstone - not strand them until DEADLINE_EXCEEDED."""
    c = FakeClient(world_size=2)
    a, b = _world(c, 0, 2), _world(c, 1, 2)
    try:
        with b._heap_lock:
            b._heap["x"] = np.zeros(2, np.int32)
        # Stop b's engine from seeing ops, then post a get that will queue.
        c.fail = lambda op, key: (
            RuntimeError("INVALID_ARGUMENT: boom")
            if op == "try_get" and "/op/1/" in key
            else None
        )
        t0 = time.monotonic()
        with pytest.raises(ProcWorldError):
            a.get(1, "x")
        # Fail-fast: poisoned reply or tombstone, not a 5 s timeout.
        assert time.monotonic() - t0 < 4.0
        deadline = time.monotonic() + 2
        while b.dead is None and time.monotonic() < deadline:
            time.sleep(0.01)
        assert b.dead is not None
        # New ops on the dead world raise immediately.
        with pytest.raises(ProcWorldError):
            b.send(0, np.int32(1))
    finally:
        c.fail = None
        a.close()
        b.close()


def test_module_future_fails_fast_on_dead_peer():
    """A ProcWorldModule future must poison (not pend forever) when the
    target rank's engine is tombstoned - same failure model as the
    blocking API - and roll back the receive-sequence claim."""
    import hclib_tpu as hc
    from hclib_tpu.modules.procworld import ProcWorldModule
    from hclib_tpu.runtime.promise import PromiseError

    c = FakeClient(world_size=2)
    a = _world(c, 0, 2, timeout_s=3.0)
    try:
        mod = ProcWorldModule(world=a)
        hc.register_module(mod)
        c.key_value_set_bytes("hcpw/dead/1", b"INTERNAL: dead peer")

        def body():
            rf = mod.irecv(1, tag=3)
            t0 = time.monotonic()
            with pytest.raises(PromiseError):
                rf.wait()
            assert time.monotonic() - t0 < 2.5  # tombstone, not timeout

        hc.launch(body, nworkers=2)
        assert a._recv_seq.get((1, 3), 0) == 0  # claim rolled back
    finally:
        a.close()


def test_await_reply_fails_fast_on_peer_tombstone():
    """Even when the reply was queued before the peer died (so it never
    got poisoned), the waiter sees the tombstone at its next poll chunk."""
    c = FakeClient(world_size=2)
    a = _world(c, 0, 2, timeout_s=6.0)
    try:
        c.key_value_set_bytes("hcpw/dead/1", b"INTERNAL: dead peer")
        t0 = time.monotonic()
        with pytest.raises(ProcWorldError, match="progress engine died"):
            a._await_reply("hcpw/re/0/999", 1)
        assert time.monotonic() - t0 < 4.0  # one chunk, not the timeout
    finally:
        a.close()
