"""Host runtime core tests, mirroring the reference's test/c and test/cpp
feature suites (async0/1, finish0/1/2, future0-3, asyncAwait, yield,
nested_finish, future_wait_in_finish; see SURVEY.md section 4)."""

import threading

import pytest

import hclib_tpu as hc


def test_async_runs_before_finish_exits():
    hit = []

    def main():
        with hc.finish():
            hc.async_(lambda: hit.append(1))
            hc.async_(lambda: hit.append(2))
        assert sorted(hit) == [1, 2]

    hc.launch(main, nworkers=2)


def test_launch_returns_value():
    assert hc.launch(lambda: 42, nworkers=1) == 42


def test_launch_propagates_exceptions():
    def main():
        raise ValueError("boom")

    with pytest.raises(ValueError):
        hc.launch(main, nworkers=2)


def test_nested_finish():
    order = []

    def main():
        with hc.finish():
            def outer():
                with hc.finish():
                    hc.async_(lambda: order.append("inner"))
                order.append("after-inner")

            hc.async_(outer)
        order.append("after-outer")

    hc.launch(main, nworkers=2)
    assert order == ["inner", "after-inner", "after-outer"]


def test_many_asyncs_single_worker():
    n = 2000
    counter = []

    def main():
        with hc.finish():
            for i in range(n):
                hc.async_(counter.append, i)

    hc.launch(main, nworkers=1)
    assert len(counter) == n


def test_many_asyncs_multi_worker():
    n = 2000
    lock = threading.Lock()
    box = [0]

    def bump():
        with lock:
            box[0] += 1

    def main():
        with hc.finish():
            for _ in range(n):
                hc.async_(bump)

    hc.launch(main, nworkers=4)
    assert box[0] == n


def test_promise_put_get():
    def main():
        p = hc.Promise()
        f = p.future
        assert not f.satisfied()
        p.put(99)
        assert f.satisfied()
        assert f.get() == 99
        assert f.wait() == 99

    hc.launch(main, nworkers=1)


def test_promise_double_put_raises():
    def main():
        p = hc.Promise()
        p.put(1)
        with pytest.raises(hc.PromiseError):
            p.put(2)

    hc.launch(main, nworkers=1)


def test_future_wait_blocks_until_put():
    def main():
        p = hc.Promise()
        with hc.finish():
            hc.async_(lambda: p.put("val"))
            assert p.future.wait() == "val"

    hc.launch(main, nworkers=2)


def test_future_wait_single_worker():
    """A blocked context must release its worker so the producer task runs
    (the reference's fiber-swap; here, identity hand-off)."""

    def main():
        p = hc.Promise()
        with hc.finish():
            hc.async_(lambda: p.put(7))
            assert p.future.wait() == 7

    hc.launch(main, nworkers=1)


def test_async_await_dependency_order():
    log = []

    def main():
        a = hc.Promise()
        b = hc.Promise()
        with hc.finish():
            hc.async_(lambda: log.append("dep-task"), await_=[a.future, b.future])
            hc.async_(lambda: (log.append("put-a"), a.put(None)))
            hc.async_(lambda: (log.append("put-b"), b.put(None)))
        assert log[-1] == "dep-task"
        assert set(log[:2]) == {"put-a", "put-b"}

    hc.launch(main, nworkers=2)


def test_async_await_many_deps():
    """More than 4 dependencies (past the reference's inline cap)."""
    n = 16

    def main():
        ps = [hc.Promise() for _ in range(n)]
        done = []
        with hc.finish():
            hc.async_(lambda: done.append(True), await_=[p.future for p in ps])
            for p in ps:
                hc.async_(p.put, None)
        assert done == [True]

    hc.launch(main, nworkers=3)


def test_async_future_returns_value():
    def main():
        f = hc.async_future(lambda: 10)
        g = hc.async_future(lambda x: x.get() + 5, f, await_=[f])
        assert g.wait() == 15

    hc.launch(main, nworkers=2)


def test_ddf_chain():
    """Chain of 100 data-driven tasks."""

    def main():
        prev = hc.async_future(lambda: 0)
        for _ in range(100):
            prev = hc.async_future(lambda p=prev: p.get() + 1, await_=[prev])
        assert prev.wait() == 100

    hc.launch(main, nworkers=2)


def test_end_finish_nonblocking():
    def main():
        hit = []
        fin = hc.start_finish()
        hc.async_(lambda: hit.append(1))
        fut = hc.end_finish_nonblocking(fin)
        fut.wait()
        assert hit == [1]

    hc.launch(main, nworkers=2)


def test_yield_runs_other_task():
    def main():
        hit = []
        with hc.finish():
            hc.async_(lambda: hit.append(1))
            hc.yield_()

    hc.launch(main, nworkers=1)


def test_future_wait_in_finish():
    """Reference: test/cpp/future_wait_in_finish.cpp."""

    def main():
        p = hc.Promise()
        out = []
        with hc.finish():
            def waiter():
                out.append(p.future.wait())

            hc.async_(waiter)
            hc.async_(lambda: p.put(3))
        assert out == [3]

    hc.launch(main, nworkers=2)


def test_async_at_locale():
    def main():
        rt = hc.current_runtime()
        central = rt.graph.central_locale()
        seen = []
        with hc.finish():
            hc.async_(lambda: seen.append(hc.current_worker()), at=central)
        assert len(seen) == 1

    hc.launch(main, nworkers=2)


def test_current_worker_and_num_workers():
    def main():
        assert hc.num_workers() == 3
        assert 0 <= hc.current_worker() < 3

    hc.launch(main, nworkers=3)


def test_remote_task_exception_propagates():
    """An exception in a task executed by a pool worker (not inline in the
    awaiting context) must surface at launch(), not vanish."""
    import time

    def main():
        with hc.finish():
            for _ in range(50):
                hc.async_(lambda: None)
            hc.async_(lambda: 1 / 0)
            time.sleep(0.05)  # give another worker time to steal it

    with pytest.raises(ZeroDivisionError):
        hc.launch(main, nworkers=4)


def test_failed_producer_poisons_dependents():
    """A failing async_future must not strand dependents: they run, see the
    poisoned promise on get(), and the error surfaces at launch()."""

    def main():
        f = hc.async_future(lambda: 1 / 0)
        hc.async_(lambda: f.get(), await_=[f])

    with pytest.raises((ZeroDivisionError, hc.PromiseError)):
        hc.launch(main, nworkers=2)


def test_failed_producer_future_wait():
    def main():
        f = hc.async_future(lambda: 1 / 0)
        with pytest.raises(hc.PromiseError):
            f.wait()

    with pytest.raises(ZeroDivisionError):
        hc.launch(main, nworkers=2)


def test_recursive_spawn_tree():
    """Binary task tree, depth 10 -> 2^10 leaves."""
    lock = threading.Lock()
    box = [0]

    def node(d):
        if d == 0:
            with lock:
                box[0] += 1
            return
        hc.async_(node, d - 1)
        hc.async_(node, d - 1)

    def main():
        with hc.finish():
            node(10)

    hc.launch(main, nworkers=4)
    assert box[0] == 1024


def test_run_on_main_executes_on_launch_thread():
    """hclib_run_on_main_ctx parity (src/hclib-runtime.c:1340-1358):
    workers hand main-thread-affine functions to the launch thread and
    block for the result; from the main thread it runs inline; errors
    re-raise in the caller."""
    import threading

    main_ident = threading.get_ident()
    seen = []

    def body():
        # inline from the main thread
        assert hc.run_on_main(threading.get_ident) == main_ident

        def from_worker():
            seen.append(hc.run_on_main(threading.get_ident))
            seen.append(hc.run_on_main(lambda a, b: a + b, 20, 22))

        with hc.finish():
            hc.async_(from_worker)

        def boom():
            def raiser():
                raise ValueError("main-ctx boom")

            try:
                hc.run_on_main(raiser)
            except ValueError as e:
                seen.append(str(e))

        with hc.finish():
            hc.async_(boom)

    hc.launch(body, nworkers=2)
    assert seen[0] == main_ident
    assert seen[1] == 42
    assert seen[2] == "main-ctx boom"


def test_run_on_main_wakes_do_not_poison_finish_parks():
    """ADVICE r5 medium regression: run_on_main wakes a main thread parked
    in help_finish through a CALLER-OWNED event registered on the finish
    (Promise._register_ctx shape), never a shared cached scope event. A
    string of wakes mid-scope must (a) each reach the main thread, (b)
    leave no set/abandoned event registered on the still-open finish, and
    (c) not degrade the pool into a busy spin (park -> instant wake)."""
    import time as _time

    main_ident = threading.get_ident()
    got = []
    waiters_seen = []

    def body():
        rt = hc.current_runtime()
        release = threading.Event()

        def blocker():
            release.wait(10.0)  # holds the root scope open

        def pesterer():
            for _ in range(5):
                got.append(rt.run_on_main(threading.get_ident))
                _time.sleep(0.02)
            fin = rt.root_finish
            with fin._lock:
                evs = list(fin._zero_events)
            waiters_seen.append([ev.is_set() for ev in evs])
            release.set()

        hc.async_(blocker)
        hc.async_(pesterer)

    rt_holder = {}

    def wrapped():
        rt_holder["rt"] = hc.current_runtime()
        return body()

    hc.launch(wrapped, nworkers=2)
    assert got == [main_ident] * 5
    # Nothing set stayed registered on the open scope (a set shared event
    # was the old busy-spin poison); at most the main park + a worker.
    (flags,) = waiters_seen
    assert len(flags) <= 2 and not any(flags)
    # Busy-spin detector: five wakes cost ~a dozen parks, not thousands.
    parks = sum(st.parks for st in rt_holder["rt"].worker_stats)
    assert parks < 100, parks


def test_run_on_main_wakes_leave_no_stale_promise_waiters():
    """ADVICE r5 low regression: a spurious run_on_main wake on the
    wait_on park path unregisters its event from Promise._ctx_waiters
    before re-parking, so repeated wakes against a long-lived promise
    never accumulate dead waiter events."""
    import time as _time

    from hclib_tpu.runtime.promise import Promise

    sizes = []

    def body():
        rt = hc.current_runtime()
        prom = Promise()

        def pesterer():
            for _ in range(6):
                rt.run_on_main(lambda: None)
                _time.sleep(0.02)
                with prom._lock:
                    sizes.append(len(prom._ctx_waiters))
            prom.put(7)

        with hc.finish():
            hc.async_(pesterer)
            prom.future.wait()  # parked main thread, pestered awake
        assert prom.get() == 7

    hc.launch(body, nworkers=2)
    # At most the main thread's one live registration at any sample point
    # (0 while it is between unregister and re-register).
    assert max(sizes) <= 1, sizes


def test_run_on_main_from_escaping_task_at_finalize():
    """An escaping task still blocked in run_on_main when the root finish
    drains is serviced by the finalize join loop (the reference's
    src/hclib-runtime.c:1420-1423)."""
    import threading
    import time as _time

    main_ident = threading.get_ident()
    got = []

    def body():
        started = threading.Event()

        def late():
            started.set()
            _time.sleep(0.15)  # root finish drains before this fires
            got.append(hc.current_runtime().run_on_main(threading.get_ident))

        hc.current_runtime().spawn(late, escaping=True)
        started.wait(5.0)  # a worker is executing it when the root drains

    hc.launch(body, nworkers=2)
    assert got == [main_ident]
