"""Host runtime core tests, mirroring the reference's test/c and test/cpp
feature suites (async0/1, finish0/1/2, future0-3, asyncAwait, yield,
nested_finish, future_wait_in_finish; see SURVEY.md section 4)."""

import threading

import pytest

import hclib_tpu as hc


def test_async_runs_before_finish_exits():
    hit = []

    def main():
        with hc.finish():
            hc.async_(lambda: hit.append(1))
            hc.async_(lambda: hit.append(2))
        assert sorted(hit) == [1, 2]

    hc.launch(main, nworkers=2)


def test_launch_returns_value():
    assert hc.launch(lambda: 42, nworkers=1) == 42


def test_launch_propagates_exceptions():
    def main():
        raise ValueError("boom")

    with pytest.raises(ValueError):
        hc.launch(main, nworkers=2)


def test_nested_finish():
    order = []

    def main():
        with hc.finish():
            def outer():
                with hc.finish():
                    hc.async_(lambda: order.append("inner"))
                order.append("after-inner")

            hc.async_(outer)
        order.append("after-outer")

    hc.launch(main, nworkers=2)
    assert order == ["inner", "after-inner", "after-outer"]


def test_many_asyncs_single_worker():
    n = 2000
    counter = []

    def main():
        with hc.finish():
            for i in range(n):
                hc.async_(counter.append, i)

    hc.launch(main, nworkers=1)
    assert len(counter) == n


def test_many_asyncs_multi_worker():
    n = 2000
    lock = threading.Lock()
    box = [0]

    def bump():
        with lock:
            box[0] += 1

    def main():
        with hc.finish():
            for _ in range(n):
                hc.async_(bump)

    hc.launch(main, nworkers=4)
    assert box[0] == n


def test_promise_put_get():
    def main():
        p = hc.Promise()
        f = p.future
        assert not f.satisfied()
        p.put(99)
        assert f.satisfied()
        assert f.get() == 99
        assert f.wait() == 99

    hc.launch(main, nworkers=1)


def test_promise_double_put_raises():
    def main():
        p = hc.Promise()
        p.put(1)
        with pytest.raises(hc.PromiseError):
            p.put(2)

    hc.launch(main, nworkers=1)


def test_future_wait_blocks_until_put():
    def main():
        p = hc.Promise()
        with hc.finish():
            hc.async_(lambda: p.put("val"))
            assert p.future.wait() == "val"

    hc.launch(main, nworkers=2)


def test_future_wait_single_worker():
    """A blocked context must release its worker so the producer task runs
    (the reference's fiber-swap; here, identity hand-off)."""

    def main():
        p = hc.Promise()
        with hc.finish():
            hc.async_(lambda: p.put(7))
            assert p.future.wait() == 7

    hc.launch(main, nworkers=1)


def test_async_await_dependency_order():
    log = []

    def main():
        a = hc.Promise()
        b = hc.Promise()
        with hc.finish():
            hc.async_(lambda: log.append("dep-task"), await_=[a.future, b.future])
            hc.async_(lambda: (log.append("put-a"), a.put(None)))
            hc.async_(lambda: (log.append("put-b"), b.put(None)))
        assert log[-1] == "dep-task"
        assert set(log[:2]) == {"put-a", "put-b"}

    hc.launch(main, nworkers=2)


def test_async_await_many_deps():
    """More than 4 dependencies (past the reference's inline cap)."""
    n = 16

    def main():
        ps = [hc.Promise() for _ in range(n)]
        done = []
        with hc.finish():
            hc.async_(lambda: done.append(True), await_=[p.future for p in ps])
            for p in ps:
                hc.async_(p.put, None)
        assert done == [True]

    hc.launch(main, nworkers=3)


def test_async_future_returns_value():
    def main():
        f = hc.async_future(lambda: 10)
        g = hc.async_future(lambda x: x.get() + 5, f, await_=[f])
        assert g.wait() == 15

    hc.launch(main, nworkers=2)


def test_ddf_chain():
    """Chain of 100 data-driven tasks."""

    def main():
        prev = hc.async_future(lambda: 0)
        for _ in range(100):
            prev = hc.async_future(lambda p=prev: p.get() + 1, await_=[prev])
        assert prev.wait() == 100

    hc.launch(main, nworkers=2)


def test_end_finish_nonblocking():
    def main():
        hit = []
        fin = hc.start_finish()
        hc.async_(lambda: hit.append(1))
        fut = hc.end_finish_nonblocking(fin)
        fut.wait()
        assert hit == [1]

    hc.launch(main, nworkers=2)


def test_yield_runs_other_task():
    def main():
        hit = []
        with hc.finish():
            hc.async_(lambda: hit.append(1))
            hc.yield_()

    hc.launch(main, nworkers=1)


def test_future_wait_in_finish():
    """Reference: test/cpp/future_wait_in_finish.cpp."""

    def main():
        p = hc.Promise()
        out = []
        with hc.finish():
            def waiter():
                out.append(p.future.wait())

            hc.async_(waiter)
            hc.async_(lambda: p.put(3))
        assert out == [3]

    hc.launch(main, nworkers=2)


def test_async_at_locale():
    def main():
        rt = hc.current_runtime()
        central = rt.graph.central_locale()
        seen = []
        with hc.finish():
            hc.async_(lambda: seen.append(hc.current_worker()), at=central)
        assert len(seen) == 1

    hc.launch(main, nworkers=2)


def test_current_worker_and_num_workers():
    def main():
        assert hc.num_workers() == 3
        assert 0 <= hc.current_worker() < 3

    hc.launch(main, nworkers=3)


def test_remote_task_exception_propagates():
    """An exception in a task executed by a pool worker (not inline in the
    awaiting context) must surface at launch(), not vanish."""
    import time

    def main():
        with hc.finish():
            for _ in range(50):
                hc.async_(lambda: None)
            hc.async_(lambda: 1 / 0)
            time.sleep(0.05)  # give another worker time to steal it

    with pytest.raises(ZeroDivisionError):
        hc.launch(main, nworkers=4)


def test_failed_producer_poisons_dependents():
    """A failing async_future must not strand dependents: they run, see the
    poisoned promise on get(), and the error surfaces at launch()."""

    def main():
        f = hc.async_future(lambda: 1 / 0)
        hc.async_(lambda: f.get(), await_=[f])

    with pytest.raises((ZeroDivisionError, hc.PromiseError)):
        hc.launch(main, nworkers=2)


def test_failed_producer_future_wait():
    def main():
        f = hc.async_future(lambda: 1 / 0)
        with pytest.raises(hc.PromiseError):
            f.wait()

    with pytest.raises(ZeroDivisionError):
        hc.launch(main, nworkers=2)


def test_recursive_spawn_tree():
    """Binary task tree, depth 10 -> 2^10 leaves."""
    lock = threading.Lock()
    box = [0]

    def node(d):
        if d == 0:
            with lock:
                box[0] += 1
            return
        hc.async_(node, d - 1)
        hc.async_(node, d - 1)

    def main():
        with hc.finish():
            node(10)

    hc.launch(main, nworkers=4)
    assert box[0] == 1024
