"""hclint v2 - the whole-program concurrency model checker (ISSUE 14):
wait-graph deadlock detection, bounded protocol interleaving, and
schedule-independence certification. Every seeded-violation fixture
must raise/report with a CONCRETE witness (the cycle's kind chain, the
interleaving prefix, the two divergent schedules), the clean
configurations must audit clean, and the verify-off path must stay
bit-identical (the analyses are host-only composition - no Pallas
build, no Mosaic)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from hclib_tpu.analysis import (
    AnalysisError,
    CreditExchangeModel,
    InjectQuiesceModel,
    certify_claim,
    certify_frontier_schedule,
    certify_tile_schedule,
    check_protocols,
    check_wait_graph,
    explore,
    wait_graph,
)
from hclib_tpu.analysis.waits import _any_wait_mentions
from hclib_tpu.device.descriptor import TaskGraphBuilder
from hclib_tpu.device.forasync_tier import (
    Slab, TileKernel, make_forasync_megakernel, run_forasync_device,
)
from hclib_tpu.device.frontier import (
    INF, FrontierKernel, Graph, _spawn_blocks, bfs_kernel,
    make_frontier_megakernel,
)
from hclib_tpu.device.megakernel import Megakernel
from hclib_tpu.device.tenants import TenantSpec, TenantTable

N, TS = 32, 8


def _mk(kernels, **kw):
    kw.setdefault("capacity", 32)
    kw.setdefault("num_values", 16)
    kw.setdefault("succ_capacity", 8)
    kw.setdefault("interpret", True)
    kw.setdefault("verify", True)
    return Megakernel(kernels=kernels, **kw)


# ------------------------------------------------ wait-graph deadlock


def test_two_kind_wait_cycle_caught_at_construction():
    """SEEDED VIOLATION: kind a waits the flag only b satisfies and
    vice versa - no schedule can order the satisfactions. Construction
    refuses with the cycle's kind chain as the witness."""

    def ka(ctx):
        ctx.wait_value(5)
        ctx.satisfy(6)

    def kb(ctx):
        ctx.wait_value(6)
        ctx.satisfy(5)

    with pytest.raises(AnalysisError, match="wait cycle") as ei:
        _mk([("a", ka), ("b", kb)])
    f = [x for x in ei.value.report.findings if x.rule == "wait-cycle"]
    assert f and tuple(f[0].witness["cycle"]) in (
        ("a", "b", "a"), ("b", "a", "b"),
    )


def test_unsatisfied_wait_is_a_guaranteed_stall():
    def w(ctx):
        ctx.wait_value(7)

    with pytest.raises(AnalysisError, match="no kind ever satisfies"):
        _mk([("w", w)])


def test_wait_gate_survives_computing_bodies_and_unmodelled_tails():
    """REGRESSION: the recording wait returns the flag word (like the
    real op), so a body that COMPUTES with the waited value still
    records its wait; and a body whose TAIL the shim cannot model
    keeps the waits recorded before it (the partial trace rides the
    ShimUnsupported) - neither spelling evades the deadlock gate."""

    def compute_with_wait(ctx):
        ctx.set_value(0, ctx.wait_value(7) + 1)

    with pytest.raises(AnalysisError, match="no kind ever satisfies"):
        _mk([("w", compute_with_wait)])

    def wait_then_unmodelled(ctx):
        ctx.wait_value(7)
        raise RuntimeError("tail the shim cannot run")

    with pytest.raises(AnalysisError, match="no kind ever satisfies"):
        _mk([("w", wait_then_unmodelled)])


def test_acyclic_wait_constructs_runs_and_satisfies():
    """A satisfier/waiter pair with an order (a before b) passes the
    gate AND runs: the promise flag write satisfies the bounded spin,
    end to end on the device path."""

    def sa(ctx):
        ctx.satisfy(5, v=7)

    def wb(ctx):
        ctx.set_value(0, ctx.wait_value(5))

    mk = _mk([("sat", sa), ("wait", wb)])
    assert mk.analysis.errors() == []
    g = wait_graph(mk)
    assert g["wait"]["waits"] and g["sat"]["satisfies"]
    b = TaskGraphBuilder()
    b.add(1)  # waiter queued first ...
    b.add(0)  # ... satisfier added last pops FIRST (LIFO owner side)
    iv, _, info = mk.run(b)
    assert int(iv[0]) == 7 and info["executed"] == 2


def test_spin_budget_exhaustion_is_diagnosed_not_wedged():
    """An unsatisfiable wait (gate suppressed to get it built) spins
    out its bounded budget and the host raises naming the promise
    budget - never a wedged core."""

    def w(ctx):
        ctx.wait_value(6, spin_cap=8)

    mk = _mk([("w", w)], verify_suppress=("wait-cycle",))
    b = TaskGraphBuilder()
    b.add(0)
    with pytest.raises(RuntimeError, match="promise-wait spin budget"):
        mk.run(b)


def test_arg_carried_promise_slots_note_not_refuse():
    """A serving-loop-shaped program plumbs its promise slot through
    DESCRIPTOR ARGS (per-request dynamic slots). The static graph
    cannot match those - it must NOTE them (the spin budget is the
    runtime backstop), never refuse a correct program as an orphan."""

    def producer(ctx):
        ctx.satisfy(ctx.arg(0))

    def consumer(ctx):
        ctx.wait_value(ctx.arg(0))

    mk = _mk([("produce", producer), ("consume", consumer)])
    assert mk.analysis.errors() == []
    notes = [f for f in mk.analysis.findings
             if f.rule == "wait-cycle" and f.severity == "info"]
    assert any("arg-carried" in f.message for f in notes)


def test_wait_free_tree_pays_no_shim_pass():
    """The cost gate: a megakernel with no wait ops is detected by the
    cheap code-object scan - no wait findings, no summaries forced at
    construction."""

    def plain(ctx):
        ctx.set_value(0, ctx.value(0) + 1)

    mk = _mk([("plain", plain)])
    assert not _any_wait_mentions(mk)
    assert getattr(mk, "_kind_summaries", None) is None
    assert all(f.rule != "wait-cycle" for f in mk.analysis.findings)


# ------------------------------------------- bounded interleaving


def test_credit_wedge_interleaving_found_with_witness():
    """SEEDED VIOLATION: the dropped-credit fault with no regeneration
    (the credit_timeout=0 lockstep wedge). The explorer finds the
    wedging interleaving and returns the action prefix as witness."""
    res = explore(CreditExchangeModel(
        (3, 0), drop_credit=0, regen=False, max_steals=2,
    ))
    assert res.violations, "the wedge was not found"
    v = res.violations[0]
    assert "credit wedge" in v.message
    assert any(a[0] == "grant" for a in v.witness)  # a real interleaving
    # The same fault WITH the shipped regeneration recovery explores
    # clean on every schedule - termination and conservation restored.
    res2 = explore(CreditExchangeModel(
        (3, 0), drop_credit=0, regen=True, max_steals=2,
    ))
    assert res2.clean and res2.complete and res2.terminals > 0
    # Through the report path the violation RAISES AnalysisError with
    # the interleaving as its witness (the hclint/CI gate).
    with pytest.raises(AnalysisError, match="credit wedge") as ei:
        check_protocols(configs=[(
            "seeded-wedge",
            CreditExchangeModel((3, 0), drop_credit=0, max_steals=2),
        )]).raise_errors()
    f = ei.value.report.errors()[0]
    assert f.rule == "interleaving" and f.witness["interleaving"]


def test_inject_poll_conservation_and_quiesce_freeze():
    """The WRR poll model (built on wrr_poll_reference itself): skewed
    weights + expired rows + a paused lane + backpressure conserve on
    every schedule; a poll that keeps consuming after the quiesce
    freeze diverges from the exported words and is refused."""
    res = explore(InjectQuiesceModel(
        [(3, 2, (1,)), (2, 1), (2, 1, (), True)], capacity=2,
    ))
    assert res.clean and res.complete and res.terminals > 0
    res_q = explore(InjectQuiesceModel(
        [(2, 1), (2, 2)], capacity=2, quiesce=True,
    ))
    assert res_q.clean, [v.message for v in res_q.violations]
    bad = explore(InjectQuiesceModel(
        [(2, 1), (2, 2)], capacity=2, quiesce=True, freeze_poll=False,
    ))
    assert bad.violations
    v = next(x for x in bad.violations if "quiesce-freeze" in x.message)
    assert any(a[0] == "quiesce" for a in v.witness)


def test_explorer_dedup_bounds_and_no_unsound_pruning():
    """The explorer is stateful (dedup bounds the work by reachable
    states) and its depth bound flags incompleteness instead of
    silently passing. REGRESSION: the footprint-vs-enabled-set pruning
    once shipped here was unsound - exec actions look independent at
    the root, but executing the victim's surplus DISABLES the steal
    request whose interleaving holds the wedge. This configuration is
    the counterexample: the wedge must be found."""
    model = CreditExchangeModel((2, 1), max_steals=2)
    full = explore(model)
    assert full.complete and full.states > 0
    assert full.transitions >= full.states - 1
    bounded = explore(model, depth=1)
    assert not bounded.complete
    hidden = explore(CreditExchangeModel(
        (2, 1), drop_credit=0, regen=False, max_steals=2,
    ))
    assert hidden.complete
    assert any("credit wedge" in v.message for v in hidden.violations)
    # REGRESSION: a victim drained between request and grant answers
    # EMPTY (deny) - no schedule may steal a row that no longer exists
    # (negative task counts once masked wedges as conservation-clean).
    assert all(
        min(v.state[0]) >= 0 for v in hidden.violations
    )


def test_tenant_roster_protocol_model_and_curated_clean():
    """TenantTable.protocol_model seeds the explorer from a real lane
    roster; the curated protocol set (hclint's) audits clean."""
    tb = TenantTable(
        [TenantSpec("gold", weight=2), TenantSpec("std")],
        16, clock=lambda: 0.0,
    )
    res = explore(tb.protocol_model(rows_per_lane=2, capacity=2))
    assert res.clean and res.terminals > 0
    rep = check_protocols()
    assert rep.actionable() == []


# ------------------------------------- schedule-independence certs


def _specs():
    return {
        "x": jax.ShapeDtypeStruct((N,), jnp.int32),
        "y": jax.ShapeDtypeStruct((N,), jnp.int32),
    }


def test_tile_certificate_and_order_dependent_refusal():
    good = TileKernel(
        loads=[Slab("xin", "x", lambda a: (pl.ds(a[1], TS),), (TS,))],
        stores=[Slab("yout", "y", lambda a: (pl.ds(a[1], TS),), (TS,))],
        compute=lambda ins: {"yout": ins["xin"] * 3 + 7},
        data_specs=_specs(),
    )
    cert = certify_tile_schedule(good, [N], [TS])
    assert cert["status"] == "certified" and cert["tiles"] == N // TS
    # SEEDED VIOLATION: an in-place loop - each tile LOADS the window
    # its neighbor STORES, so pop order changes what it reads.
    inplace = TileKernel(
        loads=[Slab("win", "y",
                    lambda a: (pl.ds((a[1] + TS) % N, TS),), (TS,))],
        stores=[Slab("wout", "y", lambda a: (pl.ds(a[1], TS),), (TS,))],
        compute=lambda ins: {"wout": ins["win"] + 1},
        data_specs=_specs(),
    )
    with pytest.raises(AnalysisError, match="order-DEPENDENT") as ei:
        certify_tile_schedule(inplace, [N], [TS])
    w = ei.value.report.findings[0].witness
    assert "schedule_a" in w and "schedule_b" in w
    assert w["schedule_a"] != w["schedule_b"]


def test_frontier_kinds_certified_and_visit_order_refused():
    for kind in ("bfs", "sssp", "pagerank"):
        cert = certify_frontier_schedule(kind)
        assert cert["status"] == "certified", cert

    # SEEDED VIOLATION: visit-order labeling (DFS-vs-BFS numbering) -
    # the classic order-dependent traversal. Refused with the two
    # divergent schedules in the diagnostic.
    def visit_order_relax(fk, kctx, u, w, carry):
        st = fk.st_base + u
        first = kctx.ivalues[st] == INF

        @pl.when(first)
        def _():
            n = kctx.ivalues[1] + 1
            kctx.ivalues[1] = n
            kctx.ivalues[st] = n
            _spawn_blocks(kctx, u, 0)

    fk = FrontierKernel(
        "fr_visit", visit_order_relax, weighted=False, state0=INF,
    )
    with pytest.raises(AnalysisError, match="order-DEPENDENT") as ei:
        certify_frontier_schedule("bfs", fk=fk)
    msg = str(ei.value)
    assert "schedule_a" in msg and "schedule_b" in msg


def test_certificates_surface_in_describe():
    """ACCEPTANCE: frontier and forasync builders carry the certificate
    in Megakernel.describe(), beside the reshard classification."""
    rng = np.random.default_rng(3)
    m = 40
    g = Graph(16, rng.integers(0, 16, m), rng.integers(0, 16, m))
    mk = make_frontier_megakernel(bfs_kernel(), g, width=4,
                                  interpret=True)
    d = mk.describe()
    assert d["schedule_independence"]["status"] == "certified"
    assert d["kinds"]["fr_bfs"]["classification"] == "link-free"

    tk = TileKernel(
        loads=[Slab("xin", "x", lambda a: (pl.ds(a[1], TS),), (TS,))],
        stores=[Slab("yout", "y", lambda a: (pl.ds(a[1], TS),), (TS,))],
        compute=lambda ins: {"yout": ins["xin"] * 3 + 7},
        data_specs=_specs(),
    )
    fmk = make_forasync_megakernel(tk, width=4, interpret=True)
    # Unbound until a run names the tile space ...
    assert "unbound" in fmk.describe()["schedule_independence"]["status"]
    out, _ = run_forasync_device(
        tk, [N], [TS],
        {"x": np.arange(N, dtype=np.int32), "y": np.zeros(N, np.int32)},
        width=4, mk=fmk,
    )
    assert (out["y"] == np.arange(N) * 3 + 7).all()
    cert = fmk.describe()["schedule_independence"]
    assert cert["status"] == "certified" and cert["tiles"] == N // TS
    assert certify_claim(fmk)["status"] == "certified"


# ------------------------------------------------ off-path guarantees


def test_verify_off_bit_identical_with_wait_kinds():
    """The model checker can only RAISE: a wait/satisfy program lowers
    to identical text (and identical results) verify-on vs verify-off."""

    def sa(ctx):
        ctx.satisfy(5, v=9)

    def wb(ctx):
        ctx.set_value(0, ctx.wait_value(5))

    outs, texts = {}, {}
    for v in (False, True):
        mk = _mk([("sat", sa), ("wait", wb)], verify=v)
        b = TaskGraphBuilder()
        b.add(1)
        b.add(0)
        iv, _, _ = mk.run(b)
        outs[v] = int(iv[0])
        b2 = TaskGraphBuilder()
        b2.add(1)
        b2.add(0)
        tasks, succ, ring, counts = b2.finalize(
            capacity=32, succ_capacity=8
        )
        texts[v] = str(
            jax.jit(mk._build_raw(16)).lower(
                jnp.asarray(tasks), jnp.asarray(succ), jnp.asarray(ring),
                jnp.asarray(counts), jnp.zeros(16, jnp.int32),
            ).as_text()
        )
    assert outs[False] == outs[True] == 9
    assert texts[False] == texts[True]


def test_model_checker_stays_host_only():
    """waits/explore/model never build kernels nor import Mosaic - the
    same off-path guarantee the PR 11 analyses carry."""
    import os as _os

    import hclib_tpu.analysis as pkg

    d = _os.path.dirname(pkg.__file__)
    for fname in ("waits.py", "explore.py", "model.py"):
        with open(_os.path.join(d, fname)) as f:
            src = f.read()
        assert "pallas_call" not in src, fname
        assert "InterpretParams" not in src, fname


def test_explicit_check_wait_graph_entry():
    """The library entry composes with an existing report/suppression
    like every other check_* (the hclint CLI path)."""

    def ka(ctx):
        ctx.wait_value(5)
        ctx.satisfy(6)

    def kb(ctx):
        ctx.wait_value(6)
        ctx.satisfy(5)

    mk = _mk([("a", ka), ("b", kb)], verify=False)
    rep = check_wait_graph(mk)
    assert [f.rule for f in rep.errors()] == ["wait-cycle"]
    rep2 = check_wait_graph(mk, suppress=("wait-cycle",))
    assert rep2.errors() == [] and rep2.findings[0].suppressed
