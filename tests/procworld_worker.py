"""Subprocess body for the cross-process ProcWorld tests: every assertion
here runs in BOTH ranks of a real 2-process jax.distributed world (the
reference's comm-module tests need mpirun + a cluster; this needs two local
processes - SURVEY section 4's 'do better without a cluster')."""

import sys

import numpy as np


def main() -> int:
    pid, n, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    import jax

    jax.distributed.initialize(
        f"localhost:{port}", num_processes=n, process_id=pid
    )
    from hclib_tpu.modules.procworld import ProcWorld

    w = ProcWorld(timeout_s=30.0)
    assert w.rank == pid and w.size == n
    peer = (pid + 1) % n

    # two-sided: ordered ping-pong with tags
    w.send(peer, np.arange(8, dtype=np.int32) + 10 * pid, tag=1)
    w.send(peer, np.float64(3.5) * (pid + 1), tag=2)
    got1 = w.recv((pid - 1) % n, tag=1)
    got2 = w.recv((pid - 1) % n, tag=2)
    src = (pid - 1) % n
    assert (got1 == np.arange(8) + 10 * src).all(), got1
    assert float(got2) == 3.5 * (src + 1), got2
    # ordering within a tag
    for i in range(4):
        w.send(peer, np.int32(i), tag=7)
    for i in range(4):
        assert int(w.recv(src, tag=7)) == i

    # collectives
    w.barrier()
    s = w.allreduce(np.arange(4, dtype=np.int64) + pid)
    assert (s == np.arange(4) * n + sum(range(n))).all(), s
    m = w.allreduce(np.float32(pid), op="max")
    assert float(m) == n - 1
    s2 = w.allreduce(np.int32(pid + 1))  # epochs keep repeats distinct
    assert int(s2) == sum(range(1, n + 1))

    # symmetric heap: put (one-sided write), fence, get (one-sided read)
    w.alloc("buf", (4 * n + 4,), np.int32)
    w.put(peer, "buf", np.full(4, 100 + pid, np.int32), offset=4 * pid)
    w.fence(peer)
    w.barrier()  # both fences done -> every put applied everywhere
    mine = w.heap("buf")
    assert (mine[4 * src : 4 * src + 4] == 100 + src).all(), mine
    # Read back this rank's own put from the peer's heap (the only region
    # of the peer's array anyone wrote is offset 4*pid).
    remote = w.get(peer, "buf", offset=4 * pid, size=4)
    assert (remote == 100 + pid).all(), remote

    # active message: remote increments its own heap cell. Register BEFORE
    # any rank can send (the engine also tolerates a short registration
    # race, but SPMD discipline is register-then-communicate).
    def bump(world, arr, slot=0):
        world.heap("buf")[slot] += int(arr[0])

    w.register_handler("bump", bump)
    w.barrier()
    w.am(peer, "bump", np.array([5 + pid]), slot=4 * n)
    w.fence(peer)
    w.barrier()
    assert int(w.heap("buf")[4 * n]) == 5 + src, w.heap("buf")[4 * n]

    # bulk allreduce: payloads over BULK_THRESHOLD ride XLA collectives
    # over the global device runtime (parallel/multihost.bulk_allreduce)
    big = np.full((1 << 15,), pid + 1, np.float32)  # 128 KiB
    s3 = w.allreduce(big)
    assert (s3 == sum(range(1, n + 1))).all(), s3[:4]
    # Strict on capable backends; a backend that cannot run multiprocess
    # computations (CPU pre-gloo jaxlib) records the degradation and the
    # KV fallback must still have produced the exact sum above.
    expect_path = "kv-fallback" if w._bulk_broken else "bulk"
    assert w.last_allreduce_path == expect_path, (
        w.last_allreduce_path, w._bulk_broken)
    small = w.allreduce(np.int32(1))
    assert int(small) == n and w.last_allreduce_path == "kv"

    # --- module integration: ProcWorld ops as COMM-locale tasks returning
    # futures that hclib tasks await (the reference's hclib_mpi.cpp:130-210
    # Isend/Irecv + pending-op polling shape) ---
    import hclib_tpu as hc
    from hclib_tpu.modules.procworld import ProcWorldModule

    w.alloc("mbuf", (2 * n,), np.int32)
    mod = ProcWorldModule(world=w)
    hc.register_module(mod)

    def body():
        out = {}
        sf = mod.isend(peer, np.arange(6, dtype=np.int64) + 7 * pid, tag=21)
        rf = mod.irecv(src, tag=21)
        pf = mod.iput(peer, "mbuf", np.full(2, 50 + pid, np.int32),
                      offset=2 * pid)
        ff = mod.ifence(peer)
        gf = mod.iget(w.rank, "mbuf", offset=0, size=2)

        def consume():
            out["msg"] = rf.get()  # this task ran gated on a comm future

        hc.async_(consume, await_=[rf])
        mod.wait_all(sf, pf, ff, gf)
        return out

    out = hc.launch(body, nworkers=2)
    assert (out["msg"] == np.arange(6) + 7 * src).all(), out["msg"]
    w.barrier()  # every rank's iput fenced -> heap slice visible
    assert (w.heap("mbuf")[2 * src : 2 * src + 2] == 50 + src).all()

    w.quiet()
    w.barrier()
    w.close()
    jax.distributed.shutdown()
    print(f"rank {pid}: OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
