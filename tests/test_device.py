"""Megakernel device-path tests.

Most tests run the Pallas kernel in interpret mode (pinned to the host CPU
backend); one smoke test compiles on the real TPU when present.
"""

import jax
import numpy as np
import pytest
from jax.experimental import pallas as pl

from hclib_tpu.device.descriptor import TaskGraphBuilder
from hclib_tpu.device.workloads import (
    SUM,
    device_arrayadd,
    device_fib,
    make_fib_megakernel,
)


def test_descriptor_builder_csr():
    b = TaskGraphBuilder()
    a = b.add(0, args=[1])
    deps = [b.add(0, args=[2], deps=[a]) for _ in range(5)]
    c = b.add(0, args=[3], deps=deps)
    tasks, succ, ring, counts = b.finalize(capacity=16, succ_capacity=16)
    # a has 5 successors: 2 inline + 3 in CSR
    assert tasks[a, 2] == deps[0] and tasks[a, 3] == deps[1]
    assert tasks[a, 5] == 3
    assert list(succ[tasks[a, 4] : tasks[a, 4] + 3]) == deps[2:]
    assert tasks[c, 1] == 5  # dep count
    assert counts[1] == 1 and ring[0] == a  # only a initially ready
    assert counts[3] == 7  # pending


def test_device_fib_interpret():
    v, info = device_fib(11, interpret=True)
    assert v == 89
    assert info["pending"] == 0
    assert info["executed"] == 430  # 2*F(12)-1 fib nodes + 143 sum joins
    # Completed rows are reclaimed (free-stack) and the owner pops LIFO
    # (depth-first), so the descriptor high-water mark is the spawn-tree
    # depth, not the task count.
    assert info["allocated"] <= 32, info["allocated"]


def test_device_arrayadd_interpret():
    a, b, c, info = device_arrayadd(4, interpret=True)
    assert np.allclose(c, a + b)
    assert info["executed"] == 4


def test_static_dag_with_csr_fanout_interpret():
    """Diamond with fan-out 5: A -> B0..B4 -> C (exercises inline + CSR
    successors and a 5-way join)."""
    mk = make_fib_megakernel(64, interpret=True)
    b = TaskGraphBuilder()
    # ivalues[0]=1, ivalues[1]=2 preset; A: v2 = v0+v1 = 3
    a = b.add(SUM, args=[0, 1], out=2)
    bs = [b.add(SUM, args=[2, 0], out=4 + i, deps=[a]) for i in range(5)]
    b.add(SUM, args=[4, 5], out=3, deps=bs)  # C: v3 = 4+4 = 8
    iv0 = np.zeros(mk.num_values, np.int32)
    iv0[0], iv0[1] = 1, 2
    iv, _, info = mk.run(b, ivalues=iv0)
    assert iv[2] == 3
    assert all(iv[4 + i] == 4 for i in range(5))
    assert iv[3] == 8
    assert info["executed"] == 7


def test_stall_detection_interpret():
    mk = make_fib_megakernel(64, interpret=True)
    b = TaskGraphBuilder()
    t = b.add(SUM, args=[0, 0], out=1)
    b._rows[t][1] = 1  # fake an unsatisfiable dependency
    with pytest.raises(RuntimeError, match="stalled"):
        mk.run(b)


def test_overflow_detection_interpret():
    # With row reclamation a table overflows only when the *live* set
    # exceeds capacity - fib's live set is its spawn-tree depth.
    with pytest.raises(RuntimeError, match="overflow"):
        device_fib(12, capacity=8, interpret=True)


def test_reclamation_runs_graphs_far_beyond_capacity_interpret():
    """fib(14) executes 1828 tasks through a 64-row table: descriptor rows
    recycle and value blocks are row-owned, so both bounds track the live
    set (~tree depth), not the 1828-task total."""
    v, info = device_fib(14, capacity=64, interpret=True)
    assert v == 377
    assert info["executed"] == 1828
    assert info["allocated"] <= 64


def test_fib_undersized_value_buffer_raises():
    # Row-owned blocks need num_values >= VBLOCK*capacity + host slots.
    with pytest.raises(ValueError, match="row-owned"):
        device_fib(14, capacity=64, interpret=True, num_values=16)


def _chain_kernel_free(ctx):
    base = ctx.alloc_values(2)
    ctx.set_value(base, ctx.arg(0))
    ctx.free_values(base)
    n = ctx.arg(0)

    @pl.when(n > 0)
    def _():
        ctx.spawn(0, [n - 1])


def _chain_kernel_leak(ctx):
    base = ctx.alloc_values(2)
    ctx.set_value(base, ctx.arg(0))
    n = ctx.arg(0)

    @pl.when(n > 0)
    def _():
        ctx.spawn(0, [n - 1])


def test_alloc_free_values_recycles_interpret():
    """200 chained alloc(2)/free rounds run through a 16-word value buffer
    (3 recyclable blocks - the bump base starts at value_alloc=1); the
    identical kernel without the free overflows on its 4th allocation."""
    from hclib_tpu.device.megakernel import Megakernel

    mk = Megakernel(kernels=[("chain", _chain_kernel_free)], capacity=16,
                    num_values=16, succ_capacity=8, interpret=True)
    b = TaskGraphBuilder()
    b.add(0, args=[200])
    _, _, info = mk.run(b)
    assert info["executed"] == 201 and not info["overflow"]

    mk2 = Megakernel(kernels=[("chain", _chain_kernel_leak)], capacity=16,
                     num_values=16, succ_capacity=8, interpret=True)
    b2 = TaskGraphBuilder()
    b2.add(0, args=[200])
    with pytest.raises(RuntimeError, match="overflow"):
        mk2.run(b2)


def _double_free_kernel(ctx):
    base = ctx.alloc_values(2)
    ctx.free_values(base)
    ctx.free_values(base)  # freeing twice walks the stack past its blocks


def test_double_free_sets_overflow_interpret():
    """More frees than blocks exist must clamp the vfree push inside the
    stack and surface C_OVERFLOW (ADVICE r1) instead of silently walking
    SMEM past the scratch window."""
    from hclib_tpu.device.megakernel import Megakernel

    mk = Megakernel(kernels=[("df", _double_free_kernel)], capacity=16,
                    num_values=8, succ_capacity=8, interpret=True)
    b = TaskGraphBuilder()
    # Three tasks: 6 frees against a 2-block stack - guaranteed to hit the
    # clamp regardless of how alloc/free interleave.
    for _ in range(3):
        b.add(0)
    with pytest.raises(RuntimeError, match="free_values|overflow"):
        mk.run(b)


@pytest.mark.skipif(jax.default_backend() != "tpu", reason="needs TPU")
def test_device_fib_tpu():
    v, info = device_fib(12, capacity=768, interpret=False)
    assert v == 144
    assert info["executed"] == 697
