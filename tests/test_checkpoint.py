"""Checkpoint/restore (ISSUE 5): preemption-tolerant snapshot + elastic
resume of the persistent megakernel.

Acceptance semantics under test: for a deterministic workload,
*checkpoint at round k then restore and run to completion* must be
bit-identical to the uninterrupted run (UTS dynamic tree, Cholesky with
the batched dispatch tier, wave-DAG SW with cross-round prefetch - all
under interpret mode); a checkpoint-disabled build must behave exactly as
before (DeviceFaultPlan discipline); corrupt or version-mismatched
bundles must be rejected with structured errors. Resident-mesh round
trips (same mesh and N -> M re-homing) need the Mosaic interpret mode and
ride the chaos marker like the other mesh tests.
"""

import os
import threading
import time

import numpy as np
import pytest

import hclib_tpu as hc
from hclib_tpu.device.descriptor import TaskGraphBuilder
from hclib_tpu.device.inject import StreamingMegakernel
from hclib_tpu.device.megakernel import Megakernel
from hclib_tpu.device.workloads import (
    UTS_NODE,
    device_uts_mk,
    make_uts_megakernel,
)
from hclib_tpu.jaxcompat import has_mosaic_interpret
from hclib_tpu.runtime import resilience
from hclib_tpu.runtime.checkpoint import (
    CheckpointBundle,
    CheckpointError,
    checkpoint_on_preempt,
    restore_megakernel,
    restore_resident,
    restore_stream,
    snapshot_megakernel,
    snapshot_resident,
    snapshot_stream,
)

needs_mosaic = pytest.mark.skipif(
    not has_mosaic_interpret(),
    reason="needs the Mosaic TPU interpret mode (pltpu.InterpretParams, "
           "jax >= 0.5): the ICI mesh kernels simulate remote DMA + "
           "semaphores on CPU",
)

UTS_KW = dict(max_depth=8, interpret=True)


def _uts_builder():
    b = TaskGraphBuilder()
    b.add(UTS_NODE, args=[1, 0])
    return b


@pytest.fixture
def uts_ckpt_mk():
    """A checkpoint-enabled UTS megakernel per round-trip test (the
    heaviest repeated build of the suite). Function-scoped since ISSUE
    18: every test gets a FRESH instance - no cross-test object
    aliasing - and the process-wide program cache
    (runtime/progcache.py) dedupes the content-identical compiles that
    session scope used to dedupe by object sharing. With the cache
    forced off each test simply pays its own build."""
    return make_uts_megakernel(checkpoint=True, **UTS_KW)


@pytest.fixture(scope="session")
def uts_ref():
    """(nodes, info) of the uninterrupted seeded traversal - the
    deterministic reference every round trip compares against, run
    once per session."""
    return device_uts_mk(**UTS_KW)


# ------------------------------------------------ megakernel round trips


def test_uts_checkpoint_then_restore_bit_identical(uts_ckpt_mk, uts_ref):
    """ACCEPTANCE (dynamic tree): quiesce the seeded UTS traversal at
    round k, resume from the exported state, and the final node count +
    executed totals are bit-identical to the uninterrupted run."""
    nodes, info_full = uts_ref
    assert nodes > 100  # the tree is a real traversal, not a stub
    mk = uts_ckpt_mk
    iv_q, _, info_q = mk.run(_uts_builder(), quiesce=nodes // 3)
    assert info_q["quiesced"] is True
    assert info_q["pending"] > 0  # genuinely mid-tree
    assert info_q["quiesce"]["executed_at"] >= nodes // 3
    iv_r, _, info_r = mk.resume(info_q["state"])
    assert int(iv_r[0]) == nodes
    assert info_r["executed"] == info_full["executed"] == nodes
    assert info_r["pending"] == 0


def test_checkpoint_chains_and_quiesce_past_end_is_clean(
    uts_ckpt_mk, uts_ref,
):
    """A resumed run can be quiesced AGAIN (chained checkpoints); a
    quiesce threshold past the workload size never fires and the run
    completes normally."""
    nodes, _ = uts_ref
    mk = uts_ckpt_mk
    _, _, q1 = mk.run(_uts_builder(), quiesce=nodes // 4)
    _, _, q2 = mk.resume(q1["state"], quiesce=nodes // 2)
    assert q2["quiesced"] and q2["pending"] > 0
    iv, _, done = mk.resume(q2["state"])
    assert int(iv[0]) == nodes and done["pending"] == 0
    # Threshold past the end: completes, not quiesced, no state attached.
    iv2, _, info2 = mk.run(_uts_builder(), quiesce=10 * nodes)
    assert int(iv2[0]) == nodes
    assert info2["quiesced"] is False and "state" not in info2


def test_checkpoint_off_path_bit_identical_and_guarded(
    uts_ckpt_mk, uts_ref,
):
    """DeviceFaultPlan discipline: a checkpoint-enabled build that never
    quiesces produces bit-identical outputs to a plain build, and a plain
    build refuses quiesce= with a clear error instead of silently
    ignoring it."""
    n0, info0 = uts_ref
    mk_on = uts_ckpt_mk
    iv_on, _, info_on = mk_on.run(_uts_builder())
    assert int(iv_on[0]) == n0
    assert info_on["executed"] == info0["executed"]
    assert info_on["quiesced"] is False
    mk_off = make_uts_megakernel(**UTS_KW)
    with pytest.raises(ValueError, match="checkpoint=True"):
        mk_off.run(_uts_builder(), quiesce=5)
    # quiesce=False is OFF (boolean plumbing), never "quiesce now" - on
    # both the plain and the checkpoint-enabled build.
    iv_f, _, info_f = mk_off.run(_uts_builder(), quiesce=False)
    assert int(iv_f[0]) == n0
    iv_f2, _, info_f2 = mk_on.run(_uts_builder(), quiesce=False)
    assert int(iv_f2[0]) == n0 and info_f2["quiesced"] is False


def test_cholesky_batch_tier_checkpoint_bit_identical(tmp_path):
    """ACCEPTANCE (static DAG + batched dispatch tier): quiesce the
    Cholesky factorization mid-graph - batch lanes spill to the ring at
    the quiesce boundary - restore THROUGH THE ON-DISK BUNDLE (the bf16
    split caches exercise the extension-dtype round trip), and L is
    bit-identical to the uninterrupted factor."""
    from hclib_tpu.device.cholesky import (
        _from_tiles,
        build_cholesky_graph,
        cholesky_buffers,
        make_cholesky_megakernel,
    )
    from hclib_tpu.models.cholesky import make_spd

    nt = 2
    a = make_spd(256).astype(np.float32)
    mk_full = make_cholesky_megakernel(nt, interpret=True)
    _, data_full, info_full = mk_full.run(
        build_cholesky_graph(nt), data=cholesky_buffers(a, nt)
    )
    L_full = np.asarray(data_full["tiles"])

    mk = make_cholesky_megakernel(nt, interpret=True, checkpoint=True)
    _, _, info_q = mk.run(
        build_cholesky_graph(nt), data=cholesky_buffers(a, nt), quiesce=2,
    )
    assert info_q["quiesced"] and info_q["pending"] > 0
    path = str(tmp_path / "chol-ckpt")
    snapshot_megakernel(mk, info_q).save(path)
    mk2 = make_cholesky_megakernel(nt, interpret=True, checkpoint=True)
    _, data_r, info_r = restore_megakernel(path, mk2)
    assert info_r["pending"] == 0
    assert info_r["executed"] == info_full["executed"]
    assert np.array_equal(np.asarray(data_r["tiles"]), L_full)
    assert np.array_equal(
        np.tril(_from_tiles(np.asarray(data_r["tiles"]), nt)),
        np.tril(_from_tiles(L_full, nt)),
    )


def test_sw_wave_prefetch_checkpoint_bit_identical():
    """ACCEPTANCE (batch tier + cross-round prefetch): quiesce the wave-
    DAG SW mid-sweep - the in-flight prefetch drains before lane spill
    (no DMA outlives the scheduler) - restore, and the full H matrix is
    bit-identical to the uninterrupted run."""
    from hclib_tpu.device.smithwaterman import (
        build_sw_wave_graph,
        make_sw_wave_megakernel,
        sw_wave_buffers,
    )
    from hclib_tpu.models.smithwaterman import random_seq

    a, b = random_seq(512, 5), random_seq(512, 6)

    def fresh_data():
        d = sw_wave_buffers(a, b)
        d["htiles"] = np.zeros((4, 4, 128, 128), np.int32)
        return d

    mk_full = make_sw_wave_megakernel(4, 4, interpret=True, chunk=1,
                                      width=2)
    iv_f, out_f, info_f = mk_full.run(
        build_sw_wave_graph(4, 4, chunk=1), data=fresh_data()
    )
    h_full = np.asarray(out_f["htiles"])

    mk = make_sw_wave_megakernel(4, 4, interpret=True, chunk=1, width=2,
                                 checkpoint=True)
    _, _, info_q = mk.run(
        build_sw_wave_graph(4, 4, chunk=1), data=fresh_data(), quiesce=6,
    )
    assert info_q["quiesced"] and info_q["pending"] > 0
    iv_r, out_r, info_r = mk.resume(info_q["state"])
    assert np.array_equal(np.asarray(out_r["htiles"]), h_full)
    assert int(iv_r[0]) == int(iv_f[0])  # best score
    assert info_r["executed"] == info_f["executed"]


# -------------------------------------------------------- bundle on disk


def test_bundle_save_load_restore_and_metrics(
    tmp_path, uts_ckpt_mk, uts_ref,
):
    """Versioned on-disk artifact: quiesce -> snapshot -> save (npz +
    manifest, sha256) -> load -> restore onto a FRESHLY built megakernel;
    checkpoint size/duration land in the MetricsRegistry."""
    nodes, _ = uts_ref
    mk = uts_ckpt_mk
    _, _, info_q = mk.run(_uts_builder(), quiesce=nodes // 2)
    bundle = snapshot_megakernel(mk, info_q)
    reg = hc.MetricsRegistry()
    path = str(tmp_path / "ckpt")
    stats = bundle.save(path, metrics=reg)
    assert stats["bundle_bytes"] > 0 and os.path.exists(
        os.path.join(path, "manifest.json")
    )
    snap = reg.snapshot()["metrics"]
    assert snap["checkpoint.bundle_bytes"] == stats["bundle_bytes"]
    assert "checkpoint.save_s" in snap
    # Restore on a fresh (same-code) kernel, straight from disk.
    mk2 = make_uts_megakernel(checkpoint=True, **UTS_KW)
    iv, _, info = restore_megakernel(path, mk2)
    assert int(iv[0]) == nodes and info["pending"] == 0


def test_bundle_corruption_and_version_rejected(
    tmp_path, uts_ckpt_mk, uts_ref,
):
    import json

    nodes, _ = uts_ref
    mk = uts_ckpt_mk
    _, _, info_q = mk.run(_uts_builder(), quiesce=nodes // 2)
    path = str(tmp_path / "ckpt")
    snapshot_megakernel(mk, info_q).save(path)
    npz = os.path.join(path, "state.npz")
    blob = open(npz, "rb").read()
    with open(npz, "wb") as f:  # flip bytes: sha256 must catch it
        f.write(blob[:-8] + b"\x00" * 8)
    with pytest.raises(CheckpointError, match="corrupt"):
        CheckpointBundle.load(path)
    with open(npz, "wb") as f:
        f.write(blob)
    man_path = os.path.join(path, "manifest.json")
    man = json.load(open(man_path))
    man["version"] = 99
    json.dump(man, open(man_path, "w"))
    with pytest.raises(CheckpointError, match="version 99"):
        CheckpointBundle.load(path)
    man["version"] = 1
    man["magic"] = "something-else"
    json.dump(man, open(man_path, "w"))
    with pytest.raises(CheckpointError, match="magic"):
        CheckpointBundle.load(path)


def test_restore_rejects_mismatched_program(uts_ckpt_mk, uts_ref):
    """A bundle only restores onto the SAME program shape: F_FN words
    index the kernel table positionally, so a different table must be
    refused, not silently misdispatched."""
    nodes, _ = uts_ref
    mk = uts_ckpt_mk
    _, _, info_q = mk.run(_uts_builder(), quiesce=nodes // 2)
    bundle = snapshot_megakernel(mk, info_q)
    other = Megakernel(
        kernels=[("bump", lambda ctx: ctx.set_value(0, ctx.value(0) + 1))],
        capacity=64, num_values=16, succ_capacity=8, interpret=True,
        checkpoint=True,
    )
    with pytest.raises(CheckpointError, match="kernel_names"):
        restore_megakernel(bundle, other)
    wrong_cap = make_uts_megakernel(checkpoint=True, capacity=512,
                                    **UTS_KW)
    with pytest.raises(CheckpointError, match="capacity"):
        restore_megakernel(bundle, wrong_cap)
    with pytest.raises(CheckpointError, match="megakernel"):
        restore_stream(bundle, StreamingMegakernel(mk))
    # And non-quiesced info has no exportable state.
    with pytest.raises(CheckpointError, match="no quiesced state"):
        snapshot_megakernel(mk, {"executed": 1})


# -------------------------------------------------------- streaming-inject


def _bump_mk(checkpoint=False):
    def bump(ctx):
        ctx.set_value(0, ctx.value(0) + ctx.arg(0))

    return Megakernel(
        kernels=[("bump", bump)], capacity=512, num_values=64,
        succ_capacity=8, interpret=True, checkpoint=checkpoint,
    )


def test_streaming_checkpoint_roundtrip(tmp_path):
    """Quiesce a live stream mid-drain, bundle it, restore on a FRESH
    stream object, inject more work there, and the grand total is exact -
    nothing lost at the cut (unconsumed ring rows ride the bundle)."""
    sm = StreamingMegakernel(_bump_mk(checkpoint=True), ring_capacity=512)
    b = TaskGraphBuilder()
    for i in range(10):
        b.add(0, args=[i + 1])
    for i in range(10, 40):
        sm.inject(0, args=[i + 1])
    sm.quiesce(after_executed=12)
    iv, info = sm.run_stream(b, quantum=4, deadline_s=120.0)
    assert info["quiesced"] and info["executed"] >= 12
    assert info["quiesce_latency_s"] is not None
    # The quiesced stream is closed: producers fail fast.
    with pytest.raises(RuntimeError, match="closed"):
        sm.inject(0, args=[99])
    path = str(tmp_path / "stream-ckpt")
    snapshot_stream(sm, info).save(path)
    sm2 = StreamingMegakernel(_bump_mk(checkpoint=True), ring_capacity=512)
    for i in range(40, 45):
        sm2.inject(0, args=[i + 1])
    sm2.close()
    iv2, info2 = restore_stream(
        CheckpointBundle.load(path), sm2, quantum=64, deadline_s=120.0,
    )
    assert int(iv2[0]) == 45 * 46 // 2
    assert info2["executed"] == 45


def test_streaming_same_object_resume_and_drained_cut():
    """Two review-hardened paths: (1) resuming on the SAME stream object
    clears the quiesce request and the quiesce-induced close, so the
    continued run drains instead of instantly re-quiescing (an explicit
    close() stays sticky across the resume - drain-and-exit works); (2) a
    quiesce threshold the workload never reaches cuts host-side once the
    stream drains (observed round -1) instead of spinning run_stream
    forever."""
    sm = StreamingMegakernel(_bump_mk(checkpoint=True), ring_capacity=256)
    b = TaskGraphBuilder()
    for i in range(30):
        b.add(0, args=[i + 1])
    sm.quiesce(after_executed=10)
    iv, info = sm.run_stream(b, quantum=4, deadline_s=120.0)
    assert info["quiesced"] and info["pending"] > 0
    # resume_state carries its own buffers: passing more is refused, not
    # silently ignored (parity with ResidentKernel.run's guard).
    with pytest.raises(ValueError, match="carries its own"):
        sm.run_stream(resume_state=info["state"],
                      ivalues=np.zeros(64, np.int32))
    sm.close()  # explicit: must survive the same-object resume
    iv2, info2 = sm.run_stream(resume_state=info["state"],
                               deadline_s=120.0)
    assert int(iv2[0]) == 30 * 31 // 2
    assert info2["pending"] == 0 and not info2.get("quiesced")

    sm3 = StreamingMegakernel(_bump_mk(checkpoint=True), ring_capacity=64)
    b3 = TaskGraphBuilder()
    b3.add(0, args=[5])
    sm3.quiesce(after_executed=1 << 30)  # unreachable threshold
    iv3, info3 = sm3.run_stream(b3, quantum=64, deadline_s=120.0)
    assert info3["quiesced"] is True
    assert info3["quiesce_observed_round"] == -1  # host-side drained cut
    assert info3["pending"] == 0 and int(iv3[0]) == 5


def test_preempt_hook_quiesces_running_stream():
    """The preemption path end to end: fire_preempt (what SIGTERM /
    HCLIB_TPU_PREEMPT / the watchdog checkpoint rung call) lands while
    the stream runs; the bound hook quiesces it, and run_stream returns a
    restorable snapshot instead of losing the graph."""
    resilience.reset_preempt()
    # Ring sized so the feeder cannot exhaust it before the preemption
    # lands even on a slow box (~0.1s / 5ms period ≈ 20 rows queued).
    sm = StreamingMegakernel(_bump_mk(checkpoint=True),
                             ring_capacity=2048)
    b = TaskGraphBuilder()
    b.add(0, args=[1])
    stop = threading.Event()

    def feeder():
        while not stop.is_set():
            try:
                sm.inject(0, args=[1])
            except RuntimeError:
                return  # quiesce closed the ring - expected
            time.sleep(0.005)

    def preempter():
        time.sleep(0.1)
        assert resilience.fire_preempt("test preemption") >= 1

    tf = threading.Thread(target=feeder)
    tp = threading.Thread(target=preempter)
    try:
        with checkpoint_on_preempt(sm):
            tf.start()
            tp.start()
            iv, info = sm.run_stream(b, quantum=16, deadline_s=120.0)
        assert info["quiesced"] is True
        assert "state" in info
        # Restorable: drain the snapshot to completion on a fresh stream.
        sm2 = StreamingMegakernel(_bump_mk(checkpoint=True),
                                  ring_capacity=2048)
        sm2.close()
        iv2, info2 = sm2.run_stream(
            resume_state=info["state"], deadline_s=120.0
        )
        assert info2["pending"] == 0
        assert int(iv2[0]) == info2["executed"]  # every bump(1) landed once
    finally:
        stop.set()
        tp.join()
        tf.join()
        resilience.reset_preempt()
    assert not resilience._preempt_hooks  # context manager unregistered


def test_preempt_env_replays_into_new_bindings(monkeypatch):
    """HCLIB_TPU_PREEMPT set before the stream starts (the wrapper-script
    spelling): register-then-replay quiesces it immediately, so even a
    notice that predates the run checkpoints instead of racing it."""
    resilience.reset_preempt()
    monkeypatch.setenv("HCLIB_TPU_PREEMPT", "1")
    sm = StreamingMegakernel(_bump_mk(checkpoint=True), ring_capacity=64)
    b = TaskGraphBuilder()
    b.add(0, args=[7])
    try:
        with checkpoint_on_preempt(sm):
            iv, info = sm.run_stream(b, quantum=16, deadline_s=120.0)
        assert info["quiesced"] is True
    finally:
        resilience.reset_preempt()


def test_install_preempt_handler_fires_hooks():
    """The SIGTERM handler wiring: install, raise the signal in-process,
    and the registered hook fires (on the handler's deferred daemon
    thread - signal frames must not take hook locks); uninstall restores
    the previous handler."""
    import signal

    resilience.reset_preempt()
    fired = threading.Event()
    hook = fired.set
    resilience.register_preempt_hook(hook)
    uninstall = resilience.install_preempt_handler()
    try:
        signal.raise_signal(signal.SIGTERM)
        assert resilience.preempt_requested()  # flag set in the frame
        assert fired.wait(10.0), "SIGTERM did not reach the preempt hooks"
    finally:
        uninstall()
        resilience.unregister_preempt_hook(hook)
        resilience.reset_preempt()


# ------------------------------------------------------- resident mesh


def _mesh_uts_rk(ndev, checkpoint=True, capacity=256):
    from hclib_tpu.device.resident import ResidentKernel
    from hclib_tpu.parallel.mesh import cpu_mesh

    mk = make_uts_megakernel(
        max_depth=6, interpret=True, capacity=capacity,
        checkpoint=checkpoint,
    )
    # homed=False: UTS rows are link-free (count-accumulate only), so
    # round-3 whole-row migration suffices - and it keeps the quiesced
    # state proxy-free, which is what makes N -> M re-homing legal.
    return ResidentKernel(
        mk, cpu_mesh(ndev, axis_name="q"), migratable_fns=[UTS_NODE],
        window=4, homed=False,
    )


def _mesh_uts_builders(ndev):
    builders = [TaskGraphBuilder() for _ in range(ndev)]
    for d in range(ndev):
        builders[d].add(UTS_NODE, args=[d + 1, 0])
    return builders


def test_resident_quiesce_validation_needs_no_mesh():
    """Host-side guards (no Mosaic needed): quiesce on a non-checkpoint
    build, malformed waits, and resume_state conflicts all refuse before
    any kernel builds. (Quiesce WITH pending waits is no longer refused -
    the wait table exports with the snapshot; see
    test_resident_quiesce_with_pending_waits_roundtrip.)"""
    rk = _mesh_uts_rk(2, checkpoint=False)
    with pytest.raises(ValueError, match="checkpoint=True"):
        rk.run(_mesh_uts_builders(2), quiesce=1)
    rk2 = _mesh_uts_rk(2, checkpoint=True)
    # Wait validation still applies (this kernel declares no channels).
    with pytest.raises(ValueError, match="bad channel id"):
        rk2.run(_mesh_uts_builders(2), quiesce=1, waits=[[(0, 1, 0)]])
    with pytest.raises(ValueError, match="exactly one"):
        rk2.run(_mesh_uts_builders(2), resume_state={})
    with pytest.raises(ValueError, match="exactly one"):
        rk2.run()
    # resume_state with mismatched wait-table / ring shapes refuses with
    # a diagnostic naming the device counts.
    with pytest.raises(ValueError, match="wait table covers"):
        rk2.run(resume_state={
            "tasks": np.zeros((2, 4, 16), np.int32),
            "succ": np.zeros((2, 8), np.int32),
            "ready": np.zeros((2, 4), np.int32),
            "counts": np.zeros((2, 8), np.int32),
            "ivalues": np.zeros((2, 16), np.int32),
            "waits": np.zeros((4, 65, 3), np.int32),
        })


def test_reshard_refuses_unsafe_rows():
    """N -> M re-homing moves only ready link-free rows (the PR 2
    dead-chip semantics): dependent rows, successor links, home-links,
    and dynamic out slots are refused with a diagnostic."""
    from hclib_tpu.device.descriptor import (
        DESC_WORDS, F_DEP, F_HOME, F_OUT, F_SUCC0, NO_TASK,
    )

    def fake_bundle(mutate):
        ndev, cap, V = 2, 8, 16
        tasks = np.zeros((ndev, cap, DESC_WORDS), np.int32)
        tasks[:, :, F_SUCC0] = NO_TASK
        tasks[:, :, 2:4] = NO_TASK
        tasks[:, :, F_HOME] = NO_TASK
        counts = np.zeros((ndev, 8), np.int32)
        counts[:, 1] = 1  # tail
        counts[:, 2] = 1  # alloc
        counts[:, 3] = 1  # pending
        counts[:, 4] = 2  # value_alloc
        ready = np.zeros((ndev, cap), np.int32)
        mutate(tasks)
        return CheckpointBundle(
            "resident", {"ndev": ndev},
            {
                "tasks": tasks, "succ": np.full((ndev, 8), -1, np.int32),
                "ready": ready, "counts": counts,
                "ivalues": np.zeros((ndev, V), np.int32),
            },
        )

    ok = fake_bundle(lambda t: None).reshard(1)
    assert int(ok.arrays["counts"][0][3]) == 2  # both rows re-homed

    def dep(t):
        t[0, 0, F_DEP] = 1

    with pytest.raises(CheckpointError, match="dependency counter"):
        fake_bundle(dep).reshard(1)

    def linked(t):
        t[0, 0, F_SUCC0] = 1

    with pytest.raises(CheckpointError, match="successor links"):
        fake_bundle(linked).reshard(1)

    def homed(t):
        t[0, 0, F_HOME] = 1

    with pytest.raises(CheckpointError, match="home-link"):
        fake_bundle(homed).reshard(1)

    def dyn_out(t):
        t[0, 0, F_OUT] = 5  # >= value_alloc 2

    with pytest.raises(CheckpointError, match="dynamic out slot"):
        fake_bundle(dyn_out).reshard(1)
    with pytest.raises(CheckpointError, match="power-of-two"):
        fake_bundle(lambda t: None).reshard(3)


def _fake_resident_bundle(ndev=2, cap=8, live_per_dev=1, extra=None):
    """Minimal clean-quiesce resident bundle for host-side reshard tests
    (live rows are ready + link-free)."""
    from hclib_tpu.device.descriptor import (
        DESC_WORDS, F_HOME, NO_TASK,
    )

    V = 16
    tasks = np.zeros((ndev, cap, DESC_WORDS), np.int32)
    tasks[:, :, 2:4] = NO_TASK  # F_SUCC0/F_SUCC1
    tasks[:, :, F_HOME] = NO_TASK
    counts = np.zeros((ndev, 8), np.int32)
    counts[:, 1] = live_per_dev  # tail
    counts[:, 2] = live_per_dev  # alloc
    counts[:, 3] = live_per_dev  # pending
    counts[:, 4] = 2  # value_alloc
    ready = np.zeros((ndev, cap), np.int32)
    arrays = {
        "tasks": tasks, "succ": np.full((ndev, 8), -1, np.int32),
        "ready": ready, "counts": counts,
        "ivalues": np.zeros((ndev, V), np.int32),
    }
    arrays.update(extra or {})
    return CheckpointBundle("resident", {"ndev": ndev}, arrays)


def test_reshard_m_edge_cases_diagnosed():
    """SATELLITE: M=1 and M>N re-home cleanly (totals conserved, empty
    new devices legal); illegal/overfull targets get diagnostics naming
    the fix, never shape errors."""
    b = _fake_resident_bundle(ndev=2, live_per_dev=2)
    one = b.reshard(1)  # M=1: everything folds onto the survivor
    assert int(one.arrays["counts"][0][3]) == 4
    big = _fake_resident_bundle(ndev=2, live_per_dev=2).reshard(8)
    assert big.arrays["tasks"].shape[0] == 8  # M > N: empty devices ok
    assert int(big.arrays["counts"][:, 3].sum()) == 4
    assert big.meta["resharded_from"] == 2
    with pytest.raises(CheckpointError, match="power-of-two"):
        _fake_resident_bundle().reshard(3)
    with pytest.raises(CheckpointError, match="power-of-two"):
        _fake_resident_bundle().reshard(0)
    with pytest.raises(CheckpointError, match="integer"):
        _fake_resident_bundle().reshard("two")
    # Overfull scale-in: the diagnostic names the minimum mesh size.
    with pytest.raises(CheckpointError, match="scale in less"):
        _fake_resident_bundle(ndev=2, cap=4, live_per_dev=3).reshard(1)


def test_reshard_rehomes_ring_residue_and_empty_waits():
    """SATELLITE (lifted limits, host half): inject-ring residue
    re-deals across mesh sizes with its count conserved, and an empty
    wait table rides along resized to the new roster (pending waits
    re-home too - the conservation matrix below)."""
    from hclib_tpu.device.inject import RING_ROW

    R = 8
    rr = np.zeros((2, R, RING_ROW), np.int32)
    ic = np.zeros((2, 8), np.int32)
    for d in range(2):
        for i in range(3):
            rr[d, i, 0] = 10 * d + i  # distinguishable payload
        ic[d, 0] = 3
        ic[d, 1] = 1
    wz = np.zeros((2, 5, 3), np.int32)
    b = _fake_resident_bundle(
        ndev=2, live_per_dev=1,
        extra={"ring_rows": rr, "ictl": ic, "waits": wz},
    )
    for m in (1, 4):
        out = b.reshard(m)
        assert int(out.arrays["ictl"][:, 0].sum()) == 6  # residue conserved
        assert out.arrays["ring_rows"].shape[:2] == (m, R)
        assert out.arrays["waits"].shape == (m, 5, 3)
        assert (out.arrays["ictl"][:, 1] == 1).all()  # close flag survives
        # Every payload survives exactly once.
        vals = sorted(
            int(out.arrays["ring_rows"][d, i, 0])
            for d in range(m)
            for i in range(int(out.arrays["ictl"][d, 0]))
        )
        assert vals == [0, 1, 2, 10, 11, 12], vals
    # Ring overflow on aggressive scale-in diagnoses, not IndexErrors.
    ic_full = ic.copy()
    ic_full[:, 0] = R
    bf = _fake_resident_bundle(
        ndev=2, live_per_dev=1,
        extra={"ring_rows": rr, "ictl": ic_full, "waits": wz},
    )
    with pytest.raises(CheckpointError, match="ring"):
        bf.reshard(1)


def test_bundle_diff():
    """SATELLITE: the structural diff the bit-identity storms use -
    equal bundles report equal; value, shape, and key differences are
    named with counts."""
    a = _fake_resident_bundle(ndev=2, live_per_dev=2)
    b = _fake_resident_bundle(ndev=2, live_per_dev=2)
    assert a.diff(b)["equal"] is True
    b.arrays["ivalues"] = b.arrays["ivalues"].copy()
    b.arrays["ivalues"][0, 0] = 7
    d = a.diff(b)
    assert d["equal"] is False
    assert d["mismatched"]["ivalues"]["n"] == 1
    assert d["mismatched"]["ivalues"]["max_abs"] == 7.0
    c = _fake_resident_bundle(ndev=4, live_per_dev=2)
    d2 = a.diff(c)
    assert not d2["equal"] and "shape" in d2["mismatched"]["tasks"]
    e = _fake_resident_bundle(
        ndev=2, live_per_dev=2,
        extra={"waits": np.zeros((2, 5, 3), np.int32)},
    )
    d3 = a.diff(e)
    assert d3["only_other"] == ["waits"] and not d3["equal"]


@needs_mosaic
@pytest.mark.chaos
def test_resident_quiesce_with_pending_waits_roundtrip():
    """ACCEPTANCE (lifted limit #1): a resident mesh with PENDING
    host-declared waits quiesces - the live wait table exports through
    the aliased output (needs rebased) - and the resumed run re-arms the
    parked rows exactly: the late put still wakes its consumer, results
    match the uninterrupted run."""
    import jax

    from hclib_tpu.device.resident import ResidentKernel
    from hclib_tpu.parallel.mesh import cpu_mesh

    ROWS, COLS = 8, 128
    BUMP, PUT, CONSUME = 0, 1, 2

    def make_rk():
        def bump(ctx):
            ctx.set_value(0, ctx.value(0) + ctx.arg(0))

        def put(ctx):
            ctx.pgas.put(ctx.arg(0), 0, ctx.arg(1), ctx.arg(2))

        def consume(ctx):
            ctx.set_value(ctx.arg(0), ctx.pgas.count(0))

        mk = Megakernel(
            kernels=[("bump", bump), ("put", put), ("consume", consume)],
            data_specs={
                "heap": jax.ShapeDtypeStruct((ROWS, COLS), np.int32)
            },
            capacity=128, num_values=64, succ_capacity=64,
            interpret=True, checkpoint=True,
        )
        return ResidentKernel(
            mk, cpu_mesh(2, axis_name="q"),
            channels={"c0": ("heap", 1)}, window=4,
        )

    def heap():
        h = np.zeros((2, ROWS, COLS), np.int32)
        for d in range(2):
            for r in range(ROWS):
                h[d, r, :] = 1000 * d + r
        return h

    def build():
        builders = [TaskGraphBuilder(), TaskGraphBuilder()]
        # The put hides behind a serial bump chain, so an early quiesce
        # cuts BEFORE it runs and the wait is still parked.
        prev = builders[0].add(BUMP, args=[1])
        for i in range(20):
            prev = builders[0].add(BUMP, args=[i + 2], deps=[prev])
        builders[0].add(PUT, args=[1, 3, 2], deps=[prev])
        t = builders[1].add(CONSUME, args=[1])
        return builders, [[], [(0, 1, t)]]

    builders, waits = build()
    iv_f, data_f, info_f = make_rk().run(
        builders, data={"heap": heap()}, waits=waits, quantum=2,
        max_rounds=4096,
    )
    assert int(np.asarray(iv_f)[1, 1]) == 1  # consumer saw the arrival

    builders, waits = build()
    rk = make_rk()
    iv_q, _, info_q = rk.run(
        builders, data={"heap": heap()}, waits=waits, quantum=2,
        max_rounds=4096, quiesce=2,
    )
    assert info_q["quiesced"] is True
    assert info_q["pending"] > 0
    w = np.asarray(info_q["state"]["waits"])
    assert int(w[1, 0, 0]) == 1, w[1]  # the wait is STILL parked
    assert int(w[1, 1, 1]) >= 1  # rebased need is still positive
    iv_r, data_r, info_r = rk.run(
        resume_state=info_q["state"], quantum=2, max_rounds=4096,
    )
    assert info_r["pending"] == 0
    assert info_r["executed"] == info_f["executed"]
    assert int(np.asarray(iv_r)[1, 1]) == 1  # re-armed wait fired
    assert np.array_equal(
        np.asarray(data_r["heap"]), np.asarray(data_f["heap"])
    )


@needs_mosaic
@pytest.mark.chaos
def test_resident_inject_cursor_survives_reshard():
    """ACCEPTANCE (lifted limit #2): a mid-stream quiesce keeps
    published-but-unconsumed inject rows as ring residue with the
    consumed cursor; the bundle reshards 4 -> 2 (residue re-dealt,
    conserved) and the resumed smaller mesh drains everything exactly."""
    from hclib_tpu.device.resident import ResidentKernel
    from hclib_tpu.parallel.mesh import cpu_mesh

    BUMP = 0

    def make_rk(ndev):
        def bump(ctx):
            ctx.set_value(0, ctx.value(0) + ctx.arg(0))

        mk = Megakernel(
            kernels=[("bump", bump)], capacity=256, num_values=1024,
            succ_capacity=8, interpret=True, checkpoint=True,
        )
        return ResidentKernel(
            mk, cpu_mesh(ndev, axis_name="q"), migratable_fns=[BUMP],
            window=4, homed=False, inject=True,
        )

    ndev = 4
    builders = [TaskGraphBuilder() for _ in range(ndev)]
    v = 0
    for d in range(ndev):
        for _ in range(2):
            v += 1
            builders[d].add(BUMP, args=[v])
    inject_rows = []
    for d in range(ndev):
        rows = []
        for _ in range(6):
            v += 1
            rows.append((BUMP, [v]))
        inject_rows.append(rows)
    want = v * (v + 1) // 2

    rk = make_rk(ndev)
    # quiesce=True: threshold round 0 - the poll never consumes, so ALL
    # inject rows are residue and the cut is maximally mid-stream.
    _, _, info_q = rk.run(
        builders, inject_rows=inject_rows, quantum=4, max_rounds=4096,
        quiesce=True,
    )
    assert info_q["quiesced"] is True
    st = info_q["state"]
    assert int(np.asarray(st["ictl"])[:, 0].sum()) == 4 * 6  # residue
    bundle = snapshot_resident(rk, info_q)
    small = bundle.reshard(2)
    assert int(np.asarray(small.arrays["ictl"])[:, 0].sum()) == 24
    rk2 = make_rk(2)
    iv, _, info = rk2.run(
        resume_state=small.state(), quantum=8, max_rounds=1 << 14,
    )
    assert info["pending"] == 0
    assert int(np.asarray(iv)[:, 0].sum()) == want
    assert info["executed"] == v
    # Partial consumption: a later cut consumes some rounds' rows first;
    # the cursor still reconciles (consumed + residue == published).
    rk3 = make_rk(ndev)
    _, _, info_q3 = rk3.run(
        builders, inject_rows=inject_rows, quantum=4, max_rounds=4096,
        quiesce=2,
    )
    if info_q3["quiesced"]:
        ic = np.asarray(info_q3["inject_ctl"])
        residue = int(np.asarray(info_q3["state"]["ictl"])[:, 0].sum())
        assert int(ic[:, 2].sum()) + residue == int(ic[:, 0].sum())
        iv3, _, info3 = rk3.run(
            resume_state=info_q3["state"], quantum=8,
            max_rounds=1 << 14,
        )
        assert int(np.asarray(iv3)[:, 0].sum()) == want


@needs_mosaic
@pytest.mark.chaos
def test_resident_mesh_checkpoint_roundtrip_same_mesh():
    """ACCEPTANCE: quiesce a 4-device resident mesh mid-traversal (the
    fold observes the word, sched stops popping, the wire drains, the
    mesh exits in lockstep), resume on the same mesh size, and the totals
    equal the uninterrupted run exactly."""
    ndev = 4
    rk_full = _mesh_uts_rk(ndev)
    iv_f, _, info_f = rk_full.run(
        _mesh_uts_builders(ndev), quantum=8, max_rounds=4096
    )
    total = int(np.asarray(iv_f)[:, 0].sum())
    assert info_f["pending"] == 0 and total == info_f["executed"]

    rk = _mesh_uts_rk(ndev)
    iv_q, _, info_q = rk.run(
        _mesh_uts_builders(ndev), quantum=8, max_rounds=4096, quiesce=2,
    )
    assert info_q["quiesced"] is True
    assert info_q["pending"] > 0
    fs = info_q["fault_stats"]
    assert all(f["quiesce_round"] >= 2 for f in fs)  # threshold honored
    iv_r, _, info_r = rk.run(
        resume_state=info_q["state"], quantum=8, max_rounds=4096
    )
    assert info_r["pending"] == 0
    assert info_r["executed"] == info_f["executed"]
    assert int(np.asarray(iv_r)[:, 0].sum()) == total


@needs_mosaic
@pytest.mark.chaos
def test_resident_mesh_restore_onto_smaller_and_larger_mesh(tmp_path):
    """ACCEPTANCE (elastic resume): a 4-chip checkpoint restores onto 2
    chips (and a 2-chip one onto 4) - per-chip queues re-homed host-side
    with the dead-chip conservation semantics, the full workload drains,
    totals conserved exactly."""
    ndev = 4
    rk_full = _mesh_uts_rk(ndev)
    iv_f, _, info_f = rk_full.run(
        _mesh_uts_builders(ndev), quantum=8, max_rounds=4096
    )
    total = int(np.asarray(iv_f)[:, 0].sum())

    rk = _mesh_uts_rk(ndev)
    _, _, info_q = rk.run(
        _mesh_uts_builders(ndev), quantum=8, max_rounds=4096, quiesce=2,
    )
    bundle = snapshot_resident(rk, info_q)
    path = str(tmp_path / "mesh-ckpt")
    bundle.save(path)

    # 4 -> 2: restore_resident reshards automatically off the manifest.
    rk_small = _mesh_uts_rk(2)
    iv_s, _, info_s = restore_resident(
        CheckpointBundle.load(path), rk_small, quantum=8,
        max_rounds=4096,
    )
    assert info_s["pending"] == 0
    assert info_s["executed"] == info_f["executed"]
    assert int(np.asarray(iv_s)[:, 0].sum()) == total

    # 2 -> 4: checkpoint the 2-chip run, grow back to 4.
    rk2 = _mesh_uts_rk(2)
    _, _, info_q2 = rk2.run(
        _mesh_uts_builders(2), quantum=8, max_rounds=4096, quiesce=2,
    )
    if info_q2["pending"] > 0:
        rk_big = _mesh_uts_rk(4)
        iv_b, _, info_b = restore_resident(
            snapshot_resident(rk2, info_q2), rk_big, quantum=8,
            max_rounds=4096,
        )
        assert info_b["pending"] == 0
        # 2-chip seeds 1,2 are a subset of the 4-chip run's totals: check
        # against the 2-chip uninterrupted run instead.
        rk2_full = _mesh_uts_rk(2)
        iv2_f, _, info2_f = rk2_full.run(
            _mesh_uts_builders(2), quantum=8, max_rounds=4096
        )
        assert info_b["executed"] == info2_f["executed"]
        assert (
            int(np.asarray(iv_b)[:, 0].sum())
            == int(np.asarray(iv2_f)[:, 0].sum())
        )


# ------------------------------------------------- durable store (ISSUE 17)


from hclib_tpu.runtime.checkpoint import (  # noqa: E402
    BundleFault,
    BundleStore,
    default_store,
)


def _waits_bundle(ndev=4, cap=8, live=1, parked=(), channels=("left",
                  "right"), host_residue=None, max_waits=4, seed=0):
    """Clean-quiesce resident bundle with wait-parked rows: each
    ``parked`` triple (device, channel, need) parks one row carrying
    exactly one dep bump, with its wait entry in the exported table."""
    from hclib_tpu.device.descriptor import (
        DESC_WORDS, F_DEP, F_FN, F_HOME, NO_TASK,
    )

    tasks = np.zeros((ndev, cap, DESC_WORDS), np.int32)
    tasks[:, :, 2:4] = NO_TASK
    tasks[:, :, F_HOME] = NO_TASK
    ready = np.full((ndev, cap), NO_TASK, np.int32)
    counts = np.zeros((ndev, 8), np.int32)
    waits = np.zeros((ndev, max_waits + 1, 3), np.int32)
    for d in range(ndev):
        for i in range(live):
            tasks[d, i, F_FN] = 1
            ready[d, i] = i
        npk = 0
        for (pd, ch, need) in parked:
            if pd != d:
                continue
            slot = live + npk
            tasks[d, slot, F_FN] = 2
            tasks[d, slot, F_DEP] = 1
            w = int(waits[d, 0, 0])
            waits[d, 1 + w] = (ch, need, slot)
            waits[d, 0, 0] = w + 1
            npk += 1
        counts[d, 1] = live
        counts[d, 2] = live + npk  # alloc
        counts[d, 3] = live + npk  # pending
        counts[d, 4] = 2  # value_alloc
    rng = np.random.default_rng(seed)
    meta = {"ndev": ndev, "channels": list(channels)}
    if host_residue:
        meta["host_residue"] = dict(host_residue)
    return CheckpointBundle("resident", meta, {
        "tasks": tasks,
        "succ": np.full((ndev, 8), -1, np.int32),
        "ready": ready, "counts": counts,
        "ivalues": rng.integers(0, 1 << 20, (ndev, 16)).astype(np.int32),
        "waits": waits,
    })


def _need_sums(waits):
    acc = {}
    w = np.asarray(waits)
    for d in range(w.shape[0]):
        for i in range(int(w[d, 0, 0])):
            ch, need, _row = (int(x) for x in w[d, 1 + i])
            acc[ch] = acc.get(ch, 0) + need
    return acc


def test_reshard_waits_conservation_matrix():
    """TENTPOLE: exported wait tables RE-HOME across mesh sizes - the
    4 -> 2 and 2 -> 4 matrix conserves wait counts, per-channel need
    sums, and the pending total; parked rows land allocated but NOT in
    the ready ring, keeping exactly one dep bump per parked wait."""
    from hclib_tpu.device.descriptor import F_DEP

    parked = [(0, 0, 3), (1, 1, 2), (2, 0, 1), (3, 1, 4)]
    b = _waits_bundle(ndev=4, parked=parked)
    want_needs = _need_sums(b.arrays["waits"])
    want_pend = int(b.arrays["counts"][:, 3].sum())
    for m in (2, 4, 1, 8):
        out = b.reshard(m) if m != 4 else b.reshard(2).reshard(4)
        w = np.asarray(out.arrays["waits"])
        assert w.shape[0] == m
        assert int(w[:, 0, 0].sum()) == len(parked)
        assert _need_sums(w) == want_needs
        assert int(out.arrays["counts"][:, 3].sum()) == want_pend
        for d in range(m):
            tail = int(out.arrays["counts"][d, 1])
            alloc = int(out.arrays["counts"][d, 2])
            for i in range(int(w[d, 0, 0])):
                _ch, _need, row = (int(x) for x in w[d, 1 + i])
                # The wait entry targets a real parked row on ITS device:
                # allocated past the ready ring, dep bump preserved.
                assert tail <= row < alloc, (d, row, tail, alloc)
                assert int(out.arrays["tasks"][d, row, F_DEP]) == 1


def test_reshard_refuses_satisfier_in_residue():
    """TENTPOLE: the narrowed refusal - waits whose satisfier sits in
    unexported host residue (meta['host_residue']) refuse with ONE
    whole-program diagnostic naming every stranded channel; residue on
    channels nobody waits on does not refuse."""
    b = _waits_bundle(
        ndev=4, parked=[(0, 0, 3), (1, 0, 1), (2, 1, 2)],
        host_residue={"left": 2, "right": 1},
    )
    with pytest.raises(CheckpointError) as ei:
        b.reshard(2)
    msg = str(ei.value)
    assert "host residue" in msg
    assert "'left'" in msg and "'right'" in msg  # every stranded channel
    assert "3 pending wait(s) on 2 channel(s)" in msg
    # Residue on an un-waited channel is harmless: the waits re-home.
    ok = _waits_bundle(
        ndev=4, parked=[(0, 0, 3)], host_residue={"right": 5},
    ).reshard(2)
    assert int(np.asarray(ok.arrays["waits"])[:, 0, 0].sum()) == 1


def test_reshard_diagnoses_wait_dep_mismatch():
    """A declared wait whose parked row does NOT carry the matching dep
    bump is a violation named per-row (the export contract), not a
    silent re-home."""
    from hclib_tpu.device.descriptor import F_DEP

    b = _waits_bundle(ndev=2, parked=[(0, 0, 2)])
    b.arrays["tasks"][0, 1, F_DEP] = 0  # strip the bump
    with pytest.raises(CheckpointError,
                       match="dependency counter 0 != its 1"):
        b.reshard(1)


def test_bundle_store_publish_retention_and_reload(tmp_path):
    """Generational publish: gen-N dirs + CURRENT pointer, bounded
    retention (keep=K prunes oldest), load_latest bit-identical to the
    newest save, provenance stamped on the loaded bundle."""
    root = str(tmp_path / "store")
    store = BundleStore(root, keep=2, fsync=False)
    bundles = [_waits_bundle(seed=i) for i in range(4)]
    gens = [store.save(b) for b in bundles]
    assert gens == [1, 2, 3, 4]
    assert store.generations() == [3, 4]  # keep=2 pruned 1, 2
    assert open(os.path.join(root, "CURRENT")).read().strip() == "4"
    got = BundleStore(root, fsync=False).load_latest()
    assert got.diff(bundles[-1])["equal"]
    assert got.generation == 4
    assert got.source_path == store.path_of(4)
    with pytest.raises(CheckpointError, match="keep"):
        BundleStore(root, keep=0)


def test_bundle_store_self_heals_and_quarantines(tmp_path):
    """Self-healing restore: a corrupted newest generation is moved to
    quarantine/ with a typed BundleFault, load_latest falls back to the
    newest VALID generation bit-identically, and the fallback/quarantine
    counters + TR_CKPT records fire."""
    from hclib_tpu.device import tracebuf as tb

    root = str(tmp_path / "store")
    reg = hc.MetricsRegistry()
    store = BundleStore(root, keep=3, fsync=False, metrics=reg)
    good = _waits_bundle(seed=1)
    store.save(good)
    store.save(_waits_bundle(seed=2))
    npz = os.path.join(store.path_of(2), "state.npz")
    blob = open(npz, "rb").read()
    with open(npz, "wb") as f:
        f.write(blob[:-4] + b"\xff" * 4)
    healer = BundleStore(root, keep=3, fsync=False, metrics=reg)
    back = healer.load_latest()
    assert back.generation == 1 and back.diff(good)["equal"]
    assert [isinstance(f, BundleFault) for f in healer.faults] == [True]
    f = healer.faults[0]
    assert (f.generation, f.reason) == (2, "corrupt")
    assert "quarantine" in f.path and os.path.isdir(f.path)
    assert healer.generations() == [1]  # the damaged one moved aside
    m = reg.snapshot()["metrics"]
    assert m["checkpoint.quarantined.count"] == 1
    assert m["checkpoint.fallback.count"] == 1
    assert m["checkpoint.load.count"] == 1
    assert m["checkpoint.save.count"] == 2
    # Every host record decodes through the CK_* name table.
    codes = [-int(r[2]) - 1 for r in healer.events]
    assert codes == [tb.CK_QUARANTINE, tb.CK_FALLBACK, tb.CK_LOAD]
    assert all(c in tb.CK_NAMES for c in codes)
    info = healer.trace_info()
    assert info["rings"][0]["written"] == 3


def test_bundle_store_unrecoverable_raises_with_every_fault(tmp_path):
    """No valid generation -> CheckpointError naming EVERY fault and
    the poison handoff (the degradation-ladder contract), never a hang
    or a partial restore."""
    root = str(tmp_path / "store")
    store = BundleStore(root, keep=3, fsync=False)
    store.save(_waits_bundle(seed=1))
    store.save(_waits_bundle(seed=2))
    for g in store.generations():
        os.remove(os.path.join(store.path_of(g), "manifest.json"))
    healer = BundleStore(root, fsync=False)
    with pytest.raises(CheckpointError) as ei:
        healer.load_latest()
    msg = str(ei.value)
    assert "unrecoverable" in msg and "poison" in msg
    assert "gen 1" in msg and "gen 2" in msg
    assert all(f.reason == "torn" for f in healer.faults)
    # An empty store raises too (cold start is explicit, not a wedge).
    with pytest.raises(CheckpointError, match="no generations"):
        BundleStore(str(tmp_path / "empty"), fsync=False).load_latest()


def test_bundle_store_crash_sites_leave_staging_invisible(tmp_path):
    """FaultPlan preempt-mid-save dies BEFORE the rename: the store is
    unchanged and the staged dir invisible; preempt-mid-restore retries
    idempotently (quarantine moves are re-entrant)."""
    from hclib_tpu.runtime.resilience import FaultPlan, InjectedFault

    root = str(tmp_path / "store")
    good = _waits_bundle(seed=3)
    BundleStore(root, fsync=False).save(good)
    plan = FaultPlan(seed=0, preempt_save_at=0)
    writer = BundleStore(root, fsync=False, fault_plan=plan)
    with pytest.raises(InjectedFault, match="mid-save"):
        writer.save(_waits_bundle(seed=4))
    after = BundleStore(root, fsync=False)
    assert after.generations() == [1]
    assert after.load_latest().diff(good)["equal"]
    # A later clean save reuses the staging slot and publishes.
    assert BundleStore(root, fsync=False).save(_waits_bundle(seed=5)) == 2
    plan = FaultPlan(seed=0, preempt_restore_at=0)
    reader = BundleStore(root, fsync=False, fault_plan=plan)
    with pytest.raises(InjectedFault, match="mid-restore"):
        reader.load_latest()
    assert reader.load_latest().generation == 2  # the retry succeeds


def test_bundle_store_env_knobs(tmp_path, monkeypatch):
    """SATELLITE: HCLIB_TPU_CKPT_DIR roots default_store();
    HCLIB_TPU_CKPT_KEEP sets retention (malformed text raises, naming
    the variable); HCLIB_TPU_CKPT_FSYNC=0 selects the fast mode."""
    monkeypatch.delenv("HCLIB_TPU_CKPT_DIR", raising=False)
    assert default_store() is None
    root = str(tmp_path / "envstore")
    monkeypatch.setenv("HCLIB_TPU_CKPT_DIR", root)
    monkeypatch.setenv("HCLIB_TPU_CKPT_KEEP", "2")
    monkeypatch.setenv("HCLIB_TPU_CKPT_FSYNC", "0")
    store = default_store()
    assert store is not None and store.root == root
    assert store.keep == 2 and store.fsync is False
    for i in range(3):
        store.save(_waits_bundle(seed=i))
    assert store.generations() == [2, 3]
    monkeypatch.setenv("HCLIB_TPU_CKPT_KEEP", "junk")
    with pytest.raises(ValueError, match="HCLIB_TPU_CKPT_KEEP"):
        default_store()


def test_bundle_load_errors_name_path_and_generation(tmp_path):
    """SATELLITE: version/corruption errors name the offending FILE and
    store generation; a kernel-table mismatch carries the positional
    diff AND the bundle's provenance."""
    import json
    import types

    root = str(tmp_path / "store")
    store = BundleStore(root, fsync=False)
    b = _waits_bundle(seed=1)
    b.meta.update({"kernel_names": ["seed", "waiter"], "capacity": 8,
                   "num_values": 16, "succ_capacity": 8,
                   "data_specs": {}})
    store.save(b)
    man_path = os.path.join(store.path_of(1), "manifest.json")
    man = json.load(open(man_path))
    man["version"] = 9
    json.dump(man, open(man_path, "w"))
    with pytest.raises(CheckpointError) as ei:
        CheckpointBundle.load(store.path_of(1), generation=1)
    assert man_path in str(ei.value) and "(generation 1)" in str(ei.value)
    man["version"] = 1
    json.dump(man, open(man_path, "w"))
    loaded = CheckpointBundle.load(store.path_of(1), generation=1)
    mk = types.SimpleNamespace(
        kernel_names=["waiter", "seed"], capacity=8, num_values=16,
        succ_capacity=8, data_specs={},
    )
    from hclib_tpu.runtime.checkpoint import _check_kernel_meta, _where

    with pytest.raises(CheckpointError) as ei:
        _check_kernel_meta(mk, loaded.meta, where=_where(loaded))
    msg = str(ei.value)
    assert "[0] 'waiter' != 'seed' in the bundle" in msg.replace(
        "'waiter' here", "'waiter'"
    )
    assert "generation 1" in msg and store.path_of(1) in msg


def test_bundle_store_model_certifies_publish_ordering():
    """SATELLITE: the BundleStoreModel explores save x crash x
    concurrent-load clean under the shipped rename-LAST ordering, and
    catches the planted publish-before-manifest bug with a concrete
    witness."""
    from hclib_tpu.analysis.explore import BundleStoreModel, explore

    ok = explore(BundleStoreModel(saves=2, crash=True, max_reads=2),
                 depth=64, budget_s=20)
    assert ok.complete and ok.clean, ok.violations
    bad = explore(
        BundleStoreModel(saves=2, crash=True, max_reads=2,
                         publish_before_manifest=True),
        depth=64, budget_s=20,
    )
    assert not bad.clean
    assert any("partial generation" in v.message for v in bad.violations)
    assert all(v.witness for v in bad.violations)


def test_autoscaler_resume_from_store_root(tmp_path):
    """SATELLITE: Autoscaler.run(resume_bundle=<store root>) walks the
    generational store with the self-healing load_latest - and an
    unrecoverable root raises the poison diagnostic instead of
    wedging."""
    from hclib_tpu.runtime.autoscaler import Autoscaler

    root = str(tmp_path / "store")
    BundleStore(root, fsync=False).save(_waits_bundle(seed=7))
    scaler = Autoscaler(lambda ndev: None, checkpoint_dir=root)
    # The store root resolves through load_latest; the resolved bundle
    # then fails the resident-kind gate only if damaged - here it
    # reaches kernel construction (our stub factory returns None).
    with pytest.raises(AttributeError):
        scaler.run(resume_bundle=root)
    for g in BundleStore(root, fsync=False).generations():
        os.remove(os.path.join(root, f"gen-{g:06d}", "manifest.json"))
    with pytest.raises(CheckpointError, match="unrecoverable"):
        scaler.run(resume_bundle=root)


# --------------------------------------- dyngraph bundles (ISSUE 20)


def _dyngraph_fixture(applied, *, serve_query=True, residue=True):
    """A synthetic ``ndev=4`` dyngraph bundle: each device has applied
    the uids in ``applied[d]`` (in that order - the host mirror of the
    device splice arithmetic), labels show divergent partial progress,
    and the scheduler holds residue rows (each device's UNapplied
    updates, a dynamic EXPAND, one pending QUERY). Returns
    ``(bundle, graph, ups, iv, counts)``."""
    from hclib_tpu.device.descriptor import (
        DESC_WORDS, F_A0, F_FN, F_OUT, NO_TASK,
    )
    from hclib_tpu.device.dyngraph import (
        DG_QUERY, DG_UPDATE, DynGraph, V_FREE, V_QUERIES, V_UPDATES,
        _bind_updates, make_dyngraph_megakernel,
    )
    from hclib_tpu.device.frontier import (
        EBLOCK, INF, V_EDGES, V_RELAX, VT_BASE,
    )
    from hclib_tpu.device.megakernel import (
        C_ALLOC, C_EXECUTED, C_PENDING, C_VALLOC,
    )

    rng = np.random.default_rng(0)
    n, m = 12, 40
    g = DynGraph(n, rng.integers(0, n, m), rng.integers(0, n, m),
                 rng.integers(1, 8, m), spare_blocks=2, upd_cap=8)
    ups = [(1, 5, 3), (2, 7, 1), (1, 9, 2), (4, 3, 6)]
    for u, v, w in ups:
        g.add_update(u, v, w)
    mk = make_dyngraph_megakernel("sssp", g, width=0, interpret=True)
    _bind_updates(mk, g)

    ndev, cap, V = 4, 32, mk.num_values
    sb, spare, bcs = g.spare_base, g.spare, g.blk_count.astype(np.int64)
    flag_base, st = g.flag_base, g.st_base
    iv = np.zeros((ndev, V), np.int64)
    ind = np.zeros((ndev,) + g.indices.shape, np.int32)
    wgt = np.zeros((ndev,) + g.weights.shape, np.int32)
    for d in range(ndev):
        iv[d] = g.preset_values(V, INF)
        ind[d] = g.indices
        wgt[d] = g.weights

    def apply_on(d, uid):
        u, v, w = ups[uid]
        vt = iv[d, VT_BASE:VT_BASE + 3 * n].reshape(n, 3)
        deg, bc = int(vt[u, 2]), int(vt[u, 1])
        if deg == bc * EBLOCK:
            r = sb + u * spare + (bc - int(bcs[u]))
            ind[d, r, :] = -1
            wgt[d, r, :] = 0
            ind[d, r, 0] = v
            wgt[d, r, 0] = w
            vt[u, 1] = bc + 1
            iv[d, V_FREE] += 1
        else:
            blk = deg // EBLOCK
            r = (int(vt[u, 0]) + blk if blk < int(bcs[u])
                 else sb + u * spare + (blk - int(bcs[u])))
            ind[d, r, deg % EBLOCK] = v
            wgt[d, r, deg % EBLOCK] = w
        vt[u, 2] = deg + 1
        iv[d, flag_base + uid] = 1
        iv[d, V_UPDATES] += 1

    for d, uids in applied.items():
        for uid in uids:
            apply_on(d, uid)
    for d in range(ndev):
        iv[d, st] = 0
        for vtx in range(1, n):
            iv[d, st + vtx] = INF if (vtx + d) % 3 else 10 + vtx + d
        iv[d, V_EDGES] = 5 + d
        iv[d, V_RELAX] = 2 + d
    if serve_query:  # one served query on device 1, out slot st + n
        iv[1, V_QUERIES] = 1
        iv[1, st + n] = 13

    tasks = np.zeros((ndev, cap, DESC_WORDS), np.int32)
    counts = np.zeros((ndev, 8), np.int32)
    ready = np.full((ndev, cap), NO_TASK, np.int32)
    succ = np.full((ndev, 16), NO_TASK, np.int32)
    for d in range(ndev):
        rows = []
        for uid in range(len(ups)):
            if uid not in applied[d]:
                u, v, w = ups[uid]
                r = np.zeros(DESC_WORDS, np.int32)
                r[F_FN] = DG_UPDATE
                r[F_A0:F_A0 + 4] = (u, v, w, uid)
                r[2] = r[3] = r[13] = NO_TASK
                rows.append(r)
        if residue:
            r = np.zeros(DESC_WORDS, np.int32)  # a dynamic EXPAND
            r[F_FN] = 0
            r[F_A0:F_A0 + 2] = (d % n, 4)
            r[2] = r[3] = r[13] = NO_TASK
            rows.append(r)
            if d == 2:  # one pending QUERY
                r = np.zeros(DESC_WORDS, np.int32)
                r[F_FN] = DG_QUERY
                r[F_A0] = 7
                r[F_OUT] = st + n + 1
                r[2] = r[3] = r[13] = NO_TASK
                rows.append(r)
        for i, r in enumerate(rows):
            tasks[d, i] = r
            ready[d, i] = i
        counts[d, 1] = counts[d, C_ALLOC] = len(rows)
        counts[d, C_PENDING] = len(rows)
        counts[d, C_VALLOC] = g.num_value_slots
        counts[d, C_EXECUTED] = 3 + d
    arrays = {
        "tasks": tasks, "succ": succ, "ready": ready, "counts": counts,
        "ivalues": iv.astype(np.int32),
        "data/indices": ind, "data/weights": wgt,
    }
    meta = {"ndev": ndev, "dyngraph": dict(mk._dyngraph),
            "kernel_names": list(mk.kernel_names)}
    return CheckpointBundle("resident", meta, arrays), g, ups, iv, counts


def test_dyngraph_reshard_shrink_grow_conserves():
    """4 -> 2 -> 4: the canonical rebuilt adjacency broadcasts
    identically, edge count conserves (static + union-applied), labels
    min-fold, accumulators sum-fold, the served query value survives,
    and residue deals without loss."""
    from hclib_tpu.device.frontier import V_EDGES, VT_BASE
    from hclib_tpu.device.dyngraph import V_QUERIES
    from hclib_tpu.device.megakernel import C_EXECUTED, C_PENDING

    applied = {d: [u for u in range(4) if (u + d) % 2 == 0]
               for d in range(4)}
    applied[1] = applied[1][::-1]  # order-divergent application
    applied[3] = applied[3][::-1]
    bundle, g, ups, iv, counts = _dyngraph_fixture(applied)
    n, st = g.n, g.st_base

    b2 = bundle.reshard(2)
    assert b2.meta["ndev"] == 2
    assert b2.meta["dyngraph_reshard"]["union_applied"] == 4
    assert b2.meta["dyngraph_reshard"]["pending_updates"] == 0
    i2 = b2.arrays["data/indices"]
    assert np.array_equal(i2[0], i2[1])  # canonical broadcast
    iv2 = b2.arrays["ivalues"].astype(np.int64)
    vt2 = iv2[0, VT_BASE:VT_BASE + 3 * n].reshape(n, 3)
    assert int(vt2[:, 2].sum()) == int(g.deg.sum()) + 4
    c2 = b2.arrays["counts"]
    assert int(c2[:, C_PENDING].sum()) == 5  # 4 EXPANDs + 1 QUERY dealt
    assert int(c2[:, C_EXECUTED].sum()) == int(counts[:, C_EXECUTED].sum())
    want = iv[:, st:st + n].min(axis=0)
    assert np.array_equal(iv2[0, st:st + n], want)
    assert np.array_equal(iv2[1, st:st + n], want)
    assert int(iv2[:, V_EDGES].sum()) == int(iv[:, V_EDGES].sum())
    assert int(iv2[:, V_QUERIES].sum()) == 1
    assert int(iv2[0, st + n]) == 13  # served query value max-folds

    b3 = b2.reshard(4)  # grow back
    assert b3.meta["ndev"] == 4 and b3.meta["resharded_from"] == 2
    for d in range(4):
        assert np.array_equal(b3.arrays["data/indices"][d], i2[0])
    iv3 = b3.arrays["ivalues"].astype(np.int64)
    vt3 = iv3[0, VT_BASE:VT_BASE + 3 * n].reshape(n, 3)
    assert int(vt3[:, 2].sum()) == int(g.deg.sum()) + 4
    assert int(iv3[:, V_EDGES].sum()) == int(iv[:, V_EDGES].sum())


def test_dyngraph_reshard_broadcasts_unapplied_update():
    """A pending update NO replica has applied dedupes by uid and
    broadcasts to every new device - the mesh invariant 'every replica
    sees every update' survives the resize."""
    from hclib_tpu.device.descriptor import F_A0, F_FN
    from hclib_tpu.device.dyngraph import DG_UPDATE
    from hclib_tpu.device.frontier import VT_BASE
    from hclib_tpu.device.megakernel import C_ALLOC

    applied = {0: [0], 1: [1, 0], 2: [], 3: [2]}  # uid 3 nowhere
    bundle, g, ups, _, _ = _dyngraph_fixture(
        applied, serve_query=False, residue=False,
    )
    b2 = bundle.reshard(2)
    rs = b2.meta["dyngraph_reshard"]
    assert rs["union_applied"] == 3 and rs["pending_updates"] == 1
    t, c = b2.arrays["tasks"], b2.arrays["counts"]
    for j in range(2):
        uids = [int(t[j, i, F_A0 + 3]) for i in range(int(c[j, C_ALLOC]))
                if int(t[j, i, F_FN]) == DG_UPDATE]
        assert uids == [3], uids
    n = g.n
    vt = b2.arrays["ivalues"][0, VT_BASE:VT_BASE + 3 * n].reshape(n, 3)
    assert int(vt[:, 2].sum()) == int(g.deg.sum()) + 3


def test_dyngraph_reshard_refusals():
    """Structured refusals: pagerank mid-run (no device-count-free
    fold), dropped splices (adjacency no longer the stream's), and
    foreign data buffers."""
    from hclib_tpu.device.dyngraph import V_DROPPED

    applied = {0: [0, 1, 2, 3], 1: [], 2: [], 3: []}
    bundle, g, ups, _, _ = _dyngraph_fixture(applied)

    pr = CheckpointBundle(
        bundle.kind,
        {**bundle.meta,
         "dyngraph": {**bundle.meta["dyngraph"], "kind": "pagerank"}},
        bundle.arrays,
    )
    with pytest.raises(CheckpointError, match="pagerank"):
        pr.reshard(2)

    dropped = {k: np.array(v) for k, v in bundle.arrays.items()}
    dropped["ivalues"] = dropped["ivalues"].copy()
    dropped["ivalues"][2, V_DROPPED] = 1
    with pytest.raises(CheckpointError, match="spare"):
        CheckpointBundle(bundle.kind, bundle.meta, dropped).reshard(2)

    extra = dict(bundle.arrays)
    extra["data/other"] = np.zeros((4, 8), np.int32)
    with pytest.raises(CheckpointError, match="extra data buffers"):
        CheckpointBundle(bundle.kind, bundle.meta, extra).reshard(2)


def test_dyngraph_quiesce_mid_update_storm_resume_bit_identical():
    """Quiesce a single-device dyngraph run mid-update-storm, snapshot
    (the layout stamp rides bundle meta), resume, and the fixpoint is
    bit-identical to the host twin on the mutated graph - with the
    vertex-table degrees conserving static + applied edge counts."""
    from hclib_tpu.device.dyngraph import (
        DynGraph, _bind_updates, _seed_builders, fk_data, host_dyngraph,
        make_dyngraph_megakernel,
    )
    from hclib_tpu.device.frontier import INF, VT_BASE

    rng = np.random.default_rng(11)
    n, m = 16, 48
    g = DynGraph(n, rng.integers(0, n, m), rng.integers(0, n, m),
                 rng.integers(1, 8, m), spare_blocks=2, upd_cap=8)
    for u, v, w in [(1, 5, 3), (2, 7, 1), (0, 9, 2), (4, 3, 6)]:
        g.add_update(u, v, w)
    mk = make_dyngraph_megakernel(
        "sssp", g, width=0, interpret=True, checkpoint=True,
    )
    _bind_updates(mk, g)
    builders, _ = _seed_builders(
        g, "sssp", 0, 1 << 14, 64, [5], mk.num_values, 1,
        lambda i, tot: 0,
    )
    iv = g.preset_values(mk.num_values, INF)
    iv[g.st_base] = 0
    _, _, info_q = mk.run(
        builders[0], data=dict(fk_data(g, mk)), ivalues=iv, quiesce=2,
    )
    assert info_q["quiesced"] is True and info_q["pending"] > 0
    bundle = snapshot_megakernel(mk, info_q)
    assert bundle.meta["dyngraph"]["kind"] == "sssp"
    assert len(bundle.meta["dyngraph"]["updates"]) == 4

    iv_r, _, info_r = mk.resume(info_q["state"])
    row = np.asarray(iv_r, np.int64)
    res = row[g.st_base : g.st_base + n].astype(np.int32)
    assert np.array_equal(res, host_dyngraph("sssp", g, 0))
    flags = row[g.flag_base : g.flag_base + g.upd_cap]
    vt = row[VT_BASE : VT_BASE + 3 * n].reshape(n, 3)
    assert int((flags != 0).sum()) == 4
    assert int(vt[:, 2].sum()) == int(g.deg.sum()) + 4  # conservation
    # The in-run query published SOME label for vertex 5 (tentative
    # when it raced the traversal, exact once drained - monotone
    # relaxation means it can only be an upper bound of the fixpoint).
    assert int(row[g.st_base + n]) >= int(res[5])

    # Restore THROUGH the bundle onto a fresh identical build: the
    # mutated adjacency rides data/ and the run completes identically.
    mk2 = make_dyngraph_megakernel(
        "sssp", g, width=0, interpret=True, checkpoint=True,
    )
    _bind_updates(mk2, g)
    iv_b, _, _ = restore_megakernel(bundle, mk2)
    assert np.array_equal(
        np.asarray(iv_b, np.int64)[g.st_base : g.st_base + n], res
    )
