"""Process-wide content-keyed program cache (ISSUE 18).

The acceptance spine: cache-on vs cache-off lowered text byte-identical
for the curated builders (fib, frontier SSSP, forasync tile, a
tenant+egress stream, a checkpoint-enabled build); a content-identical
second instance's first run is a HIT sharing the first instance's
executable with bit-identical results; every key component - the hclint
layout table, the kernel roster, kernel bodies, each device-word knob,
the mesh shape, the runner variant - provably misses when changed; cap
semantics (malformed or non-positive raises, cap=1 evicts and the
rebuild is bit-identical); fail-open on unfingerprintable input.
"""

import numpy as np
import pytest

import jax

from hclib_tpu.device.descriptor import TaskGraphBuilder
from hclib_tpu.device.frontier import _KINDS, Graph, make_frontier_megakernel
from hclib_tpu.device.forasync_tier import make_forasync_megakernel
from hclib_tpu.device.inject import StreamingMegakernel
from hclib_tpu.device.megakernel import Megakernel
from hclib_tpu.device.tenants import TenantSpec, TenantTable
from hclib_tpu.device.egress import EgressSpec
from hclib_tpu.device.workloads import (
    FIB,
    make_fib_megakernel,
    make_uts_megakernel,
    rmat_edges,
    stencil_loop,
)
from hclib_tpu.runtime import progcache
from hclib_tpu.runtime.progcache import (
    Uncacheable,
    cache_cap,
    cache_stats,
    enabled,
    fingerprint,
    layout_fingerprint,
    megakernel_fingerprint,
    mesh_key,
    probe,
    shared_build,
)


@pytest.fixture(autouse=True)
def _fresh_cache():
    """Counter/entry isolation: the registry is process-wide state."""
    progcache.reset()
    yield
    progcache.reset()


def _lowered_text(mk, fuel=1 << 12):
    """The program the megakernel would run, as bytes: stage an empty
    graph for shapes only (lowered text depends on specs, not data)."""
    tasks, succ, ring, counts = TaskGraphBuilder().finalize(
        capacity=mk.capacity, succ_capacity=mk.succ_capacity
    )
    args = [tasks, succ, ring, counts, np.zeros(mk.num_values, np.int32)]
    for s in mk.data_specs.values():
        args.append(np.zeros(s.shape, s.dtype))
    if mk.checkpoint:
        args.append(Megakernel.quiesce_words(None))
    structs = [
        jax.ShapeDtypeStruct(np.asarray(x).shape, np.asarray(x).dtype)
        for x in args
    ]
    return mk._build_raw(fuel).lower(*structs).as_text()


def _bump_mk(**kw):
    def bump(ctx):
        ctx.set_value(0, ctx.value(0) + ctx.arg(0))

    kw.setdefault("capacity", 128)
    kw.setdefault("num_values", 4)
    return Megakernel(
        kernels=[("bump", bump)], succ_capacity=8, interpret=True, **kw,
    )


# ------------------------------------------------ fingerprint basics


def test_fingerprint_is_content_not_identity():
    def mk_fn(k):
        def f(ctx):
            ctx.set_value(0, k)

        return f

    # Two distinct function OBJECTS with identical content agree...
    assert fingerprint(mk_fn(3)) == fingerprint(mk_fn(3))
    # ...and a closure-cell (or constant) change is content.
    assert fingerprint(mk_fn(3)) != fingerprint(mk_fn(4))
    a = np.arange(8, dtype=np.int32)
    assert fingerprint(a) == fingerprint(a.copy())
    b = a.copy()
    b[3] = 99
    assert fingerprint(a) != fingerprint(b)


def test_fingerprint_cycle_and_depth_fail_open():
    cyc = []
    cyc.append(cyc)
    fingerprint(cyc)  # cycle guard terminates, no raise
    deep = ()
    for _ in range(64):
        deep = (deep,)
    with pytest.raises(Uncacheable):
        fingerprint(deep)


# --------------------------- key sensitivity, one test per component


def test_key_sensitive_to_layout_table(monkeypatch):
    """ANY device-word layout drift invalidates every key (a stale
    program against a new ABI must be impossible)."""
    from hclib_tpu.analysis import layout as L

    mk = _bump_mk()
    before = megakernel_fingerprint(mk)
    lf = layout_fingerprint()
    patched = dict(L.LAYOUT)
    patched["__progcache_test_word__"] = ("smem", 0, 1)
    monkeypatch.setattr(L, "LAYOUT", patched)
    assert layout_fingerprint() != lf
    assert megakernel_fingerprint(mk) != before


def test_key_sensitive_to_kernel_roster():
    def bump(ctx):
        ctx.set_value(0, ctx.value(0) + ctx.arg(0))

    one = Megakernel(
        kernels=[("bump", bump)], capacity=128, num_values=4,
        succ_capacity=8, interpret=True,
    )
    two = Megakernel(
        kernels=[("bump", bump), ("bump2", bump)], capacity=128,
        num_values=4, succ_capacity=8, interpret=True,
    )
    assert megakernel_fingerprint(one) != megakernel_fingerprint(two)


def test_key_sensitive_to_kernel_body():
    def mk_with(body):
        return Megakernel(
            kernels=[("k", body)], capacity=128, num_values=4,
            succ_capacity=8, interpret=True,
        )

    def body_a(ctx):
        ctx.set_value(0, ctx.arg(0) + 1)

    def body_b(ctx):
        ctx.set_value(0, ctx.arg(0) + 2)

    assert (
        megakernel_fingerprint(mk_with(body_a))
        != megakernel_fingerprint(mk_with(body_b))
    )


@pytest.mark.parametrize(
    "kw",
    [
        {"checkpoint": True},
        {"quiesce_stride": 4},
        {"trace": 4096},
        {"capacity": 256},
        {"num_values": 8},
    ],
)
def test_key_sensitive_to_each_device_word_knob(kw):
    """One knob flipped from the baseline = a different program key."""
    base = _bump_mk()
    other = _bump_mk(**kw)
    assert megakernel_fingerprint(base) != megakernel_fingerprint(other)


@pytest.mark.parametrize("attr,value", [
    ("lane_max_age", 7),
    ("priority_buckets", 4),
])
def test_key_sensitive_to_dispatch_tier_knobs(attr, value):
    """lane_max_age / priority_buckets ride the key directly (the
    fingerprint reads the resolved attributes, so the env spellings
    are covered by the same read)."""
    base = make_fib_megakernel(interpret=True, batch_width=2)
    other = make_fib_megakernel(interpret=True, batch_width=2)
    assert megakernel_fingerprint(base) == megakernel_fingerprint(other)
    setattr(other, attr, getattr(other, attr) + value)
    assert megakernel_fingerprint(base) != megakernel_fingerprint(other)


def test_key_sensitive_to_batch_routing():
    scalar = make_fib_megakernel(interpret=True)
    routed = make_fib_megakernel(interpret=True, batch_width=2)
    assert (
        megakernel_fingerprint(scalar) != megakernel_fingerprint(routed)
    )


def test_key_sensitive_to_mesh_and_variant():
    from hclib_tpu.parallel.mesh import cpu_mesh

    m2, m4 = cpu_mesh(2), cpu_mesh(4)
    assert mesh_key(m2) != mesh_key(m4)
    assert mesh_key(m2) == mesh_key(cpu_mesh(2))
    # The runner variant (hop order, quantum, windows...) is half the
    # key: same megakernel, different variant = different program.
    mk = _bump_mk()

    def build():
        return object()

    a, sa = shared_build(mk, ("resident", mesh_key(m2), 64), build)
    b, sb = shared_build(mk, ("resident", mesh_key(m2), 32), build)
    assert not sa["hit"] and not sb["hit"] and a is not b
    c, sc = shared_build(mk, ("resident", mesh_key(m2), 64), build)
    assert sc["hit"] and c is a


def test_key_sensitive_to_tenants_and_egress():
    """Compiled-surface stream facts key the variant: tenant count,
    region rows, egress depth (WRR weights ride tctl and must not)."""
    mk = _bump_mk()
    variants = [
        ("stream", 32, None, None, 8, 1 << 12),
        ("stream", 32, (1, 32), None, 8, 1 << 12),
        ("stream", 32, (2, 16), None, 8, 1 << 12),
        ("stream", 32, (1, 32), 64, 8, 1 << 12),
    ]
    digests = {fingerprint(v) for v in variants}
    assert len(digests) == len(variants)


# ------------------------------- byte identity: the curated builders


CURATED = {
    "fib": lambda: make_fib_megakernel(interpret=True),
    "fib-checkpoint": lambda: make_fib_megakernel(
        interpret=True, checkpoint=True
    ),
    "uts-checkpoint": lambda: make_uts_megakernel(
        max_depth=6, interpret=True, checkpoint=True
    ),
}


def _frontier_mk():
    n, src, dst, w = rmat_edges(4, efactor=4, seed=7)
    return make_frontier_megakernel(
        _KINDS["sssp"](), Graph(n, src, dst, w), width=4, interpret=True
    )


def _forasync_mk():
    tk, _, _ = stencil_loop(16, 512)
    return make_forasync_megakernel(tk, width=4, interpret=True)


CURATED["frontier-sssp"] = _frontier_mk
CURATED["forasync-tile"] = _forasync_mk


@pytest.mark.parametrize("name", sorted(CURATED))
def test_cache_on_off_lowered_text_byte_identical(name, monkeypatch):
    """The cache changes WHEN a program is built, never WHAT: with the
    cache forced off, a fresh content-identical instance lowers to the
    exact bytes the cache-on instance lowers to."""
    factory = CURATED[name]
    monkeypatch.delenv("HCLIB_TPU_PROGRAM_CACHE", raising=False)
    assert enabled()
    on_text = _lowered_text(factory())
    monkeypatch.setenv("HCLIB_TPU_PROGRAM_CACHE", "0")
    assert not enabled()
    off_text = _lowered_text(factory())
    assert on_text == off_text
    # Content-identical instances agree byte-for-byte (key-equal
    # implies program-equal for the builder), so sharing is sound.
    monkeypatch.delenv("HCLIB_TPU_PROGRAM_CACHE", raising=False)
    assert _lowered_text(factory()) == on_text


def test_second_identical_fib_instance_hits_and_matches():
    b1, b2 = TaskGraphBuilder(), TaskGraphBuilder()
    b1.add(FIB, args=[8], out=0)
    b2.add(FIB, args=[8], out=0)
    iv1, _, i1 = make_fib_megakernel(interpret=True).run(b1)
    assert i1["program_cache"]["hit"] is False
    assert i1["program_cache"]["build_s"] > 0.0
    iv2, _, i2 = make_fib_megakernel(interpret=True).run(b2)
    assert i2["program_cache"]["hit"] is True
    assert i2["program_cache"]["build_s"] == 0.0
    assert iv1.tobytes() == iv2.tobytes()
    s = cache_stats()
    assert s["hits"] == 1 and s["misses"] == 1 and s["entries"] == 1


def test_stream_cold_start_hits_and_matches(monkeypatch):
    """Serving cold start: a second identical tenant+egress stream's
    first entry reuses the first stream's executable, bit-identically;
    the cache-off arm produces the same bytes with counters untouched."""
    def serve(tag):
        table = TenantTable(
            [TenantSpec("gold")], 32, clock=lambda: 100.0,
            egress=EgressSpec(depth=64),
        )
        sm = StreamingMegakernel(
            _bump_mk(), ring_capacity=32, tenants=table
        )
        subs = [sm.submit("gold", 0, args=[i + 1]) for i in range(4)]
        sm.close()
        b = TaskGraphBuilder()
        b.add(0, args=[1000])
        iv, info = sm.run_stream(b)
        for sub in subs:
            sub.future.result(timeout=5.0)
        return iv.tobytes(), info

    cold_bytes, cold_info = serve("cold")
    assert cold_info["program_cache"]["hit"] is False
    warm_bytes, warm_info = serve("warm")
    assert warm_info["program_cache"]["hit"] is True
    assert warm_bytes == cold_bytes
    before = cache_stats()
    monkeypatch.setenv("HCLIB_TPU_PROGRAM_CACHE", "0")
    off_bytes, off_info = serve("off")
    assert off_bytes == cold_bytes
    assert off_info["program_cache"]["hit"] is False
    assert cache_stats() == before


# ------------------------------------------------ knobs + cap + LRU


def test_enabled_spelling(monkeypatch):
    monkeypatch.delenv("HCLIB_TPU_PROGRAM_CACHE", raising=False)
    assert enabled()
    for off in ("", "0"):
        monkeypatch.setenv("HCLIB_TPU_PROGRAM_CACHE", off)
        assert not enabled()
    monkeypatch.setenv("HCLIB_TPU_PROGRAM_CACHE", "1")
    assert enabled()


def test_cap_validation(monkeypatch):
    monkeypatch.delenv("HCLIB_TPU_PROGRAM_CACHE_CAP", raising=False)
    assert cache_cap() == 256
    monkeypatch.setenv("HCLIB_TPU_PROGRAM_CACHE_CAP", "banana")
    with pytest.raises(ValueError):
        cache_cap()
    for bad in ("0", "-3"):
        monkeypatch.setenv("HCLIB_TPU_PROGRAM_CACHE_CAP", bad)
        with pytest.raises(ValueError, match="PROGRAM_CACHE_CAP"):
            cache_cap()


def test_cap_one_evicts_and_rebuild_is_bit_identical(monkeypatch):
    """cap=1: program B evicts A; rebuilding A misses (the eviction
    counted) and the rebuilt executable produces A's exact bytes."""
    monkeypatch.setenv("HCLIB_TPU_PROGRAM_CACHE_CAP", "1")

    def run_fib(n):
        b = TaskGraphBuilder()
        b.add(FIB, args=[n], out=0)
        iv, _, info = make_fib_megakernel(interpret=True).run(b)
        return iv.tobytes(), info["program_cache"]

    def run_bump():
        b = TaskGraphBuilder()
        b.add(0, args=[7])
        iv, _, info = _bump_mk().run(b)
        return iv.tobytes(), info["program_cache"]

    first, pc1 = run_fib(8)
    assert not pc1["hit"]
    _, pcb = run_bump()          # different program: evicts fib at cap=1
    assert not pcb["hit"]
    assert cache_stats()["evictions"] >= 1
    assert cache_stats()["entries"] == 1
    again, pc2 = run_fib(8)
    assert not pc2["hit"]        # evicted = a real rebuild
    assert again == first        # ...and bit-identical


def test_lru_order_refreshes_on_hit(monkeypatch):
    monkeypatch.setenv("HCLIB_TPU_PROGRAM_CACHE_CAP", "2")
    mk = _bump_mk()
    a, _ = shared_build(mk, ("v", 1), object)
    shared_build(mk, ("v", 2), object)
    a2, sa2 = shared_build(mk, ("v", 1), object)   # refresh A
    assert sa2["hit"] and a2 is a
    shared_build(mk, ("v", 3), object)             # evicts B, not A
    a3, sa3 = shared_build(mk, ("v", 1), object)
    assert sa3["hit"] and a3 is a


def test_eviction_is_cost_weighted():
    """An expensive build survives a burst of cheap ones that would
    have rolled it off a plain LRU tail; uniform costs stay exact LRU."""
    from hclib_tpu.runtime.progcache import ProgramCache

    cap = 8
    pc = ProgramCache()
    pc.put(("exp",), "EXP", cap, build_s=40.0)
    for i in range(cap - 1):
        pc.put(("cheap", i), i, cap, build_s=0.01)
    assert len(pc) == cap and pc.evictions == 0
    pc.put(("cheap", cap - 1), cap - 1, cap, build_s=0.01)  # overflow
    assert pc.evictions == 1
    assert pc.contains(("exp",))          # LRU-oldest, but costly: kept
    assert not pc.contains(("cheap", 0))  # cheapest in the LRU window
    assert pc.get(("exp",)) == "EXP"

    pc2 = ProgramCache()
    for i in range(cap + 1):
        pc2.put(("u", i), i, cap, build_s=0.5)
    assert not pc2.contains(("u", 0)) and pc2.contains(("u", 1))
    assert pc2.evictions == 1


def test_probe_reads_without_counting():
    mk = _bump_mk()
    assert probe(mk, ("v",)) is False
    fn, _ = shared_build(mk, ("v",), object)
    before = cache_stats()
    assert probe(mk, ("v",)) is True
    assert cache_stats() == before


def test_unfingerprintable_variant_fails_open():
    """Irreducible input = a private build: no counters move, nothing
    enters the table, and the build still happens."""
    deep = ()
    for _ in range(64):
        deep = (deep,)
    mk = _bump_mk()
    before = cache_stats()
    fn, stats = shared_build(mk, deep, object)
    assert fn is not None and stats["hit"] is False
    assert cache_stats() == before


def test_metrics_exports_program_cache_gauges():
    from hclib_tpu.runtime.metrics import MetricsRegistry

    b = TaskGraphBuilder()
    b.add(FIB, args=[6], out=0)
    _, _, info = make_fib_megakernel(interpret=True).run(b)
    reg = MetricsRegistry()
    reg.add_run_info("fib", info)
    m = reg.snapshot()["metrics"]
    assert m["program_cache.misses"] == 1.0
    assert m["program_cache.entries"] == 1.0
    assert m["program_cache.hits"] == 0.0
    assert m["program_cache.evictions"] == 0.0
    assert "fib.program_cache.build_s" in m
    assert "fib.program_cache.cache_lookup_s" in m
