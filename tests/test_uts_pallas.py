"""Fused-Pallas UTS engine (device/uts_pallas.py): exactness vs the
sequential spec and vs the XLA engine, in interpret mode on CPU."""

import jax
import pytest

from hclib_tpu.device.uts_pallas import uts_pallas
from hclib_tpu.device.uts_vec import uts_vec
from hclib_tpu.models.uts import FIXED, T3, UTSParams, count_seq


def _cpu():
    return jax.devices("cpu")[0]


def test_uts_pallas_t3_exact():
    r = uts_pallas(T3, target_roots=64, device=_cpu(), interpret=True)
    assert (r["nodes"], r["leaves"], r["max_depth"]) == count_seq(T3)


def test_uts_pallas_deeper_tree_exact():
    p = UTSParams(shape=FIXED, gen_mx=7, b0=4.0, root_seed=19)
    r = uts_pallas(p, target_roots=256, device=_cpu(), interpret=True)
    assert (r["nodes"], r["leaves"], r["max_depth"]) == count_seq(p)


def test_uts_pallas_matches_xla_engine_steps():
    """Identical refill/step semantics: node counts AND step counts match
    the XLA engine exactly (the step fn is literally shared)."""
    p = UTSParams(shape=FIXED, gen_mx=8, b0=4.0, root_seed=7)
    rv = uts_vec(p, target_roots=2048, device=_cpu())
    rp = uts_pallas(p, target_roots=2048, device=_cpu(), interpret=True)
    assert rv["nodes"] == rp["nodes"]
    assert rv["leaves"] == rp["leaves"]
    assert rv["max_depth"] == rp["max_depth"]
    assert rv["steps"] == rp["steps"]


def test_uts_pallas_requires_128_lanes():
    with pytest.raises(ValueError, match="128"):
        uts_pallas(T3, lanes=(8, 64), device=_cpu(), interpret=True)
