"""Fused-Pallas UTS engine (device/uts_pallas.py): exactness vs the
sequential spec and vs the XLA engine, in interpret mode on CPU.

Every depth-varying test passes stack_pad=10 + table_cols=100 so all of
them (LINEAR / CYCLIC / EXPDEC) land on ONE padded (16, 100)-table,
stack-10 engine and the suite pays a single ~1 min trace instead of one
per tree - the compile-sharing knobs exist precisely for this."""

import jax
import pytest

from hclib_tpu.device.uts_pallas import uts_pallas
from hclib_tpu.runtime.env import env_flag
from hclib_tpu.device.uts_vec import uts_vec
from hclib_tpu.models.uts import FIXED, T3, UTSParams, count_seq


def _cpu():
    return jax.devices("cpu")[0]


def test_uts_pallas_t3_exact():
    r = uts_pallas(T3, target_roots=64, device=_cpu(), interpret=True,
                   stack_pad=8)
    assert (r["nodes"], r["leaves"], r["max_depth"]) == count_seq(T3)


def test_uts_pallas_deeper_tree_exact():
    p = UTSParams(shape=FIXED, gen_mx=7, b0=4.0, root_seed=19)
    r = uts_pallas(p, target_roots=256, device=_cpu(), interpret=True,
                   stack_pad=8)
    assert (r["nodes"], r["leaves"], r["max_depth"]) == count_seq(p)


def test_uts_pallas_matches_xla_engine_steps():
    """Identical refill/step semantics: node counts AND step counts match
    the XLA engine exactly (the step fn is literally shared)."""
    p = UTSParams(shape=FIXED, gen_mx=7, b0=4.0, root_seed=7)
    rv = uts_vec(p, target_roots=1024, device=_cpu(), stack_pad=8)
    rp = uts_pallas(p, target_roots=1024, device=_cpu(), interpret=True,
                    stack_pad=8)
    assert rv["nodes"] == rp["nodes"]
    assert rv["leaves"] == rp["leaves"]
    assert rv["max_depth"] == rp["max_depth"]
    assert rv["steps"] == rp["steps"]


def test_uts_pallas_requires_128_lanes():
    with pytest.raises(ValueError, match="128"):
        uts_pallas(T3, lanes=(8, 64), device=_cpu(), interpret=True)


@pytest.mark.skipif(
    jax.default_backend() != "tpu" or not env_flag("HCLIB_TPU_BIG_TESTS"),
    reason="needs TPU + HCLIB_TPU_BIG_TESTS (fresh ~60s compile + ~20s run)",
)
def test_uts_pallas_t1xxl_exact_on_tpu():
    """The canonical T1XXL tree: 4,230,646,601 nodes - genuinely beyond
    int32 totals (2^31 = 2.147B), counted exactly because the per-lane
    planes are summed in int64 on the host; an int32 total would wrap.
    (T1XL's 1.635B, by contrast, still fits int32.) Round-5 re-measure
    under the fixed best-of-3 timing: 2,228 M nodes/s, four bracketed
    trials within 0.03% (see README)."""
    from hclib_tpu.models.uts import T1XXL

    r = uts_pallas(
        T1XXL, target_roots=1024 * 1024, lanes=(64, 128), min_idle_div=32,
        timing_reps=1,  # counts only; skip the best-of-3 rate protocol
    )
    assert r["nodes"] == 4_230_646_601
    assert r["leaves"] == 3_384_495_738
    assert r["max_depth"] == 15


def test_uts_pallas_linear_exact():
    """LINEAR (T5-family) shape fused: exact per-depth threshold tables
    realized as in-row take_along_axis lookups (VERDICT round-2 item 7)."""
    from hclib_tpu.models.uts import LINEAR

    p = UTSParams(shape=LINEAR, gen_mx=6, b0=4.0, root_seed=34)
    r = uts_pallas(p, target_roots=64, device=_cpu(), interpret=True,
                   stack_pad=10, table_cols=100)
    assert r["roots"] > 0  # the fused kernel actually ran
    assert (r["nodes"], r["leaves"], r["max_depth"]) == count_seq(p)


def test_uts_pallas_cyclic_exact():
    from hclib_tpu.models.uts import CYCLIC

    # gen_mx=1 keeps the depth cap at 7 (5*gen_mx+2) - interpret-mode
    # trace time grows steeply with the per-lane stack height - while the
    # 181-node tree still spans the full cyclic period (depths 0..6), so
    # every row of the per-depth threshold table is exercised.
    p = UTSParams(shape=CYCLIC, gen_mx=1, b0=6.0, root_seed=7)
    # target_roots 8: a larger target lets the host BFS consume the whole
    # tree before the kernel ever runs (roots == 0 would make this a
    # host-only test).
    r = uts_pallas(p, target_roots=8, device=_cpu(), interpret=True,
                   stack_pad=10, table_cols=100)
    assert r["roots"] > 0
    assert (r["nodes"], r["leaves"], r["max_depth"]) == count_seq(p)


def test_uts_pallas_expdec_exact():
    from hclib_tpu.models.uts import EXPDEC

    p = UTSParams(shape=EXPDEC, gen_mx=3, b0=3.0, root_seed=502)
    # This 217-node tree's true max depth is 7; a 9-bound keeps the
    # interpret-mode stack (and so trace size) small while still
    # validating - a too-small bound raises loudly rather than truncating
    # counts.
    r = uts_pallas(
        p, target_roots=16, device=_cpu(), interpret=True, depth_bound=9,
        stack_pad=10, table_cols=100,
    )
    assert r["roots"] > 0
    assert (r["nodes"], r["leaves"], r["max_depth"]) == count_seq(p)


def test_uts_pallas_depth_varying_matches_xla_engine():
    """The fused in-row table lookup and the XLA row gather are the same
    function of (r, depth): node AND step counts match exactly."""
    from hclib_tpu.models.uts import LINEAR

    p = UTSParams(shape=LINEAR, gen_mx=6, b0=4.0, root_seed=34)
    rv = uts_vec(p, target_roots=64, device=_cpu(), stack_pad=10,
                 table_cols=100)
    rp = uts_pallas(p, target_roots=64, device=_cpu(), interpret=True,
                    stack_pad=10, table_cols=100)
    assert rp["roots"] > 0  # the fused kernel actually traversed subtrees
    assert (rv["nodes"], rv["leaves"], rv["max_depth"], rv["steps"]) == (
        rp["nodes"], rp["leaves"], rp["max_depth"], rp["steps"]
    )
