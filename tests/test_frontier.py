"""Graph-analytics frontier tier (ISSUE 10): BFS/SSSP/PageRank over a
blocked-CSR adjacency on the batch lanes, the age-triggered lane firing
policy, locality-ordered resident XOR hops, and checkpoint mid-frontier.

The acceptance spine: BFS and SSSP distance arrays bit-identical to the
host reference across scalar dispatch, the batched frontier tier, and
the 4-device mesh (PageRank bit-identical to its integer push twin and
within tolerance of float PageRank), with the firing-policy knob
bounding lane starvation and off-behavior unchanged.
"""

import os

import numpy as np
import pytest
from jax.experimental import pallas as pl

import hclib_tpu as hc
from hclib_tpu.device.descriptor import TaskGraphBuilder
from hclib_tpu.device.frontier import (
    EBLOCK,
    FR_EXPAND,
    INF,
    Graph,
    _KINDS,
    host_bfs,
    host_pagerank,
    host_pagerank_push,
    host_sssp,
    make_frontier_megakernel,
    run_frontier,
)
from hclib_tpu.device.megakernel import C_EXECUTED, Megakernel
from hclib_tpu.device.workloads import batch_of, rmat_edges
from hclib_tpu.runtime.locality import (
    MeshPlacement,
    load_locality_file,
    xor_hop_order,
)

GRAPHS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "locality_graphs",
)

# One small seeded R-MAT graph shared by every arm in this file (each
# distinct megakernel build is an XLA compile - keep the set tight).
N, SRC, DST, W = rmat_edges(5, efactor=6, seed=3)
G = Graph(N, SRC, DST, W)
BFS_REF = host_bfs(G, 0)
SSSP_REF = host_sssp(G, 0)
M0, REPS = 1 << 12, 64


# Shared builds, now by CONTENT not by fixture lifetime (ISSUE 18):
# the process-wide program cache (runtime/progcache.py) keys jitted
# executables on the megakernel's content fingerprint, so every test
# gets a FRESH instance (function scope - no cross-test object
# aliasing) while content-identical rebuilds share one compile. With
# the cache forced off the fixtures still work - each test just pays
# its own build.


@pytest.fixture
def bfs_w4_mk():
    """The batched BFS build (width=4, default capacity) used by the
    three-arm, metrics, and any other single-device batched-BFS test -
    a fresh instance per test; the program cache dedupes the compile."""
    return make_frontier_megakernel(
        _KINDS["bfs"](), G, width=4, interpret=True
    )


@pytest.fixture
def sssp_arms():
    """The scalar + batched SSSP builds (bit-identity arms)."""
    return {
        0: make_frontier_megakernel(
            _KINDS["sssp"](), G, width=0, interpret=True
        ),
        4: make_frontier_megakernel(
            _KINDS["sssp"](), G, width=4, interpret=True
        ),
    }


# -------------------------------------------------- graph container math


def test_rmat_and_blocked_csr_layout():
    # Seeded determinism: same args, same graph.
    n2, s2, d2, w2 = rmat_edges(5, efactor=6, seed=3)
    assert n2 == N and np.array_equal(s2, SRC) and np.array_equal(w2, W)
    # Blocked CSR: per-vertex block runs hold exactly the adjacency,
    # -1-padded to the block, and block_cnt sums back to the degree.
    for v in range(G.n):
        d = int(G.deg[v])
        b0, bc = int(G.blk_start[v]), int(G.blk_count[v])
        assert bc == (d + EBLOCK - 1) // EBLOCK
        flat = G.indices[b0 : b0 + bc].reshape(-1)
        assert np.array_equal(np.sort(flat[:d]), np.sort(G.adj[v]))
        assert (flat[d:] == -1).all()
        assert sum(G.block_cnt(v, i) for i in range(bc)) == d
    # Vertex table + state layout fit the preset row.
    iv = G.preset_values(G.num_value_slots, INF)
    assert iv[8 + 3 * 5] == G.blk_start[5]
    assert (iv[G.st_base : G.st_base + G.n] == INF).all()
    with pytest.raises(ValueError, match="out of range"):
        Graph(4, [0, 9], [1, 2])
    with pytest.raises(ValueError, match="num_values"):
        G.preset_values(4, 0)


# ------------------------------------------------- three-arm bit-identity


def test_bfs_three_arms_bit_identical(bfs_w4_mk):
    d_sc, info_sc = run_frontier("bfs", G, 0, width=0, interpret=True)
    assert np.array_equal(d_sc, BFS_REF)
    assert info_sc["edges"] > 0 and info_sc["relaxations"] > 0

    d_bt, info_bt = run_frontier("bfs", G, 0, mk=bfs_w4_mk,
                                 interpret=True)
    assert np.array_equal(d_bt, BFS_REF)
    t = info_bt["tiers"]
    assert t["scalar_tasks"] == 0 and t["batch_tasks"] == info_bt["executed"]
    # The cross-round edge-slab prefetch engaged.
    assert t["prefetch_hits"] > 0
    # Frontier builds default the age-triggered policy ON (4 * width).
    assert info_bt["executed"] > 0


def test_sssp_three_arms_bit_identical(sssp_arms):
    d_sc, _ = run_frontier("sssp", G, 0, mk=sssp_arms[0],
                           interpret=True)
    assert np.array_equal(d_sc, SSSP_REF)
    d_bt, info = run_frontier("sssp", G, 0, mk=sssp_arms[4],
                              interpret=True)
    assert np.array_equal(d_bt, SSSP_REF)
    assert info["tiers"]["batch_tasks"] == info["executed"]
    # Unreached vertices stay INF in every arm (the min-combine identity
    # depends on the sentinel surviving untouched).
    unreached = BFS_REF == INF
    assert np.array_equal(d_bt == INF, unreached)


def test_pagerank_exact_twin_and_float_tolerance():
    twin, deliveries = host_pagerank_push(G, m0=M0, reps=REPS)
    # Mass conserves exactly: every vertex seeded M0, every unit lands
    # in some rank.
    assert twin.sum() == G.n * M0
    r_sc, i_sc = run_frontier(
        "pagerank", G, width=0, m0=M0, reps=REPS, interpret=True,
        capacity=768,
    )
    assert np.array_equal(r_sc, twin)
    assert i_sc["relaxations"] == deliveries
    r_bt, _ = run_frontier(
        "pagerank", G, width=8, m0=M0, reps=REPS, interpret=True,
        capacity=768,
    )
    assert np.array_equal(r_bt, twin)
    # Within tolerance of real (float) PageRank at this threshold, and
    # the error SHRINKS as the fixed-point resolution grows (the
    # convergence direction - the approximation is the fold threshold,
    # not a bug).
    ref = host_pagerank(G, m0=1.0)
    err = np.abs(r_sc / M0 - ref).sum() / ref.sum()
    assert err < 0.2, err
    fine, _ = host_pagerank_push(G, m0=1 << 16, reps=REPS)
    err_fine = np.abs(fine / (1 << 16) - ref).sum() / ref.sum()
    assert err_fine < err


# ------------------------------------------------------------- mesh arms


@pytest.fixture
def mesh_kernel():
    """A batched BFS megakernel + 4-device sharded runner per mesh
    test (the steal build is the expensive compile here - deduped
    across tests by the program cache, not by fixture lifetime)."""
    from hclib_tpu.device.sharded import ShardedMegakernel
    from hclib_tpu.parallel.mesh import cpu_mesh

    mk = make_frontier_megakernel(
        _KINDS["bfs"](), G, width=4, capacity=256, interpret=True
    )
    smk = ShardedMegakernel(mk, cpu_mesh(4, axis_name="q"),
                            migratable_fns=[FR_EXPAND])
    return mk, smk


def test_mesh_bfs_bit_identical(mesh_kernel):
    mk, _ = mesh_kernel
    d, info = run_frontier(
        "bfs", G, 0, mk=mk, interpret=True,
        placement=MeshPlacement(4, policy="block"), quantum=2, window=4,
    )
    assert np.array_equal(d, BFS_REF)
    per_dev = np.asarray(info["per_device_counts"])[:, C_EXECUTED]
    assert int(per_dev.sum()) == info["executed"] > 0


def test_mesh_skewed_seeds_complete_by_stealing(mesh_kernel):
    """All seeds on device 0 (the natural single-source shape): dynamic
    EXPANDs migrate through the locality-ordered steal exchange, so the
    frontier spreads and the result stays exact."""
    mk, _ = mesh_kernel
    d, info = run_frontier(
        "bfs", G, 0, mk=mk, interpret=True,
        placement=MeshPlacement(4, policy="single", device=0),
        quantum=2, window=4,
    )
    assert np.array_equal(d, BFS_REF)
    per_dev = np.asarray(info["per_device_counts"])[:, C_EXECUTED]
    assert int((per_dev > 0).sum()) > 1, per_dev.tolist()


def test_mesh_sssp_and_pagerank():
    """SSSP distances min-combine and PageRank ranks sum-combine across
    per-device caches - both end exactly at the single-device result."""
    d, _ = run_frontier(
        "sssp", G, 0, width=4, interpret=True, capacity=256,
        placement=MeshPlacement(4, policy="block"), quantum=2, window=4,
    )
    assert np.array_equal(d, SSSP_REF)
    twin, _ = host_pagerank_push(G, m0=M0, reps=REPS)
    r, _ = run_frontier(
        "pagerank", G, width=4, m0=M0, reps=REPS, interpret=True,
        capacity=512, placement=MeshPlacement(4, policy="cyclic"),
        quantum=4, window=8,
    )
    assert np.array_equal(r, twin)


# ------------------------------------------- checkpoint mid-frontier


def test_checkpoint_mid_frontier_resume_bit_identical():
    fk = _KINDS["bfs"]()
    mk = make_frontier_megakernel(
        fk, G, width=4, capacity=256, interpret=True, checkpoint=True
    )
    iv = G.preset_values(mk.num_values, INF)
    iv[G.st_base] = 0

    def builder():
        b = TaskGraphBuilder()
        b.reserve_values(G.num_value_slots)
        for i in range(int(G.blk_count[0])):
            b.add(FR_EXPAND, args=[0, int(G.blk_start[0]) + i, 0,
                                   G.block_cnt(0, i)])
        return b

    data = {"indices": G.indices}
    iv_full, _, info_full = mk.run(builder(), data=dict(data),
                                   ivalues=iv.copy())
    full = np.asarray(iv_full)[G.st_base : G.st_base + G.n]
    assert np.array_equal(full.astype(np.int32), BFS_REF)

    _, _, q = mk.run(
        builder(), data=dict(data), ivalues=iv.copy(),
        quiesce=max(2, info_full["executed"] // 2),
    )
    assert q["quiesced"] and q["pending"] > 0
    # The device-side age gauge rode the export (tstats is part of the
    # quiesced info); live age counters re-arm from zero on resume - a
    # fresh entry cannot already be starved.
    assert q["tiers"]["max_starved_age"] >= 0
    iv_r, _, info_r = mk.resume(q["state"])
    assert info_r["pending"] == 0
    resumed = np.asarray(iv_r)[G.st_base : G.st_base + G.n]
    assert np.array_equal(resumed, full)


# ------------------------------- age-triggered firing policy (the fix)

PUMP, PTILE = 0, 1


def _pump_hot(ctx):
    """Dynamic spawner that keeps the ready ring CONTINUOUSLY hot: each
    PUMP immediately spawns one batch-routed PTILE and the next PUMP
    (no dependency), so under pure ring-drain-first firing the lane
    cannot fire until every pump has run - the starvation shape the age
    trigger exists to bound."""
    d = ctx.arg(0)

    @pl.when(d > 0)
    def _():
        ctx.spawn(PTILE, [d], nargs=1)
        ctx.spawn(PUMP, [d - 1], nargs=1)


def _ptile(ctx):
    ctx.set_value(0, ctx.value(0) + 1)


def _pump_mk(depth, lane_max_age, trace=4096, width=4):
    return Megakernel(
        kernels=[("pump", _pump_hot), ("ptile", _ptile)],
        route={"ptile": batch_of(_ptile, width=width)},
        capacity=256, num_values=16, succ_capacity=8,
        interpret=True, trace=trace, lane_max_age=lane_max_age,
    )


def _run_pump(mk, depth=24):
    b = TaskGraphBuilder()
    b.add(PUMP, args=[depth])
    iv, _, info = mk.run(b)
    assert int(iv[0]) == depth
    return info


def test_age_trigger_bounds_starvation_on_hot_ring():
    from hclib_tpu.device.tracebuf import TR_FIRE_AGE, TR_FIRE_BATCH, records_of

    depth = 24
    off = _run_pump(_pump_mk(depth, lane_max_age=0))
    on = _run_pump(_pump_mk(depth, lane_max_age=8))
    # Same work either way (results bit-identical by construction).
    assert on["executed"] == off["executed"] == 2 * depth + 1
    # Without the trigger the lane's first fire waits out the WHOLE pump
    # chain (ring never drains); with it the first batch fires mid-chain
    # and the device age gauge stays bounded by the knob.
    first_off = records_of(off["trace"], TR_FIRE_BATCH)[0, 1]
    first_on = records_of(on["trace"], TR_FIRE_BATCH)[0, 1]
    assert first_off > depth, (first_off, depth)
    assert first_on < first_off
    assert off["tiers"]["age_fires"] == 0
    assert on["tiers"]["age_fires"] > 0
    assert 0 < on["tiers"]["max_starved_age"] <= 8
    age_recs = records_of(on["trace"], TR_FIRE_AGE)
    assert len(age_recs) == on["tiers"]["age_fires"]
    assert (age_recs[:, 3] >= 8).all()  # b word: age at fire


def test_pr9_chained_spawner_bounded_age_with_knob():
    """PR 9's seeded chained-spawner scenario (PUMP dep-chained on its
    PTILE, tests/test_forasync_device.py) completes with bounded device
    age when lane_max_age is set, and bit-identically to the knob-off
    run."""

    def pump_chain(ctx):
        d = ctx.arg(0)

        @pl.when(d > 0)
        def _():
            nxt = ctx.spawn(PUMP, [d - 1], dep_count=1, nargs=1)
            ctx.spawn(PTILE, [d], succ0=nxt, nargs=1)

    def build(lane_max_age):
        return Megakernel(
            kernels=[("pump", pump_chain), ("ptile", _ptile)],
            route={"ptile": batch_of(_ptile, width=4)},
            capacity=128, num_values=16, succ_capacity=8,
            interpret=True, trace=4096, lane_max_age=lane_max_age,
        )

    infos = {}
    for age in (0, 4):
        b = TaskGraphBuilder()
        b.add(PUMP, args=[24])
        iv, _, infos[age] = build(age).run(b)
        assert int(iv[0]) == 24
    assert infos[0]["executed"] == infos[4]["executed"]
    assert infos[4]["tiers"]["max_starved_age"] <= 4
    # The detector gauge still sees the width-1 partial cadence (the
    # chain exposes no batch width to recover) - the knob bounds AGE,
    # it cannot invent same-kind concurrency.
    assert infos[4]["tiers"]["lane_partial_ages"][PTILE] >= 1


def test_lane_max_age_off_reproduces_today_bit_identically():
    """lane_max_age=0 (and unset) is the pre-knob scheduler: identical
    results AND identical dispatch counters on the starvation scenario."""
    base = _run_pump(_pump_mk(24, lane_max_age=0))
    unset = _run_pump(
        Megakernel(
            kernels=[("pump", _pump_hot), ("ptile", _ptile)],
            route={"ptile": batch_of(_ptile, width=4)},
            capacity=256, num_values=16, succ_capacity=8,
            interpret=True, trace=4096,
        )
    )
    def device_tiers(info):
        # build_s / cache_lookup_s are host-side program-cache timings,
        # not device counters - never comparable across arms.
        return {
            k: v for k, v in info["tiers"].items()
            if k not in ("build_s", "cache_lookup_s")
        }

    assert device_tiers(base) == device_tiers(unset)
    assert base["executed"] == unset["executed"]


def test_age_never_trips_on_static_tiles():
    """A static same-kind tile set (the forasync shape): the ring drains
    before any reasonable age bound, so the trigger never fires and the
    tier counters match the knob-off build exactly."""

    def run(age):
        mk = Megakernel(
            kernels=[("pump", _pump_hot), ("ptile", _ptile)],
            route={"ptile": batch_of(_ptile, width=4)},
            capacity=128, num_values=16, succ_capacity=8,
            interpret=True, lane_max_age=age,
        )
        b = TaskGraphBuilder()
        for k in range(8):
            b.add(PTILE, args=[k + 1])
        iv, _, info = mk.run(b)
        assert int(iv[0]) == 8
        return info

    on, off = run(16), run(0)
    assert on["tiers"]["age_fires"] == 0
    # build_s / cache_lookup_s are host-side program-cache timings,
    # never comparable across arms.
    skip = ("max_starved_age", "build_s", "cache_lookup_s")
    t_on = {k: v for k, v in on["tiers"].items() if k not in skip}
    t_off = {k: v for k, v in off["tiers"].items() if k not in skip}
    assert t_on == t_off


def test_starved_lane_beats_drain_priority_across_lanes():
    """With several batch-routed kinds, a starved lane must beat the
    lowest-F_FN drain priority, or its age is unbounded: lane 0 (80
    entries) monopolizes the drained ring for ~20 rounds while lane 1
    (4 entries, routed first, aging since round ~1) crosses the knob -
    the starved pass fires it mid-monopoly, keeping the gauge within
    N + nlanes - 1."""

    def bump_b(ctx):
        ctx.set_value(1, ctx.value(1) + 1)

    N_AGE = 90
    mk = Megakernel(
        kernels=[("a", _ptile), ("b", bump_b)],
        route={"a": batch_of(_ptile, width=4),
               "b": batch_of(bump_b, width=4)},
        capacity=256, num_values=16, succ_capacity=8,
        interpret=True, lane_max_age=N_AGE,
    )
    b = TaskGraphBuilder()
    for _ in range(80):
        b.add(0)
    for _ in range(4):  # seeded last => LIFO ring routes them FIRST
        b.add(1)
    iv, _, info = mk.run(b)
    assert int(iv[0]) == 80 and int(iv[1]) == 4
    t = info["tiers"]
    # Bounded at ~N by the starved pass (the drain-priority-only policy
    # would read ~104 here: lane 1 waits out lane 0's whole monopoly).
    # age_fires stays 0 - it counts RING jumps, and this jump was over
    # another lane's drain priority on an already-drained ring.
    assert t["max_starved_age"] <= N_AGE + 4, t
    assert t["age_fires"] == 0, t


def test_prebuilt_mk_refuses_other_graph_and_mesh_fuel(mesh_kernel):
    # Reuse the mesh fixture's build: the refusal is a host-side layout
    # check, so no fresh compile is needed.
    mk, _ = mesh_kernel
    n2, s2, d2, w2 = rmat_edges(4, efactor=4, seed=9)
    other = Graph(n2, s2, d2, w2)
    with pytest.raises(ValueError, match="frontier layout"):
        run_frontier("bfs", other, 0, mk=mk, interpret=True)
    with pytest.raises(ValueError, match="single-device"):
        run_frontier("bfs", G, 0, width=4, interpret=True, fuel=1000,
                     placement=MeshPlacement(4, policy="block"))


def test_lane_max_age_env_and_validation(monkeypatch):
    monkeypatch.setenv("HCLIB_TPU_LANE_MAX_AGE", "12")
    mk = _pump_mk(8, lane_max_age=None, trace=None)
    assert mk.lane_max_age == 12
    monkeypatch.setenv("HCLIB_TPU_LANE_MAX_AGE", "banana")
    with pytest.raises(ValueError):
        _pump_mk(8, lane_max_age=None, trace=None)
    monkeypatch.delenv("HCLIB_TPU_LANE_MAX_AGE")
    with pytest.raises(ValueError, match="lane_max_age"):
        _pump_mk(8, lane_max_age=-1, trace=None)
    # Frontier builds default it on at 4*width; env wins when set.
    fk = _KINDS["bfs"]()
    mk2 = make_frontier_megakernel(fk, G, width=8, interpret=True)
    assert mk2.lane_max_age == 32


# ---------------------------------------- resident XOR-hop ordering


def test_xor_hop_order_from_graphs():
    assert xor_hop_order(os.path.join(GRAPHS, "v5e_4.json")) in (
        [1, 2], [2, 1],
    )
    g8 = load_locality_file(os.path.join(GRAPHS, "v5e_8.json"))
    order = xor_hop_order(g8)
    assert sorted(order) == [1, 2, 4]  # always a FULL permutation
    with pytest.raises(ValueError, match="tpu devices"):
        xor_hop_order(g8, ndev=4)
    p = MeshPlacement.from_file(
        os.path.join(GRAPHS, "v5e_4.place_block.json")
    )
    assert sorted(p.xor_hop_order()) == [1, 2]
    assert MeshPlacement(4, policy="block").xor_hop_order() is None


def test_resident_hop_order_validation():
    from hclib_tpu.device.resident import ResidentKernel
    from hclib_tpu.parallel.mesh import cpu_mesh

    mk = Megakernel(kernels=[("noop", lambda ctx: None)], capacity=64,
                    num_values=16, succ_capacity=8, interpret=True)
    rk = ResidentKernel(mk, cpu_mesh(4, axis_name="q"), migratable_fns=[])
    # Graph-absent behavior unchanged: None maps to bit-position order.
    assert rk._hop_bits(None) == (0, 1)
    assert rk._hop_bits([2, 1]) == (1, 0)
    for bad in ([2], [3, 1], [1, 1], [1, 2, 4]):
        with pytest.raises(ValueError, match="permutation"):
            rk._hop_bits(bad)


from hclib_tpu.jaxcompat import has_mosaic_interpret  # noqa: E402

needs_mosaic = pytest.mark.skipif(
    not has_mosaic_interpret(),
    reason="needs pltpu.InterpretParams (jax >= 0.5)",
)


@needs_mosaic
def test_resident_frontier_bfs_with_graph_hop_order():
    """The resident runner consumes frontier descriptors (placement
    seeding is runner-agnostic data) and its XOR exchange takes the
    graph-ordered hop sequence: results bit-identical to the host
    reference with and without the reordering."""
    d, info = run_frontier(
        "bfs", G, 0, width=4, interpret=True, capacity=256,
        placement=MeshPlacement.from_file(
            os.path.join(GRAPHS, "v5e_4.place_block.json")
        ),
        runner="resident", quantum=8, window=4,
    )
    assert np.array_equal(d, BFS_REF)
    assert info["hop_order"] is not None
    d2, info2 = run_frontier(
        "bfs", G, 0, width=4, interpret=True, capacity=256,
        placement=MeshPlacement(4, policy="block"),
        runner="resident", quantum=8, window=4,
    )
    assert np.array_equal(d2, BFS_REF)  # graph-absent default unchanged
    assert info2["hop_order"] is None


# ------------------------------------------------------- metrics gauges


def test_metrics_edge_rate_and_age_gauges(bfs_w4_mk):
    _, info = run_frontier("bfs", G, 0, mk=bfs_w4_mk, interpret=True)
    info["elapsed_s"] = 0.5
    reg = hc.MetricsRegistry()
    reg.add_run_info("graph", info)
    m = reg.snapshot()["metrics"]
    assert m["graph.teps"] == info["edges"] / 0.5
    assert "graph.lane_max_starved_age.0" in m
    assert "graph.lane_occupancy.0" in m
