"""Multi-host layer (parallel/multihost.py): single-process graceful path +
global mesh over the virtual 8-device backend."""

import numpy as np
import pytest

from hclib_tpu.parallel import multihost as mh


_CLUSTER_VARS = (
    "JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
    "MEGASCALE_COORDINATOR_ADDRESS", "SLURM_STEP_NUM_TASKS",
    "OMPI_COMM_WORLD_SIZE", "PMI_SIZE", "TPU_WORKER_HOSTNAMES",
)


def test_single_process_degrades_gracefully(monkeypatch):
    for k in _CLUSTER_VARS:
        monkeypatch.delenv(k, raising=False)
    mh.init_multihost()  # no cluster env: must be a no-op
    assert mh.process_index() == 0
    assert mh.process_count() == 1
    assert not mh.is_multihost()


def test_global_mesh_covers_all_devices():
    import jax

    cpus = jax.devices("cpu")
    mesh = mh.global_mesh("dp", devices=cpus)
    assert int(np.prod(mesh.devices.shape)) == len(cpus) == 8
    mesh2 = mh.global_mesh("a", "b", axis_shape=(2, 4), devices=cpus)
    assert mesh2.devices.shape == (2, 4)
    with pytest.raises(ValueError):
        mh.global_mesh("a", "b", devices=cpus)  # multi-axis needs a shape
    with pytest.raises(ValueError):
        mh.global_mesh("a", "b", axis_shape=(3, 5), devices=cpus)
    with pytest.raises(ValueError):
        mh.global_mesh("a", axis_shape=(4,), devices=cpus)  # 4 != 8 devices


def test_sync_global_runs():
    mh.sync_global(tag=7)  # completes = every (single) participant arrived
    mh.sync_global(tag=7)  # second call hits the cached compiled barrier
    assert mh._local_barrier.cache_info().hits >= 1


def test_cluster_env_detection(monkeypatch):
    for k in _CLUSTER_VARS:
        monkeypatch.delenv(k, raising=False)
    assert not mh._cluster_env_present()
    monkeypatch.setenv("SLURM_STEP_NUM_TASKS", "1")
    assert not mh._cluster_env_present()  # single-task step: not a cluster
    monkeypatch.setenv("SLURM_NTASKS", "4")  # sbatch leak, no srun step
    assert not mh._cluster_env_present()
    monkeypatch.setenv("SLURM_STEP_NUM_TASKS", "4")
    assert mh._cluster_env_present()
    monkeypatch.delenv("SLURM_STEP_NUM_TASKS")
    monkeypatch.delenv("SLURM_NTASKS")
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host-0")
    assert not mh._cluster_env_present()
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host-0,host-1")
    assert mh._cluster_env_present()
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES")
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:1234")
    assert mh._cluster_env_present()


def test_sharded_megakernel_over_global_mesh():
    """The same sharded scheduler code runs over the multihost-global mesh
    (here: 8 virtual devices standing in for a pod's)."""
    from hclib_tpu.device.descriptor import TaskGraphBuilder
    from hclib_tpu.device.megakernel import Megakernel
    from hclib_tpu.device.sharded import ShardedMegakernel

    def bump(ctx):
        ctx.set_value(0, ctx.value(0) + ctx.arg(0))

    import jax

    mesh = mh.global_mesh("queues", devices=jax.devices("cpu"))
    ndev = int(np.prod(mesh.devices.shape))
    mk = Megakernel(kernels=[("bump", bump)], capacity=64, num_values=4,
                    succ_capacity=8, interpret=True)
    smk = ShardedMegakernel(mk, mesh, migratable_fns=[0])
    builders = [TaskGraphBuilder() for _ in range(ndev)]
    for i in range(4 * ndev):
        builders[0].add(0, args=[1])
    iv, _, info = smk.run(builders, steal=True, quantum=4, window=8)
    assert info["pending"] == 0
    assert int(iv[:, 0].sum()) == 4 * ndev


def test_two_process_real_multihost():
    """A REAL 2-process jax.distributed world driving global_mesh /
    sync_global / bulk_allreduce (multihost_worker.py asserts in both
    ranks; the reference cannot test its multi-node paths without a
    cluster at all - SURVEY section 4)."""
    import os
    import socket
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(repo, "tests", "multihost_worker.py")
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = str(s.getsockname()[1])
    n = 2
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo)
    env.pop("XLA_FLAGS", None)  # workers get their own plain device count
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(pid), str(n), port],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in range(n)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {pid} failed:\n{out}"
        assert f"rank {pid}: OK" in out, out
