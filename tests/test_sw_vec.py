"""Vectorized batched Smith-Waterman (device/sw_vec.py): exactness vs the
sequential reference DP."""

import numpy as np

from hclib_tpu.device.sw_vec import sw_score_one, sw_scores
from hclib_tpu.models.smithwaterman import random_seq, sw_seq


def test_single_pair_exact():
    for n, m, sa, sb in [(64, 64, 1, 2), (128, 96, 3, 4), (200, 300, 5, 6)]:
        a, b = random_seq(n, sa), random_seq(m, sb)
        assert sw_score_one(a, b) == int(sw_seq(a, b).max())


def test_batch_exact():
    B = 8
    A = np.stack([random_seq(96, i) for i in range(B)])
    Bs = np.stack([random_seq(96, 100 + i) for i in range(B)])
    got = list(np.asarray(sw_scores(A, Bs)))
    want = [int(sw_seq(A[i], Bs[i]).max()) for i in range(B)]
    assert got == want


def test_identical_sequences_score_perfect():
    a = random_seq(80, 7)
    assert sw_score_one(a, a) == 2 * 80  # MATCH=2 along the diagonal


def test_disjoint_alphabets_score_zero():
    a = np.zeros(64, np.int32)
    b = np.ones(64, np.int32)
    assert sw_score_one(a, b) == 0
