"""hclint build-time verifier (hclib_tpu/analysis): seeded-bad kernels
produce the expected findings with concrete witnesses, clean kernels
produce none, and the verify-off path is bit-identical. Everything here
is host-only composition - no Pallas build, no Mosaic, no device run
(except the one bit-identity pair, which runs the fast interpreter)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from hclib_tpu.analysis import (
    AnalysisError,
    check_layout,
    check_migratable,
    check_tile_windows,
    classify_megakernel,
)
from hclib_tpu.device.descriptor import (
    DESC_WORDS, F_DEP, F_FN, F_SUCC0, NO_TASK, TaskGraphBuilder,
)
from hclib_tpu.device.forasync_tier import Slab, TileKernel, \
    make_forasync_megakernel
from hclib_tpu.device.megakernel import BatchSpec, Megakernel
from hclib_tpu.device.workloads import FIB, make_fib_megakernel
from hclib_tpu.runtime import env as envmod
from hclib_tpu.runtime.checkpoint import CheckpointBundle, CheckpointError

N, TS = 64, 8


def _specs():
    return {
        "x": jax.ShapeDtypeStruct((N,), jnp.int32),
        "y": jax.ShapeDtypeStruct((N,), jnp.int32),
    }


def _tile_kernel(store_index):
    return TileKernel(
        loads=[Slab("xin", "x", lambda a: (pl.ds(a[1], TS),), (TS,))],
        stores=[Slab("yout", "y", store_index, (TS,))],
        compute=lambda ins: {"yout": ins["xin"] * 3 + 7},
        data_specs=_specs(),
    )


# ------------------------------------------------------- tile windows


def test_tile_windows_clean():
    tk = _tile_kernel(lambda a: (pl.ds(a[1], TS),))
    rep = check_tile_windows(tk, [N], [TS])
    assert rep.findings == []


def test_tile_race_concrete_witness():
    """The planted bug: a store index ignoring the tile args - every
    tile writes window [0, TS). The witness names the two colliding
    tile coordinates."""
    tk = _tile_kernel(lambda a: (pl.ds(0, TS),))
    rep = check_tile_windows(tk, [N], [TS])
    assert len(rep.findings) == 1
    f = rep.findings[0]
    assert f.rule == "tile-race" and f.severity == "error"
    assert f.witness["tile_a"] == (0,) and f.witness["tile_b"] == (TS,)
    assert f.witness["window_a"] == ((0, TS),)


def test_tile_race_caught_at_construction():
    """Even without bounds, the synthetic-batch shim catches the same
    bug at Megakernel construction (slot-distinct args map to one
    window)."""
    tk = _tile_kernel(lambda a: (pl.ds(0, TS),))
    with pytest.raises(AnalysisError, match="batch-race"):
        make_forasync_megakernel(tk, width=4, interpret=True)


def test_clean_tile_kernel_constructs():
    tk = _tile_kernel(lambda a: (pl.ds(a[1], TS),))
    mk = make_forasync_megakernel(tk, width=4, interpret=True)
    assert mk.verify and mk.analysis is not None
    assert mk.analysis.errors() == []


# -------------------------------------------------- prefetch protocol


def _protocol_spec(body, drain):
    return BatchSpec(body, width=4, prefetch=True, drain=drain)


def _mk_with(spec, scratch):
    return Megakernel(
        kernels=[("k", lambda ctx: None)],
        route={"k": spec},
        data_specs=_specs(),
        scratch_specs=scratch,
        capacity=64, num_values=16, succ_capacity=8,
        interpret=True, verify=True,
    )


def _pf_scratch():
    from jax.experimental.pallas import tpu as pltpu

    return {
        "buf": pltpu.VMEM((2, 4, TS), jnp.int32),
        "sem": pltpu.SemaphoreType.DMA((2, 4)),
    }


def _start_loads(ctx, buf, s, base, wait):
    from jax.experimental.pallas import tpu as pltpu

    cp = pltpu.make_async_copy(
        ctx.data["x"].at[pl.ds(base, TS)],
        ctx.scratch["buf"].at[buf, s],
        ctx.scratch["sem"].at[buf, s],
    )
    (cp.wait if wait else cp.start)()


def _good_body(ctx):
    for s in range(ctx.width):
        @pl.when(ctx.live(s) & (jnp.int32(s) >= ctx.prefetched))
        def _(s=s):
            _start_loads(ctx, ctx.buf, s, ctx.arg(s, 1), wait=False)
    for s in range(ctx.width):
        @pl.when(jnp.int32(s) < ctx.prefetch_count)
        def _(s=s):
            _start_loads(ctx, 1 - ctx.buf, s, ctx.next_arg(s, 1),
                         wait=False)
    for s in range(ctx.width):
        @pl.when(ctx.live(s))
        def _(s=s):
            _start_loads(ctx, ctx.buf, s, ctx.arg(s, 1), wait=True)


def _good_drain(ctx):
    for s in range(ctx.width):
        @pl.when(jnp.int32(s) < ctx.prefetched)
        def _(s=s):
            _start_loads(ctx, ctx.buf, s, ctx.arg(s, 1), wait=True)


def test_prefetch_protocol_clean():
    mk = _mk_with(_protocol_spec(_good_body, _good_drain), _pf_scratch())
    assert mk.analysis.errors() == []


def test_prefetch_start_count_mismatch():
    """Planted bug: the body ignores ctx.prefetch_count (issues no
    prefetch starts) - the tier's announcement contract is violated."""

    def body(ctx):
        for s in range(ctx.width):
            @pl.when(ctx.live(s) & (jnp.int32(s) >= ctx.prefetched))
            def _(s=s):
                _start_loads(ctx, ctx.buf, s, ctx.arg(s, 1), wait=False)
        for s in range(ctx.width):
            @pl.when(ctx.live(s))
            def _(s=s):
                _start_loads(ctx, ctx.buf, s, ctx.arg(s, 1), wait=True)

    with pytest.raises(AnalysisError, match="no residual DMA starts"):
        _mk_with(_protocol_spec(body, _good_drain), _pf_scratch())


def test_prefetch_missing_drain():
    """Planted bug: a drain that retires nothing - the unmatched DMA
    start is the witness."""
    with pytest.raises(AnalysisError, match="never drained"):
        _mk_with(
            _protocol_spec(_good_body, lambda ctx: None), _pf_scratch()
        )


def test_unwaited_start_without_prefetch():
    """A non-prefetch batch body that starts a DMA and never waits it
    would let the copy outlive its completions."""

    def body(ctx):
        for s in range(ctx.width):
            @pl.when(ctx.live(s))
            def _(s=s):
                _start_loads(ctx, 0, s, ctx.arg(s, 1), wait=False)

    with pytest.raises(AnalysisError, match="never waited"):
        _mk_with(BatchSpec(body, width=4), _pf_scratch())


# --------------------------------------- approximate-trace demotion


def test_exact_window_finding_survives_truncated_loop():
    """The ISSUE 14 demotion fix, refusal side: an unmatched DMA WAIT
    that happens BEFORE any arg-dependent loop cannot have its missing
    start hidden in the skipped iterations - it stays an error even
    though the body also contains a truncated loop (the old blanket
    demotion would have silenced it)."""

    def body(ctx):
        _start_loads(ctx, 0, 0, ctx.arg(0, 1), wait=True)  # no start!
        jax.lax.fori_loop(0, ctx.arg(0, 0), lambda i, c: c, 0)

    with pytest.raises(AnalysisError, match="no matching start"):
        _mk_with(BatchSpec(body, width=4), _pf_scratch())


def test_truncation_dependent_finding_demotes_to_info():
    """Demotion side: an unmatched START whose matching wait could sit
    inside the truncated window (the cholesky arg-dependent-loop case)
    demotes to one info note - construction succeeds."""

    def body(ctx):
        _start_loads(ctx, 0, 0, ctx.arg(0, 1), wait=False)
        jax.lax.fori_loop(0, ctx.arg(0, 0), lambda i, c: c, 0)

    mk = _mk_with(BatchSpec(body, width=4), _pf_scratch())
    assert mk.analysis.errors() == []
    notes = [f for f in mk.analysis.findings
             if f.rule == "shim-unsupported"]
    assert notes and "truncated" in notes[0].message


# ------------------------------------------------- value-slot races


def test_blind_value_overwrite_is_a_race():
    """Planted bug: every slot's per-slot context clobbers value slot 3
    without reading it - slots 0..width-2's outputs are lost."""

    def body(ctx):
        for s in range(ctx.width):
            @pl.when(ctx.live(s))
            def _(s=s):
                ctx.slot_ctx(s).set_value(3, jnp.int32(s))

    with pytest.raises(AnalysisError, match="blind overwrite"):
        _mk_with(BatchSpec(body, width=4), {})


def test_sequential_accumulator_is_clean():
    """ptile-style read-modify-write on one shared slot is the
    legitimate sequential pattern (slots run in order)."""

    def body(ctx):
        for s in range(ctx.width):
            @pl.when(ctx.live(s))
            def _(s=s):
                k = ctx.slot_ctx(s)
                k.set_value(0, k.value(0) + 1)

    mk = _mk_with(BatchSpec(body, width=4), {})
    assert mk.analysis.errors() == []


# ------------------------------------------------------------- layout


def test_layout_table_clean():
    assert check_layout(force=True).findings == []


def test_layout_catches_drift(monkeypatch):
    from hclib_tpu.analysis import layout as lay

    bad = dict(lay.LAYOUT)
    bad["DESC_WORDS"] = (17, ("hclib_tpu.device.descriptor",))
    monkeypatch.setattr(lay, "LAYOUT", bad)
    rep = lay.check_layout(force=True)
    assert any(
        f.rule == "layout" and f.witness.get("word") == "DESC_WORDS"
        and f.witness.get("actual") == 16
        for f in rep.findings
    )
    # restore the memo for later tests
    monkeypatch.undo()
    assert lay.check_layout(force=True).findings == []


# ----------------------------------------------- classification/reshard


def test_classification_and_describe():
    mk = make_fib_megakernel(128, interpret=True)
    classes = classify_megakernel(mk)
    assert classes == {"fib": "home-linked", "sum": "link-free"}
    d = mk.describe()
    assert d["kinds"]["fib"]["classification"] == "home-linked"
    assert d["kinds"]["fib"]["dispatch"] == "scalar"
    assert d["verify"] is True


def test_migratable_audit_and_suppression():
    mk = make_fib_megakernel(128, interpret=True)
    rep = check_migratable(mk, [FIB], "test")
    assert [f.rule for f in rep.actionable()] == ["reshard-class"]
    assert rep.actionable()[0].witness["classification"] == "home-linked"
    # The workload's own annotation (verify_suppress on the builder)
    # marks the intent: finding present, not actionable.
    rep2 = check_migratable(mk, [FIB], "test", suppress=mk.verify_suppress)
    assert rep2.actionable() == []
    assert [f.suppressed for f in rep2.findings] == [True]


def _linked_bundle():
    ndev, cap, V = 2, 8, 4
    tasks = np.zeros((ndev, cap, DESC_WORDS), np.int32)
    tasks[:, :, F_DEP] = -1  # tombstones by default
    for d in range(ndev):
        for i in range(2):
            tasks[d, i, F_DEP] = 0
            tasks[d, i, F_FN] = 0
            tasks[d, i, F_SUCC0] = 1  # linked!
    counts = np.zeros((ndev, 8), np.int32)
    counts[:, 2] = 2  # alloc
    counts[:, 3] = 2  # pending
    counts[:, 4] = 2  # value_alloc
    arrays = {
        "tasks": tasks,
        "succ": np.full((ndev, 4), NO_TASK, np.int32),
        "ready": np.full((ndev, cap), NO_TASK, np.int32),
        "counts": counts,
        "ivalues": np.zeros((ndev, V), np.int32),
    }
    meta = {
        "kernel_names": ["fib", "sum"],
        "kind_classes": {"fib": "home-linked", "sum": "link-free"},
        "ndev": ndev,
    }
    return CheckpointBundle("resident", meta, arrays)


def test_reshard_upfront_whole_program_diagnostic():
    """The classification consumer: reshard refuses with ONE diagnostic
    naming every offending kind (with its build-time class and row
    count) instead of the first bad row."""
    with pytest.raises(CheckpointError) as ei:
        _linked_bundle().reshard(1)
    msg = str(ei.value)
    assert "4 live row(s)" in msg
    assert "'fib' [home-linked]: 4 row(s)" in msg
    assert "successor links" in msg  # the example row's reason


# ------------------------------------------------ off-path guarantees


def test_verify_off_is_bit_identical():
    """verify=False compiles the SAME program: identical lowered text,
    identical results - the verifier is pure host analysis and can only
    raise."""
    outs = {}
    texts = {}
    for v in (False, True):
        mk = make_fib_megakernel(128, interpret=True)
        mk2 = Megakernel(
            kernels=list(zip(mk.kernel_names, mk.kernel_fns)),
            capacity=128, num_values=mk.num_values, succ_capacity=64,
            interpret=True, uses_row_values=True, verify=v,
        )
        b = TaskGraphBuilder()
        b.add(FIB, args=[10], out=0)
        iv, _, _ = mk2.run(b)
        outs[v] = int(iv[0])
        b2 = TaskGraphBuilder()
        b2.add(FIB, args=[10], out=0)
        tasks, succ, ring, counts = b2.finalize(
            capacity=128, succ_capacity=64
        )
        texts[v] = str(
            jax.jit(mk2._build_raw(64)).lower(
                jnp.asarray(tasks), jnp.asarray(succ), jnp.asarray(ring),
                jnp.asarray(counts),
                jnp.zeros(mk2.num_values, jnp.int32),
            ).as_text()
        )
    assert outs[False] == outs[True] == 55
    assert texts[False] == texts[True]


def test_verifier_never_invokes_mosaic():
    """The analysis package must stay host-only: its sources never
    build a kernel (pallas_call) nor touch the Mosaic interpreter
    (InterpretParams) - the off-path guarantee that verification can
    never change compiled programs."""
    import os as _os

    import hclib_tpu.analysis as pkg

    d = _os.path.dirname(pkg.__file__)
    for fname in sorted(_os.listdir(d)):
        if not fname.endswith(".py"):
            continue
        with open(_os.path.join(d, fname)) as f:
            src = f.read()
        assert "pallas_call" not in src, fname
        assert "InterpretParams" not in src, fname
        for line in src.splitlines():
            if line.strip().startswith(("import ", "from ")):
                assert "mosaic" not in line.lower(), (fname, line)


# ----------------------------------------------------------- env gate


def test_verify_env_gate(monkeypatch):
    def build():
        return Megakernel(
            kernels=[("noop", lambda ctx: None)],
            capacity=16, num_values=8, succ_capacity=8, interpret=True,
        )

    monkeypatch.setenv("HCLIB_TPU_VERIFY", "0")
    assert build().verify is False
    monkeypatch.setenv("HCLIB_TPU_VERIFY", "1")
    assert build().verify is True
    monkeypatch.delenv("HCLIB_TPU_VERIFY")
    assert build().verify is True  # default-on under pytest


def test_suppression_at_construction():
    tk = _tile_kernel(lambda a: (pl.ds(0, TS),))
    spec = BatchSpec(
        tk.batch_body, width=4, prefetch=True, drain=tk.batch_drain,
        verify_suppress=("batch-race",),
    )
    mk = Megakernel(
        kernels=[(tk.name, lambda ctx: None)],
        route={tk.name: spec},
        data_specs=tk.data_specs,
        scratch_specs=tk.batch_scratch(4),
        capacity=64, num_values=16, succ_capacity=8,
        interpret=True, verify=True,
    )
    sup = [f for f in mk.analysis.findings if f.suppressed]
    assert sup and sup[0].rule == "batch-race"
    assert mk.analysis.errors() == []


# -------------------------------------------------------- env registry


def test_env_registry_typed_parsing(monkeypatch):
    monkeypatch.setenv("HCLIB_TPU_QUIESCE_STRIDE", "7")
    assert envmod.env_int("HCLIB_TPU_QUIESCE_STRIDE") == 7
    monkeypatch.setenv("HCLIB_TPU_QUIESCE_STRIDE", "zap")
    with pytest.raises(ValueError, match="HCLIB_TPU_QUIESCE_STRIDE"):
        envmod.env_int("HCLIB_TPU_QUIESCE_STRIDE")
    assert envmod.env_int(
        "HCLIB_TPU_QUIESCE_STRIDE", malformed=1
    ) == 1
    monkeypatch.setenv("HCLIB_TPU_METRICS", "0")
    assert envmod.env_bool("HCLIB_TPU_METRICS") is False
    monkeypatch.setenv("HCLIB_TPU_STATS", "0")
    assert envmod.env_flag("HCLIB_TPU_STATS") is True  # legacy wart
    # legacy alias resolution
    monkeypatch.delenv("HCLIB_TPU_WORKERS", raising=False)
    monkeypatch.setenv("HCLIB_WORKERS", "3")
    assert envmod.env_int("HCLIB_TPU_WORKERS") == 3
    # name built dynamically so the lint registry rule (which scans
    # string constants tree-wide) doesn't see a phantom knob
    with pytest.raises(KeyError, match="not in the hclib_tpu env"):
        envmod.env_int("HCLIB_TPU_" + "NOT_A" + "_KNOB")
    rows = envmod.registry_table()
    assert any(r[0] == "HCLIB_TPU_VERIFY" for r in rows)


def test_lint_env_rules(tmp_path):
    import importlib.util
    import os as _os

    spec = importlib.util.spec_from_file_location(
        "lintmod",
        _os.path.join(_os.path.dirname(_os.path.dirname(
            _os.path.abspath(__file__))), "tools", "lint.py"),
    )
    lintmod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lintmod)
    repo = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    reg = lintmod.registry_names(repo)
    assert "HCLIB_TPU_VERIFY" in reg and "HCLIB_WORKERS" in reg
    bad = tmp_path / "bad.py"
    phantom = "HCLIB_TPU_" + "NEW" + "_KNOB"
    bad.write_text(
        "import os\n"
        "x = os.environ.get('HCLIB_TPU_TRACE', '')\n"
        f"y = os.environ['{phantom}']\n"
        "os.environ['HCLIB_TPU_TRACE'] = '1'\n"  # write: legal
    )
    probs = lintmod._check_python(str(bad), bad.read_text(), repo, reg)
    msgs = [m for _, m in probs]
    assert sum("raw os.environ read" in m for m in msgs) == 2
    assert any(phantom in m for m in msgs)


def test_hclint_cli_tree_is_clean(tmp_path):
    """Acceptance: the whole in-repo builder set - the curated 13
    builders plus the frontier/tenant programs and the protocol
    explorer - audits clean via tools/hclint.py, and the --json-out
    artifact carries machine-readable findings + certificates."""
    import importlib.util
    import json as _json
    import os as _os
    import sys as _sys

    saved = _os.environ.get("HCLIB_TPU_VERIFY")
    tools = _os.path.join(_os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))), "tools")
    _sys.path.insert(0, tools)
    try:
        spec = importlib.util.spec_from_file_location(
            "hclintmod", _os.path.join(tools, "hclint.py")
        )
        hclint = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(hclint)
        out = str(tmp_path / "hclint-findings.json")
        assert hclint.main(["--json-out", out]) == 0
        doc = _json.load(open(out))
        assert "protocols" in doc and "tenants:front_door" in doc
        assert doc["frontier:fr_bfs"]["certificates"]["bfs"][
            "status"] == "certified"
        assert doc["forasync:jacobi2d"]["certificates"]["fa_tile"][
            "status"] == "certified"
        for sec in doc.values():
            for f in sec["findings"]:
                assert {"rule", "severity", "kernel", "message",
                        "witness"} <= set(f)
    finally:
        _sys.path.remove(tools)
        if saved is None:
            _os.environ.pop("HCLIB_TPU_VERIFY", None)
        else:
            _os.environ["HCLIB_TPU_VERIFY"] = saved


def test_lint_trace_table_rule(tmp_path):
    """The one-table-edit invariant, enforced: a TR_* tag without a
    TAG_NAMES row (or never decoded by timeline.py) is a lint
    violation; the live tree is clean."""
    import importlib.util
    import os as _os

    spec = importlib.util.spec_from_file_location(
        "lintmod2",
        _os.path.join(_os.path.dirname(_os.path.dirname(
            _os.path.abspath(__file__))), "tools", "lint.py"),
    )
    lintmod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lintmod)
    repo = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    assert lintmod.check_trace_tables(repo) == []
    # Seed a drifted copy: a new tag with no name row and no decode.
    fake = tmp_path
    (fake / "hclib_tpu" / "device").mkdir(parents=True)
    (fake / "tools").mkdir()
    (fake / "hclib_tpu" / "device" / "tracebuf.py").write_text(
        "TR_ROUND_BEGIN = 1\n"
        "TR_PHANTOM = 99\n"
        "SC_LOST = 42\n"
        "TAG_NAMES = {TR_ROUND_BEGIN: 'round_begin'}\n"
        "SC_NAMES = {}\n"
    )
    (fake / "tools" / "timeline.py").write_text(
        "import tracebuf as tb\n"
        "x = tb.TR_ROUND_BEGIN\n"
    )
    probs = lintmod.check_trace_tables(str(fake))
    msgs = [m for _p, _l, m in probs]
    assert any("TR_PHANTOM has no TAG_NAMES row" in m for m in msgs)
    assert any("TR_PHANTOM has no decode row" in m for m in msgs)
    assert any("SC_LOST has no SC_NAMES row" in m for m in msgs)
