"""Workload apps: nqueens, qsort, cilksort, FFT (+ perf-regression harness).

These are the reference's performance-regression suite apps (BASELINE.md
rows; test/performance-regression/full-apps/) implemented against the new
API; every run() self-checks its output.
"""

import subprocess
import sys

import numpy as np

import hclib_tpu as hc
from hclib_tpu.models import fft, nqueens, sort


def test_nqueens_counts():
    for n in (5, 6, 8):
        r = nqueens.run(n, nworkers=4)
        assert r["value"] == nqueens.KNOWN_COUNTS[n]


def test_nqueens_cutoff_variants():
    assert nqueens.run(7, cutoff=1, nworkers=2)["value"] == 40
    assert nqueens.run(7, cutoff=7, nworkers=2)["value"] == 40


def test_qsort_sorts():
    r = sort.run(1 << 14, "qsort", threshold=512, nworkers=4)
    assert r["keys_per_sec"] > 0


def test_qsort_adversarial_inputs():
    for arr in (
        np.zeros(5000, np.int64),
        np.arange(5000, dtype=np.int64),
        np.arange(5000, dtype=np.int64)[::-1].copy(),
    ):
        expect = np.sort(arr.copy())
        hc.launch(sort.qsort_par, arr, 256, nworkers=4)
        np.testing.assert_array_equal(arr, expect)


def test_cilksort_sorts():
    r = sort.run(1 << 14, "cilksort", threshold=512, nworkers=4)
    assert r["keys_per_sec"] > 0


def test_cilksort_non_power_of_four():
    arr = np.random.default_rng(1).integers(0, 1000, 10_000).astype(np.int64)
    expect = np.sort(arr.copy())
    hc.launch(sort.cilksort, arr, 333, nworkers=4)
    np.testing.assert_array_equal(arr, expect)


def test_fft_matches_numpy():
    r = fft.run(1 << 12, threshold=1 << 9, nworkers=4)
    assert r["rel_err"] < 1e-8


def test_fft_device_path():
    r = fft.run(1 << 10, device=True)
    assert r["rel_err"] < 1e-2


def test_fft_rejects_non_power_of_two():
    import pytest

    with pytest.raises(ValueError):
        fft.fft_par(np.zeros(100))


def test_perf_regression_harness_quick(tmp_path):
    out = subprocess.run(
        [sys.executable, "tools/perf_regression.py", "--quick", "--trials", "1",
         "--log-dir", str(tmp_path),
         "--apps", "fib,nqueens,qsort,cilksort,fft,fib-ddt"],
        capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, out.stderr
    assert "fib" in out.stdout and "log written" in out.stdout
    # second run compares against the first
    out2 = subprocess.run(
        [sys.executable, "tools/perf_regression.py", "--quick", "--trials", "1",
         "--log-dir", str(tmp_path), "--tolerance", "1000", "--apps", "fib"],
        capture_output=True, text=True, timeout=300,
    )
    assert out2.returncode == 0, out2.stderr
    assert "vs prev" in out2.stdout
