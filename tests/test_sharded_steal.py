"""Bulk-synchronous work stealing across sharded megakernel queues
(device/sharded.py steal rounds; CPU interpret mode over an 8-device virtual
mesh)."""

import jax
import numpy as np
import pytest

from hclib_tpu.device.descriptor import TaskGraphBuilder
from hclib_tpu.device.megakernel import Megakernel
from hclib_tpu.device.sharded import ShardedMegakernel
from hclib_tpu.parallel.mesh import cpu_mesh

BUMP = 0


def _bump_kernel(ctx):
    # Location-independent counter task: accumulate arg0 into value slot 0
    # (per device; the host sums across devices).
    ctx.set_value(0, ctx.value(0) + ctx.arg(0))


def _make_mk(capacity=512):
    return Megakernel(
        kernels=[("bump", _bump_kernel)],
        capacity=capacity,
        num_values=4,
        succ_capacity=8,
        interpret=True,
    )


def _skewed_builders(ndev, ntasks):
    """All work lands on device 0's queue; the rest start empty."""
    builders = [TaskGraphBuilder() for _ in range(ndev)]
    for i in range(ntasks):
        builders[0].add(BUMP, args=[i + 1])
    return builders


def test_steal_rebalances_skewed_load():
    ndev, ntasks = 8, 200
    mesh = cpu_mesh(ndev, axis_name="queues")
    smk = ShardedMegakernel(_make_mk(), mesh, migratable_fns=[BUMP])
    iv, _, info = smk.run(
        _skewed_builders(ndev, ntasks), steal=True, quantum=8, window=16
    )
    assert info["pending"] == 0
    assert info["executed"] == ntasks
    total = int(iv[:, 0].sum())
    assert total == ntasks * (ntasks + 1) // 2
    per_dev = info["per_device_counts"][:, 5]  # C_EXECUTED
    assert int(per_dev.sum()) == ntasks
    # The point of stealing: the skewed load spread beyond device 0.
    assert int((per_dev > 0).sum()) >= 3, per_dev
    assert info["steal_rounds"] >= 1


def test_no_steal_keeps_static_partition():
    ndev, ntasks = 8, 64
    mesh = cpu_mesh(ndev, axis_name="queues")
    smk = ShardedMegakernel(_make_mk(), mesh, migratable_fns=[BUMP])
    iv, _, info = smk.run(_skewed_builders(ndev, ntasks), steal=False)
    per_dev = info["per_device_counts"][:, 5]
    assert int(per_dev[0]) == ntasks  # everything ran where it was placed
    assert int(iv[0, 0]) == ntasks * (ntasks + 1) // 2


def test_steal_with_balanced_load_still_correct():
    ndev, ntasks = 4, 120
    mesh = cpu_mesh(ndev, axis_name="queues")
    smk = ShardedMegakernel(_make_mk(), mesh, migratable_fns=[BUMP])
    builders = [TaskGraphBuilder() for _ in range(ndev)]
    for i in range(ntasks):
        builders[i % ndev].add(BUMP, args=[1])
    iv, _, info = smk.run(builders, steal=True, quantum=16, window=8)
    assert info["pending"] == 0
    assert int(iv[:, 0].sum()) == ntasks


def test_steal_respects_whitelist():
    """With no migratable kernels, steal rounds must not move anything -
    and dependency graphs (fib-style) stay correct under the round loop."""
    from hclib_tpu.device.workloads import FIB, make_fib_megakernel

    ndev = 4
    mesh = cpu_mesh(ndev, axis_name="queues")
    mk = make_fib_megakernel(capacity=2048, interpret=True)
    smk = ShardedMegakernel(mk, mesh)  # empty whitelist
    builders = []
    expected = {10: 55, 11: 89, 12: 144, 13: 233}
    ns = [10, 11, 12, 13]
    for d in range(ndev):
        b = TaskGraphBuilder()
        b.add(FIB, args=[ns[d]], out=0)
        builders.append(b)
    iv, _, info = smk.run(builders, steal=True, quantum=32, window=8)
    assert info["pending"] == 0
    for d in range(ndev):
        assert int(iv[d, 0]) == expected[ns[d]]
    per_dev = info["per_device_counts"][:, 5]
    assert all(int(x) > 1 for x in per_dev)  # each ran its own tree


@pytest.mark.skipif(jax.default_backend() != "tpu", reason="needs TPU")
def test_reentrant_staging_on_tpu():
    """Re-entrant kernel entries on REAL TPU: SMEM output windows do not
    inherit the aliased input's contents, so value slots carried between
    entries (row-owned fib blocks) depend on stage_all_values - interpret
    mode cannot catch this. (The tunnel cannot compile shard_map kernels,
    so this drives the bare kernel through a host re-entry loop, which is
    what the sharded round loop does on-device.)"""
    import jax.numpy as jnp

    from hclib_tpu.device.megakernel import C_PENDING
    from hclib_tpu.device.workloads import FIB, make_fib_megakernel

    # capacity far below the task total: freed rows must be rediscovered
    # from tombstones at each re-entry (live set is ~tree depth).
    mk = make_fib_megakernel(capacity=128, interpret=False)
    kernel = jax.jit(mk._build_raw(200, stage_all_values=True))
    b = TaskGraphBuilder()
    b.add(FIB, args=[13], out=0)  # 1129 dynamic tasks, ~6 entries
    tasks, succ, ring, counts = b.finalize(
        capacity=mk.capacity, succ_capacity=mk.succ_capacity
    )
    iv = np.zeros(mk.num_values, np.int32)
    for _ in range(64):
        outs = kernel(
            jnp.asarray(tasks), jnp.asarray(succ), jnp.asarray(ring),
            jnp.asarray(counts), jnp.asarray(iv),
        )
        tasks, ring, counts, iv = (np.asarray(o) for o in outs[:4])
        if counts[C_PENDING] == 0:
            break
    assert counts[C_PENDING] == 0
    assert int(iv[0]) == 233


def test_rounds_reuse_freed_rows():
    """fib(13) executes 1129 tasks through a 256-row table with quantum=32
    (~35 kernel re-entries): rows freed in earlier rounds must be
    rediscovered from completion tombstones, or the alloc cursor ratchets
    to overflow long before the graph finishes."""
    from hclib_tpu.device.workloads import FIB, make_fib_megakernel

    mesh = cpu_mesh(2, axis_name="queues")
    mk = make_fib_megakernel(capacity=256, interpret=True)
    smk = ShardedMegakernel(mk, mesh)
    builders = [TaskGraphBuilder(), TaskGraphBuilder()]
    builders[0].add(FIB, args=[13], out=0)
    builders[1].add(FIB, args=[12], out=0)
    iv, _, info = smk.run(builders, steal=True, quantum=32, window=8)
    assert info["pending"] == 0
    assert int(iv[0, 0]) == 233 and int(iv[1, 0]) == 144


def test_non_migratable_head_does_not_block_export():
    """A non-migratable task parked at the ring head must not pin the
    migratable backlog behind it: export compacts eligible candidates
    across the scanned window (ADVICE r1), so the BUMPs still diffuse."""
    ndev, ntasks = 8, 200
    mesh = cpu_mesh(ndev, axis_name="queues")
    mk = Megakernel(
        kernels=[("stay", lambda ctx: ctx.set_value(1, ctx.value(1) + 1)),
                 ("bump", _bump_kernel)],
        capacity=512, num_values=4, succ_capacity=8, interpret=True,
    )
    smk = ShardedMegakernel(mk, mesh, migratable_fns=[1])  # bump only
    builders = [TaskGraphBuilder() for _ in range(ndev)]
    builders[0].add(0)  # STAY lands at the head (owner pops LIFO from tail)
    for i in range(ntasks):
        builders[0].add(1, args=[i + 1])
    iv, _, info = smk.run(builders, steal=True, quantum=4, window=16)
    assert info["pending"] == 0
    assert info["executed"] == ntasks + 1
    assert int(iv[:, 0].sum()) == ntasks * (ntasks + 1) // 2
    assert int(iv[:, 1].sum()) == 1  # STAY ran exactly once, on its owner
    assert int(iv[0, 1]) == 1
    per_dev = info["per_device_counts"][:, 5]
    assert int((per_dev > 0).sum()) >= 3, per_dev


def _spawner_kernel(ctx):
    # Emit one migratable BUMP per step and chain to self: a generator
    # whose cumulative output far exceeds the table capacity.
    from jax.experimental import pallas as pl

    n = ctx.arg(0)
    ctx.spawn(1, [1])  # BUMP is fn 1 in this table

    @pl.when(n > 1)
    def _():
        ctx.spawn(0, [n - 1])


def test_steal_heavy_run_reuses_rows_everywhere():
    """A generator on device 0 emits 600 migratable tasks through 64-row
    tables: victims reclaim exported rows (tombstoned at export) and
    importers reuse freed rows instead of ratcheting the bump cursor -
    without either, cumulative traffic overflows 64 rows quickly."""
    ndev, ntasks = 8, 600
    mesh = cpu_mesh(ndev, axis_name="queues")
    mk = Megakernel(
        kernels=[("spawner", _spawner_kernel), ("bump", _bump_kernel)],
        capacity=64, num_values=4, succ_capacity=8, interpret=True,
    )
    smk = ShardedMegakernel(mk, mesh, migratable_fns=[1])
    builders = [TaskGraphBuilder() for _ in range(ndev)]
    builders[0].add(0, args=[ntasks])
    iv, _, info = smk.run(
        builders, steal=True, quantum=8, window=16, max_rounds=1 << 12
    )
    assert info["pending"] == 0
    assert info["executed"] == 2 * ntasks  # generators + bumps
    assert int(iv[:, 0].sum()) == ntasks
    per_dev = info["per_device_counts"][:, 5]
    # The serial generator limits backlog, so diffusion stays shallow; what
    # matters here is that migration happened at all while every table
    # stayed within 64 rows for 1200 cumulative tasks.
    assert int((per_dev > 0).sum()) >= 2  # work actually migrated
