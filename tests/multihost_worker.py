"""Subprocess body for the REAL multi-process multihost tests: drives
parallel/multihost.py's global_mesh / sync_global / bulk_allreduce in both
ranks of an actual 2-process jax.distributed world (VERDICT r4 #9: the
single-process fallback path was the only one exercised before). Gloo
backs the CPU cross-process collectives, so bulk_allreduce really crosses
process boundaries through XLA, not the coordination-service KV store."""

import sys

import numpy as np


def main() -> int:
    pid, n, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

    from hclib_tpu.parallel import multihost as mh

    # The explicit-argument init path (the cluster-env path is covered by
    # unit tests; here WE are the launcher).
    mh.init_multihost(
        coordinator_address=f"localhost:{port}",
        num_processes=n,
        process_id=pid,
    )
    assert mh.is_multihost()
    assert mh.process_index() == pid and mh.process_count() == n

    # Global mesh spans every process's devices.
    mesh = mh.global_mesh("dp")
    ndev = int(np.prod(mesh.devices.shape))
    nlocal = len(mh.local_devices())
    assert ndev == n * nlocal, (ndev, n, nlocal)

    # Cross-process barrier (multihost path: coordination-service barrier,
    # either through sync_global_devices or - on a backend that cannot run
    # multiprocess device computations - its structured KV-barrier
    # degradation; both are real rendezvous).
    mh.sync_global(tag=1)

    # bulk_allreduce: a real XLA all-reduce across processes. A backend
    # without multiprocess device computations (CPU pre-gloo jaxlib) must
    # raise the STRUCTURED capability error, never a dispatch-internal
    # one; capable backends must produce exact sums.
    def bulk(a, **kw):
        try:
            return mh.bulk_allreduce(a, **kw)
        except RuntimeError as e:
            assert str(e).startswith("UNIMPLEMENTED:"), e
            return None

    arr = np.arange(6, dtype=np.int64) + 100 * pid
    s = bulk(arr)
    if s is not None:
        want = np.arange(6) * n + 100 * sum(range(n))
        assert (s == want).all(), (s, want)
        mx = mh.bulk_allreduce(np.float32([pid + 1, 2 * pid]), op="max")
        assert mx[0] == n and mx[1] == 2 * (n - 1), mx
        # Repeat with the same shape: hits the cached compiled reducer.
        s2 = mh.bulk_allreduce(np.arange(6, dtype=np.int64))
        assert (s2 == np.arange(6) * n).all(), s2
    else:
        print(f"rank {pid}: bulk degraded (no multiprocess backend)",
              flush=True)

    mh.sync_global(tag=2)
    mh.shutdown()
    print(f"rank {pid}: OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
