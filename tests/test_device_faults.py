"""Device-level fault tolerance (ISSUE 2): in-quantum abort propagation,
seeded ICI chaos (DeviceFaultPlan - dropped/duplicated steal credits,
delayed transfers, dead chip), credit-timeout regeneration, heartbeat
detection + quarantine + task re-homing, and the host-side plumbing
(abort-on-cancel hooks, locality-graph quarantine).

Every mesh test is seeded and asserts byte-for-byte reproducibility of the
fault trace, matching the host FaultPlan's determinism contract.
"""

import threading
import time

import pytest

from hclib_tpu.device.descriptor import TaskGraphBuilder
from hclib_tpu.device.inject import StreamingMegakernel
from hclib_tpu.device.megakernel import Megakernel
from hclib_tpu.jaxcompat import has_mosaic_interpret
from hclib_tpu.runtime.resilience import (
    CancelledError,
    CancelScope,
    DeviceFaultPlan,
    StallError,
)

pytestmark = pytest.mark.chaos

needs_mosaic = pytest.mark.skipif(
    not has_mosaic_interpret(),
    reason="needs the Mosaic TPU interpret mode (pltpu.InterpretParams, "
           "jax >= 0.5): the ICI mesh kernels simulate remote DMA + "
           "semaphores on CPU",
)

BUMP = 0


def _bump_kernel(ctx):
    ctx.set_value(0, ctx.value(0) + ctx.arg(0))


def _bump_mk(capacity=128, num_values=1024):
    return Megakernel(
        kernels=[("bump", _bump_kernel)],
        capacity=capacity,
        num_values=num_values,
        succ_capacity=8,
        interpret=True,
    )


def _mesh_rk(ndev, plan=None, capacity=192, window=4, **kw):
    from hclib_tpu.device.resident import ResidentKernel
    from hclib_tpu.parallel.mesh import cpu_mesh

    return ResidentKernel(
        _bump_mk(capacity=capacity), cpu_mesh(ndev, axis_name="q"),
        migratable_fns=[BUMP], window=window, fault_plan=plan, **kw,
    )


def _skewed(ndev, ntasks, dev=0):
    builders = [TaskGraphBuilder() for _ in range(ndev)]
    for i in range(ntasks):
        builders[dev].add(BUMP, args=[i + 1])
    return builders


# ------------------------------------------------- streaming abort (host)


def test_streaming_abort_mid_stream_closes_ring_and_raises():
    """Satellite: abort() while run_stream is live. The ring must close
    (concurrent producers fail fast with the reason), run_stream must
    raise CancelledError per its docstring, and stats_dict must surface
    the abort latency measured through the in-kernel abort word."""
    sm = StreamingMegakernel(_bump_mk(capacity=512), ring_capacity=512)
    b = TaskGraphBuilder()
    b.add(BUMP, args=[1])
    closed_msgs = []

    def feeder():
        try:
            while True:
                sm.inject(BUMP, args=[1])
                time.sleep(0.002)
        except RuntimeError as e:
            closed_msgs.append(str(e))

    def aborter():
        time.sleep(0.25)
        sm.abort("operator abort")

    tf = threading.Thread(target=feeder)
    ta = threading.Thread(target=aborter)
    tf.start()
    ta.start()
    try:
        with pytest.raises(CancelledError, match="operator abort"):
            sm.run_stream(b, quantum=64, deadline_s=120.0)
    finally:
        ta.join()
        tf.join()
    assert closed_msgs and "operator abort" in closed_msgs[0]
    st = sm.stats_dict()
    assert st["aborts"] == 1
    assert st["abort_reason"] == "operator abort"
    # The kernel observed the ctl abort word inside its round loop.
    assert st["abort_observed_round"] is not None
    assert st["abort_observed_round"] >= 0
    assert st["abort_latency_s"] is not None and st["abort_latency_s"] < 60
    assert st["abort_drain_executed"] is not None
    # Closed for good: even direct injects fail now.
    with pytest.raises(RuntimeError, match="operator abort"):
        sm.inject(BUMP, args=[1])


def test_streaming_abort_on_cancel_scope():
    """Root-finish-style cancellation stops a RUNNING stream: cancelling
    the bound CancelScope fires the registered abort hook, the abort word
    lands in the kernel's round loop, and run_stream raises
    CancelledError instead of draining the open stream forever."""
    from hclib_tpu.modules.tpu import abort_on_cancel

    sm = StreamingMegakernel(_bump_mk(), ring_capacity=64)
    b = TaskGraphBuilder()
    b.add(BUMP, args=[1])
    scope = CancelScope()

    def canceller():
        time.sleep(0.2)
        scope.cancel("watchdog escalated")

    t = threading.Thread(target=canceller)
    t.start()
    try:
        with abort_on_cancel(sm, scope=scope):
            with pytest.raises(CancelledError, match="watchdog escalated"):
                sm.run_stream(b, quantum=16, deadline_s=120.0)
    finally:
        t.join()
    assert sm.stats_dict()["aborts"] == 1


def test_abort_on_cancel_replays_already_cancelled_scope():
    """A scope cancelled BEFORE the hook registers must still abort the
    stream (register-then-replay closes the check/register race)."""
    from hclib_tpu.modules.tpu import abort_on_cancel

    sm = StreamingMegakernel(_bump_mk(), ring_capacity=8)
    scope = CancelScope()
    scope.cancel("already dead")
    with abort_on_cancel(sm, scope=scope):
        pass
    with pytest.raises(RuntimeError, match="already dead"):
        sm.inject(BUMP)


def test_abort_hook_unregisters_after_stream():
    """A finished stream's hook must not linger: cancelling a scope later
    must not abort an unrelated fresh stream."""
    from hclib_tpu.runtime import resilience

    sm = StreamingMegakernel(_bump_mk(), ring_capacity=8)
    b = TaskGraphBuilder()
    b.add(BUMP, args=[5])
    scope = CancelScope()
    sm.close()
    iv, info = sm.run_stream(b, cancel_scope=scope)
    assert int(iv[0]) == 5
    n_before = len(resilience._abort_hooks)
    scope.cancel("late cancel")  # must be a no-op for the closed stream
    assert len(resilience._abort_hooks) == n_before
    assert sm.stats_dict()["aborts"] == 0


# --------------------------------------------------- DeviceFaultPlan (host)


def test_device_fault_plan_validation_and_env(monkeypatch):
    with pytest.raises(ValueError):
        DeviceFaultPlan(drop_credit_rate=1.5)
    with pytest.raises(ValueError):
        DeviceFaultPlan(credit_timeout=-1)
    monkeypatch.setenv("HCLIB_TPU_CREDIT_TIMEOUT", "7")
    monkeypatch.setenv("HCLIB_TPU_HEARTBEAT_TIMEOUT", "9")
    p = DeviceFaultPlan(drop_credit_rate=0.25)
    assert p.credit_timeout == 7
    assert p.heartbeat_timeout == 9
    assert p.enabled() and p.drops_credits() and not p.dups_credits()
    assert not DeviceFaultPlan().enabled()
    assert DeviceFaultPlan(dead_device=2).enabled()
    assert DeviceFaultPlan(dup_credit_at=[(1, 0, 1)]).dups_credits()


def test_plan_requires_steal_and_valid_dead_device():
    from hclib_tpu.device.resident import ResidentKernel
    from hclib_tpu.parallel.mesh import cpu_mesh

    with pytest.raises(ValueError, match="steal"):
        ResidentKernel(
            _bump_mk(), cpu_mesh(2, axis_name="q"), steal=False,
            fault_plan=DeviceFaultPlan(drop_credit_rate=0.1),
        )
    with pytest.raises(ValueError, match="dead_device"):
        ResidentKernel(
            _bump_mk(capacity=32), cpu_mesh(2, axis_name="q"),
            migratable_fns=[BUMP],
            fault_plan=DeviceFaultPlan(dead_device=5),
        )


def test_nonpof2_mesh_rejects_fault_plan():
    from hclib_tpu.device.ici_steal import ICIStealMegakernel
    from hclib_tpu.parallel.mesh import cpu_mesh

    with pytest.raises(ValueError, match="power-of-two"):
        ICIStealMegakernel(
            _bump_mk(), cpu_mesh(3, axis_name="d"), migratable_fns=[BUMP],
            fault_plan=DeviceFaultPlan(drop_credit_rate=0.1),
        )


def test_quarantine_locales_removes_dead_chip_paths():
    from hclib_tpu.parallel.mesh import (
        cpu_mesh, mesh_locality_graph, quarantine_locales,
    )

    g = mesh_locality_graph(cpu_mesh(4), nworkers=4)
    removed = quarantine_locales(g, [2])
    assert removed > 0
    dead = {
        l.id for l in g.locales
        if l.type == "tpu" and l.metadata.get("ordinal") == 2
    }
    for w in range(4):
        assert not (dead & set(g.pop_paths[w]))
        assert not (dead & set(g.steal_paths[w]))
        assert g.pop_paths[w] and g.steal_paths[w]  # paths stay usable
    assert any(l.is_special("DEAD") for l in g.locales)
    assert quarantine_locales(g, [2]) == 0  # idempotent


# ------------------------------------------------ mesh kernels (interpret)


@needs_mosaic
def test_abort_word_stops_resident_mesh_mid_run():
    """The host abort word stops a running 4-device mesh within one round
    (folded into the termination collective -> lockstep exit), leaving
    pending work abandoned instead of drained - and no hang, no raise."""
    ndev, ntasks = 4, 64
    rk = _mesh_rk(ndev)
    iv, _, info = rk.run(
        _skewed(ndev, ntasks), quantum=2, abort=True, max_rounds=512,
    )
    assert info["aborted"]
    assert info["rounds"] <= 2  # bounded abort latency, surfaced below
    assert info["pending"] > 0
    assert all(f["abort_round"] == 0 for f in info["fault_stats"])


@needs_mosaic
def test_abort_word_ici_ring_nonpof2():
    """The non-pof2 ring kernel polls the same abort word (folded into
    its ring allreduce)."""
    from hclib_tpu.device.ici_steal import ICIStealMegakernel
    from hclib_tpu.parallel.mesh import cpu_mesh

    sk = ICIStealMegakernel(
        _bump_mk(), cpu_mesh(3, axis_name="d"), migratable_fns=[BUMP],
        window=4,
    )
    iv, _, info = sk.run(
        _skewed(3, 30), quantum=2, abort=True, max_rounds=256,
    )
    assert info["aborted"]
    assert info["pending"] > 0
    assert info["steal_rounds"] <= 2


@needs_mosaic
def test_dead_chip_rehomes_and_survivors_drain_workload():
    """ACCEPTANCE: seeded dead chip on an 8-device interpret mesh. Every
    device holds work; device 3's scheduler dies at round 2 (wire stays
    up). The surviving 7 chips must complete the WHOLE workload - the
    dead chip's queue re-homed, totals conserved - instead of hanging;
    survivors must detect the frozen heartbeat and quarantine the chip;
    and the entire run must be byte-for-byte reproducible from the seed.
    """
    ndev, per, dead = 8, 6, 3
    plan = DeviceFaultPlan(
        seed=7, dead_device=dead, dead_round=2, heartbeat_timeout=2,
    )
    rk = _mesh_rk(ndev, plan, capacity=256, window=4)

    def build():
        builders = [TaskGraphBuilder() for _ in range(ndev)]
        v = 0
        for d in range(ndev):
            for _ in range(per):
                v += 1
                builders[d].add(BUMP, args=[v])
        return builders, v * (v + 1) // 2

    builders, total = build()
    iv, _, info = rk.run(builders, quantum=2, max_rounds=4096)
    assert info["pending"] == 0          # drained, not hung
    assert info["executed"] == ndev * per  # totals conserved
    assert int(iv[:, 0].sum()) == total    # every task's effect landed once
    fs = info["fault_stats"]
    assert fs[dead]["rehomed_rows"] > 0    # the dead queue moved out
    assert any(
        dead in f["quarantined"] for d, f in enumerate(fs) if d != dead
    ), fs
    detect = [
        f["dead_detected_round"] for d, f in enumerate(fs)
        if d != dead and f["dead_detected_round"] >= 0
    ]
    assert detect and min(detect) >= 2     # detected only after the death
    per_dev = info["per_device_counts"][:, 5]
    assert per_dev[dead] <= 2 * 2          # 2 alive rounds x quantum 2
    # Determinism: same seed, same mesh -> identical fault trace and
    # identical final task counts, twice.
    builders2, _ = build()
    iv2, _, info2 = rk.run(builders2, quantum=2, max_rounds=4096)
    assert info2["fault_stats"] == fs
    assert (info2["per_device_counts"] == info["per_device_counts"]).all()
    assert (iv2 == iv).all()


@needs_mosaic
def test_dropped_credit_regenerates_and_run_is_exact():
    """ACCEPTANCE (credit half): a dropped steal credit stalls its channel
    for credit_timeout rounds, then the writer regenerates it; the
    workload completes exactly and both endpoints' traces agree."""
    ndev, ntasks = 2, 40
    plan = DeviceFaultPlan(
        seed=3, drop_credit_at=[(1, 0, 1)], credit_timeout=2,
    )
    rk = _mesh_rk(ndev, plan, capacity=128, window=4)
    iv, _, info = rk.run(_skewed(ndev, ntasks), quantum=2, max_rounds=4096)
    assert info["pending"] == 0
    assert info["executed"] == ntasks
    assert int(iv[:, 0].sum()) == ntasks * (ntasks + 1) // 2
    fs = info["fault_stats"]
    assert fs[1]["credits_dropped"] == 1       # granter side of the fault
    assert fs[0]["credits_regenerated"] == 1   # starved writer recovered
    iv2, _, info2 = rk.run(_skewed(ndev, ntasks), quantum=2,
                           max_rounds=4096)
    assert info2["fault_stats"] == fs          # reproducible from the seed
    assert (iv2 == iv).all()


@needs_mosaic
def test_dropped_credit_without_regeneration_raises_stallerror():
    """credit_timeout=0 disables regeneration: the mesh must exit in
    lockstep and raise StallError NAMING the starved channel - never
    hang on the dead semaphore."""
    plan = DeviceFaultPlan(
        seed=3, drop_credit_at=[(1, 0, 1)], credit_timeout=0,
    )
    rk = _mesh_rk(2, plan, capacity=128, window=4)
    with pytest.raises(StallError, match="hop-0 .*granter device 1"):
        rk.run(_skewed(2, 40), quantum=2, max_rounds=4096)


@needs_mosaic
def test_duplicated_credit_tolerated_exactly():
    """A duplicated credit must not corrupt flow control: the surplus is
    absorbed and the exit drain still balances every semaphore."""
    ndev, ntasks = 2, 40
    plan = DeviceFaultPlan(
        seed=5, dup_credit_at=[(1, 0, 1)], credit_timeout=2,
    )
    rk = _mesh_rk(ndev, plan, capacity=128, window=4)
    iv, _, info = rk.run(_skewed(ndev, ntasks), quantum=2, max_rounds=4096)
    assert info["pending"] == 0
    assert info["executed"] == ntasks
    assert int(iv[:, 0].sum()) == ntasks * (ntasks + 1) // 2
    assert info["fault_stats"][1]["credits_duplicated"] == 1


@needs_mosaic
def test_delayed_xfers_only_slow_the_run():
    """Seeded transfer delays reorder migration but never lose work."""
    ndev, ntasks = 2, 40
    plan = DeviceFaultPlan(seed=11, delay_xfer_rate=0.5, credit_timeout=2)
    rk = _mesh_rk(ndev, plan, capacity=128, window=4)
    iv, _, info = rk.run(_skewed(ndev, ntasks), quantum=2, max_rounds=4096)
    assert info["pending"] == 0
    assert info["executed"] == ntasks
    assert int(iv[:, 0].sum()) == ntasks * (ntasks + 1) // 2
    assert sum(f["xfers_delayed"] for f in info["fault_stats"]) > 0
