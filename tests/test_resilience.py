"""Resilience subsystem tests (ISSUE 1): cancellation propagation,
deadline StallError, retry/backoff/quarantine, watchdog escalation,
chaos-plan determinism, and the seeded worker-kill + peer-crash
acceptance run. Every blocking scenario runs under its own deadline -
no test here can hang past it (the feature bounding its own tests)."""

import logging
import subprocess
import sys
import time
from types import SimpleNamespace

import numpy as np
import pytest

import hclib_tpu as hc
from hclib_tpu.models import fib, uts
from hclib_tpu.runtime.resilience import _hash01


# ---------------------------------------------------------------- cancel

def test_cancel_scope_skips_queued_tasks():
    """Cancelling a scope drops its queued tasks (they drain without
    running) and end_finish raises CancelledError."""
    ran = []

    def body():
        with pytest.raises(hc.CancelledError):
            with hc.finish() as fin:
                fin.scope.cancel("test cancel")
                # Spawns into the cancelled scope refuse; pre-queued tasks
                # are exercised below with tasks queued BEFORE the cancel.
                hc.async_(ran.append, -1)
        fin = hc.start_finish()
        hc.async_(lambda: time.sleep(0.05))
        for i in range(200):
            hc.async_(ran.append, i)
        time.sleep(0.01)
        fin.scope.cancel("drop the backlog")
        with pytest.raises(hc.CancelledError):
            hc.end_finish(fin)
        # Drain the cancelled backlog inline: skipped bodies count as
        # cancelled_tasks, and the finish quiesces without running them.
        while hc.yield_():
            pass

    rt = hc.Runtime(nworkers=2)
    rt.run(body, deadline_s=30)
    assert -1 not in ran
    assert len(ran) < 200  # the bulk was dropped, not executed
    assert rt.cancelled_tasks > 0
    assert rt.stats_dict()["resilience"]["cancelled_tasks"] > 0


def test_cancel_is_inherited_by_child_scopes():
    """A child finish of a cancelled parent is cancelled by inheritance."""

    def body():
        with pytest.raises(hc.CancelledError):
            with hc.finish() as outer:
                outer.scope.cancel("outer down")
                with hc.finish() as inner:
                    assert inner.scope.cancelled()  # by inheritance
                    hc.async_(lambda: None)  # must refuse
                pytest.fail("child scope accepted work under cancel")

    hc.launch(body, nworkers=2, deadline_s=30)


def test_cancel_wakes_blocked_waiter():
    """A context blocked in Promise.wait inside a cancelled scope wakes
    and raises promptly (event-driven, not a timeout)."""
    woke = []

    def body():
        p = hc.Promise()
        with pytest.raises(hc.CancelledError):
            with hc.finish() as fin:
                def waiter():
                    try:
                        p.future.wait()
                    except hc.CancelledError:
                        woke.append(time.monotonic())
                        raise

                hc.async_(waiter)
                time.sleep(0.1)  # let the waiter park
                t0 = time.monotonic()
                fin.scope.cancel("wake up")
                woke.append(t0)

    hc.launch(body, nworkers=2, deadline_s=30)
    assert len(woke) == 2
    t0, t_wake = min(woke), max(woke)
    assert t_wake - t0 < 5.0  # woken by the cancel, not any timeout


def test_spawn_into_cancelled_scope_raises():
    def body():
        with pytest.raises(hc.CancelledError):
            with hc.finish() as fin:
                fin.scope.cancel()
                hc.async_(lambda: None)

    hc.launch(body, nworkers=2, deadline_s=30)


# -------------------------------------------------------------- deadline

def test_deadline_raises_structured_stall_error():
    """A wedged launch surfaces as StallError (with a stats snapshot) in
    bounded time instead of hanging forever."""
    t0 = time.monotonic()
    with pytest.raises(hc.StallError) as ei:
        hc.launch(
            lambda: hc.Promise().future.wait(), nworkers=2, deadline_s=0.3
        )
    assert time.monotonic() - t0 < 10.0
    assert "deadline" in str(ei.value)
    assert ei.value.stats.get("nworkers") == 2  # snapshot attached


def test_promise_wait_timeout_is_recoverable():
    """Future.wait(timeout=) raises StallError but the runtime (and the
    promise) survive: a later put + wait succeeds."""

    def body():
        p = hc.Promise()
        with pytest.raises(hc.StallError):
            p.future.wait(timeout=0.2)
        p.put("late")
        return p.future.wait()

    assert hc.launch(body, nworkers=2, deadline_s=30) == "late"


def test_finish_timeout_cancels_and_raises():
    """finish(timeout=) bounds the join. The waiter must be adopted by a
    pool worker first (help-first would otherwise inline it onto the
    joining context, whose untimed inner wait parks past the finish
    timeout - the documented help_finish caveat)."""

    def body():
        hang = hc.Promise()
        with pytest.raises(hc.StallError):
            with hc.finish(timeout=0.4):
                hc.async_(lambda: hang.future.wait())
                time.sleep(0.15)  # a pool worker adopts + parks the waiter
        hang.put(None)  # unblock the cancelled waiter

    hc.launch(body, nworkers=2, deadline_s=30)


# ----------------------------------------------------------------- retry

def test_retry_heals_flaky_task():
    calls = [0]

    def flaky():
        calls[0] += 1
        if calls[0] < 3:
            raise ValueError("flake")
        return 42

    pol = hc.RetryPolicy(max_attempts=5, backoff_s=0, jitter=0)
    rt = hc.Runtime(nworkers=2)
    out = rt.run(lambda: hc.async_future(flaky, retry=pol).wait(),
                 deadline_s=30)
    assert out == 42
    assert calls[0] == 3
    assert rt.stats_dict()["resilience"]["retries"] == 2


def test_retry_deferred_backoff_keeps_finish_open():
    """A nonzero backoff defers the re-run through a timer; the finish
    must stay open (no early quiesce, no double check_out) until the
    retried attempt really completes."""
    calls = [0]

    def flaky():
        calls[0] += 1
        if calls[0] < 4:
            raise ValueError("flake")

    done = []
    pol = hc.RetryPolicy(max_attempts=8, backoff_s=0.005, multiplier=1.0,
                         jitter=0)

    def body():
        with hc.finish():
            hc.async_(flaky, retry=pol)
        done.append(calls[0])  # the finish joined AFTER the last attempt

    hc.launch(body, nworkers=2, deadline_s=30)
    assert done == [4]


def test_retry_exhausted_propagates_by_default():
    pol = hc.RetryPolicy(max_attempts=3, backoff_s=0, jitter=0)

    def body():
        with hc.finish():
            hc.async_(lambda: 1 / 0, retry=pol)

    with pytest.raises(ZeroDivisionError):
        hc.launch(body, nworkers=2, deadline_s=30)


def test_retry_quarantine_contains_poison_task():
    """quarantine=True: the run completes, the failure is recorded in
    stats_dict()['resilience'] with fn/attempts/error."""

    def poison():
        raise ValueError("always fails")

    pol = hc.RetryPolicy(max_attempts=2, backoff_s=0, jitter=0,
                         quarantine=True)
    rt = hc.Runtime(nworkers=2)

    def body():
        with hc.finish():
            hc.async_(poison, retry=pol)
            hc.async_(lambda: None)
        return "survived"

    assert rt.run(body, deadline_s=30) == "survived"
    res = rt.stats_dict()["resilience"]
    assert res["quarantined"] == 1
    q = res["quarantine"][0]
    assert q["fn"] == "poison" and q["attempts"] == 2
    assert "always fails" in q["error"]


def test_retry_policy_backoff_and_jitter_deterministic():
    pol = hc.RetryPolicy(max_attempts=5, backoff_s=0.1, multiplier=2.0,
                         jitter=0)
    assert pol.delay_s(1) == pytest.approx(0.1)
    assert pol.delay_s(3) == pytest.approx(0.4)
    a = hc.RetryPolicy(backoff_s=0.1, jitter=0.5, seed=3)
    b = hc.RetryPolicy(backoff_s=0.1, jitter=0.5, seed=3)
    assert [a.delay_s(1) for _ in range(4)] == [b.delay_s(1) for _ in range(4)]
    # Cancellation/stall signals never retry.
    assert not pol.should_retry(0, hc.CancelledError("x"))
    assert not pol.should_retry(0, hc.StallError("x"))
    assert pol.should_retry(0, ValueError("x"))


# -------------------------------------------------------------- watchdog

def test_watchdog_escalates_to_stall_error(caplog):
    """The escalation ladder's last rung cancels the root scope: a wedged
    launch fails with StallError after ~3 intervals instead of hanging."""
    t0 = time.monotonic()
    with caplog.at_level(logging.WARNING, logger="hclib_tpu.resilience"):
        with pytest.raises(hc.StallError) as ei:
            hc.launch(lambda: hc.Promise().future.wait(),
                      nworkers=1, watchdog_s=0.15)
    assert time.monotonic() - t0 < 30.0
    assert "watchdog" in str(ei.value)
    msgs = [r.getMessage() for r in caplog.records]
    assert any("watchdog" in m for m in msgs)  # rung 1: report via logging
    assert any("runtime stats" in m for m in msgs)  # rung 2: stats dump


def test_watchdog_shuts_down_promptly():
    """Event-based watchdog sleep: a 60s interval must not delay runtime
    teardown (the old time.sleep loop would park the thread for the full
    interval)."""
    rt = hc.Runtime(nworkers=2, watchdog_s=60.0)
    rt.run(lambda: None)
    rt._watchdog_thread.join(timeout=2.0)
    assert not rt._watchdog_thread.is_alive()


# ----------------------------------------------------------------- chaos

def test_fault_plan_hash_is_pure():
    assert _hash01(1, "task", 0) == _hash01(1, "task", 0)
    assert _hash01(1, "task", 0) != _hash01(2, "task", 0)
    assert 0.0 <= _hash01(5, "steal", 9) < 1.0


def test_chaos_same_seed_same_failure_trace():
    """The decision table is a pure function of the seed: two runs of the
    same workload with the same seed fire the same faults; a different
    seed fires a different set."""

    def run(seed):
        plan = hc.FaultPlan(seed=seed, task_failure_rate=0.25)
        v = hc.launch(
            fib.fib_finish, 10, 2, nworkers=2, fault_plan=plan,
            default_retry=hc.RetryPolicy(max_attempts=99, backoff_s=0,
                                         jitter=0),
            deadline_s=60,
        )
        assert v == 55
        return plan.trace_key()

    t1, t2, t3 = run(7), run(7), run(8)
    assert len(t1) > 0
    assert t1 == t2
    assert t1 != t3


def test_chaos_retry_with_backoff_under_load():
    """Injected faults + deferred (timer-based) retries across workers:
    the exact case that exposed the double-check_out and identity-leak
    wedges - must produce the right answer in bounded time."""
    plan = hc.FaultPlan(seed=11, task_failure_rate=0.15,
                        max_task_failures=50)
    out = fib.run(
        12, "finish", nworkers=2, fault_plan=plan,
        default_retry=hc.RetryPolicy(max_attempts=8, backoff_s=0.0005,
                                     jitter=0),
        deadline_s=60.0,
    )
    assert out["value"] == 144


def test_seeded_chaos_worker_kill_and_peer_crash():
    """Acceptance: ONE seeded FaultPlan kills a worker mid-UTS AND
    crashes a procworld peer; the traversal stays exact (worker identity
    re-binds) and the blocked peer op fails with a structured
    ProcWorldError - all in bounded time."""
    from test_procworld_unit import FakeClient
    from hclib_tpu.modules.procworld import ProcWorld, ProcWorldError

    plan = hc.FaultPlan(seed=5, kill_worker=1, kill_worker_after=1,
                        steal_delay_rate=0.1, steal_delay_s=0.001,
                        peer_crash_rank=1, peer_crash_after=0)
    kv = FakeClient(world_size=2)
    w0 = ProcWorld(_client=kv, _rank=0, _size=2, timeout_s=20.0)
    w1 = ProcWorld(_client=kv, _rank=1, _size=2, timeout_s=20.0,
                   fault_plan=plan)
    try:
        with w1._heap_lock:
            w1._heap["x"] = np.zeros(2, np.int32)
        expect = uts.count_seq(uts.T3)[0]
        t0 = time.monotonic()
        # On a loaded 1-vCPU host the whole (50-100 ms) traversal can
        # finish before the doomed worker's OS thread is ever scheduled,
        # so the kill is raced against the run: every attempt must be
        # exact, and the kill must land within a few attempts.
        deaths = 0
        for _ in range(5):
            rt = hc.Runtime(nworkers=4, fault_plan=plan)

            def main():
                n = hc.SumReducer()

                def visit(state, depth):
                    n.add(1)
                    for i in range(uts.num_children(uts.T3, state, depth)):
                        hc.async_(visit, uts.spawn_state(state, i),
                                  depth + 1)

                with hc.finish():
                    hc.async_(visit, uts.root_state(uts.T3.root_seed), 0)
                return n.gather()

            assert rt.run(main, deadline_s=120) == expect
            deaths += rt.worker_deaths
            if deaths:
                break
        with pytest.raises(ProcWorldError):
            w0.get(1, "x")
        assert time.monotonic() - t0 < 60.0
        assert deaths == 1
        key = plan.trace_key()
        assert ("kill_worker", 1) in key and ("peer_crash", 1) in key
    finally:
        w0.close()
        w1.close()


def test_procworld_barrier_names_dead_peer():
    """Unified tombstone protocol: a barrier against a tombstoned peer
    raises ProcWorldError naming the dead rank, not an anonymous
    DEADLINE_EXCEEDED."""
    from test_procworld_unit import FakeClient
    from hclib_tpu.modules.procworld import ProcWorld, ProcWorldError

    kv = FakeClient(world_size=2)
    w0 = ProcWorld(_client=kv, _rank=0, _size=2, timeout_s=2.0)
    try:
        kv.key_value_set_bytes("hcpw/dead/1", b"INTERNAL: dead peer")
        with pytest.raises(ProcWorldError, match="rank 1"):
            w0.barrier()
    finally:
        w0.close()


# ---------------------------------------------------------------- device

def test_streaming_megakernel_context_manager_closes_on_error():
    """__exit__ guarantees close() when the producer body raises, so an
    aborted producer never leaves the injection ring open (host-side
    logic only: no kernel involved)."""
    from hclib_tpu.device.inject import StreamingMegakernel

    sk = StreamingMegakernel(SimpleNamespace(), ring_capacity=8)
    with pytest.raises(RuntimeError, match="producer blew up"):
        with sk:
            sk.inject(fn=0)
            raise RuntimeError("producer blew up")
    assert sk._closed
    with pytest.raises(RuntimeError, match="stream closed"):
        sk.inject(fn=0)


def test_streaming_megakernel_abort_flag():
    from hclib_tpu.device.inject import StreamingMegakernel

    sk = StreamingMegakernel(SimpleNamespace(), ring_capacity=8)
    sk.abort("host gave up")
    with pytest.raises(RuntimeError, match="host gave up"):
        sk.inject(fn=0)


# ------------------------------------------------------------ chaos soak

def _run_soak(extra):
    import os

    return subprocess.run(
        [sys.executable, "tools/chaos_soak.py", "--timeout-s", "240"]
        + extra,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=280,
    )


def test_chaos_soak_smoke():
    """tools/chaos_soak.py smoke sweep: every scenario on one seed, with
    the tool's own hang enforcement; nonzero exit = regression."""
    p = _run_soak(["--seeds", "1"])
    assert p.returncode == 0, f"soak failed:\n{p.stdout}\n{p.stderr}"
    assert '"failures": 0' in p.stdout


@pytest.mark.slow
def test_chaos_soak_full():
    """Standalone soak: more seeds at soak scale (slow tier)."""
    p = _run_soak(["--seeds", "4", "--scale", "soak"])
    assert p.returncode == 0, f"soak failed:\n{p.stdout}\n{p.stderr}"
    assert '"failures": 0' in p.stdout
