"""Multi-tenant streaming front door (device/tenants.py + inject.py).

Host-side admission (quotas, token buckets, deadlines, poison ladder,
cancellation) tests run against a deterministic injected clock and the
numpy WRR reference model (``wrr_poll_reference`` - the executable spec
of the in-kernel poll), so every decision is a pure function of the
submission sequence. Device tests drive the real interpret-mode
streaming kernel: exact per-tenant totals, isolation under a poisoned +
greedy mix, and quiesce -> resume -> reshard conservation."""

import time

import numpy as np
import pytest

from hclib_tpu.device.descriptor import (
    RING_ROW,
    TEN_EXPIRED,
    TEN_ID,
    TaskGraphBuilder,
)
from hclib_tpu.device.inject import StreamingMegakernel
from hclib_tpu.device.megakernel import Megakernel
from hclib_tpu.device.tenants import (
    ADMIT_ACCEPTED,
    ADMIT_QUEUED,
    TC_CONSUMED,
    TC_DROPPED,
    TC_INSTALLED,
    TC_PAUSE,
    TC_TAIL,
    TC_WEIGHT,
    TenantSpec,
    TenantTable,
    TokenBucket,
    build_row,
    normalize_tenants,
    per_tenant_ring_counts,
    tenants_from_env,
    wrr_poll_reference,
)
from hclib_tpu.runtime.resilience import CancelScope, RetryPolicy

BUMP = 0


class FakeClock:
    """Monotonic test clock: admission decisions become a pure function
    of the submission sequence."""

    def __init__(self, t: float = 100.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _table(specs, region=16, clock=None):
    return TenantTable(specs, region, clock=clock or FakeClock())


def _row(i=0):
    return build_row(BUMP, [i])


def _drive(table, ring, polls=64, headroom=1 << 20, start_round=0):
    """One host entry + ``polls`` device rounds of the reference poll,
    echo absorbed - the deterministic stand-in for run_stream's inner
    loop."""
    tctl = table.pump(ring)
    installed = []
    for r in range(start_round, start_round + polls):
        installed += wrr_poll_reference(
            ring, tctl, table.region_rows, r, headroom
        )
    table.absorb(tctl)
    return installed


# ---------------------------------------------------------------- host


def test_admission_verdicts_accept_queue_and_every_reject_reason():
    """The typed Admission ladder: ACCEPTED under the in-flight budget,
    QUEUED over it, REJECTED("backlog") past queue_capacity,
    REJECTED("rate") when the bucket is dry, REJECTED("ring") at region
    exhaustion - checked cheapest-first, each reason machine-readable."""
    clock = FakeClock()
    t = _table(
        [TenantSpec("a", max_in_flight=2, queue_capacity=5,
                    rate=1.0, burst=8.0)],
        region=16, clock=clock,
    )
    ring = np.zeros((16, RING_ROW), np.int32)
    verdicts = [t.admit("a", _row(i)) for i in range(5)]
    assert [v.status for v in verdicts] == [
        ADMIT_ACCEPTED, ADMIT_ACCEPTED,            # within in-flight budget
        ADMIT_QUEUED, ADMIT_QUEUED, ADMIT_QUEUED,  # over it, backlog ok
    ]
    assert verdicts[0] and verdicts[2]            # both truthy (admitted)
    assert verdicts[0].accepted and verdicts[2].queued
    over = t.admit("a", _row())
    assert over.rejected and over.reason == "backlog"
    assert not over
    # Rate: burst exhausted (5 accepted + 1 rejected probe took none).
    clock.advance(0.0)
    t2 = _table([TenantSpec("b", rate=1.0, burst=2.0)], clock=clock)
    assert t2.admit("b", _row()) and t2.admit("b", _row())
    dry = t2.admit("b", _row())
    assert dry.rejected and dry.reason == "rate"
    clock.advance(1.0)  # one token refills at rate=1/s
    assert t2.admit("b", _row()).accepted
    # Ring: lifetime region budget (published + queued >= region_rows).
    t3 = _table([TenantSpec("c", queue_capacity=100)], region=8)
    for i in range(8):
        assert t3.admit("c", _row(i))
    full = t3.admit("c", _row())
    assert full.rejected and full.reason == "ring"
    # Unknown tenants raise, they don't silently reject - and negative
    # indices never wrap around to the last lane.
    with pytest.raises(KeyError):
        t.admit("nobody", _row())
    with pytest.raises(KeyError):
        t.admit(-1, _row())
    assert t3.stats()["c"]["rejected"] == 1


def test_token_bucket_deterministic_under_fake_clock():
    """Identical clock scripts produce identical token decisions -
    admission determinism is the token bucket's determinism."""
    def script(bucket, clock):
        out = []
        for dt in (0.0, 0.0, 0.3, 0.0, 0.5, 2.0, 0.0, 0.0):
            clock.advance(dt)
            out.append(bucket.try_take())
        return out

    runs = []
    for _ in range(2):
        clock = FakeClock()
        runs.append(script(TokenBucket(2.0, 2.0, clock), clock))
    assert runs[0] == runs[1]
    assert runs[0] == [True, True, False, False, True, True, True, False]
    b = TokenBucket(2.0, 2.0, FakeClock())
    b.try_take(2)
    assert b.wait_s(1) == pytest.approx(0.5)
    assert TokenBucket(0.0, 1.0, FakeClock()).wait_s(2) == float("inf")
    with pytest.raises(ValueError):
        TokenBucket(-1.0, 1.0)


def test_wrr_fairness_ratios_match_weights():
    """Saturated lanes drain in exact weight proportion: the WRR poll
    installs ``weight`` rows per lane per round, so a 4:2:1 spec yields
    4:2:1 installs over any whole number of rounds."""
    specs = [
        TenantSpec("gold", weight=4, queue_capacity=256),
        TenantSpec("silver", weight=2, queue_capacity=256),
        TenantSpec("bronze", weight=1, queue_capacity=256),
    ]
    t = _table(specs, region=64)
    ring = np.zeros((3 * 64, RING_ROW), np.int32)
    for lane in range(3):
        for i in range(56):  # 8 rounds' worth at the summed rate
            t.admit(lane, _row(i))
    installed = _drive(t, ring, polls=8)
    got = {tid: s["completed"] for tid, s in t.stats().items()}
    assert got == {"gold": 32, "silver": 16, "bronze": 8}
    # Install order interleaves lanes (no head-of-line monopoly) and the
    # rows carry their lane tag.
    lanes_seen = [int(r[TEN_ID]) for r in installed]
    assert set(lanes_seen) == {0, 1, 2}
    assert lanes_seen[:7].count(0) == 4  # first round: 4 gold, 2 silver...


def test_wrr_headroom_backpressure_not_overflow():
    """A tiny scheduler headroom bounds TOTAL installs per poll; the
    un-installed rows stay on the ring as host-visible backpressure
    (consumed cursor lags tail) instead of tripping an overflow."""
    t = _table([TenantSpec("a", weight=8), TenantSpec("b", weight=8)])
    ring = np.zeros((32, RING_ROW), np.int32)
    for lane in ("a", "b"):
        for i in range(8):
            t.admit(lane, _row(i))
    tctl = t.pump(ring)
    got = wrr_poll_reference(ring, tctl, t.region_rows, 0, headroom=3)
    assert len(got) == 3
    t.absorb(tctl)
    s = t.stats()
    assert s["a"]["completed"] + s["b"]["completed"] == 3
    assert s["a"]["in_flight"] + s["b"]["in_flight"] == 13  # still ringed


def test_deadline_admission_reject_drop_and_ring_mark():
    """The three expiry points: expired-at-admission rejects on the
    spot; expired-while-host-queued drops at the next pump (counted
    host-side); expired-while-published is marked on the ring row and
    lazily dropped by the poll (counted device-side) - and the
    conservation identity accepted == completed + expired holds."""
    clock = FakeClock()
    t = _table(
        [TenantSpec("a", weight=4, max_in_flight=4, queue_capacity=64)],
        clock=clock,
    )
    ring = np.zeros((16, RING_ROW), np.int32)
    # 1) expired at admission.
    dead = t.admit("a", _row(), deadline_at=clock() - 1.0)
    assert dead.rejected and dead.reason == "expired"
    # 2) four rows publish now; four more queue behind the budget.
    for i in range(8):
        assert t.admit("a", _row(i), deadline_at=clock() + 5.0)
    tctl = t.pump(ring)          # publishes the first 4
    assert tctl[0, TC_TAIL] == 4
    clock.advance(10.0)          # every deadline passes
    # 3) next pump: published rows get the TEN_EXPIRED mark for the
    # device to drop (the host-queued four stay parked: the in-flight
    # budget is full, so their lazy drop waits for freed budget).
    tctl = t.pump(ring)
    assert all(ring[i, TEN_EXPIRED] == 1 for i in range(4))
    installed = wrr_poll_reference(
        ring, tctl, t.region_rows, 0, headroom=100
    )
    assert installed == []       # all four dropped at the poll
    t.absorb(tctl)               # consumed cursor frees the budget...
    t.pump(ring)                 # ...and this pump drops the queued four
    s = t.stats()["a"]
    assert s["accepted"] == 8 and s["completed"] == 0
    assert s["expired"] == 8     # 4 device-dropped + 4 host-dropped
    assert s["rejected"] == 1    # the at-admission one
    assert s["accepted"] == s["completed"] + s["expired"]


def test_cancel_scope_deadline_chain_feeds_admission():
    """resolve_deadline precedence: explicit deadline_s beats the scope
    chain's nearest deadline beats the lane default; CancelScope
    deadlines inherit parent-to-child and the earliest wins."""
    clock = FakeClock()
    t = _table([TenantSpec("a", deadline_s=60.0)], clock=clock)
    parent = CancelScope().set_deadline(at=clock() + 5.0)
    child = CancelScope(parent=parent)
    child.set_deadline(at=clock() + 30.0)
    assert child.effective_deadline() == clock() + 5.0  # parent earlier
    assert t.resolve_deadline("a", None, child) == clock() + 5.0
    assert t.resolve_deadline("a", 1.0, child) == clock() + 1.0
    assert t.resolve_deadline("a", None, None) == clock() + 60.0
    assert not child.deadline_expired(now=clock() + 4.0)
    assert child.deadline_expired(now=clock() + 5.0)
    # Re-arm keeps the earliest; exactly-one-argument contract enforced.
    parent.set_deadline(at=clock() + 99.0)
    assert parent.deadline_t == clock() + 5.0
    with pytest.raises(ValueError):
        CancelScope().set_deadline()
    # A cancelled scope rejects at admission as "cancelled".
    child.cancel("user hit ^C")
    adm = t.admit("a", _row(), cancel_scope=child)
    assert adm.rejected and adm.reason == "cancelled"


def test_deadline_budget_cancels_lane_without_touching_siblings():
    """A tenant drowning in expirations (budget exhausted) gets its
    per-lane CancelScope cancelled at the pump; the sibling lane keeps
    flowing."""
    clock = FakeClock()
    t = _table(
        [TenantSpec("doomed", deadline_budget=3, queue_capacity=64),
         TenantSpec("fine", queue_capacity=64)],
        clock=clock,
    )
    ring = np.zeros((32, RING_ROW), np.int32)
    for i in range(4):
        t.admit("doomed", _row(i), deadline_at=clock() + 1.0)
    t.admit("fine", _row())
    clock.advance(5.0)
    _drive(t, ring, polls=2)  # pump drops the 4 expired, trips the budget
    _drive(t, ring, polls=1)  # budget observed -> lane scope cancels
    s = t.stats()
    assert s["doomed"]["expired"] >= 3
    adm = t.admit("doomed", _row())
    assert adm.rejected and adm.reason == "cancelled"
    assert t._lane("doomed").scope.cancelled()
    assert not t.scope.cancelled()           # parent untouched
    assert t.admit("fine", _row()).accepted  # sibling untouched
    assert "deadline budget" in str(t._lane("doomed").scope.reason)


def test_poison_ladder_throttles_then_quarantines_one_lane():
    """Terminal failures climb throttle (WRR weight clamps to 1) ->
    quarantine (lane paused, backlog dropped, submissions rejected);
    the sibling lane never notices. Cancellation never poisons."""
    t = _table(
        [TenantSpec("bad", weight=4, poison_throttle=2,
                    poison_quarantine=4),
         TenantSpec("good", weight=2)],
    )
    ring = np.zeros((32, RING_ROW), np.int32)
    for i in range(6):
        t.admit("bad", _row(i))
    from hclib_tpu.runtime.resilience import CancelledError
    t.report_failure("bad", CancelledError("control"))  # not poison
    assert t.stats()["bad"]["poisoned"] == 0
    t.report_failure("bad")
    t.report_failure("bad")
    tctl = t.pump(ring)
    assert tctl[0, TC_WEIGHT] == 1   # weight clamped: throttled
    assert t.stats()["bad"]["throttled"] == 1
    t.report_failure("bad")
    t.report_failure("bad")          # 4th terminal failure: quarantine
    s = t.stats()["bad"]
    assert s["quarantined"] == 1 and "poison" in s["quarantine_reason"]
    adm = t.admit("bad", _row())
    assert adm.rejected and adm.reason == "quarantined"
    # The paused lane's published residue is swept, not installed, and
    # the good lane keeps flowing.
    t.admit("good", _row())
    tctl = t.pump(ring)
    assert tctl[0, TC_PAUSE] == 1 and tctl[1, TC_PAUSE] == 0
    installed = wrr_poll_reference(ring, tctl, t.region_rows, 0, 100)
    assert [int(r[TEN_ID]) for r in installed] == [1]
    assert int(tctl[0, TC_DROPPED]) > 0
    assert int(tctl[0, TC_CONSUMED]) == int(tctl[0, TC_TAIL])  # swept
    t.absorb(tctl)
    assert t.stats()["good"]["completed"] == 1
    # Swept rows land in dropped (conservation holds for the paused
    # lane) and never pollute the install-latency reservoir.
    sb = t.stats()["bad"]
    assert sb["dropped"] == 6
    assert sb["accepted"] == (
        sb["completed"] + sb["expired"] + sb["dropped"]
    )
    assert t.latency_stats("bad")["n"] == 0
    assert t.drained()               # a quarantined lane can't wedge exit


def test_validator_retry_policy_and_control_signal_drops():
    """The lane validator retries per its RetryPolicy before poisoning;
    a control-signal failure (CancelledError) drops the row without
    climbing the ladder."""
    calls = {"n": 0}

    def flaky(row):
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("transient")

    t = _table(
        [TenantSpec("a", validator=flaky,
                    retry=RetryPolicy(max_attempts=3, backoff_s=0.0))],
    )
    ring = np.zeros((16, RING_ROW), np.int32)
    t.admit("a", _row())
    t.pump(ring)
    assert calls["n"] == 3                      # retried to success
    assert t.stats()["a"]["poisoned"] == 0
    from hclib_tpu.runtime.resilience import CancelledError

    def cancels(row):
        raise CancelledError("scope died")

    t2 = _table([TenantSpec("b", validator=cancels)])
    t2.admit("b", _row())
    t2.pump(ring)
    s = t2.stats()["b"]
    assert s["poisoned"] == 0 and s["dropped"] == 1


def test_per_tenant_cancel_drops_backlog_prospectively():
    """cancel(tenant) cancels that lane's scope, drops its host
    backlog, and pauses its lane at the next pump - completed work
    stays completed, siblings untouched."""
    t = _table(
        [TenantSpec("a", weight=2, max_in_flight=2, queue_capacity=64),
         TenantSpec("b")],
    )
    ring = np.zeros((32, RING_ROW), np.int32)
    for i in range(6):
        t.admit("a", _row(i))
    _drive(t, polls=1, ring=ring)    # 2 in flight install
    t.cancel("a", "tenant offboarded")
    s = t.stats()["a"]
    assert s["completed"] == 2 and s["queued"] == 0 and s["dropped"] == 4
    adm = t.admit("a", _row())
    assert adm.rejected and adm.reason == "cancelled"
    assert t.admit("b", _row()).accepted
    tctl = t.pump(ring)
    assert tctl[0, TC_PAUSE] == 1 and tctl[1, TC_PAUSE] == 0


def test_export_resume_conserves_per_tenant_counts():
    """The survivability core, host half: quiesce-export mid-stream,
    resume into a FRESH table, finish - per-tenant accepted/completed/
    expired counts and residue all conserved exactly."""
    clock = FakeClock()
    specs = lambda: [  # noqa: E731
        TenantSpec("x", weight=2, queue_capacity=64),
        TenantSpec("y", queue_capacity=64),
        TenantSpec("z", queue_capacity=64),
    ]
    t = _table(specs(), clock=clock)
    ring = np.zeros((3 * 16, RING_ROW), np.int32)
    sub = {"x": 10, "y": 7, "z": 4}
    for tid, n in sub.items():
        for i in range(n):
            t.admit(tid, _row(i))
    _drive(t, ring, polls=2)         # partial consumption
    done_before = {
        tid: s["completed"] for tid, s in t.stats().items()
    }
    state = t.export_state(ring)
    # A submit that loses the race with the quiesce cut gets a clean
    # "closed" verdict - never a silently-dropped ACCEPTED row.
    late = t.admit("x", _row(99))
    assert late.rejected and late.reason == "closed"
    # Residue is tenant-tagged and accounts for everything un-consumed.
    res_counts = per_tenant_ring_counts(state["ring_rows"])
    for i, (tid, n) in enumerate(sub.items()):
        assert res_counts.get(i, 0) == n - done_before[tid]
    # Resume into a fresh table + fresh ring: the next pump re-publishes
    # residue per lane from region slot 0.
    t2 = _table(specs(), clock=clock)
    t2.resume_from(state)
    ring2 = np.zeros((3 * 16, RING_ROW), np.int32)
    _drive(t2, ring2, polls=64)      # drain fully
    s2 = t2.stats()
    for tid, n in sub.items():
        assert s2[tid]["accepted"] == n
        assert s2[tid]["completed"] == n
        assert s2[tid]["expired"] == 0
    assert t2.drained()
    # resume_from reopens the front door the export closed.
    assert t2.admit("x", _row(0))
    # Lane-count mismatch is diagnosed, not silently misfiled.
    with pytest.raises(ValueError, match="lanes"):
        _table([TenantSpec("only")]).resume_from(state)
    # So is a same-count REORDERED roster: lane state is keyed by
    # index, so resuming x/y/z residue into y/x/z would silently
    # credit one tenant's work and quotas to another.
    t3 = _table([TenantSpec("y"), TenantSpec("x"), TenantSpec("z")],
                clock=clock)
    with pytest.raises(ValueError, match="roster"):
        t3.resume_from(state)
    # A tenant-LESS snapshot (plain stream: ring_rows only) is refused
    # rather than misfiling every row into lane 0.
    with pytest.raises(ValueError, match="without\\s+tenant lanes"):
        _table(specs(), clock=clock).resume_from(
            {"ring_rows": state["ring_rows"]}
        )
    # Oversized residue is diagnosed at resume, not a forever-wedge.
    t4 = _table([TenantSpec("only")], region=8)
    with pytest.raises(ValueError, match="exceeds"):
        t4.resume_from({
            "ring_rows": np.stack([_row(i) for i in range(10)]),
            "tctl": np.zeros((1, 8), np.int32),
            "tstats": np.zeros((1, 8), np.int32),
        })


def test_submit_wait_true_blocks_through_transient_rejection():
    """submit(wait=True) converts a dry token bucket into a bounded
    blocking wait; terminal rejections (quarantine) return immediately."""
    mk = _bump_mk()
    sm = StreamingMegakernel(
        mk, ring_capacity=32,
        tenants=[TenantSpec("a", rate=50.0, burst=1.0)],
    )
    assert sm.submit("a", BUMP, args=[1]).accepted   # burst token
    t0 = time.monotonic()
    adm = sm.submit("a", BUMP, args=[2], wait=True, wait_timeout_s=5.0)
    waited = time.monotonic() - t0
    assert adm.accepted
    assert 0.001 < waited < 2.0      # blocked for roughly a refill
    sm.tenants.quarantine("a", "test")
    t0 = time.monotonic()
    adm = sm.submit("a", BUMP, args=[3], wait=True, wait_timeout_s=5.0)
    assert adm.rejected and adm.reason == "quarantined"
    assert time.monotonic() - t0 < 1.0  # terminal: no blocking
    # Wait respects the submission's own deadline.
    sm2 = StreamingMegakernel(
        _bump_mk(), ring_capacity=32,
        tenants=[TenantSpec("b", rate=0.01, burst=1.0)],
    )
    sm2.submit("b", BUMP, args=[1])
    adm = sm2.submit(
        "b", BUMP, args=[2], wait=True, deadline_s=0.05,
        wait_timeout_s=30.0,
    )
    assert adm.rejected and adm.reason == "expired"


def test_normalize_and_env_spelling(monkeypatch):
    """tenants= plumbing: int, str/dict/TenantSpec sequences, False;
    the HCLIB_TPU_TENANTS* env spelling incl. weight override."""
    assert normalize_tenants(False) is None
    assert [s.id for s in normalize_tenants(3)] == ["t0", "t1", "t2"]
    specs = normalize_tenants(
        ["a", {"id": "b", "weight": 5}, TenantSpec("c")]
    )
    assert [s.id for s in specs] == ["a", "b", "c"]
    assert specs[1].weight == 5
    with pytest.raises(TypeError):
        normalize_tenants([42])
    with pytest.raises(ValueError):
        normalize_tenants(0)
    # bool is an int: True must not silently become one anonymous lane.
    with pytest.raises(ValueError, match="ambiguous"):
        normalize_tenants(True)
    monkeypatch.delenv("HCLIB_TPU_TENANTS", raising=False)
    monkeypatch.delenv("HCLIB_TPU_TENANT_WEIGHTS", raising=False)
    assert tenants_from_env() is None
    assert normalize_tenants(None) is None
    monkeypatch.setenv("HCLIB_TPU_TENANTS", "2")
    got = normalize_tenants(None)
    assert [s.id for s in got] == ["t0", "t1"]
    # Both set and disagreeing is a loud config error, not a silent
    # lane-count change.
    monkeypatch.setenv("HCLIB_TPU_TENANT_WEIGHTS", "4,2,1")
    with pytest.raises(ValueError, match="disagrees"):
        tenants_from_env()
    monkeypatch.delenv("HCLIB_TPU_TENANTS")
    monkeypatch.setenv("HCLIB_TPU_TENANT_RATE", "10")
    monkeypatch.setenv("HCLIB_TPU_TENANT_DEADLINE_S", "1.5")
    got = tenants_from_env()
    assert [s.weight for s in got] == [4, 2, 1]  # weights alone set N
    assert got[0].rate == 10.0 and got[2].deadline_s == 1.5
    # A spec'd table validates its shape contracts.
    with pytest.raises(ValueError, match="duplicate"):
        TenantTable([TenantSpec("a"), TenantSpec("a")], 16)
    with pytest.raises(ValueError, match="multiple of 8"):
        TenantTable([TenantSpec("a")], 12)
    with pytest.raises(ValueError, match="weight"):
        TenantSpec("w", weight=0)
    # Malformed env values raise loudly - a typo must not silently run
    # the stream as a single anonymous firehose (or drop a quota).
    monkeypatch.setenv("HCLIB_TPU_TENANTS", "three")
    monkeypatch.delenv("HCLIB_TPU_TENANT_WEIGHTS", raising=False)
    with pytest.raises(ValueError, match="HCLIB_TPU_TENANTS"):
        tenants_from_env()
    monkeypatch.setenv("HCLIB_TPU_TENANTS", "3")
    monkeypatch.setenv("HCLIB_TPU_TENANT_WEIGHTS", "4;2;1")
    with pytest.raises(ValueError, match="WEIGHTS"):
        tenants_from_env()
    monkeypatch.setenv("HCLIB_TPU_TENANT_WEIGHTS", "4,2,1")
    monkeypatch.setenv("HCLIB_TPU_TENANT_RATE", "fast")
    with pytest.raises(ValueError, match="RATE"):
        tenants_from_env()
    monkeypatch.delenv("HCLIB_TPU_TENANT_RATE")
    monkeypatch.setenv("HCLIB_TPU_TENANT_WEIGHTS", "4,0,1")
    with pytest.raises(ValueError, match="weights must be >= 1"):
        tenants_from_env()
    monkeypatch.setenv("HCLIB_TPU_TENANT_WEIGHTS", "4,,1")
    with pytest.raises(ValueError, match="comma-separated"):
        tenants_from_env()  # empty entry = typo, not a shorter roster
    monkeypatch.setenv("HCLIB_TPU_TENANT_WEIGHTS", "4,2,1")
    monkeypatch.setenv("HCLIB_TPU_TENANT_INFLIGHT", "2.9")
    with pytest.raises(ValueError, match="whole"):
        tenants_from_env()
    monkeypatch.delenv("HCLIB_TPU_TENANT_INFLIGHT")
    monkeypatch.setenv("HCLIB_TPU_TENANT_BURST", "16")
    with pytest.raises(ValueError, match="BURST needs"):
        tenants_from_env()  # burst without rate builds no bucket
    monkeypatch.delenv("HCLIB_TPU_TENANT_BURST")


def test_submit_wait_timeout_is_wall_clock_bounded():
    """wait_timeout_s is a WALL-clock bound: a frozen injected table
    clock (whose token bucket therefore never refills) must yield a
    bounded 'rate' rejection, not an unbounded spin."""
    sm = StreamingMegakernel(
        _bump_mk(), ring_capacity=32,
        tenants=TenantTable(
            [TenantSpec("a", rate=10.0, burst=1.0)], 32,
            clock=lambda: 0.0,
        ),
    )
    assert sm.submit("a", BUMP, args=[1]).accepted   # burst token
    t0 = time.monotonic()
    adm = sm.submit("a", BUMP, args=[2], wait=True, wait_timeout_s=0.3)
    assert adm.rejected and adm.reason == "rate"
    assert time.monotonic() - t0 < 5.0


# -------------------------------------------------------------- device


def _bump_mk(checkpoint=False, trace=None):
    def bump(ctx):
        ctx.set_value(0, ctx.value(0) + ctx.arg(0))

    return Megakernel(
        kernels=[("bump", bump)], capacity=128, num_values=4,
        succ_capacity=8, interpret=True, checkpoint=checkpoint,
        trace=trace,
    )


def _seed_builder():
    b = TaskGraphBuilder()
    b.add(BUMP, args=[1000])
    return b


def test_stream_wrr_exact_totals_and_stats_fold():
    """DEVICE: a 3-lane weighted stream executes every admitted task
    exactly once (value algebra proves it) and stats_dict names each
    tenant's counters - the StallError-names-the-tenant satellite."""
    sm = StreamingMegakernel(
        _bump_mk(), ring_capacity=96,
        tenants=[TenantSpec("gold", weight=4), TenantSpec("silver",
                 weight=2), TenantSpec("bronze")],
    )
    expect = 1000
    for i, tid in enumerate(("gold", "silver", "bronze")):
        for k in range(6 + 4 * i):
            sm.submit(tid, BUMP, args=[k + 1])
            expect += k + 1
    sm.close()
    iv, info = sm.run_stream(_seed_builder())
    assert int(iv[0]) == expect
    ten = info["tenants"]
    assert ten["gold"]["completed"] == 6
    assert ten["silver"]["completed"] == 10
    assert ten["bronze"]["completed"] == 14
    assert all(s["backlog"] == 0 for s in ten.values())
    sd = sm.stats_dict()
    assert sd["tenants"]["gold"]["accepted"] == 6
    # The drain exit closed the front door atomically: a submit that
    # raced it gets "closed", never an ACCEPTED row that will not run.
    late = sm.tenants.admit("gold", _row(1))
    assert late.rejected and late.reason == "closed"
    # inject() sugar routes through the default (first) lane.
    sm2 = StreamingMegakernel(
        _bump_mk(), ring_capacity=32, tenants=2,
    )
    sm2.inject(BUMP, args=[7])
    sm2.close()
    iv2, info2 = sm2.run_stream(_seed_builder())
    assert int(iv2[0]) == 1007
    assert info2["tenants"]["t0"]["completed"] == 1


def test_stream_greedy_and_poisoned_tenants_isolated():
    """DEVICE ISOLATION PROOF (single-chip half): one tenant poisoned
    via its validator, one greedy tenant pushing far past its quota -
    the victim lane still completes its exact totals."""
    def poison(row):
        raise RuntimeError("boom")

    sm = StreamingMegakernel(
        _bump_mk(), ring_capacity=96,
        tenants=[
            TenantSpec("bad", validator=poison, poison_throttle=1,
                       poison_quarantine=2),
            TenantSpec("greedy", max_in_flight=2, queue_capacity=4),
            TenantSpec("victim", weight=2),
        ],
    )
    expect = 1000
    for i in range(6):
        sm.submit("bad", BUMP, args=[10_000])  # would wreck the value
    greedy_admitted = 0
    greedy_rejected = 0
    for i in range(40):
        adm = sm.submit("greedy", BUMP, args=[1])
        if adm:
            greedy_admitted += 1
        else:
            greedy_rejected += 1
            assert adm.reason == "backlog"
    assert greedy_rejected > 0       # quota actually pushed back
    expect += greedy_admitted
    for k in range(12):
        assert sm.submit("victim", BUMP, args=[100])
        expect += 100
    sm.close()
    iv, info = sm.run_stream(_seed_builder())
    assert int(iv[0]) == expect      # no poison row ever executed
    ten = info["tenants"]
    assert ten["victim"]["completed"] == 12
    assert ten["greedy"]["completed"] == greedy_admitted
    assert ten["bad"]["completed"] == 0
    assert ten["bad"]["quarantined"] == 1
    assert ten["bad"]["poisoned"] >= 2


def test_stream_tenant_quiesce_resume_conserves_counts():
    """DEVICE SURVIVABILITY PROOF (stream half): quiesce mid-stream with
    3 tenants live, residue tenant-tagged, resume re-publishes per lane
    - per-tenant accepted/completed/expired conserved exactly and the
    final value is bit-identical to an uninterrupted run."""
    def fresh(n=64):
        return StreamingMegakernel(
            _bump_mk(checkpoint=True), ring_capacity=n,
            tenants=["x", "y", "z"],
        )

    sub = {"x": 9, "y": 6, "z": 3}
    expect = 1000 + sum((tid_i + 1) * n
                        for tid_i, n in enumerate(sub.values()))
    sm = fresh()
    for i, (tid, n) in enumerate(sub.items()):
        for _ in range(n):
            sm.submit(tid, BUMP, args=[i + 1])
    sm.quiesce(after_executed=4)
    iv, info = sm.run_stream(_seed_builder())
    assert info["quiesced"] is True
    st = info["state"]
    res = per_tenant_ring_counts(st["ring_rows"])
    ten_q = {i: int(st["tctl"][i, TC_INSTALLED]) for i in range(3)}
    for i, n in enumerate(sub.values()):
        assert ten_q[i] + res.get(i, 0) == n   # conserved at the cut
    # The bundle path refuses a reordered roster (lane state is keyed
    # by index) instead of silently crediting the wrong tenant.
    from hclib_tpu.runtime.checkpoint import (
        CheckpointError, restore_stream, snapshot_stream,
    )
    bundle = snapshot_stream(sm, info)
    assert bundle.meta["tenants"] == ["x", "y", "z"]
    reordered = StreamingMegakernel(
        _bump_mk(checkpoint=True), ring_capacity=64,
        tenants=["y", "x", "z"],
    )
    with pytest.raises(CheckpointError, match="roster"):
        restore_stream(bundle, reordered)
    plain = StreamingMegakernel(
        _bump_mk(checkpoint=True), ring_capacity=64,
    )
    with pytest.raises(CheckpointError, match="roster"):
        restore_stream(bundle, plain)
    sm2 = fresh()
    sm2.close()
    iv2, info2 = sm2.run_stream(resume_state=st)
    assert int(iv2[0]) == expect
    ten = info2["tenants"]
    for tid, n in sub.items():
        assert ten[tid]["accepted"] == n and ten[tid]["completed"] == n
    # Uninterrupted reference run: bit-identical final value.
    sm3 = fresh()
    for i, (tid, n) in enumerate(sub.items()):
        for _ in range(n):
            sm3.submit(tid, BUMP, args=[i + 1])
    sm3.close()
    iv3, _ = sm3.run_stream(_seed_builder())
    assert int(iv3[0]) == int(iv2[0])


def test_reshard_conserves_tenant_tagged_ring_residue():
    """SURVIVABILITY PROOF (mesh half, host-side): a resident bundle's
    per-device inject-ring residue carries TEN_ID on the row, so
    reshard(4 -> 2) and (4 -> 8) re-deal conserves per-tenant counts
    exactly - by construction, checked by the probe the chaos soak
    uses."""
    from hclib_tpu.device.descriptor import DESC_WORDS, F_HOME, NO_TASK
    from hclib_tpu.runtime.checkpoint import CheckpointBundle

    ndev, cap, R = 4, 8, 8
    rr = np.zeros((ndev, R, RING_ROW), np.int32)
    ic = np.zeros((ndev, 8), np.int32)
    lane_of = lambda d, i: (d + i) % 3  # noqa: E731 - mixed ownership
    for d in range(ndev):
        for i in range(4):
            rr[d, i] = build_row(BUMP, [d * 10 + i])
            rr[d, i, TEN_ID] = lane_of(d, i)
        ic[d, 0] = 4
    before = per_tenant_ring_counts(rr, ic)
    assert sum(before.values()) == 16
    # Minimal clean-quiesce resident bundle (live rows ready+link-free).
    tasks = np.zeros((ndev, cap, DESC_WORDS), np.int32)
    tasks[:, :, 2:4] = NO_TASK  # F_SUCC0/F_SUCC1
    tasks[:, :, F_HOME] = NO_TASK
    counts = np.zeros((ndev, 8), np.int32)
    counts[:, 1:4] = 1  # tail / alloc / pending
    counts[:, 4] = 2    # value_alloc
    b = CheckpointBundle("resident", {"ndev": ndev}, {
        "tasks": tasks, "succ": np.full((ndev, 8), -1, np.int32),
        "ready": np.zeros((ndev, cap), np.int32), "counts": counts,
        "ivalues": np.zeros((ndev, 16), np.int32),
        "ring_rows": rr, "ictl": ic,
    })
    for m in (2, 8):
        out = b.reshard(m)
        after = per_tenant_ring_counts(
            out.arrays["ring_rows"], out.arrays["ictl"]
        )
        assert after == before
    with pytest.raises(ValueError, match="ictl"):
        per_tenant_ring_counts(rr)  # 3-D residue needs the cursors


def test_resident_inject_rows_accept_tenant_tags():
    """Mesh-side plumbing: ResidentKernel.run's ring packer takes
    (fn, args[, out[, tenant]]) tuples and prebuilt RING_ROW rows; both
    land on the per-device ring with TEN_ID stamped (the full mesh run
    is the Mosaic-gated chaos soak's job)."""
    from hclib_tpu.device.descriptor import F_A0, F_FN, F_OUT
    from hclib_tpu.device.resident import pack_inject_rows

    tagged = build_row(BUMP, [5])
    tagged[TEN_ID] = 2
    ring, n = pack_inject_rows([(BUMP, (1,), 3, 1), tagged], R=4)
    assert n == 2
    assert ring[0, F_FN] == BUMP and ring[0, F_A0] == 1
    assert ring[0, F_OUT] == 3 and ring[0, TEN_ID] == 1
    assert (ring[1] == tagged).all()
    ic = np.zeros((1, 8), np.int32)
    ic[0, 0] = 2
    assert per_tenant_ring_counts(ring[None], ic) == {1: 1, 2: 1}
    with pytest.raises(ValueError, match="overflow"):
        pack_inject_rows([(BUMP, ())] * 5, R=4)


# ----------------------- deadline survival + mesh-wide tenancy (ISSUE 13)


def test_deadline_budget_survives_export_resume():
    """SATELLITE: deadlines export as REMAINING budget (TEN_DEADLINE_MS
    on the residue row, never a wall-clock instant) and re-arm against
    the resuming clock - a deadline storm straddling a cut reconciles
    exactly: rows with budget left complete, rows whose re-armed budget
    lapses expire, and nothing resumes deadline-free."""
    from hclib_tpu.device.descriptor import TEN_DEADLINE_MS

    clock = FakeClock()
    t = _table([TenantSpec("a", queue_capacity=64)], clock=clock)
    ring = np.zeros((16, RING_ROW), np.int32)
    # Three deadline classes: none, ample (60 s), tight (2 s).
    assert t.admit("a", _row(0))
    assert t.admit("a", _row(1), deadline_at=clock() + 60.0)
    assert t.admit("a", _row(2), deadline_at=clock() + 2.0)
    state = t.export_state(ring)  # nothing pumped: all three queued
    ms = sorted(int(r[TEN_DEADLINE_MS]) for r in state["ring_rows"])
    assert ms == [0, 2000, 60000]
    # Resume on a MUCH later clock: a wall-clock instant would have
    # doomed every row; remaining budget re-arms from now.
    clock.advance(100.0)
    t2 = _table([TenantSpec("a", queue_capacity=64)], clock=clock)
    t2.resume_from(state)
    clock.advance(5.0)  # only the tight row's re-armed 2 s lapses
    ring2 = np.zeros((16, RING_ROW), np.int32)
    _drive(t2, ring2, polls=4)
    s = t2.stats()["a"]
    assert s["accepted"] == 3
    assert s["completed"] == 2 and s["expired"] == 1, s
    assert s["accepted"] == s["completed"] + s["expired"]
    # The republished rows carry a CLEAN deadline word (stamped only at
    # export) - byte-parity with freshly admitted rows.
    assert all(int(r[TEN_DEADLINE_MS]) == 0 for r in ring2[:2])
    # A row already past its deadline AT export folds into the expired
    # count right there (doomed either way), not into the residue.
    t3 = _table([TenantSpec("b", queue_capacity=64)], clock=clock)
    assert t3.admit("b", _row(), deadline_at=clock() + 1.0)
    clock.advance(2.0)
    st3 = t3.export_state(np.zeros((16, RING_ROW), np.int32))
    assert st3["ring_rows"].shape[0] == 0
    assert t3.stats()["b"]["expired"] == 1


def test_mesh_table_routing_quota_and_isolation():
    """Mesh front door (the tentpole's host half): least-backlogged
    routing with explicit device override, the typed Admission ladder
    verbatim per replica, a MESH-WIDE rate bucket, and the poison
    ladder enforced on aggregate counts across devices."""
    from hclib_tpu.device.tenants import MeshTenantTable

    def boom(row):
        raise RuntimeError("poison")

    clock = FakeClock()
    mt = MeshTenantTable(
        [TenantSpec("a", weight=2, queue_capacity=64),
         TenantSpec("rated", rate=1.0, burst=2.0, queue_capacity=64),
         TenantSpec("poi", validator=boom, poison_throttle=1,
                    poison_quarantine=2, queue_capacity=64)],
        ndev=2, region_rows=16, clock=clock,
    )
    rings = np.zeros((2, 3 * 16, RING_ROW), np.int32)
    # Least-backlog routing alternates devices (ties to the lowest id).
    devs = [mt.submit("a", BUMP, args=[i]).device for i in range(4)]
    assert devs == [0, 1, 0, 1]
    # Explicit placement override.
    assert mt.submit("a", BUMP, args=[9], device=1).device == 1
    with pytest.raises(KeyError):
        mt.submit("a", BUMP, device=7)
    with pytest.raises(KeyError):
        mt.submit("nobody", BUMP)
    # The rate quota is MESH-WIDE: burst 2 admits two, the third
    # rejects "rate" no matter which replica it would land on.
    assert mt.submit("rated", BUMP, args=[1])
    assert mt.submit("rated", BUMP, args=[2])
    adm = mt.submit("rated", BUMP, args=[3])
    assert adm.rejected and adm.reason == "rate"
    # Aggregate poison: ONE terminal validator failure per device - no
    # single replica reaches a threshold, the mesh-wide count does.
    assert mt.submit("poi", BUMP, args=[1], device=0)
    assert mt.submit("poi", BUMP, args=[2], device=1)
    mt.pump(rings)   # validator poisons one row on each device
    mt.pump(rings)   # aggregate (2 >= quarantine) applies everywhere
    snap = mt.stats()["poi"]
    assert snap["quarantined"] == 1 and snap["poisoned"] == 2
    for d in range(2):
        adm = mt.submit("poi", BUMP, args=[0], device=d)
        assert adm.rejected and adm.reason == "quarantined"
    # Per-tenant conservation on the aggregate identity.
    for tid, s in mt.stats().items():
        assert s["accepted"] == (
            s["completed"] + s["expired"] + s["dropped"] + s["backlog"]
        ), (tid, s)


def test_mesh_export_reshard_resume_conserves_and_guards():
    """The mesh survivability core, host half: export mid-flight,
    resume on a DIFFERENT device count - per-tenant counts conserved
    exactly, residue re-dealt round-robin, roster mismatches and
    tenant-less states refused (never misfiled)."""
    from hclib_tpu.device.tenants import MeshTenantTable

    clock = FakeClock()
    specs = lambda: [  # noqa: E731
        TenantSpec("x", weight=2, queue_capacity=64),
        TenantSpec("y", queue_capacity=64),
        TenantSpec("z", queue_capacity=64),
    ]
    mt = MeshTenantTable(specs(), 4, 16, clock=clock)
    rings = np.zeros((4, 3 * 16, RING_ROW), np.int32)
    sub = {"x": 11, "y": 7, "z": 5}
    for tid, n in sub.items():
        for i in range(n):
            assert mt.submit(tid, BUMP, args=[i])
    # Partial consumption, then the cut.
    tctl = mt.pump(rings)
    for d in range(4):
        wrr_poll_reference(rings[d], tctl[d], 16, 0, 1 << 20)
    mt.absorb(tctl)
    done_at_cut = {t: mt.stats()[t]["completed"] for t in sub}
    mt2, state = mt.reshard(rings, 2)
    res = per_tenant_ring_counts(state["ring_rows"], state["ictl"])
    for i, (tid, n) in enumerate(sub.items()):
        assert done_at_cut[tid] + res.get(i, 0) == n
    # A submit racing the cut gets a clean "closed" verdict.
    late = mt.submit("x", BUMP, args=[0])
    assert late.rejected and late.reason == "closed"
    # Drain on the 2-device successor: per-tenant totals exact.
    rings2 = np.zeros((2, 3 * 16, RING_ROW), np.int32)
    for r in range(64):
        tctl = mt2.pump(rings2)
        for d in range(2):
            wrr_poll_reference(rings2[d], tctl[d], 16, r, 1 << 20)
        mt2.absorb(tctl)
        if mt2.drained():
            break
    assert mt2.drained()
    for tid, n in sub.items():
        s = mt2.stats()[tid]
        assert s["accepted"] == n and s["completed"] == n, (tid, s)
    # Roster mismatch / tenant-less state / lane-count guards.
    bad = MeshTenantTable(
        [TenantSpec("y"), TenantSpec("x"), TenantSpec("z")], 2, 16,
        clock=clock,
    )
    with pytest.raises(ValueError, match="roster"):
        bad.resume_from(state)
    with pytest.raises(ValueError, match="tctl"):
        MeshTenantTable(specs(), 2, 16, clock=clock).resume_from(
            {"ring_rows": state["ring_rows"], "ictl": state["ictl"]}
        )
    with pytest.raises(ValueError, match="lanes"):
        MeshTenantTable([TenantSpec("only")], 2, 16,
                        clock=clock).resume_from(state)


def test_mesh_tenants_env_and_normalize(monkeypatch):
    """HCLIB_TPU_MESH_TENANTS spelling: lane count, shared per-lane
    knobs, weight-count agreement, and RAISE-on-malformed semantics."""
    from hclib_tpu.device.tenants import (
        mesh_tenants_from_env,
        normalize_mesh_tenants,
    )

    for var in ("HCLIB_TPU_MESH_TENANTS", "HCLIB_TPU_TENANT_WEIGHTS",
                "HCLIB_TPU_TENANT_RATE"):
        monkeypatch.delenv(var, raising=False)
    assert mesh_tenants_from_env() is None
    assert normalize_mesh_tenants(None) is None
    assert normalize_mesh_tenants(False) is None
    monkeypatch.setenv("HCLIB_TPU_MESH_TENANTS", "3")
    specs = normalize_mesh_tenants(None)
    assert [s.id for s in specs] == ["t0", "t1", "t2"]
    monkeypatch.setenv("HCLIB_TPU_TENANT_WEIGHTS", "4,2,1")
    assert [s.weight for s in normalize_mesh_tenants(None)] == [4, 2, 1]
    monkeypatch.setenv("HCLIB_TPU_TENANT_WEIGHTS", "4,2")
    with pytest.raises(ValueError, match="lanes"):
        mesh_tenants_from_env()
    monkeypatch.delenv("HCLIB_TPU_TENANT_WEIGHTS")
    monkeypatch.setenv("HCLIB_TPU_MESH_TENANTS", "nope")
    with pytest.raises(ValueError, match="MESH_TENANTS"):
        mesh_tenants_from_env()


def test_resident_mesh_tenancy_construction_and_off_path():
    """Tenancy-off mesh builds carry ZERO tenant state - no lane count,
    no tctl inputs/outputs, no region partition (the structural half of
    the bit-identity acceptance; the compiled-run half needs Mosaic and
    rides the chaos job) - and the tenant-enabled construction
    validates every shape up front, before any kernel builds."""
    from hclib_tpu.device.resident import ResidentKernel
    from hclib_tpu.device.tenants import MeshTenantTable
    from hclib_tpu.parallel.mesh import cpu_mesh

    rk_off = ResidentKernel(
        _bump_mk(checkpoint=True), cpu_mesh(2, axis_name="q"),
        inject=True,
    )
    assert rk_off.T == 0 and rk_off.tenant_specs is None
    assert rk_off.region_rows == 0
    rk = ResidentKernel(
        _bump_mk(checkpoint=True), cpu_mesh(2, axis_name="q"),
        inject=True, tenants=["x", "y", "z"], ring_capacity=96,
    )
    assert rk.T == 3
    assert rk.ring_capacity == rk.T * rk.region_rows
    assert rk.region_rows % 8 == 0
    with pytest.raises(ValueError, match="inject=True"):
        ResidentKernel(_bump_mk(), cpu_mesh(2, axis_name="q"),
                       tenants=2)
    builders = [TaskGraphBuilder() for _ in range(2)]
    # Rows enter only through the table on a tenant mesh.
    with pytest.raises(ValueError, match="MeshTenantTable"):
        rk.run(builders, inject_rows=[[(BUMP, (1,))]])
    # Table shape must match the mesh exactly.
    with pytest.raises(ValueError, match="mismatch"):
        rk.run(builders,
               tenant_table=MeshTenantTable([TenantSpec("x")], 2, 16))
    # A tenancy-off mesh refuses a table outright.
    with pytest.raises(ValueError, match="tenant-enabled"):
        rk_off.run(builders,
                   tenant_table=MeshTenantTable(
                       [TenantSpec("x")], 2, 16))


needs_mosaic = pytest.mark.skipif(
    not __import__(
        "hclib_tpu.jaxcompat", fromlist=["has_mosaic_interpret"]
    ).has_mosaic_interpret(),
    reason="needs the Mosaic TPU interpret mode (jax >= 0.5)",
)


@needs_mosaic
@pytest.mark.chaos
def test_resident_mesh_tenant_wrr_and_quiesce_reshard():
    """DEVICE ACCEPTANCE (mesh half): the in-kernel WRR tenant poll on
    a 4-device mesh installs every routed admission exactly once (value
    algebra proves it), a mid-stream quiesce exports deadline-stamped
    tenant-tagged residue + aggregate counter blocks, and a reshard to
    2 devices resumes with per-tenant totals conserved exactly."""
    import numpy as np

    from hclib_tpu.device.resident import ResidentKernel
    from hclib_tpu.device.tenants import MeshTenantTable
    from hclib_tpu.parallel.mesh import cpu_mesh
    from hclib_tpu.runtime.checkpoint import (
        restore_resident, snapshot_resident,
    )

    specs = lambda: ["gold", "std", "bg"]  # noqa: E731

    def make(ndev):
        return ResidentKernel(
            _bump_mk(checkpoint=True), cpu_mesh(ndev, axis_name="q"),
            migratable_fns=[BUMP], homed=False, window=4, inject=True,
            tenants=specs(), ring_capacity=96,
        )

    def table_for(rk):
        return MeshTenantTable(
            rk.tenant_specs, rk.ndev, rk.region_rows
        )

    def seed(ndev):
        bs = [TaskGraphBuilder() for _ in range(ndev)]
        for b in bs:
            b.add(BUMP, args=[0])
        return bs

    sub = {"gold": 10, "std": 6, "bg": 4}
    # Full run: every admitted row installs + executes exactly once.
    rk = make(4)
    table = table_for(rk)
    expect = 0
    for i, (tid, n) in enumerate(sub.items()):
        for _ in range(n):
            assert table.submit(tid, BUMP, args=[i + 1])
            expect += i + 1
    iv, _, info = rk.run(seed(4), quantum=2, max_rounds=4096,
                         tenant_table=table)
    assert info["pending"] == 0
    assert int(np.asarray(iv)[:, 0].sum()) == expect
    ten = info["tenants"]
    for tid, n in sub.items():
        assert ten[tid]["accepted"] == n and ten[tid]["completed"] == n
    # Quiesce mid-stream, reshard 4 -> 2, resume: totals conserved.
    rk2 = make(4)
    t2 = table_for(rk2)
    for i, (tid, n) in enumerate(sub.items()):
        for _ in range(n):
            assert t2.submit(tid, BUMP, args=[i + 1])
    _, _, info_q = rk2.run(seed(4), quantum=1, max_rounds=4096,
                           quiesce=1, tenant_table=t2)
    assert info_q["quiesced"], info_q
    assert "tctl" in info_q["state"]
    bundle = snapshot_resident(rk2, info_q)
    assert bundle.meta["tenants"] == specs()
    rk3 = make(2)
    iv3, _, info3 = restore_resident(
        bundle, rk3, quantum=4, max_rounds=4096,
        tenant_table=table_for(rk3),
    )
    assert info3["pending"] == 0
    total3 = int(np.asarray(iv3)[:, 0].sum())
    assert total3 == expect, (total3, expect)
