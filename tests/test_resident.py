"""The unified resident kernel (device/resident.py): general migration of
dependency-bearing tasks, steal + PGAS + AM + injection in ONE kernel,
device-side remote atomics and locks.

Reference parity targets: the thief taking ANY task - dependency edges
included - from a victim's deque (/root/reference/src/hclib-deque.c:75-106),
one scheduler serving every module's locales
(/root/reference/inc/hclib-module.h:79-97), and the SHMEM AMO + lock layer
(/root/reference/modules/openshmem/src/hclib_openshmem.cpp:572-600,124-134).
"""

import jax
import numpy as np
import pytest

from hclib_tpu.device.descriptor import TaskGraphBuilder
from hclib_tpu.device.megakernel import Megakernel, VBLOCK
from hclib_tpu.device.resident import ResidentKernel, lock_block_slots
from hclib_tpu.device.workloads import FIB, SUM, make_fib_megakernel
from hclib_tpu.models.fib import fib_seq, task_count
from hclib_tpu.parallel.mesh import cpu_mesh, make_mesh

BUMP = 0


def _exec_count(n):
    """Descriptors the kernel executes for fib(n): every FIB node plus one
    SUM continuation per internal node (task_count counts FIB calls only)."""
    t = task_count(n)
    return t + (t - 1) // 2


def _bump_kernel(ctx):
    ctx.set_value(0, ctx.value(0) + ctx.arg(0))


def _bump_mk(capacity=256, num_values=512):
    return Megakernel(
        kernels=[("bump", _bump_kernel)],
        capacity=capacity,
        num_values=num_values,
        succ_capacity=8,
        interpret=True,
    )


def _fib_mk(capacity=512):
    # Migration reserves one result slot per row at the top of the value
    # buffer: size num_values = row blocks + host slots + result slots.
    return make_fib_megakernel(
        capacity=capacity,
        interpret=True,
        num_values=VBLOCK * capacity + 16 + capacity,
    )


# ---------------------------------------------------------------- migration


def test_skewed_fib_rebalances_across_devices():
    """THE round-3 gap: a skewed dynamic fib graph - every task carrying
    successor links - rebalances over the in-kernel steal. Device 0 holds
    fib(9) (109 FIB tasks); >= 4 of 8 devices must execute work; the
    value and net executed count must be exact. (fib(13)/753 tasks passes
    identically - interpret-mode wall time scales with task count, so the
    suite runs the smallest tree that still spreads over half the mesh:
    fib(9), 109 FIB tasks.)"""
    ndev, n = 8, 9
    mk = _fib_mk(capacity=160)
    rk = ResidentKernel(
        mk, cpu_mesh(ndev, axis_name="q"),
        migratable_fns={FIB: (), SUM: (0, 1)},
        window=8, am_window=8,
    )
    builders = [TaskGraphBuilder() for _ in range(ndev)]
    builders[0].add(FIB, args=[n], out=0)
    iv, _, info = rk.run(builders, quantum=16)
    assert info["pending"] == 0
    # exactly one device's slot 0 holds the result (root may migrate whole)
    assert int(iv[:, 0].sum()) == fib_seq(n)
    assert info["executed"] == _exec_count(n)
    per_dev = info["per_device_counts"][:, 5]
    assert int((per_dev > 0).sum()) >= 4, per_dev


def test_homed_chain_two_devices_exact():
    """2-device fib: stolen FIB tasks leave proxies whose successors fire
    only when the remote-completion AM lands; totals and the value must be
    exact even with migration forced aggressively (window > backlog)."""
    ndev, n = 2, 8
    mk = _fib_mk(capacity=96)
    rk = ResidentKernel(
        mk, cpu_mesh(ndev, axis_name="q"),
        migratable_fns={FIB: (), SUM: (0, 1)},
        window=16, am_window=8,
    )
    builders = [TaskGraphBuilder() for _ in range(ndev)]
    builders[0].add(FIB, args=[n], out=0)
    iv, _, info = rk.run(builders, quantum=4)
    assert info["pending"] == 0
    assert int(iv[:, 0].sum()) == fib_seq(n)
    assert info["executed"] == _exec_count(n)
    assert info["per_device_counts"][1, 5] > 0  # work actually migrated


def test_migration_race_free_under_detector():
    """Mosaic interpret race detection over the full home-link protocol
    (steal + remote completion + value-arg rehydration)."""
    from jax.experimental.pallas import tpu as pltpu

    ndev, n = 2, 6
    mk = _fib_mk(capacity=64)
    rk = ResidentKernel(
        mk, cpu_mesh(ndev, axis_name="q"),
        migratable_fns={FIB: (), SUM: (0, 1)},
        window=8, am_window=8,
    )
    orig = rk._build

    def build_with_detector(quantum, max_rounds):
        import unittest.mock as m

        real = pltpu.InterpretParams
        with m.patch.object(
            pltpu, "InterpretParams",
            # Ignore incoming kwargs: the suite's fast-interpret mode
            # (eager DMA, unchecked OOB) must not leak into race
            # detection, which needs the async on_wait DMA model.
            lambda **kw: real(detect_races=True),
        ):
            return orig(quantum, max_rounds)

    rk._build = build_with_detector
    builders = [TaskGraphBuilder() for _ in range(ndev)]
    builders[0].add(FIB, args=[n], out=0)
    iv, _, info = rk.run(builders, quantum=8)
    assert int(iv[:, 0].sum()) == fib_seq(n)
    assert info["executed"] == _exec_count(n)


def test_proxy_cap_throttles_migration_but_stays_exact():
    """The outstanding-proxy budget (migrate-once hardening): with
    proxy_cap=1 at most one dep-bearing subtree may be outstanding per
    device at a time, so exports throttle hard - totals and values must
    still be exact (throttling must never deadlock or drop work; local
    execution continues while the budget is spent)."""
    ndev, n = 2, 8
    mk = _fib_mk(capacity=96)
    rk = ResidentKernel(
        mk, cpu_mesh(ndev, axis_name="q"),
        migratable_fns={FIB: (), SUM: (0, 1)},
        window=16, am_window=8, proxy_cap=1,
    )
    builders = [TaskGraphBuilder() for _ in range(ndev)]
    builders[0].add(FIB, args=[n], out=0)
    iv, _, info = rk.run(builders, quantum=4)
    assert info["pending"] == 0
    assert int(iv[:, 0].sum()) == fib_seq(n)
    assert info["executed"] == _exec_count(n)


def test_homed_fib_migrates_on_3d_mesh():
    """Dependency-bearing migration across a 3D torus: the home-link
    protocol's completion AMs route over all three axes of a 2x2x2 mesh
    (the earlier 3D test moves only link-free rows)."""
    n = 6
    mk = _fib_mk(capacity=64)
    rk = ResidentKernel(
        mk, make_mesh((2, 2, 2), ("x", "y", "z"), jax.devices("cpu")[:8]),
        migratable_fns={FIB: (), SUM: (0, 1)},
        window=8, am_window=8,
    )
    builders = [TaskGraphBuilder() for _ in range(8)]
    builders[0].add(FIB, args=[n], out=0)
    iv, _, info = rk.run(builders, quantum=4)
    assert info["pending"] == 0
    assert int(iv[:, 0].sum()) == fib_seq(n)
    assert info["executed"] == _exec_count(n)
    per_dev = info["per_device_counts"][:, 5]
    assert int((per_dev > 0).sum()) >= 2, per_dev


def test_successor_free_rows_still_migrate_whole():
    """Link-free tasks keep the cheap whole-row path (no proxy, no AM):
    the classic skewed-bump workload is exact and spreads."""
    ndev, ntasks = 4, 28
    rk = ResidentKernel(
        _bump_mk(capacity=128), cpu_mesh(ndev, axis_name="q"),
        migratable_fns=[BUMP], window=8,
    )
    builders = [TaskGraphBuilder() for _ in range(ndev)]
    for i in range(ntasks):
        builders[0].add(BUMP, args=[i + 1])
    iv, _, info = rk.run(builders, quantum=8)
    assert info["pending"] == 0
    assert info["executed"] == ntasks
    assert int(iv[:, 0].sum()) == ntasks * (ntasks + 1) // 2
    per_dev = info["per_device_counts"][:, 5]
    assert int((per_dev > 0).sum()) >= 3, per_dev


# ------------------------------------------------------------- composition


ROWS, COLS = 8, 128
PUT = 1
CONSUME = 2


def _compose_mk(ndev, capacity=256):
    def bump(ctx):
        ctx.set_value(0, ctx.value(0) + ctx.arg(0))

    def put(ctx):
        ctx.pgas.put(ctx.arg(0), 0, ctx.arg(1), ctx.arg(2))

    def consume(ctx):
        ctx.set_value(ctx.arg(0), ctx.pgas.count(0))

    return Megakernel(
        kernels=[("bump", bump), ("put", put), ("consume", consume)],
        data_specs={"heap": jax.ShapeDtypeStruct((ROWS, COLS), np.int32)},
        capacity=capacity,
        num_values=512,
        succ_capacity=8,
        interpret=True,
    )


def _heap(ndev):
    h = np.zeros((ndev, ROWS, COLS), np.int32)
    for d in range(ndev):
        for r in range(ROWS):
            h[d, r, :] = 1000 * d + r
    return h


def test_steal_pgas_and_injection_coexist():
    """ONE kernel per device does all three at once (round-3 directive #2):
    a skewed bump load rebalances by stealing, device 0 puts a row into
    device 1 whose parked consumer wakes on arrival, and injected stream
    rows land mid-run on several devices."""
    ndev, ntasks = 4, 24
    mk = _compose_mk(ndev, capacity=128)
    rk = ResidentKernel(
        mk, cpu_mesh(ndev, axis_name="q"),
        migratable_fns=[BUMP],
        channels={"c0": ("heap", 1)},
        inject=True,
        window=4,
    )
    builders = [TaskGraphBuilder() for _ in range(ndev)]
    for i in range(ntasks):
        builders[0].add(BUMP, args=[i + 1])
    builders[0].add(PUT, args=[1, 3, 2])  # my row 2 -> dev1 row 3
    t = builders[1].add(CONSUME, args=[1])
    waits = [[], [(0, 1, t)], [], []]
    inject_rows = [[(BUMP, [1000])], [], [(BUMP, [2000])], [(BUMP, [3000])]]
    iv, data, info = rk.run(
        builders, data={"heap": _heap(ndev)}, waits=waits,
        inject_rows=inject_rows, quantum=4,
    )
    assert info["pending"] == 0
    base = ntasks * (ntasks + 1) // 2
    assert int(iv[:, 0].sum()) == base + 1000 + 2000 + 3000
    assert (np.asarray(data["heap"])[1, 3] == 2).all()  # the put landed
    assert iv[1, 1] == 1  # parked consumer saw the arrival
    per_dev = info["per_device_counts"][:, 5]
    assert int((per_dev > 0).sum()) >= 3, per_dev


def test_pgas_on_2d_mesh():
    """Channels work on a 2D mesh (round-3 missing #4): puts cross both
    axes of a 2x2 torus; consumers wake on arrival."""
    cpus = jax.devices("cpu")
    mesh = make_mesh((2, 2), ("r", "c"), cpus[:4])
    mk = _compose_mk(4)
    rk = ResidentKernel(
        mk, mesh, channels={"c0": ("heap", 1)}, steal=False,
    )
    builders = [TaskGraphBuilder() for _ in range(4)]
    waits = [[] for _ in range(4)]
    # device 0 puts to 1 (same row), 2 (other row), 3 (diagonal)
    for d in (1, 2, 3):
        builders[0].add(PUT, args=[d, d, d])
        t = builders[d].add(CONSUME, args=[1])
        waits[d].append((0, 1, t))
    iv, data, info = rk.run(
        builders, data={"heap": _heap(4)}, waits=waits, quantum=8,
    )
    heap = np.asarray(data["heap"])
    for d in (1, 2, 3):
        assert (heap[d, d] == d).all(), heap[d, d][:4]
        assert iv[d, 1] == 1
    assert info["pending"] == 0


def test_steal_and_pgas_on_3d_mesh():
    """3D torus (v4/v5p slice shape): the hypercube hops decompose over
    all three axes of a 2x2x2 mesh - a skewed bump load spreads by
    stealing while puts cross each axis (neighbor along z, y, x and the
    full diagonal) and wake parked consumers."""
    cpus = jax.devices("cpu")
    mesh = make_mesh((2, 2, 2), ("x", "y", "z"), cpus[:8])
    mk = _compose_mk(8, capacity=128)
    rk = ResidentKernel(
        mk, mesh, migratable_fns=[BUMP], channels={"c0": ("heap", 1)},
        window=4,
    )
    ntasks = 12
    builders = [TaskGraphBuilder() for _ in range(8)]
    for i in range(ntasks):
        builders[0].add(BUMP, args=[i + 1])
    waits = [[] for _ in range(8)]
    # puts from device 0 along each axis and across all three at once
    for d in (1, 2, 4, 7):
        builders[0].add(PUT, args=[d, d % ROWS, d % ROWS])
        t = builders[d].add(CONSUME, args=[1])
        waits[d].append((0, 1, t))
    iv, data, info = rk.run(
        builders, data={"heap": _heap(8)}, waits=waits, quantum=4,
    )
    assert info["pending"] == 0
    heap = np.asarray(data["heap"])
    for d in (1, 2, 4, 7):
        assert (heap[d, d % ROWS] == d % ROWS).all(), heap[d, d % ROWS][:4]
        assert iv[d, 1] == 1  # parked consumer saw the arrival
    base = ntasks * (ntasks + 1) // 2
    assert int(iv[:, 0].sum()) == base
    per_dev = info["per_device_counts"][:, 5]
    assert int((per_dev > 0).sum()) >= 3, per_dev


# --------------------------------------------------------- atomics + locks


FADD_ALL = 0
CSECT = 1
LOCKER = 2


def test_remote_atomics_and_lock():
    """One kernel, one compile, four protocols at once (interpret-mode
    compiles dominate suite time, so the AMO family shares a table):

    - fire-and-forget fadd: every device adds its rank+1 into device 0's
      slot 5, twice - owner-computes atomicity must sum exactly;
    - fadd_get: device 1 parks a continuation until the owner's reply
      deposits the OLD value of slot 6 (exact fetch-add semantics);
    - compare-swap: device 2 cswaps device 0's slot 12 (55 -> 77) and its
      parked continuation must observe old == 55 (the reply path routes
      device/row/slot words exactly - a dropped src word here once
      shifted the whole reply);
    - distributed lock: every device bumps a counter pair on device 0
      under the lock FIFO; the queue must drain and the lock must end
      released."""
    ndev, per = 4, 2
    qcap = ndev
    LBASE = 16
    X, Y = 8, 9
    ASKER, CONSUME_R, LOCKER_FN, CSECT_FN, SWAPPER = 1, 2, 3, 4, 5

    def fadd_all(ctx):
        for _ in range(per):
            ctx.pgas.fadd(0, 5, 1 + ctx.pgas.me)

    def asker(ctx):
        row = ctx.spawn(CONSUME_R, args=[3], dep_count=1)
        ctx.pgas.fadd_get(0, 6, 10, row, 3)

    def consume_r(ctx):
        ctx.set_value(4, ctx.value(ctx.arg(0)))

    def swapper(ctx):
        row = ctx.spawn(CONSUME_R, args=[3], dep_count=1)
        ctx.pgas.cswap(0, 12, 55, 77, row, 3)

    def locker(ctx):
        row = ctx.spawn(CSECT_FN, dep_count=1)
        ctx.pgas.lock(0, LBASE, row, qcap)

    def csect(ctx):
        ctx.pgas.fadd(0, X, 1)
        ctx.pgas.fadd(0, Y, 1)
        ctx.pgas.unlock(0, LBASE, qcap)

    mk = Megakernel(
        kernels=[("fadd_all", fadd_all), ("asker", asker),
                 ("consume_r", consume_r), ("locker", locker),
                 ("csect", csect), ("swapper", swapper)],
        capacity=64, num_values=256, succ_capacity=8, interpret=True,
    )
    rk = ResidentKernel(mk, cpu_mesh(ndev, axis_name="q"), steal=False)
    builders = [TaskGraphBuilder() for _ in range(ndev)]
    for d in range(ndev):
        builders[d].add(FADD_ALL)
        builders[d].add(LOCKER_FN)
        builders[d].reserve_values(LBASE + lock_block_slots(qcap))
    builders[1].add(ASKER)
    builders[2].add(SWAPPER)
    iv0 = np.zeros((ndev, 256), np.int32)
    iv0[0, 6] = 100
    iv0[0, 12] = 55
    iv, _, info = rk.run(builders, ivalues=iv0, quantum=8)
    assert iv[0, 5] == per * sum(1 + d for d in range(ndev))
    assert iv[0, 6] == 110  # owner applied the fetch-add
    assert iv[1, 4] == 100  # asker observed the OLD value
    assert iv[0, 12] == 77  # cswap matched and swapped
    assert iv[2, 4] == 55  # swapper observed the OLD value
    assert iv[0, X] == ndev and iv[0, Y] == ndev, iv[0, :12]
    assert iv[0, LBASE] == 0  # lock released
    assert iv[0, LBASE + 1] == 0  # queue drained
    assert info["pending"] == 0


# ------------------------------------------------------------ real hardware


@pytest.mark.skipif(jax.default_backend() != "tpu", reason="needs TPU")
def test_resident_compiles_and_runs_on_tpu():
    """The FULL five-way composition on the real chip (1-device
    self-loop): work stealing enabled, one-sided put + wait machinery,
    AMs (fetch-add + lock acquire/release), and an injected task stream,
    all in one kernel compiled through Mosaic. (The interpret-mode dry
    run exercises the same class in four-way compositions; stacking every
    feature's SMEM scratch in one interpreted kernel wedges the Mosaic
    interpreter on 1-vCPU hosts, so hardware carries the five-way proof.)
    """
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:1]), ("q",))
    qcap = 2
    LBASE = 16
    BUMPF = 2

    def driver(ctx):
        ctx.pgas.fadd(0, 5, 7)
        row = ctx.spawn(1, dep_count=1)
        ctx.pgas.lock(0, LBASE, row, qcap)
        ctx.pgas.put(0, 0, 3, 2)  # self-put row 2 -> row 3

    def csect(ctx):
        ctx.pgas.fadd(0, 5, 30)
        ctx.pgas.unlock(0, LBASE, qcap)

    def bump(ctx):
        ctx.set_value(6, ctx.value(6) + ctx.arg(0))

    mk = Megakernel(
        kernels=[("driver", driver), ("csect", csect), ("bump", bump)],
        data_specs={"heap": jax.ShapeDtypeStruct((ROWS, COLS), np.int32)},
        capacity=64, num_values=256, succ_capacity=8, interpret=False,
    )
    rk = ResidentKernel(
        mk, mesh, channels={"c0": ("heap", 1)}, steal=True,
        migratable_fns=[0], inject=True,
    )
    b = TaskGraphBuilder()
    b.add(0)
    b.reserve_values(LBASE + lock_block_slots(qcap))
    iv, data, info = rk.run(
        [b], data={"heap": _heap(1)}, quantum=8,
        inject_rows=[[(BUMPF, [41]), (BUMPF, [1])]],
    )
    assert iv[0, 5] == 37
    assert iv[0, 6] == 42  # injected stream rows ran
    assert (np.asarray(data["heap"])[0, 3] == 2).all()
    assert info["pending"] == 0


@pytest.mark.skipif(jax.default_backend() != "tpu", reason="needs TPU")
def test_resident_volume_stress_on_tpu():
    """Protocol VOLUME on the real chip (round-3 weak item: the resident
    protocols had only been exercised on tiny graphs). One kernel run,
    compiled through Mosaic, simultaneously:

    - runs a 1,828-descriptor dynamic fib(14) graph through the scalar
      scheduler (rows + value blocks recycling far past capacity);
    - pushes 64 fire-and-forget fetch-adds through the outbox pacer
      (16 senders x 4 AMs each; the self-loop inbox window drains only
      a handful per round, so the outbox carry-over path runs for many
      consecutive rounds - emitting faster than the credit-paced drain
      exhausts the outbox, which the overflow bitmask names exactly);
    - contends one lock FIFO from 8 waiters whose critical sections
      compare-swap an occupancy flag 0->1 on entry and reset it on exit:
      every observed old value must be 0, so overlapping grants are
      DETECTED, not just summed away (cswap replies are atomic either
      way - the observation, not the counter, is the tripwire);
    - drains a 64-row injected task stream;
    - parks a consumer on a channel until 4 self-puts land.

    Every effect is asserted exactly."""
    from jax.sharding import Mesh

    from hclib_tpu.device import workloads as _wl

    mesh = Mesh(np.array(jax.devices()[:1]), ("q",))
    qcap = 8
    LBASE = 32
    FADD_SLOT, X, Y, OCC, TEAR = 2, 4, 5, 10, 11
    RS0 = 20  # per-locker cswap reply slots [RS0, RS0 + nlockers)
    (FIBF, SUMF, BUMPF, FADDER, LOCKER_F, CSECT_F, PUTF, CONSUMEF,
     OBS_F) = range(9)
    nfadders, per_fadder = 16, 4
    nlockers = 8
    ninject = 64
    nputs = 4

    def fadder(ctx):
        for _ in range(per_fadder):
            ctx.pgas.fadd(0, FADD_SLOT, ctx.arg(0))

    def locker(ctx):
        row = ctx.spawn(CSECT_F, args=[ctx.arg(0)], dep_count=1)
        ctx.pgas.lock(0, LBASE, row, qcap)

    def csect(ctx):
        # Occupancy tripwire: cswap(OCC: 0 -> 1). The observer parks
        # until the reply deposits the OLD value into this locker's own
        # reply slot; under mutual exclusion every old is 0. The AMs are
        # FIFO per target, so OCC is back to 0 before unlock grants the
        # next waiter.
        s = ctx.arg(0)
        obs = ctx.spawn(OBS_F, args=[s], dep_count=1)
        ctx.pgas.cswap(0, OCC, 0, 1, obs, s)
        ctx.pgas.fadd(0, X, 1)
        ctx.pgas.fadd(0, Y, 1)
        ctx.pgas.fadd(0, OCC, -1)
        ctx.pgas.unlock(0, LBASE, qcap)

    def observe(ctx):
        # Accumulate the observed old occupancy; any overlap makes TEAR
        # nonzero.
        ctx.pgas.fadd(0, TEAR, ctx.value(ctx.arg(0)))

    def putk(ctx):
        ctx.pgas.put(0, 0, ctx.arg(0), 0)  # my row 0 -> row arg0

    def consume(ctx):
        ctx.set_value(6, ctx.pgas.count(0))

    def bump(ctx):
        ctx.set_value(7, ctx.value(7) + ctx.arg(0))

    # SMEM pads scalar words to ~32 B, so the table budget is tight:
    # capacity 512 x 16 words x 32 B = 256 KB per window (in + out =
    # 512 KB of the chip's ~1 MB); rows and value blocks recycle, so
    # the 1.8k-task graph runs through the 512-row table regardless.
    cap = 512
    mk = Megakernel(
        kernels=[("fib", _wl._fib_kernel), ("sum", _wl._sum_kernel),
                 ("bump", bump), ("fadder", fadder), ("locker", locker),
                 ("csect", csect), ("put", putk), ("consume", consume),
                 ("observe", observe)],
        data_specs={"heap": jax.ShapeDtypeStruct((ROWS, COLS), np.int32)},
        capacity=cap,
        num_values=VBLOCK * cap + 64 + cap,
        succ_capacity=64,
        interpret=False,
        uses_row_values=True,
    )
    rk = ResidentKernel(
        mk, mesh,
        migratable_fns={FIBF: (), SUMF: (0, 1)},
        channels={"c0": ("heap", 1)},
        inject=True,
        window=8, am_window=8, outbox=128,
    )
    b = TaskGraphBuilder()
    b.add(FIBF, args=[14], out=3)
    for i in range(nfadders):
        b.add(FADDER, args=[i + 1])
    for i in range(nlockers):
        b.add(LOCKER_F, args=[RS0 + i])
    for r in range(nputs):
        b.add(PUTF, args=[2 + r])
    t = b.add(CONSUMEF)
    b.reserve_values(LBASE + lock_block_slots(qcap))
    inject_rows = [[(BUMPF, [j + 1]) for j in range(ninject)]]
    iv, data, info = rk.run(
        [b], data={"heap": _heap(1)}, waits=[[(0, nputs, t)]],
        inject_rows=inject_rows, quantum=4,
    )
    assert info["pending"] == 0
    assert int(iv[0, 3]) == fib_seq(14)
    assert int(iv[0, FADD_SLOT]) == per_fadder * sum(
        i + 1 for i in range(nfadders)
    )
    assert int(iv[0, X]) == nlockers and int(iv[0, Y]) == nlockers
    assert int(iv[0, TEAR]) == 0  # no critical section saw another inside
    assert int(iv[0, OCC]) == 0  # occupancy balanced
    assert int(iv[0, LBASE]) == 0 and int(iv[0, LBASE + 1]) == 0
    assert int(iv[0, 7]) == ninject * (ninject + 1) // 2
    assert int(iv[0, 6]) == nputs  # consumer saw all four arrivals
    heap = np.asarray(data["heap"])
    for r in range(nputs):
        assert (heap[0, 2 + r] == 0).all()  # row 0 (value 0) landed
    assert info["executed"] == (
        _exec_count(14) + nfadders + 3 * nlockers + nputs + 1 + ninject
    )


# ------------------------------------- batched dispatch on the mesh (ISSUE 7)

from hclib_tpu.jaxcompat import has_mosaic_interpret  # noqa: E402

needs_mosaic = pytest.mark.skipif(
    not has_mosaic_interpret(),
    reason="needs pltpu.InterpretParams (Mosaic TPU interpret mode)",
)


def _batched_fib_rk(ndev, batch_width=0, capacity=160, trace=None,
                    window=8):
    mk = make_fib_megakernel(
        capacity=capacity,
        interpret=True,
        num_values=VBLOCK * capacity + 16 + capacity,
        batch_width=batch_width or None,
        trace=trace,
    )
    rk = ResidentKernel(
        mk, cpu_mesh(ndev, axis_name="q"),
        migratable_fns={FIB: (), SUM: (0, 1)},
        window=window, am_window=8,
    )
    return rk, mk


@needs_mosaic
def test_mesh_batch_fib_matches_scalar_resident():
    """ISSUE 7 acceptance (resident arm): the batch-routed skewed fib
    mesh - homed migration, remote completions, the full round loop -
    computes the exact scalar-mesh result, every executed total matches,
    and info['tiers'] reports per-device occupancy with nonzero batch
    rounds where work ran."""
    ndev, n = 4, 9
    rk_s, _ = _batched_fib_rk(ndev)
    builders = [TaskGraphBuilder() for _ in range(ndev)]
    builders[0].add(FIB, args=[n], out=0)
    iv_s, _, info_s = rk_s.run(builders, quantum=16)
    assert "tiers" not in info_s

    rk_b, _ = _batched_fib_rk(ndev, batch_width=4)
    builders = [TaskGraphBuilder() for _ in range(ndev)]
    builders[0].add(FIB, args=[n], out=0)
    iv_b, _, info_b = rk_b.run(builders, quantum=16)
    assert info_b["pending"] == 0
    assert int(iv_b[:, 0].sum()) == int(iv_s[:, 0].sum()) == fib_seq(n)
    assert info_b["executed"] == info_s["executed"] == _exec_count(n)
    tiers = info_b["tiers"]
    assert len(tiers) == ndev
    batched = sum(t["batch_tasks"] for t in tiers)
    scalar = sum(t["scalar_tasks"] for t in tiers)
    assert batched + scalar == info_b["executed"]
    assert tiers[0]["batch_rounds"] > 0  # the seed device fired batches
    per_dev = info_b["per_device_counts"][:, 5]
    assert int((per_dev > 0).sum()) >= 2, per_dev


@needs_mosaic
def test_mesh_batch_trace_reconciles_with_tstats():
    """Mesh TR_FIRE_BATCH records (the ROADMAP lane-firing-policy
    detector, now live on the mesh): per device, the flight-recorder
    batch records reconcile EXACTLY with that device's tstats counters -
    rounds, dispatched tasks, and occupancy all read the same from
    either source."""
    from hclib_tpu.device.tracebuf import TR_FIRE_BATCH, records_of

    ndev, n = 2, 8
    rk, mk = _batched_fib_rk(ndev, batch_width=4, trace=512)
    builders = [TaskGraphBuilder() for _ in range(ndev)]
    builders[0].add(FIB, args=[n], out=0)
    iv, _, info = rk.run(builders, quantum=8)
    assert info["pending"] == 0
    assert int(iv[:, 0].sum()) == fib_seq(n)
    tiers = info["tiers"]
    for d in range(ndev):
        ring = info["trace"]["rings"][d]
        assert ring["dropped"] == 0  # capacity covers the whole run
        recs = records_of(info["trace"], TR_FIRE_BATCH, ring=d)
        assert recs.shape[0] == tiers[d]["batch_rounds"]
        takes = (recs[:, 2] & 0xFFFF).sum() if recs.size else 0
        assert int(takes) == tiers[d]["batch_tasks"]


@needs_mosaic
@pytest.mark.chaos
def test_mesh_batch_checkpoint_reshard_4_to_2():
    """Checkpoint/reshard with lanes ACTIVE: a batch-routed UTS mesh
    quiesces mid-traversal (sched()'s exit spilled every lane entry to
    the ring and drained prefetches before the lockstep cut, so the
    bundle sees only ring rows), reshards 4 -> 2, and the resumed
    smaller batched mesh drains the remainder with totals conserved
    exactly."""
    from hclib_tpu.device.workloads import UTS_NODE, make_uts_megakernel
    from hclib_tpu.runtime.checkpoint import snapshot_resident

    def make_rk(ndev):
        mk = make_uts_megakernel(
            max_depth=6, interpret=True, capacity=256,
            checkpoint=True, batch_width=4,
        )
        # homed=False: UTS rows are link-free, which is what makes the
        # N -> M re-homing legal (reshard refuses linked rows).
        return ResidentKernel(
            mk, cpu_mesh(ndev, axis_name="q"),
            migratable_fns=[UTS_NODE], window=4, homed=False,
        )

    def builders_of(ndev):
        builders = [TaskGraphBuilder() for _ in range(ndev)]
        for d in range(ndev):
            builders[d].add(UTS_NODE, args=[d + 1, 0])
        return builders

    ndev = 4
    iv_f, _, info_f = make_rk(ndev).run(
        builders_of(ndev), quantum=8, max_rounds=4096
    )
    total = int(np.asarray(iv_f)[:, 0].sum())
    assert info_f["pending"] == 0 and total == info_f["executed"]
    assert sum(t["batch_tasks"] for t in info_f["tiers"]) > 0

    rk = make_rk(ndev)
    iv_q, _, info_q = rk.run(
        builders_of(ndev), quantum=8, max_rounds=4096, quiesce=2,
    )
    assert info_q["quiesced"] is True
    assert info_q["pending"] > 0
    bundle = snapshot_resident(rk, info_q)
    small = bundle.reshard(2)  # refuses any lane-shaped residue
    rk2 = make_rk(2)
    iv_r, _, info_r = rk2.run(
        resume_state=small.state(), quantum=8, max_rounds=1 << 14,
    )
    assert info_r["pending"] == 0
    assert int(np.asarray(iv_r)[:, 0].sum()) == total
    # reshard folds the executed counters, so the resumed total equals
    # the uninterrupted run's.
    assert info_r["executed"] == info_f["executed"]
