"""Live telemetry plane (ISSUE 19): on-device latency histograms,
mid-run scrape, and SLO-driven autoscale signals.

Math half: the log2 bucket spec (``bucket_of`` / ``bucket_edges``),
the fold reference (overflow counted, never dropped), the
conservative quantile bound, and the ``EpochBracket`` rounds->ns
conversion. Device half: the real interpret-mode streaming kernel
stamping lifecycles, folding per-tenant histograms that reconcile
bit-exactly with the spans and the egress ledger, scraped MID-RUN by
a ``TelemetryPoller``, and conserved across a quiesce/resume cut.
Mesh half: the 4 -> 2 -> 4 host-model reshard where per-device blocks
merge and per-tenant totals close against resolved futures exactly.
SLO half: streaming quantiles + multi-window burn rates, the typed
``slo_out`` policy rung (fires before the deadline watchdog, during
cooldown), the Perfetto request flow events, the Prometheus
exposition (registry + HTTP endpoint), and the env knobs (typed,
raise on malformed). Off-path: a telemetry-off build lowers to the
EXACT text an env-free build lowers to, even with the env knob set."""

import threading
import urllib.request

import numpy as np
import pytest

import hclib_tpu as hc
from hclib_tpu.device.descriptor import (
    RING_ROW,
    TEN_ADMIT_ROUND,
    TEN_ID,
    TEN_TOKEN,
    TaskGraphBuilder,
)
from hclib_tpu.device.egress import EGR_WORDS, EgressSpec, HostMailbox
from hclib_tpu.device.inject import StreamingMegakernel
from hclib_tpu.device.megakernel import Megakernel
from hclib_tpu.device.telemetry import (
    LAT_BUCKETS,
    LAT_WORDS,
    TG_RETIRES,
    TG_ROUNDS,
    TelemetryBlock,
    TelemetryPoller,
    bucket_edges,
    bucket_of,
    hist_fold_reference,
    quantile_from_hist,
    unpack_spans,
)
from hclib_tpu.device.tenants import (
    MeshTenantTable,
    TenantSpec,
    TenantTable,
    wrr_poll_reference,
)
from hclib_tpu.runtime.clockprobe import EpochBracket
from hclib_tpu.runtime.slo import SloEstimator, parse_windows

BUMP = 0


def _bump_mk(checkpoint=False):
    def bump(ctx):
        ctx.set_value(0, ctx.value(0) + ctx.arg(0))

    return Megakernel(
        kernels=[("bump", bump)], capacity=128, num_values=4,
        succ_capacity=8, interpret=True, checkpoint=checkpoint,
    )


def _seed_builder():
    b = TaskGraphBuilder()
    b.add(BUMP, args=[1000])
    return b


def _table(specs=None, region=32, depth=64):
    return TenantTable(
        specs or [TenantSpec("a", queue_capacity=64),
                  TenantSpec("b", queue_capacity=64)],
        region, egress=EgressSpec(depth=depth),
    )


def _stream(checkpoint=False, telemetry=True, **kw):
    return StreamingMegakernel(
        _bump_mk(checkpoint=checkpoint), ring_capacity=64,
        tenants=_table(**kw), telemetry=telemetry,
    )


# ------------------------------------------------------- bucket math


def test_bucket_of_matches_edges_and_clamps():
    """The branch-free in-kernel formula's host spec lands every delta
    in the bucket whose [lo, hi) brackets it; negatives clamp to 0;
    everything at or past 2^(B-1) lands in the overflow bucket."""
    edges = bucket_edges()
    assert len(edges) == LAT_BUCKETS and edges[0] == (0, 2)
    assert edges[-1][1] is None
    for i, (lo, hi) in enumerate(edges):
        assert bucket_of(lo) == i
        if hi is not None:
            assert bucket_of(hi - 1) == i
            assert bucket_of(hi) == i + 1
    assert bucket_of(-5) == 0
    assert bucket_of(1 << (LAT_BUCKETS - 1)) == LAT_BUCKETS - 1
    assert bucket_of((1 << 30) + 7) == LAT_BUCKETS - 1


def test_hist_fold_reference_counts_overflow_and_validates():
    """Overflow retirements are COUNTED in the last bucket (never
    dropped), TG_RETIRES tracks the histogram mass, and bad shapes or
    tenant indices are refused loudly."""
    tele = np.zeros((3, LAT_BUCKETS), np.int64)
    out = hist_fold_reference(
        tele, [(0, 1), (0, 1 << 20), (1, -3), (1, 3)]
    )
    assert out[1, 0] == 1 and out[1, LAT_BUCKETS - 1] == 1
    assert out[2, 0] == 1 and out[2, 1] == 1  # -3 clamps to bucket 0
    assert out[0, TG_RETIRES] == 4
    assert tele.sum() == 0  # folds a copy
    with pytest.raises(ValueError, match="tenant"):
        hist_fold_reference(tele, [(2, 1)])
    with pytest.raises(ValueError, match="tele block"):
        hist_fold_reference(np.zeros((3, 4), np.int64), [])


def test_quantile_from_hist_is_conservative_upper_edge():
    """The quantile is the UPPER edge of the bucket holding the
    ceil(q*total)-th sample; the unbounded overflow bucket reports its
    LOWER edge; empty histograms report None; q is validated."""
    counts = np.zeros(LAT_BUCKETS, np.int64)
    counts[2] = 6           # six samples in [4, 8)
    counts[5] = 4           # four in [32, 64)
    assert quantile_from_hist(counts, 0.5) == 8.0
    assert quantile_from_hist(counts, 0.99) == 64.0
    counts[LAT_BUCKETS - 1] = 90
    assert quantile_from_hist(counts, 0.99) == float(
        1 << (LAT_BUCKETS - 1)
    )
    assert quantile_from_hist(np.zeros(LAT_BUCKETS), 0.5) is None
    with pytest.raises(ValueError, match="quantile"):
        quantile_from_hist(counts, 1.5)


def test_unpack_spans_roundtrip():
    admit, install, fire, retire = unpack_spans(10, (7 << 16) | 3)
    assert (admit, install, fire) == (10, 13, 20)
    assert retire == fire  # dispatch/completion atomic per round


# -------------------------------------------------- rounds->ns bracket


def test_epoch_bracket_monotone_and_clamped():
    """The wall bracket accumulates (t1-t0, rounds) per entry; the
    factor is total/total; negative wall or round deltas clamp to 0 so
    a clock step never drives the conversion negative; to_ns is
    monotone in rounds."""
    br = EpochBracket()
    assert br.ns_per_round() is None and br.to_ns(5) is None
    br.accumulate(1000, 3000, 4)       # 500 ns/round
    br.accumulate(3000, 7000, 4)       # 1000 ns/round -> avg 750
    assert br.ns_per_round() == pytest.approx(750.0)
    assert br.to_ns(2) == pytest.approx(1500.0)
    assert br.to_ns(4) > br.to_ns(2)
    before = br.ns_per_round()
    br.accumulate(9000, 8000, -3)      # clamped: moves nothing
    assert br.ns_per_round() == before
    assert br.entries == 3


# ---------------------------------------------------- off-path gates


def test_telemetry_requires_egress_stream():
    """Histograms are per-tenant and fold at the egress retire: a
    telemetry build without an egress-enabled tenant stream is a
    loud construction error, not a silent no-op."""
    with pytest.raises(ValueError, match="egress"):
        StreamingMegakernel(_bump_mk(), ring_capacity=32,
                            telemetry=True)
    with pytest.raises(ValueError, match="egress"):
        StreamingMegakernel(
            _bump_mk(), ring_capacity=32,
            tenants=TenantTable([TenantSpec("a")], 16,
                                clock=lambda: 0.0),
            telemetry=True,
        )


def _lower_text(sm):
    mk = sm.mk
    tasks, succ, ready, counts = _seed_builder().finalize(
        capacity=mk.capacity, succ_capacity=mk.succ_capacity
    )
    args = [
        tasks, succ, ready, counts,
        np.zeros(mk.num_values, np.int32),
        np.zeros((sm.ring_capacity, RING_ROW), np.int32),
        np.zeros(8, np.int32),
        np.zeros((len(sm.tenants), 8), np.int32),
        np.zeros((sm._egress.depth, EGR_WORDS), np.int32),
        np.zeros((sm._egress.depth, EGR_WORDS), np.int32),
        np.zeros(8, np.int32),
        np.zeros(mk.capacity, np.int32),
    ]
    if sm.telemetry:
        args += [
            np.zeros((1 + len(sm.tenants), LAT_BUCKETS), np.int32),
            np.zeros((mk.capacity, LAT_WORDS), np.int32),
        ]
    return sm._build(1 << 10, 64).lower(*args).as_text()


def test_off_path_compiles_zero_telemetry_words(monkeypatch):
    """ACCEPTANCE: telemetry unset lowers to the EXACT text an env-free
    build lowers to, even with HCLIB_TPU_TELEMETRY set - and the
    enabled build differs (the tele/tlat words exist only on-path)."""
    monkeypatch.delenv("HCLIB_TPU_TELEMETRY", raising=False)
    base = _lower_text(_stream(telemetry=None))
    monkeypatch.setenv("HCLIB_TPU_TELEMETRY", "1")
    off = _lower_text(_stream(telemetry=False))
    assert off == base
    on = _lower_text(_stream(telemetry=None))  # env spelling enables
    assert on != base


# ------------------------------------------------- device histograms


def test_device_histograms_reconcile_with_spans_and_ledger():
    """DEVICE: every tracked retirement lands in exactly one per-tenant
    bucket; refolding the per-row (fire - admit) spans through the
    reference reproduces the device block bit-exactly; per-tenant
    totals equal the ledger's resolved counts."""
    sm = _stream()
    futs = {"a": [], "b": []}
    for i in range(12):
        tid = "a" if i % 3 else "b"
        adm = sm.submit(tid, BUMP, args=[1])
        assert adm
        futs[tid].append(adm.future)
    sm.close()
    iv, info = sm.run_stream(_seed_builder())
    assert int(iv[0]) == 1000 + 12
    snap = sm.telemetry_snapshot()
    assert snap is not None and snap["entries"] >= 1
    blk = TelemetryBlock(snap["tele"], snap.get("ns_per_round"))
    g = blk.gauges()
    assert g["retires"] == blk.total() == 12
    assert g["rounds"] > 0 and g["installs"] >= 12
    assert blk.total(0) == len(futs["a"]) == sum(
        1 for f in futs["a"] if f.state == "RESULT"
    )
    assert blk.total(1) == len(futs["b"])
    spans = sm.telemetry_spans()
    assert len(spans) == 12
    refold = np.zeros((1 + 2, LAT_BUCKETS), np.int64)
    per_row = []
    for tok, (admit, install, fire) in spans.items():
        assert 0 <= admit <= install <= fire
        ten = 0 if any(f.token == tok for f in futs["a"]) else 1
        per_row.append((ten, fire - admit))
    refold = hist_fold_reference(refold, per_row)
    assert np.array_equal(refold[1:], blk.tele[1:]), (refold, blk.tele)
    assert info["telemetry"]["rounds"] == g["rounds"]


def test_device_quantiles_within_one_bucket_of_exact_stamps():
    """ACCEPTANCE: the histogram-derived p50/p99 equal the upper edge
    of the bucket holding the EXACT order statistic computed from the
    per-request stamps - i.e. they agree within one log2 bucket."""
    sm = _stream()
    for i in range(16):
        assert sm.submit(i % 2, BUMP, args=[1])
    sm.close()
    sm.run_stream(_seed_builder(), max_rounds=8)
    blk = TelemetryBlock(sm.telemetry_snapshot()["tele"])
    deltas = sorted(
        fire - admit
        for admit, _, fire in sm.telemetry_spans().values()
    )
    assert len(deltas) == 16
    for q in (0.5, 0.99):
        exact = deltas[max(1, int(np.ceil(q * len(deltas)))) - 1]
        lo, hi = bucket_edges()[bucket_of(exact)]
        assert blk.quantile(q) == float(hi if hi is not None else lo)
        assert blk.quantile(q) >= exact  # conservative bound


def test_live_stream_scraped_midrun_two_monotone_snapshots():
    """ACCEPTANCE: a TelemetryPoller thread snapshots the RUNNING
    stream at least twice, seq and histogram mass monotonically
    advancing, with at least one snapshot strictly before the final
    state (a true mid-run scrape, not an exit artifact)."""
    sm = _stream()
    for i in range(24):
        assert sm.submit(i % 2, BUMP, args=[1])
    sm.close()
    poller = TelemetryPoller(sm.telemetry_snapshot,
                             interval_s=0.001).start()
    sm.run_stream(_seed_builder(), max_rounds=4)
    midrun = len(poller.snapshots)
    poller.stop(final_poll=True)
    assert midrun >= 2, "poller never caught the stream mid-run"
    seqs = [s["seq"] for s in poller.snapshots]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    totals = [int(np.asarray(s["tele"])[1:].sum())
              for s in poller.snapshots]
    rounds = [int(np.asarray(s["tele"])[0, TG_ROUNDS])
              for s in poller.snapshots]
    assert totals == sorted(totals) and rounds == sorted(rounds)
    assert totals[-1] == 24
    assert totals[0] < 24, "first scrape already saw the final state"
    assert poller.latest_block().total() == 24
    assert poller.wait_for(2, timeout_s=0.1)


def test_quiesce_resume_conserves_histograms():
    """A checkpoint cut carries the tele/tlat blocks in the bundle: the
    resumed stream keeps folding into the SAME cumulative histogram,
    and the final per-tenant totals equal every tracked retirement
    across both halves of the cut."""
    def fresh():
        return _stream(checkpoint=True)

    sm = fresh()
    t1 = sm.tenants
    futs = [sm.submit("a", BUMP, args=[1]).future for _ in range(8)]
    sm.quiesce(after_executed=3)
    _, info = sm.run_stream(_seed_builder())
    assert info["quiesced"]
    state = info["state"]
    assert "tele" in state and "tlat" in state
    cut_rounds = int(np.asarray(state["tele"])[0, TG_ROUNDS])
    cut_mass = int(np.asarray(state["tele"])[1:].sum())
    assert 0 < cut_mass < 8
    tokens = [f.resume_token for f in futs if f.state == "PREEMPTED"]
    assert tokens
    sm2 = fresh()
    sm2.close()
    sm2.run_stream(resume_state=state)
    for tok in tokens:
        f = sm2.tenants.reattach(tok)
        assert f.result(timeout=2.0) is not None
    snap = sm2.telemetry_snapshot()
    blk = TelemetryBlock(snap["tele"])
    assert blk.total() == 8, "histogram mass lost across the cut"
    assert blk.gauges()["rounds"] > cut_rounds  # timebase continued
    # Both halves' ledgers close, and the CUMULATIVE histogram mass
    # equals the resolutions summed across the cut.
    c1 = t1.futures.conservation()
    c2 = sm2.tenants.futures.conservation()
    assert c1["ok"] and c2["ok"], (c1, c2)
    assert c2["reattached"] == len(tokens)
    assert blk.total() == c1["resolved"] + c2["resolved"]


# ------------------------------------------------- mesh reconciliation


def test_mesh_reshard_reconciles_histograms_with_ledger():
    """ACCEPTANCE: across a live 4 -> 2 -> 4 reshard (host model:
    wrr_poll_reference + HostMailbox + hist_fold_reference per device,
    merged per phase), per-tenant histogram totals equal the ledger's
    per-tenant resolved counts EXACTLY, and
    submitted == hist_total + expired + poisoned closes globally."""
    region = 16
    clk = [100.0]
    spec = EgressSpec(depth=64)
    rng = np.random.default_rng(42)
    table = MeshTenantTable(
        [TenantSpec("gold", weight=2, queue_capacity=512),
         TenantSpec("std", queue_capacity=512)],
        4, region, clock=lambda: clk[0], egress=spec,
    )
    futures = table.futures
    merged = TelemetryBlock(np.zeros((3, LAT_BUCKETS), np.int64))
    submitted = 0
    resolved_by = {"gold": 0, "std": 0}

    def drive(table, rings, polls=4, start=0):
        nonlocal merged
        boxes = [HostMailbox(spec, park_cap=8 * region)
                 for _ in range(table.ndev)]
        teles = [np.zeros((3, LAT_BUCKETS), np.int64)
                 for _ in range(table.ndev)]
        table.set_admit_round(start)
        tctl = table.pump(rings)
        for r in range(start, start + polls):
            for d in range(table.ndev):
                rows = wrr_poll_reference(
                    rings[d], tctl[d], table.region_rows, r, 1 << 20
                )
                retires = []
                for row in rows:
                    ten = int(row[TEN_ID])
                    retires.append(
                        (ten, r - int(row[TEN_ADMIT_ROUND]))
                    )
                    resolved_by["gold" if ten == 0 else "std"] += 1
                teles[d] = hist_fold_reference(teles[d], retires)
                boxes[d].publish([
                    (int(row[TEN_TOKEN]), 0, BUMP, 0, 7)
                    for row in rows
                ])
        table.absorb(tctl)
        for d, box in enumerate(boxes):
            box.drain(futures=futures)
            merged = merged.merge(TelemetryBlock(teles[d]))
        clk[0] += 0.05

    def rings_for(ndev):
        return np.zeros((ndev, 2 * region, RING_ROW), np.int32)

    sizes = [4, 2, 4]
    rings = rings_for(4)
    live = []
    for phase, ndev in enumerate(sizes):
        for i in range(10):
            doomed = rng.random() < 0.2
            adm = table.submit(
                i % 2, BUMP, args=[i],
                deadline_s=(0.01 if doomed else 600.0),
            )
            if adm:
                submitted += 1
                live.append(adm.future)
            clk[0] += float(rng.random() * 0.02)
        drive(table, rings, polls=2, start=4 * phase)
        if phase == len(sizes) - 1:
            break
        state = table.export_state(rings)
        tokens = [f.resume_token for f in live
                  if f.state == "PREEMPTED"]
        nxt = table.resized(sizes[phase + 1])
        assert nxt.futures is futures
        nxt.resume_from(state)
        for tok in tokens:
            nxt.reattach(tok)
        table, rings = nxt, rings_for(nxt.ndev)
    for r in range(20, 60):
        drive(table, rings, polls=1, start=r)
        if table.drained():
            break
    assert table.drained()
    cons = futures.conservation()
    assert cons["ok"] and cons["pending"] == 0, cons
    # Per-tenant: histogram mass IS the resolved count.
    assert merged.total(0) == resolved_by["gold"]
    assert merged.total(1) == resolved_by["std"]
    assert merged.total() == cons["resolved"]
    # Global: every submission is accounted for, exactly.
    assert submitted == (
        merged.total() + cons["expired"] + cons["poisoned"]
    ), (submitted, cons)
    assert cons["expired"] > 0, "storm never exercised expiry"


# --------------------------------------------------------- SLO engine


def _degraded_estimator(**kw):
    est = SloEstimator(objective_rounds=64, quantile=0.99,
                       windows_s=(5.0, 30.0), **kw)
    counts, t = np.zeros(LAT_BUCKETS, np.int64), 0.0
    for lo, hi in ((4, 32), (256, 4096)):
        rng = np.random.default_rng(int(lo))
        for _ in range(6):
            for d in rng.integers(lo, hi, size=16):
                counts[bucket_of(int(d))] += 1
            t += 1.0
            est.observe(counts.copy(), t)
    return est, t


def test_slo_estimator_quantiles_and_burn_rates():
    """Streaming quantiles ride the cumulative histogram; burn rates
    are (bad/total)/(1-q) per window over the DELTA from the window's
    baseline snapshot; pressure is the max across windows."""
    est, t = _degraded_estimator()
    qs = est.quantiles((0.5, 0.99))
    assert qs[0.99] >= 256 and qs[0.5] >= 8
    burns = est.burn_rates(t)
    assert set(burns) == {5.0, 30.0}
    # The short window sees only degraded traffic: bad/total ~ 1.0,
    # budget 0.01 -> burn ~100x. The long window dilutes with the
    # healthy prefix but still burns.
    assert burns[5.0] > burns[30.0] > 1.0
    assert est.latency_pressure(t) == max(burns.values())
    st = est.stats()
    assert st["objective_rounds"] == 64 and st["total"] == est.total
    with pytest.raises(ValueError, match="width"):
        est.observe(np.zeros(4, np.int64), t + 1.0)


def test_slo_no_objective_is_inert():
    """No objective -> zero pressure and empty burn map, whatever the
    stream does (the off path a metrics-only deployment rides)."""
    est = SloEstimator(objective_rounds=None, quantile=0.99,
                       windows_s=(5.0,))
    counts = np.zeros(LAT_BUCKETS, np.int64)
    counts[LAT_BUCKETS - 1] = 1000
    for t in (1.0, 2.0, 3.0):
        est.observe(counts * int(t), t)
    assert est.latency_pressure(3.0) == 0.0


def test_parse_windows_and_env_knobs_raise_on_malformed(monkeypatch):
    """Typed env contract: every SLO knob raises NAMING the variable on
    malformed text instead of limping on a default."""
    assert parse_windows("60,300") == (60.0, 300.0)
    assert parse_windows(" 5 ") == (5.0,)
    assert parse_windows("60,,300") == (60.0, 300.0)  # blanks skip
    for bad in ("", "60,nope", "0", "-5"):
        with pytest.raises(ValueError, match="HCLIB_TPU_SLO_WINDOWS_S"):
            parse_windows(bad)
    monkeypatch.setenv("HCLIB_TPU_SLO_QUANTILE", "ninety-nine")
    with pytest.raises(ValueError, match="HCLIB_TPU_SLO_QUANTILE"):
        SloEstimator(objective_rounds=64)
    monkeypatch.delenv("HCLIB_TPU_SLO_QUANTILE", raising=False)
    monkeypatch.setenv("HCLIB_TPU_SLO_OBJECTIVE_ROUNDS", "fast")
    with pytest.raises(ValueError,
                       match="HCLIB_TPU_SLO_OBJECTIVE_ROUNDS"):
        SloEstimator()
    monkeypatch.delenv("HCLIB_TPU_SLO_OBJECTIVE_ROUNDS", raising=False)
    with pytest.raises(ValueError, match="quantile"):
        SloEstimator(objective_rounds=64, quantile=1.5)
    with pytest.raises(ValueError, match="objective"):
        SloEstimator(objective_rounds=-1)
    monkeypatch.setenv("HCLIB_TPU_SLO_BURN", "0")
    with pytest.raises(ValueError, match="slo_burn"):
        hc.AutoscalerPolicy(min_devices=1, max_devices=8,
                            scale_out_backlog=64.0,
                            scale_in_backlog=4.0)


def test_policy_slo_out_fires_before_watchdog_and_rides_trace():
    """The slo_out rung bypasses hysteresis AND cooldown (like
    evacuate/deadline_out), sits BELOW deadline_out in the ladder, and
    the typed event rides TR_SCALE + metrics + Perfetto via SC_NAMES -
    the one-table edit that keeps every renderer in sync."""
    from hclib_tpu.device.tracebuf import (
        SC_NAMES,
        SC_SLO_OUT,
        TR_SCALE,
        records_of,
    )

    assert SC_NAMES[SC_SLO_OUT] == "slo out"

    def policy():
        p = hc.AutoscalerPolicy(
            min_devices=1, max_devices=8, scale_out_backlog=1e9,
            scale_in_backlog=4.0, hysteresis=2, cooldown=3,
            tenant_pressure=0.25, slo_burn=2.0,
        )
        p._cooling = 3  # prove the rung bypasses the gate
        return p

    obs = hc.Observation(2, [4, 4], executed_delta=8, slice_s=1.0,
                         latency_pressure=5.0)
    target, kind, reason = policy().decide(obs)
    assert (target, kind) == (4, "slo_out") and "burn" in reason
    # Zeroing the burn signal: the same observation holds (nothing
    # else would have scaled - the SLO rung acted alone).
    quiet = hc.Observation(2, [4, 4], executed_delta=8, slice_s=1.0,
                           latency_pressure=0.0)
    assert policy().decide(quiet)[1] == "hold"
    # Ladder order: a draining deadline budget outranks the burn
    # (drain is a DELTA, so seed the baseline first).
    p = policy()
    p.decide(hc.Observation(
        2, [4, 4], executed_delta=8, slice_s=1.0,
        tenants={"t": {"expired": 0, "budget": 20}},
    ))
    t2, k2, _ = p.decide(hc.Observation(
        2, [4, 4], executed_delta=8, slice_s=1.0,
        tenants={"t": {"expired": 10, "budget": 20}},
        latency_pressure=5.0,
    ))
    assert k2 == "deadline_out", k2
    # Respects max_devices: already at the ceiling -> not slo_out.
    at_cap = hc.Observation(8, [4] * 8, executed_delta=8, slice_s=1.0,
                            latency_pressure=5.0)
    assert policy().decide(at_cap)[1] != "slo_out"
    # The typed event: ScaleEvent validates the kind via SC_NAMES,
    # Autoscaler mirrors it into metrics + the TR_SCALE host ring.
    reg = hc.MetricsRegistry()
    asc = hc.Autoscaler(lambda n: None, policy(), metrics=reg)
    asc._event(hc.ScaleEvent("slo_out", 1, 2, 4, reason))
    recs = records_of(asc.trace_info(), TR_SCALE)
    assert len(recs) == 1 and int(recs[0][2]) == (2 << 8) | 4
    snap = reg.snapshot()["metrics"]
    assert snap["autoscale.slo_out.count"] == 1.0
    with pytest.raises(ValueError, match="kind"):
        hc.ScaleEvent("slo_sideways", 0, 2, 4, "no")


# ----------------------------------------------- perfetto flow events


class _FakeFuture:
    def __init__(self, token, t_submit=None, t_done=None):
        self.token = token
        self.t_submit = t_submit
        self.t_done = t_done


def _timeline():
    from conftest import timeline_mod

    return timeline_mod()


def test_request_flow_events_join_host_and_device_stamps():
    """Each request renders as queued + inflight slices and a flow
    chain; a resolved future adds a RESULT marker anchored on the
    round axis through ns_per_round, never before the fire."""
    timeline = _timeline()
    spans = {7: (2, 3, 9), 8: (4, 4, 6)}
    futs = [_FakeFuture(7, t_submit=10.0, t_done=10.0 + 20e-6)]
    ev = timeline.request_flow_events(spans, futs,
                                      ns_per_round=1000.0)
    names = [e.get("name", "") for e in ev]
    assert "req 7 queued" in names and "req 7 inflight" in names
    assert "req 8 queued" in names
    # 20us host wall at 1000 ns/round = 20 rounds past admit=2.
    res = [e for e in ev if e.get("name") == "req 7 result"]
    assert len(res) == 1 and res[0]["ts"] == pytest.approx(22.0)
    chain7 = [e for e in ev
              if e.get("cat") == "request" and e.get("id") == 7]
    assert [e["ph"] for e in chain7] == ["s", "t", "t", "f"]
    assert chain7[-1]["ts"] >= 9  # the finish never precedes the fire
    chain8 = [e for e in ev
              if e.get("cat") == "request" and e.get("id") == 8]
    assert [e["ph"] for e in chain8] == ["s", "t", "f"]
    assert chain8[-1]["ts"] == 6  # no host stamp: flow ends at fire
    assert any(e.get("ph") == "M" for e in ev)  # track names present


def test_export_perfetto_renders_tr_latency():
    """A TR_LATENCY device record decodes tenant/bucket from its packed
    a-word and renders on the events track."""
    timeline = _timeline()
    from hclib_tpu.device.tracebuf import TAG_NAMES, TR_LATENCY

    assert TAG_NAMES[TR_LATENCY] == "latency"
    trace = {
        "epoch": {"t0_ns": 1_000_000, "t1_ns": 2_000_000},
        "rings": [{
            "records": np.array(
                [[int(TR_LATENCY), 5, (2 << 16) | 3, 12]], np.int64
            ),
            "written": 1, "dropped": 0, "capacity": 8,
        }],
    }
    doc = timeline.export_perfetto("", traces=[trace])
    names = [e.get("name", "") for e in doc["traceEvents"]]
    assert any(n.startswith("latency t2 2^3") for n in names), names


# ------------------------------------------------ metrics + exposition


def test_registry_watch_refreshes_and_survives_source_death():
    """watch() polls the source on a daemon thread and records the
    latest mapping; a raising source records an error flag but keeps
    the last good value; unwatch stops the thread; re-watching a name
    replaces the old watch."""
    reg = hc.MetricsRegistry()
    with pytest.raises(ValueError, match="interval"):
        reg.watch("w", lambda: {}, interval_s=0.0)
    hits = threading.Event()
    state = {"n": 0, "die": False}

    def source():
        if state["die"]:
            raise RuntimeError("scrape target gone")
        state["n"] += 1
        hits.set()
        return {"n": state["n"]}

    reg.watch("live", source, interval_s=0.002)
    assert hits.wait(timeout=2.0)
    deadline = 50
    while reg.snapshot()["metrics"].get("live.n", 0) < 1 and deadline:
        deadline -= 1
        threading.Event().wait(0.01)
    assert reg.snapshot()["metrics"]["live.n"] >= 1
    state["die"] = True
    err_seen = 0
    for _ in range(100):
        m = reg.snapshot()["metrics"]
        if m.get("live.error") == 1.0:
            err_seen = 1
            break
        threading.Event().wait(0.01)
    assert err_seen, "raising source never surfaced live.error"
    reg.unwatch("live")


def test_prometheus_latency_exposition_is_cumulative():
    """Native histogram form: per-tenant CUMULATIVE bucket counts, le =
    the bucket's upper edge in rounds, overflow mass ONLY in +Inf,
    plus _count and the rounds->ns gauge."""
    reg = hc.MetricsRegistry()
    tele = np.zeros((2, LAT_BUCKETS), np.int64)
    tele[1, 0], tele[1, 2], tele[1, LAT_BUCKETS - 1] = 3, 2, 4
    reg.record_latency(
        TelemetryBlock(tele, ns_per_round=250.0),
        labels={0: "gold"},
    )
    text = reg.to_prometheus()
    assert '# TYPE hclib_latency histogram' in text
    assert 'hclib_latency_bucket{tenant="gold",le="2"} 3' in text
    assert 'hclib_latency_bucket{tenant="gold",le="8"} 5' in text
    # Overflow: counted in +Inf (total), in NO bounded bucket - the
    # last bounded edge still reads 5, not 9.
    top = 1 << (LAT_BUCKETS - 1)
    assert f'hclib_latency_bucket{{tenant="gold",le="{top}"}} 5' in text
    assert 'hclib_latency_bucket{tenant="gold",le="+Inf"} 9' in text
    assert 'hclib_latency_count{tenant="gold"} 9' in text
    assert "hclib_latency_ns_per_round 250.0" in text


def test_metrics_serve_http_endpoint():
    """tools/metrics_serve.py: a stdlib http.server thread exposes the
    registry at /metrics; other paths 404; the server shuts down
    cleanly."""
    import os
    import sys

    sys.path.insert(
        0, os.path.join(os.path.dirname(__file__), "..", "tools")
    )
    import metrics_serve

    reg = hc.MetricsRegistry()
    reg.record("svc", {"up": 1})
    tele = np.zeros((2, LAT_BUCKETS), np.int64)
    tele[1, 3] = 5
    reg.record_latency(TelemetryBlock(tele))
    httpd, thread = metrics_serve.serve(reg, port=0)
    try:
        port = httpd.server_address[1]
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5.0
        ).read().decode()
        assert "hclib_tpu_svc_up 1.0" in body
        assert 'hclib_latency_bucket{tenant="0",le="16"} 5' in body
        with pytest.raises(Exception):
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/nope", timeout=5.0
            )
    finally:
        httpd.shutdown()
        thread.join(timeout=5.0)


# ----------------------------------------------------- env registry


def test_telemetry_env_rows_registered():
    """Every telemetry/SLO knob is a typed registry row (runtime/env.py
    refuses unregistered reads; the registry is the documentation)."""
    from hclib_tpu.runtime.env import registry_table

    names = {row[0] for row in registry_table()}
    for knob in (
        "HCLIB_TPU_TELEMETRY",
        "HCLIB_TPU_TELEMETRY_POLL_S",
        "HCLIB_TPU_SLO_OBJECTIVE_ROUNDS",
        "HCLIB_TPU_SLO_QUANTILE",
        "HCLIB_TPU_SLO_WINDOWS_S",
        "HCLIB_TPU_SLO_BURN",
    ):
        assert knob in names, knob
