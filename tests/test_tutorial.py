"""Every tutorial lesson is a self-checking script; run each as a user
would (fresh subprocess, repo root on path via the lesson's own bootstrap)."""

import pathlib
import subprocess
import sys

import pytest

TUTORIAL = pathlib.Path(__file__).resolve().parent.parent / "tutorial"
LESSONS = sorted(p.name for p in TUTORIAL.glob("[0-2][0-9]_*.py"))


def test_tutorial_is_complete():
    assert len(LESSONS) == 24


@pytest.mark.parametrize("lesson", LESSONS)
def test_lesson_runs(lesson):
    proc = subprocess.run(
        [sys.executable, str(TUTORIAL / lesson)],
        capture_output=True,
        text=True,
        timeout=420,
    )
    assert proc.returncode == 0, (lesson, proc.stdout[-800:], proc.stderr[-800:])
