"""Request/response serving loop (ISSUE 16): completion-mailbox egress,
submit futures, and the wedge-proof degradation ladder.

Host half: the typed ``Future`` face (RESULT | EXPIRED | POISONED |
PREEMPTED - exactly one, exactly once), the ``FutureTable`` ledger's
conservation identity, and the numpy executable specs
(``egress_reference`` / ``flush_parked_reference`` / ``HostMailbox``)
of the in-kernel publish path. Device half: the real interpret-mode
streaming kernel publishing through the completion mailbox, parking on
full (tiny depth forces it), preempting across a quiesce cut, and
poisoning on abort. Protocol half: the ``EgressMailboxModel`` explored
over every schedule - a full mailbox with a DEAD poller provably cannot
wedge the quiesce export or the drained exit - plus the seeded
forgot-the-park-ring bug the explorer must find. Off-path: an
egress-off build lowers to the exact text an env-free build lowers to,
even with the egress env knobs set."""

import os

import numpy as np
import pytest

from hclib_tpu.device.descriptor import TaskGraphBuilder
from hclib_tpu.device.egress import (
    EC_CONSUMED,
    EC_PARK_COUNT,
    EC_PARK_HEAD,
    EC_PARKED,
    EC_WRITE,
    EGR_TOKEN,
    EGR_WORDS,
    EgressProtocolError,
    EgressSpec,
    FutureExpired,
    FuturePoisoned,
    FuturePreempted,
    FutureTable,
    FutureTimeout,
    HostMailbox,
    egress_from_env,
    egress_reference,
    flush_parked_reference,
    normalize_egress,
)
from hclib_tpu.device.inject import StreamingMegakernel
from hclib_tpu.device.megakernel import Megakernel
from hclib_tpu.device.tenants import MeshTenantTable, TenantSpec, TenantTable

BUMP = 0


def _bump_mk(checkpoint=False):
    def bump(ctx):
        ctx.set_value(0, ctx.value(0) + ctx.arg(0))

    return Megakernel(
        kernels=[("bump", bump)], capacity=128, num_values=4,
        succ_capacity=8, interpret=True, checkpoint=checkpoint,
    )


def _seed_builder():
    b = TaskGraphBuilder()
    b.add(BUMP, args=[1000])
    return b


def _table(specs=None, region=16, egress=None, clock=None):
    return TenantTable(
        specs or [TenantSpec("a")], region,
        clock=clock or (lambda: 100.0), egress=egress,
    )


# ------------------------------------------------------ future ladder


def test_future_timeout_is_typed_and_carries_stats():
    """result(timeout=) on a PENDING future raises FutureTimeout - a
    TimeoutError subclass - carrying the ledger's stats_dict, so the
    timeout handler can see submitted/resolved/pending without another
    call."""
    ft = FutureTable(backoff_s=0.001)
    f = ft.create("gold", fn=BUMP, slot=0)
    with pytest.raises(FutureTimeout) as ei:
        f.result(timeout=0.02)
    assert isinstance(ei.value, TimeoutError)
    assert ei.value.stats["pending"] == 1
    assert ei.value.stats["submitted"] == 1
    assert f.state == "PENDING"          # a timeout is NOT terminal
    ft.resolve(f.token, 42)              # late result still lands
    assert f.result(timeout=1.0) == 42


def test_double_resolution_is_impossible():
    """Exactly-once: any second terminal transition on a token -
    resolve/resolve, resolve/expire, expire/poison - raises
    EgressProtocolError, as does resolving a token never minted."""
    ft = FutureTable()
    f = ft.create("a", 0, 0)
    ft.resolve(f.token, 7)
    for hit in (lambda: ft.resolve(f.token, 8),
                lambda: ft.expire(f.token, "late"),
                lambda: ft.poison(f.token, "late")):
        with pytest.raises(EgressProtocolError, match="already"):
            hit()
    assert f.result() == 7               # the first resolution stands
    with pytest.raises(EgressProtocolError, match="unknown"):
        ft.resolve(999_999, 0)
    g = ft.create("a", 0, 0)
    ft.expire(g.token, "deadline")
    with pytest.raises(FutureExpired):
        g.result()
    cons = ft.conservation()
    assert cons["ok"] and cons["resolved"] == 1 and cons["expired"] == 1


def test_cancelled_scope_futures_poison_not_hang():
    """Cancelling a tenant (scope semantics: its lane's CancelScope
    cancels and queued work drains) resolves every queued future
    POISONED - result() raises immediately instead of hanging."""
    t = _table([TenantSpec("a", max_in_flight=1, queue_capacity=8)])
    t.egress = EgressSpec(depth=8)
    t.futures = FutureTable()
    t._owns_futures = True
    futs = [t.submit("a", BUMP, args=[i]).future for i in range(4)]
    assert all(f is not None for f in futs)
    t.cancel("a", "caller gave up")
    # Nothing pumped yet: accepted head AND queued tail all drain
    # through the cancel - every future lands POISONED, none hang.
    assert [f.state for f in futs] == ["POISONED"] * 4
    with pytest.raises(FuturePoisoned, match="cancelled"):
        futs[0].result(timeout=1.0)


def test_expired_future_reconciles_with_expiry_counters():
    """A queued row whose deadline passes resolves EXPIRED, and the
    ledger's expired count reconciles with the lane's expiry stats."""
    clk = [100.0]
    t = TenantTable(
        [TenantSpec("a", queue_capacity=8)], 16,
        clock=lambda: clk[0], egress=EgressSpec(depth=8),
    )
    keep = t.submit("a", BUMP, args=[1])
    doomed = t.submit("a", BUMP, args=[2], deadline_s=0.5)
    clk[0] += 5.0
    ring = np.zeros((16, 256), np.int32)
    t.pump(ring)
    assert doomed.future.state == "EXPIRED"
    with pytest.raises(FutureExpired):
        doomed.future.result()
    assert keep.future.state == "PENDING"
    assert t.futures.stats_dict()["expired"] == t.stats()["a"]["expired"]


# ------------------------------------------- executable spec + mailbox


def test_egress_reference_parks_on_full_and_flushes_fifo():
    """The numpy spec of the kernel publish path: a full mailbox PARKS
    (head-cursor ring, counted, never dropped), token-0 rows are
    skipped, and the entry-start flush drains the park ring FIFO as
    room opens."""
    depth = 2
    egr = np.zeros((depth, EGR_WORDS), np.int32)
    park = np.zeros((3, EGR_WORDS), np.int32)
    ectl = np.zeros(8, np.int32)
    rows = [(t, 0, BUMP, 0, 10 * t) for t in (1, 2, 3, 4)]
    rows.insert(2, (0, 0, BUMP, 0, 999))  # untracked: skipped
    published = egress_reference(rows, egr, park, ectl, depth)
    assert published == 2
    assert int(ectl[EC_PARK_COUNT]) == 2 and int(ectl[EC_PARKED]) == 2
    # Consume one, flush: park head (token 3) moves in, FIFO order.
    ectl[EC_CONSUMED] = 1
    egr[0] = 0
    assert flush_parked_reference(egr, park, ectl, depth) == 1
    assert int(egr[int(ectl[EC_WRITE] - 1) % depth][EGR_TOKEN]) == 3
    assert int(ectl[EC_PARK_HEAD]) == 1 and int(ectl[EC_PARK_COUNT]) == 1
    # Park overflow = a broken install credit gate, loudly.
    ectl[EC_PARK_COUNT] = park.shape[0]
    with pytest.raises(EgressProtocolError, match="credit gate"):
        egress_reference([(9, 0, 0, 0, 0)], egr, park, ectl, depth)


def test_host_mailbox_slow_poller_loses_nothing():
    """Satellite 1's core property at unit scale: a poller consuming
    one row per call against a depth-2 mailbox under 9 publishes -
    backpressure parks rows (park_events > 0) but every token resolves
    exactly once; conservation exact."""
    ft = FutureTable()
    futs = [ft.create("a", BUMP, 0) for _ in range(9)]
    box = HostMailbox(EgressSpec(depth=2), park_cap=16)
    for f in futs:
        box.publish([(f.token, 0, BUMP, 0, f.token * 11)])
    assert box.park_events() > 0
    drained = []
    while True:
        got = box.drain(futures=ft, limit=1)   # the slow poller
        if not got:
            break
        drained += got
    assert len(drained) == 9
    assert box.occupancy() == 0 and box.parked() == 0
    for f in futs:
        assert f.result(timeout=1.0) == f.token * 11
    assert ft.conservation()["ok"]


def test_mailbox_double_consume_is_a_protocol_error():
    box = HostMailbox(EgressSpec(depth=4))
    box.publish([(1, 0, BUMP, 0, 5)])
    box.drain()
    box.ectl[EC_CONSUMED] -= 1               # corrupt the cursor
    with pytest.raises(EgressProtocolError, match="consumed twice"):
        box.drain()


# --------------------------------------------------- protocol model


def test_egress_model_full_mailbox_cannot_wedge():
    """Every schedule of a 1-deep mailbox with a DEAD poller and a
    mid-flight quiesce reaches a clean terminal: both regions drained,
    every row resolved or preempted - the tentpole's wedge-proof
    claim, model-checked."""
    from hclib_tpu.analysis.explore import EgressMailboxModel, explore

    for m in (
        EgressMailboxModel(rows=4, depth=1, poller=False, quiesce=True),
        EgressMailboxModel(rows=3, depth=1, poller=True),
        EgressMailboxModel(rows=3, depth=2, poller=True, quiesce=True),
    ):
        res = explore(m, depth=64, budget_s=30)
        assert res.complete and res.clean, [
            v.message for v in res.violations
        ]
        assert res.terminals > 0


def test_egress_model_finds_the_seeded_park_leak():
    """drain_parked=False plants the bug where the quiesce export
    forgets the park ring; the exploration returns the concrete action
    prefix that loses the parked rows' futures."""
    from hclib_tpu.analysis.explore import EgressMailboxModel, explore

    res = explore(
        EgressMailboxModel(rows=4, depth=1, poller=False, quiesce=True,
                           drain_parked=False),
        depth=64, budget_s=30,
    )
    bad = [v for v in res.violations if "egress-wedge" in v.message]
    assert bad, [v.message for v in res.violations]
    assert any(a[0] == "retire" for a in bad[0].witness)


def test_check_protocols_curated_set_includes_egress_and_is_clean():
    from hclib_tpu.analysis.explore import check_protocols

    rep = check_protocols()
    assert not rep.actionable(), [f.message for f in rep.findings]


def test_layout_table_pins_the_egress_words():
    from hclib_tpu.analysis.layout import LAYOUT, check_layout

    assert not check_layout(force=True).actionable()
    for w in ("EGR_STATUS", "EGR_TOKEN", "EGR_VALUE", "EC_WRITE",
              "EC_PARK_HEAD", "EC_INFLIGHT"):
        assert w in LAYOUT


# ------------------------------------------------------- env knobs


def test_egress_env_knobs_registered_and_raise_on_malformed(monkeypatch):
    from hclib_tpu.runtime.env import REGISTRY

    assert {"HCLIB_TPU_EGRESS_DEPTH",
            "HCLIB_TPU_EGRESS_BACKOFF_S"} <= set(REGISTRY)
    monkeypatch.delenv("HCLIB_TPU_EGRESS_DEPTH", raising=False)
    monkeypatch.delenv("HCLIB_TPU_EGRESS_BACKOFF_S", raising=False)
    assert egress_from_env() is None
    assert normalize_egress(None) is None
    monkeypatch.setenv("HCLIB_TPU_EGRESS_DEPTH", "16")
    monkeypatch.setenv("HCLIB_TPU_EGRESS_BACKOFF_S", "0.01")
    spec = normalize_egress(None)
    assert spec.depth == 16 and spec.backoff_s == 0.01
    assert normalize_egress(False) is None   # explicit off beats env
    monkeypatch.setenv("HCLIB_TPU_EGRESS_DEPTH", "not-an-int")
    with pytest.raises(ValueError, match="HCLIB_TPU_EGRESS_DEPTH"):
        egress_from_env()
    monkeypatch.setenv("HCLIB_TPU_EGRESS_DEPTH", "8")
    monkeypatch.setenv("HCLIB_TPU_EGRESS_BACKOFF_S", "fast")
    with pytest.raises(ValueError, match="HCLIB_TPU_EGRESS_BACKOFF_S"):
        egress_from_env()
    with pytest.raises(ValueError, match="depth"):
        EgressSpec(depth=0)


# ------------------------------------------------- device (interpret)


def test_stream_serve_futures_resolve_with_parking():
    """DEVICE: a depth-4 mailbox under 12 submits forces in-kernel
    parking; every future still resolves RESULT and the ledger's
    conservation identity closes exactly."""
    table = _table(
        [TenantSpec("gold", weight=4), TenantSpec("silver")],
        egress=EgressSpec(depth=4),
    )
    sm = StreamingMegakernel(_bump_mk(), ring_capacity=32, tenants=table)
    futs = []
    for i in range(8):
        adm = sm.submit("gold", BUMP, args=[i + 1])
        assert adm.accepted and adm.future.token > 0
        futs.append(adm.future)
    for _ in range(4):
        futs.append(sm.submit("silver", BUMP, args=[100]).future)
    sm.close()
    iv, info = sm.run_stream(_seed_builder())
    assert int(iv[0]) == 1000 + sum(range(1, 9)) + 400
    for f in futs:
        assert isinstance(f.result(timeout=2.0), int)
        assert f.state == "RESULT" and f.latency_s() is not None
    cons = table.futures.conservation()
    assert cons["ok"] and cons["resolved"] == 12, cons
    assert sm.stats_dict()["egress"]["resolved"] == 12


def test_stream_quiesce_preempts_then_reattaches_across_resume():
    """DEVICE: a checkpoint cut mid-flight lands every in-flight future
    in RESULT or PREEMPTED (resume token); a fresh equivalent stream
    resumes the snapshot, re-adopts the tokens (etok rides the state),
    and reattached futures resolve - conservation closes on both
    ledgers."""
    t1 = _table([TenantSpec("x"), TenantSpec("y")], region=32,
                egress=EgressSpec(depth=64))
    sm = StreamingMegakernel(_bump_mk(checkpoint=True),
                             ring_capacity=64, tenants=t1)
    futs = [sm.submit("x", BUMP, args=[1]).future for _ in range(10)]
    sm.quiesce(after_executed=3)
    _, info = sm.run_stream(_seed_builder())
    assert info["quiesced"] and "etok" in info["state"]
    assert {f.state for f in futs} <= {"RESULT", "PREEMPTED"}
    tokens = []
    for f in futs:
        if f.state == "PREEMPTED":
            with pytest.raises(FuturePreempted) as ei:
                f.result()
            assert ei.value.resume_token == f.resume_token
            tokens.append(f.resume_token)
    assert tokens, "expected preempted futures at a cut after 3 tasks"
    c1 = t1.futures.conservation()
    assert c1["ok"] and c1["preempted"] == len(tokens)
    t2 = _table([TenantSpec("x"), TenantSpec("y")], region=32,
                egress=EgressSpec(depth=64))
    sm2 = StreamingMegakernel(_bump_mk(checkpoint=True),
                              ring_capacity=64, tenants=t2)
    sm2.close()
    iv2, _ = sm2.run_stream(resume_state=info["state"])
    assert int(iv2[0]) == 1000 + 10
    for tok in tokens:
        f = sm2.tenants.reattach(tok)
        assert f.result(timeout=2.0) is not None and f.state == "RESULT"
    c2 = t2.futures.conservation()
    assert c2["ok"] and c2["reattached"] == len(tokens)


def test_resume_onto_tiny_mailbox_reseeds_inflight_credit():
    """DEVICE regression: a snapshot's ectl block is NOT exported (the
    mailbox drains before the cut), but its adopted etok tokens ARE in
    flight - resume must reseed EC_INFLIGHT from the adopted count or
    each adopted retirement drives it negative, the install credit
    gate inflates, and a depth-4 park ring overwraps its own counted
    rows (found by driving resume under parking pressure)."""
    def table():
        return _table([TenantSpec("x"), TenantSpec("y")], region=32,
                      egress=EgressSpec(depth=4))

    sm = StreamingMegakernel(_bump_mk(checkpoint=True),
                             ring_capacity=64, tenants=table())
    futs = [sm.submit("x" if i % 2 else "y", BUMP, args=[i + 1]).future
            for i in range(14)]
    sm.quiesce(after_executed=4)
    _, info = sm.run_stream(_seed_builder())
    assert info["quiesced"]
    tokens = [f.resume_token for f in futs if f.state == "PREEMPTED"]
    assert len(tokens) > 4, "need more adopted tokens than the depth"
    t2 = table()
    sm2 = StreamingMegakernel(_bump_mk(checkpoint=True),
                              ring_capacity=64, tenants=t2)
    sm2.close()
    iv2, _ = sm2.run_stream(resume_state=info["state"])
    assert int(iv2[0]) == 1000 + sum(range(1, 15))
    for tok in tokens:
        f = sm2.tenants.reattach(tok)
        assert f.result(timeout=2.0) is not None and f.state == "RESULT"
    cons = t2.futures.conservation()
    assert cons["ok"] and cons["pending"] == 0, cons


def test_stream_abort_poisons_outstanding_futures():
    """DEVICE: abort() is the ladder's bottom rung - results already
    in the mailbox resolve, every other outstanding future poisons
    (typed raise, no hang)."""
    t = _table(egress=EgressSpec(depth=64), region=32)
    sm = StreamingMegakernel(_bump_mk(), ring_capacity=32, tenants=t)
    futs = [sm.submit("a", BUMP, args=[1]).future for _ in range(5)]
    sm.abort("client disconnect")
    with pytest.raises(Exception, match="abort"):
        sm.run_stream(_seed_builder())
    for f in futs:
        assert f.state in ("RESULT", "POISONED")
        if f.state == "POISONED":
            with pytest.raises(FuturePoisoned, match="abort"):
                f.result(timeout=1.0)
    assert t.futures.conservation()["ok"]
    assert t.futures.pending() == 0      # nothing hangs


# ------------------------------------------------ off-path identity


def _lower_text(sm):
    mk = sm.mk
    tasks, succ, ready, counts = _seed_builder().finalize(
        capacity=mk.capacity, succ_capacity=mk.succ_capacity
    )
    args = [
        tasks, succ, ready, counts,
        np.zeros(mk.num_values, np.int32),
        np.zeros((sm.ring_capacity, 256), np.int32),
        np.zeros(8, np.int32),
    ]
    if sm.tenants is not None:
        args.append(np.zeros((len(sm.tenants), 8), np.int32))
    if sm._egress is not None:
        d = sm._egress.depth
        args += [
            np.zeros((d, EGR_WORDS), np.int32),
            np.zeros((d, EGR_WORDS), np.int32),
            np.zeros(8, np.int32),
            np.zeros(mk.capacity, np.int32),
        ]
    return sm._build(1 << 10, 64).lower(*args).as_text()


def test_off_path_builds_compile_zero_egress_words(monkeypatch):
    """egress=False (and plain egress-free tables) lower to the EXACT
    text an env-free tenant build lowers to, even with the egress env
    knobs set - the ISSUE 16 off-path bit-identity gate. An egress-ON
    build lowers cleanly and differs (the words exist only on-path)."""
    monkeypatch.delenv("HCLIB_TPU_EGRESS_DEPTH", raising=False)
    base = _lower_text(
        StreamingMegakernel(_bump_mk(), ring_capacity=32, tenants=["a"])
    )
    monkeypatch.setenv("HCLIB_TPU_EGRESS_DEPTH", "64")
    off = _lower_text(
        StreamingMegakernel(
            _bump_mk(), ring_capacity=32,
            tenants=TenantTable([TenantSpec("a")], 32,
                                clock=lambda: 0.0, egress=False),
        )
    )
    assert off == base
    on = _lower_text(
        StreamingMegakernel(
            _bump_mk(), ring_capacity=32,
            tenants=TenantTable([TenantSpec("a")], 32,
                                clock=lambda: 0.0,
                                egress=EgressSpec(depth=8)),
        )
    )
    assert on != base          # egress words compile only on-path


# ------------------------------------------------- mesh conservation


def test_mesh_serve_conservation_across_4_2_4_reshards():
    """THE SOAK IDENTITY at test scale: a 4-device mesh front door with
    futures, driven on the WRR reference model + per-device host
    mailboxes, resharded live 4 -> 2 -> 4 with futures in flight. At
    every cut: in-flight futures preempt with valid resume tokens and
    reattach on the resized table; at the end
    submitted == resolved + expired + poisoned, exactly."""
    from hclib_tpu.device.descriptor import RING_ROW, TEN_TOKEN
    from hclib_tpu.device.tenants import wrr_poll_reference

    region = 16
    clk = [100.0]
    spec = EgressSpec(depth=4)

    def specs():
        return [TenantSpec("gold", weight=2), TenantSpec("std")]

    table = MeshTenantTable(specs(), 4, region, clock=lambda: clk[0],
                            egress=spec)
    futures = table.futures
    assert futures is not None
    submitted = 0

    def drive(table, rings, polls=4, start=0):
        boxes = [HostMailbox(spec) for _ in range(table.ndev)]
        tctl = table.pump(rings)
        for r in range(start, start + polls):
            for d in range(table.ndev):
                rows = wrr_poll_reference(
                    rings[d], tctl[d], table.region_rows, r, 1 << 20
                )
                boxes[d].publish([
                    (int(row[TEN_TOKEN]), 0, BUMP, 0, 7)
                    for row in rows
                ])
        table.absorb(tctl)
        for box in boxes:
            box.drain(futures=futures)

    def rings_for(ndev):
        return np.zeros((ndev, 2 * region, RING_ROW), np.int32)

    sizes = [4, 2, 4]
    rings = rings_for(4)
    live = []
    for phase, ndev in enumerate(sizes):
        for i in range(8):
            adm = table.submit(i % 2, BUMP, args=[i])
            if adm:
                submitted += 1
                live.append(adm.future)
        drive(table, rings, polls=2, start=phase * 4)
        if phase == len(sizes) - 1:
            break
        # live reshard: export (preempts in-flight), resize, re-adopt.
        state = table.export_state(rings)
        tokens = [f.resume_token for f in live
                  if f.state == "PREEMPTED"]
        nxt = table.resized(sizes[phase + 1])
        assert nxt.futures is futures     # ONE ledger across cuts
        nxt.resume_from(state)
        for tok in tokens:
            nxt.reattach(tok)
        table = nxt
        rings = rings_for(table.ndev)
    # final drain: pump/poll until every lane empties.
    for r in range(20, 40):
        drive(table, rings, polls=1, start=r)
        if table.drained():
            break
    cons = futures.conservation()
    assert cons["ok"], cons
    assert cons["pending"] == 0, cons
    assert submitted == (
        cons["resolved"] + cons["expired"] + cons["poisoned"]
    ), (submitted, cons)


def test_mesh_serve_fallback_restore_reattaches_futures(tmp_path):
    """DURABLE STORE x SERVING: a mesh export rides a CheckpointBundle
    into a generational BundleStore; the newest generation is then
    bit-flipped on disk. load_latest self-heals (quarantine + fallback
    to the older valid save of the SAME cut), the table resumes from
    the fallback arrays, preempted futures reattach, and the serving
    ledger's conservation identity still closes exactly."""
    from hclib_tpu.device.descriptor import RING_ROW, TEN_TOKEN
    from hclib_tpu.device.tenants import wrr_poll_reference
    from hclib_tpu.runtime.checkpoint import BundleStore, CheckpointBundle

    region = 16
    clk = [100.0]
    spec = EgressSpec(depth=4)
    table = MeshTenantTable(
        [TenantSpec("gold", weight=2), TenantSpec("std")], 2, region,
        clock=lambda: clk[0], egress=spec,
    )
    futures = table.futures
    rings = np.zeros((2, 2 * region, RING_ROW), np.int32)
    submitted = 0
    live = []
    for i in range(8):
        adm = table.submit(i % 2, BUMP, args=[i], deadline_s=600.0)
        if adm:
            submitted += 1
            live.append(adm.future)
    # the cut: export preempts in-flight futures, bundle -> store x2.
    state = table.export_state(rings)
    tokens = [f.resume_token for f in live if f.state == "PREEMPTED"]
    assert tokens, "expected in-flight futures at the cut"
    store = BundleStore(str(tmp_path / "store"), keep=3, fsync=False)
    bundle = CheckpointBundle(
        "resident", {"schema": "mesh-serve-export"}, state
    )
    store.save(bundle)
    store.save(bundle)
    npz = os.path.join(store.path_of(2), "state.npz")
    blob = open(npz, "rb").read()
    with open(npz, "wb") as f:
        f.write(blob[:12] + bytes([blob[12] ^ 0x40]) + blob[13:])
    healer = BundleStore(str(tmp_path / "store"), fsync=False)
    back = healer.load_latest()
    assert back.generation == 1, "fallback to the older valid save"
    assert [f.reason for f in healer.faults] == ["corrupt"]
    # resume from the FALLBACK arrays, reattach, drive to the drain.
    nxt = table.resized(2)
    assert nxt.futures is futures
    nxt.resume_from({k: back.arrays[k] for k in state})
    for tok in tokens:
        f = nxt.reattach(tok)
        assert f.state == "PENDING"
    boxes = [HostMailbox(spec) for _ in range(2)]
    for r in range(40):
        tctl = nxt.pump(rings)
        for d in range(2):
            rows = wrr_poll_reference(
                rings[d], tctl[d], nxt.region_rows, r, 1 << 20
            )
            boxes[d].publish([
                (int(row[TEN_TOKEN]), 0, BUMP, 0, 7) for row in rows
            ])
        nxt.absorb(tctl)
        for box in boxes:
            box.drain(futures=futures)
        if nxt.drained():
            break
    cons = futures.conservation()
    assert cons["ok"], cons
    assert cons["pending"] == 0, cons
    assert cons["reattached"] == len(tokens), cons
    assert submitted == (
        cons["resolved"] + cons["expired"] + cons["poisoned"]
    ), (submitted, cons)
