"""C++ native runtime tests (built on demand via make; skipped without g++)."""

import shutil

import numpy as np
import pytest

# Match the Makefile's default compiler (CXX ?= g++, overridable via env).
import os

_cxx = os.environ.get("CXX", "g++")
pytestmark = pytest.mark.skipif(
    shutil.which(_cxx) is None, reason=f"no C++ compiler ({_cxx})"
)


@pytest.fixture(scope="module")
def rt():
    from hclib_tpu.native import NativeRuntime

    with NativeRuntime(2) as r:
        yield r


def test_native_fib(rt):
    assert rt.fib(20) == 6765
    assert rt.fib(1) == 1
    assert rt.fib(0) == 0


def test_native_uts_t3(rt):
    # T3: FIXED shape, depth 5, b0=4, seed 42 (pinned in models/uts.py)
    assert rt.uts(3, 5, 4.0, 42) == (1279, 1018, 5)


def test_native_uts_matches_python_spec(rt):
    from hclib_tpu.models import uts

    params = uts.UTSParams(shape=uts.FIXED, gen_mx=4, b0=3.0, root_seed=7)
    seq = uts.count_seq(params)
    assert rt.uts(3, 4, 3.0, 7) == seq


def test_native_arrayadd(rt):
    n = 10_000
    a = np.arange(n, dtype=np.float64)
    b = 2.0 * np.arange(n, dtype=np.float64)
    c = np.zeros(n)
    rt.arrayadd(a, b, c, tile=512)
    assert np.array_equal(c, a + b)


def test_native_stats(rt):
    before = rt.executed
    rt.fib(15)
    assert rt.executed > before
