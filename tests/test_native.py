"""C++ native runtime tests (built on demand via make; skipped without g++)."""

import shutil

import numpy as np
import pytest

# Match the Makefile's default compiler (CXX ?= g++, overridable via env).
import os

_cxx = os.environ.get("CXX", "g++")
pytestmark = pytest.mark.skipif(
    shutil.which(_cxx) is None, reason=f"no C++ compiler ({_cxx})"
)


@pytest.fixture(scope="module")
def rt():
    from hclib_tpu.native import NativeRuntime

    with NativeRuntime(2) as r:
        yield r


def test_native_fib(rt):
    assert rt.fib(20) == 6765
    assert rt.fib(1) == 1
    assert rt.fib(0) == 0


def test_native_uts_t3(rt):
    # T3: FIXED shape, depth 5, b0=4, seed 42 (pinned in models/uts.py)
    assert rt.uts(3, 5, 4.0, 42) == (1279, 1018, 5)


def test_native_uts_matches_python_spec(rt):
    from hclib_tpu.models import uts

    params = uts.UTSParams(shape=uts.FIXED, gen_mx=4, b0=3.0, root_seed=7)
    seq = uts.count_seq(params)
    assert rt.uts(3, 4, 3.0, 7) == seq


def test_native_arrayadd(rt):
    n = 10_000
    a = np.arange(n, dtype=np.float64)
    b = 2.0 * np.arange(n, dtype=np.float64)
    c = np.zeros(n)
    rt.arrayadd(a, b, c, tile=512)
    assert np.array_equal(c, a + b)


def test_native_stats(rt):
    before = rt.executed
    rt.fib(15)
    assert rt.executed > before


def test_native_fib_ddt(rt):
    # Promise-based fib (reference workload test/misc/fib-ddt): every join
    # is an async_await on two child promises.
    assert rt.fib_ddt(18) == 2584
    assert rt.fib_ddt(2) == 1


def _sw_python_reference(nx, ny, ts, seed):
    """Replicates the native splitmix64 sequence generation + DP scoring."""
    mask = (1 << 64) - 1

    def gen(state, count):
        out = []
        s = state
        for _ in range(count):
            s = (s + 0x9E3779B97F4A7C15) & mask
            z = s
            z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & mask
            z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & mask
            out.append((z ^ (z >> 31)) & 3)
        return out, s

    s0 = (seed * 2654435761 + 1) & mask
    a, s1 = gen(s0, nx * ts)
    b, _ = gen(s1, ny * ts)
    n, m = len(a), len(b)
    prev = [0] * (m + 1)
    best = 0
    for i in range(1, n + 1):
        cur = [0] * (m + 1)
        for j in range(1, m + 1):
            sc = 1 if a[i - 1] == b[j - 1] else -1
            v = max(prev[j - 1] + sc, prev[j] - 1, cur[j - 1] - 1, 0)
            cur[j] = v
            if v > best:
                best = v
        prev = cur
    return best


def test_native_smithwaterman_matches_reference_dp(rt):
    got = rt.smithwaterman(2, 2, 24, seed=5)
    assert got == _sw_python_reference(2, 2, 24, 5)


def test_native_smithwaterman_deterministic(rt):
    a = rt.smithwaterman(4, 4, 32, seed=9)
    b = rt.smithwaterman(4, 4, 32, seed=9)
    assert a == b and a > 0


def test_native_python_tasks_finish(rt):
    import threading

    hits = []
    lock = threading.Lock()
    with rt.finish() as f:
        for i in range(50):
            rt.async_(lambda i=i: (lock.acquire(), hits.append(i), lock.release()),
                      finish=f)
    assert sorted(hits) == list(range(50))


def test_native_promise_dependencies(rt):
    order = []
    p1 = rt.promise()
    p2 = rt.promise()
    with rt.finish() as f:
        rt.async_(lambda: order.append("dep"), finish=f, deps=(p1, p2))
        rt.async_(lambda: (order.append("a"), p1.put(7)), finish=f)
        rt.async_(lambda: (order.append("b"), p2.put(9)), finish=f)
    assert order[-1] == "dep" and set(order) == {"a", "b", "dep"}
    assert p1.wait() == 7 and p2.get() == 9
    p1.free()
    p2.free()


def test_native_end_finish_nonblocking(rt):
    import time

    done = []
    f = rt.finish()
    rt.async_(lambda: (time.sleep(0.01), done.append(1)), finish=f)
    p = f.end_nonblocking()
    assert p.wait() == 0  # promise satisfied once the scope drains
    assert done == [1]


def test_native_forasync(rt):
    n = 1000
    out = [0] * n
    rt.forasync1d(lambda i: out.__setitem__(i, i * 2), n, tile=64)
    assert out == [2 * i for i in range(n)]
    grid = [[0] * 8 for _ in range(8)]
    rt.forasync2d(lambda i, j: grid[i].__setitem__(j, i + j), 8, 8, 2, 2)
    assert grid == [[i + j for j in range(8)] for i in range(8)]


def test_native_forasync_recursive(rt):
    n = 513
    out = [0] * n
    rt.forasync1d(lambda i: out.__setitem__(i, i + 1), n, tile=32, recursive=True)
    assert out == [i + 1 for i in range(n)]


def test_native_locality_graph():
    from hclib_tpu.native import NativeRuntime
    from hclib_tpu.runtime.locality import generate_default_graph

    g = generate_default_graph(2)
    with NativeRuntime(graph=g) as rt:
        assert rt.nlocales == len(g.locales)
        assert rt.fib(15) == 610
        # Spawn at a non-default locale; a worker whose steal path covers it
        # must pick it up.
        hits = []
        with rt.finish() as f:
            rt.async_(lambda: hits.append(1), finish=f, locale=2)
        assert hits == [1]
        sm = rt.steal_matrix()
        assert len(sm) == 2 and len(sm[0]) == 2
        assert "executed=" in rt.format_stats()


def test_native_yield(rt):
    ran = []
    with rt.finish() as f:
        rt.async_(lambda: ran.append(1), finish=f)
        # Give the spawned task a chance to be picked up by the main thread.
        rt.yield_()
    assert ran == [1]


def test_affinity_pins_workers(monkeypatch):
    """HCLIB_TPU_AFFINITY=strided pins worker w to CPU w % ncpu
    (reference: HCLIB_AFFINITY, src/hclib-runtime.c:731-900)."""
    import os

    from hclib_tpu.native import NativeRuntime

    monkeypatch.setenv("HCLIB_TPU_AFFINITY", "strided")
    allowed = sorted(os.sched_getaffinity(0))  # respects cgroup/taskset
    with NativeRuntime(nworkers=2) as r:
        assert r.pinned_cpus() == [allowed[w % len(allowed)] for w in range(2)]
        assert r.fib(15) == 610  # still schedules correctly while pinned
    # Teardown restored the caller's mask: later runtimes must be unpinned.
    assert sorted(os.sched_getaffinity(0)) == allowed


def test_no_affinity_by_default(monkeypatch):
    from hclib_tpu.native import NativeRuntime

    monkeypatch.delenv("HCLIB_TPU_AFFINITY", raising=False)
    monkeypatch.delenv("HCLIB_AFFINITY", raising=False)
    with NativeRuntime(nworkers=2) as r:
        assert r.pinned_cpus() == [-1, -1]


def test_unknown_affinity_mode_ignored(monkeypatch):
    """Only strided|chunked activate pinning; anything else is rejected
    (a stray HCLIB_AFFINITY=none must not hard-pin the host thread)."""
    from hclib_tpu.native import NativeRuntime

    monkeypatch.setenv("HCLIB_TPU_AFFINITY", "none")
    with NativeRuntime(nworkers=2) as r:
        assert r.pinned_cpus() == [-1, -1]


def test_multicore_speedup():
    """Where cores exist, more workers must actually help - the measured
    CPU-baseline story depends on it (gated: the TPU bench host has 1
    core; CI runners have >= 2)."""
    import os
    import time

    import pytest

    from hclib_tpu.native import NativeRuntime

    ncpu = os.cpu_count() or 1
    if ncpu < 2:
        pytest.skip("single-core host")

    def wall(workers):
        with NativeRuntime(nworkers=workers) as r:
            r.fib(24)  # warm the pools
            t0 = time.perf_counter()
            r.fib(27)
            return time.perf_counter() - t0

    t1 = min(wall(1) for _ in range(2))
    tn = min(wall(min(ncpu, 4)) for _ in range(2))
    assert tn < t1 / 1.15, (t1, tn)


def test_typed_cpp_promise_future():
    """promise_t<int>/future_t<double> (reference inc/hclib_promise.h:41-124):
    a typed int promise chained through async_await into a typed double
    future; the demo returns 1000*42 + 2."""
    from hclib_tpu.native import NativeRuntime

    with NativeRuntime(nworkers=2) as r:
        assert r._lib.hcn_typed_promise_demo(r._handle) == 42002


def test_lint_clean():
    """The static-check gate (tools/lint.py - the reference's astyle +
    cppcheck station): the whole tree must pass, so style violations fail
    a plain pytest run locally, not just CI."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "lint.py")],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, f"lint violations:\n{r.stdout}"
