"""Comm-module layer: two-sided messaging, collectives, one-sided ops,
wait-sets, distributed locks, active messages, PGAS.

Mirrors the reference's module test suites (modules/mpi/test/{send_recv,
isend_irecv}.cpp, modules/openshmem/test/ wait/async_when/lock-stress,
modules/openshmem-am/test/, modules/upcxx/test/) against the new API, runnable
single-host - the multi-node behavior the reference leaves untested.
"""


import numpy as np
import pytest

import hclib_tpu as hc
from hclib_tpu.modules import (
    CommModule,
    DistLock,
    OneSidedModule,
    SharedArray,
    async_remote,
    remote_finish,
    set_world,
    symm_array,
)
from hclib_tpu.modules import comm as C
from hclib_tpu.modules import oneside as O
from hclib_tpu.modules.pgas import async_after
from hclib_tpu.parallel.mesh import cpu_mesh, mesh_locality_graph


@pytest.fixture(autouse=True)
def _reset_world():
    set_world(None)
    yield
    set_world(None)


def _mesh_args(ndev=2, nworkers=3):
    return {"locality_graph": mesh_locality_graph(cpu_mesh(ndev), nworkers=nworkers)}


def _launch_comm(body, **kw):
    hc.register_module(CommModule())
    return hc.launch(body, **kw)


def _launch_oneside(body, **kw):
    hc.register_module(OneSidedModule())
    return hc.launch(body, **kw)


# ---------------------------------------------------------------- two-sided


def test_send_recv_blocking():
    def body():
        out = []

        def sender():
            C.send(np.arange(4), dst=1, tag=7)

        def receiver():
            out.append(C.recv(tag=7, rank=1))

        with hc.finish():
            hc.async_(sender)
            hc.async_(receiver)
        np.testing.assert_array_equal(np.asarray(out[0]), np.arange(4))

    _launch_comm(body, **_mesh_args())


def test_isend_irecv_futures_and_waitall():
    def body():
        futs = [C.irecv(tag=t, rank=0) for t in range(3)]
        for t in range(3):
            C.isend(t * 10, dst=0, tag=t)
        vals = C.wait_all(futs)
        assert vals == [0, 10, 20]

    _launch_comm(body, **_mesh_args())


def test_send_commits_payload_to_dst_device():
    def body():
        import jax

        C.send(np.ones(8, np.float32), dst=1, tag=0)
        arr = C.recv(tag=0, rank=1)
        assert isinstance(arr, jax.Array)
        w = C._active().world()
        assert arr.devices() == {w.device_for(1)}

    _launch_comm(body, **_mesh_args())


def test_tag_and_source_matching():
    def body():
        C.isend("a", dst=0, tag=1, src=5)
        C.isend("b", dst=0, tag=2, src=6)
        assert C.recv(tag=2, rank=0) == "b"
        assert C.recv(src=5, tag=1, rank=0) == "a"

    _launch_comm(body, **_mesh_args())


# --------------------------------------------------------------- collectives


def test_collectives_roundtrip():
    def body():
        n = C.comm_rank_count()
        assert n == 2
        vals = [np.full(4, r + 1.0, np.float32) for r in range(n)]
        out = C.allreduce(vals)
        assert len(out) == n
        np.testing.assert_array_equal(np.asarray(out[0]), np.full(4, 3.0))
        red = C.reduce(vals, op=np.maximum, root=1)
        np.testing.assert_array_equal(np.asarray(red), np.full(4, 2.0))
        bc = C.broadcast(np.arange(3), root=0)
        np.testing.assert_array_equal(np.asarray(bc[1]), np.arange(3))
        C.barrier()
        sc = C.scatter([10, 20])
        assert sc == [10, 20]
        ag = C.allgather([1, 2])
        assert ag[0] == [1, 2] and ag[1] == [1, 2]
        a2a = C.alltoall([[0, 1], [2, 3]])
        assert a2a[0] == [0, 2] and a2a[1] == [1, 3]

    _launch_comm(body, **_mesh_args())


def test_allreduce_device_values_stay_on_device():
    def body():
        import jax
        import jax.numpy as jnp

        w = C._active().world()
        vals = [
            jax.device_put(jnp.ones(4) * (r + 1), w.device_for(r)) for r in range(2)
        ]
        out = C.allreduce(vals)
        assert out[1].devices() == {w.device_for(1)}
        np.testing.assert_allclose(np.asarray(out[0]), np.full(4, 3.0))

    _launch_comm(body, **_mesh_args())


# ----------------------------------------------------------------- one-sided


def test_put_get_symmetric_heap():
    def body():
        arr = symm_array(4, np.int32)
        O.put(arr, rank=1, value=7, index=2)
        assert O.get(arr, rank=1, index=2) == 7
        assert O.get(arr, rank=0, index=2) == 0  # ranks are distinct copies

    _launch_oneside(body, **_mesh_args())


def test_symmetric_heap_device_backed():
    def body():
        import jax

        arr = symm_array(4, np.float32)
        w = O._active().world()
        assert isinstance(arr.buffer(0), jax.Array)
        assert arr.buffer(1).devices() == {w.device_for(1)}

    _launch_oneside(body, **_mesh_args())


def test_fetch_add_and_compare_swap():
    def body():
        arr = symm_array(1, np.int64)
        old = O.fetch_add(arr, rank=0, delta=5)
        assert old == 0
        assert O.get(arr, rank=0, index=0) == 5
        seen = O.compare_swap(arr, rank=0, expected=5, desired=9)
        assert seen == 5 and O.get(arr, rank=0, index=0) == 9
        seen = O.compare_swap(arr, rank=0, expected=5, desired=1)
        assert seen == 9 and O.get(arr, rank=0, index=0) == 9

    _launch_oneside(body, **_mesh_args())


def test_fetch_add_concurrent_atomicity():
    def body():
        arr = symm_array(1, np.int64)

        def bump():
            for _ in range(50):
                O.fetch_add(arr, rank=0, delta=1)

        with hc.finish():
            for _ in range(4):
                hc.async_(bump)
        assert O.get(arr, rank=0, index=0) == 200

    _launch_oneside(body, nworkers=4)


def test_wait_until_and_async_when():
    def body():
        flag = symm_array(1, np.int32)

        def producer():
            O.put(flag, rank=0, value=42, index=0)

        fut = O.async_when(flag, "eq", 42, rank=0, index=0)
        hc.async_(producer)
        assert fut.wait() == 0  # index of matching entry

    _launch_oneside(body, **_mesh_args())


def test_wait_until_any_multiple_sets():
    def body():
        a = symm_array(1, np.int32)
        b = symm_array(1, np.int32)

        def producer():
            O.put(b, rank=1, value=3, index=0)

        hc.async_(producer)
        idx = O.wait_until_any(
            [(a, 0, "gt", 10, 0), (b, 1, "eq", 3, 0)]
        )
        assert idx == 1

    _launch_oneside(body, **_mesh_args())


def test_dist_lock_mutual_exclusion():
    def body():
        counter = {"v": 0, "max_in": 0}

        def critical():
            with DistLock.named("L"):
                counter["max_in"] += 1
                assert counter["max_in"] == 1
                counter["v"] += 1
                counter["max_in"] -= 1

        with hc.finish():
            for _ in range(20):
                hc.async_(critical)
        assert counter["v"] == 20

    _launch_oneside(body, nworkers=4)


def test_per_worker_contexts_and_quiet():
    def body():
        arr = symm_array(8, np.int32)
        ctx = O.my_context()
        for i in range(8):
            O.iput(arr, rank=0, value=i, index=i)
        O.quiet()
        assert len(ctx.outstanding) == 0
        np.testing.assert_array_equal(
            np.asarray(arr.buffer(0)), np.arange(8, dtype=np.int32)
        )

    _launch_oneside(body, **_mesh_args())


# ----------------------------------------------------------- active messages


def _double(x):
    return x * 2


def test_async_remote_by_name_and_closure():
    def body():
        assert async_remote(_double, 1, 21).wait() == 42
        y = 5
        assert async_remote(lambda x: x + y, 0, 1).wait() == 6

    _launch_oneside(body, **_mesh_args())


def test_async_remote_error_propagates():
    def body():
        def boom():
            raise ValueError("remote failure")

        from hclib_tpu.runtime.promise import PromiseError

        with pytest.raises(PromiseError):
            async_remote(boom, 0).wait()

    _launch_oneside(body, **_mesh_args())


def test_am_packet_roundtrip_is_bytes():
    from hclib_tpu.modules.am import pack_am, unpack_am

    fn, args = unpack_am(pack_am(_double, (3,)))
    assert fn is _double and fn(*args) == 6


# ----------------------------------------------------------------------- pgas


def test_global_ref_and_shared_array():
    def body():
        sa = SharedArray(10, np.int64)
        for i in range(10):
            sa[i] = i * i
        assert [sa[i] for i in range(10)] == [i * i for i in range(10)]
        # cyclic layout: element i on rank i % size
        r = sa.ref(3)
        assert r.rank == 3 % 2 and r.index == 3 // 2
        r2 = r + 1
        assert r2.index == r.index + 1

    _launch_oneside(body, **_mesh_args())


def test_async_after_chains():
    def body():
        arr = symm_array(1, np.int32)
        f1 = O.iput(arr, rank=0, value=10, index=0)
        f2 = async_after(f1, lambda: O.get(arr, rank=0, index=0) + 1)
        assert f2.wait() == 11

    _launch_oneside(body, **_mesh_args())


def test_remote_finish_awaits_all():
    def body():
        hits = []

        def mark(r):
            hits.append(r)
            return r

        with remote_finish() as rf:
            for r in range(2):
                rf.remote(mark, r, r)
        assert sorted(hits) == [0, 1]

    _launch_oneside(body, **_mesh_args())
